#!/usr/bin/env python
"""Lint gate: no silent exception swallowing in nnstreamer_tpu/.

Flags two patterns that hide failures from the resilience layer (which
classifies and reports errors — see Documentation/resilience.md):

* bare ``except:`` — catches SystemExit/KeyboardInterrupt too;
* ``except Exception:`` / ``except BaseException:`` whose body is only
  ``pass`` — an error black hole (no log, no bus message, no counter).

Narrow handlers with ``pass`` (``except ValueError: pass``) are fine —
they document exactly what is being ignored.  Genuinely-intended
swallow-alls (``__del__``, teardown of already-dead resources) carry an
inline ``# allow-silent: <reason>`` on the ``except`` or ``pass`` line,
or go on the file:line allowlist below with a reason.

Exit status: 0 clean, 1 violations (printed as file:line).  Run directly
or via the tier-1 test ``tests/test_resilience.py::test_no_bare_except``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = ["nnstreamer_tpu", "tools"]

# file:line entries that are allowed to keep a flagged pattern, with WHY
ALLOWLIST: dict = {
    # (none today — add "path/to/file.py:123" -> "reason" as needed)
}

_BARE = re.compile(r"^\s*except\s*:\s*(#.*)?$")
_BROAD = re.compile(r"^\s*except\s+(Exception|BaseException)\s*(as\s+\w+)?\s*:\s*(#.*)?$")
_PASS = re.compile(r"^\s*pass\s*(#.*)?$")
_ALLOW = re.compile(r"#\s*allow-silent:\s*\S")


def scan(root: Path = ROOT) -> list:
    bad = []
    for d in SCAN_DIRS:
        for path in sorted((root / d).rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            lines = path.read_text(encoding="utf-8").splitlines()
            for i, line in enumerate(lines, 1):
                key = f"{rel}:{i}"
                if _BARE.match(line):
                    if key not in ALLOWLIST and not _ALLOW.search(line):
                        bad.append((key, "bare except:"))
                    continue
                if _BROAD.match(line) and not _ALLOW.search(line):
                    # flag only when the handler body is a lone `pass`
                    # (comment-only lines before it don't count as a body)
                    j = i
                    while j < len(lines) and (
                        not lines[j].strip()
                        or lines[j].strip().startswith("#")
                    ):
                        j += 1
                    if j < len(lines) and _PASS.match(lines[j]):
                        indent = len(line) - len(line.lstrip())
                        body_indent = len(lines[j]) - len(lines[j].lstrip())
                        more = (
                            j + 1 < len(lines)
                            and lines[j + 1].strip()
                            and (len(lines[j + 1])
                                 - len(lines[j + 1].lstrip())) > indent
                        )
                        if body_indent > indent and not more:
                            if (key not in ALLOWLIST
                                    and not _ALLOW.search(lines[j])):
                                bad.append(
                                    (key, "except Exception: pass "
                                     "(silent swallow-all)"))
    return bad


def main() -> int:
    bad = scan()
    for key, why in bad:
        print(f"{key}: {why}")
    if bad:
        print(f"\n{len(bad)} silent exception handler(s); log, re-raise, "
              "narrow the type, or allowlist with a reason "
              "(tools/check_no_bare_except.py)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
