#!/usr/bin/env python
"""Lint gate: no unbounded blocking calls in the I/O layers.

The liveness layer (core/liveness.py, Documentation/resilience.md
"Liveness & overload") exists because a call that blocks forever takes a
worker thread — and eventually the pipeline — down *silently*.  This
gate keeps the audited state of ``nnstreamer_tpu/distributed/`` and
``nnstreamer_tpu/elements/`` from regressing.  Flagged patterns:

* ``sock.settimeout(None)`` — switches a socket to unbounded blocking;
* zero-argument blocking waits: ``.get()`` / ``.wait()`` / ``.join()``
  / ``.result()`` (queue pops, event waits, thread joins, and future
  results must carry a timeout — a wedged peer/worker otherwise parks
  the caller forever);
* ``socket.create_connection(...)`` without a ``timeout=``.

Deliberate unbounded blocking (a pub/sub stream idling on a quiet
publisher, interruptible via ``close()``) carries an inline
``# allow-blocking: <reason>`` on the flagged line or within the three
lines above it, or a file:line ALLOWLIST entry below with a reason.

Exit status: 0 clean, 1 violations (printed as file:line).  Run directly
or via the tier-1 test ``tests/test_liveness.py::test_no_unbounded_blocking``.
"""

from __future__ import annotations

import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
SCAN_DIRS = ["nnstreamer_tpu/distributed", "nnstreamer_tpu/elements"]

# file:line entries that are allowed to keep a flagged pattern, with WHY
ALLOWLIST: dict = {
    # (none today — add "path/to/file.py:123" -> "reason" as needed)
}

_SETTIMEOUT_NONE = re.compile(r"\.settimeout\(\s*None\s*\)")
_ZERO_ARG_WAIT = re.compile(r"\.(get|wait|join|result)\(\s*\)")
_CREATE_CONN = re.compile(r"create_connection\(")
_ALLOW = re.compile(r"#\s*allow-blocking:\s*\S")


def _annotated(lines: list, i: int) -> bool:
    """allow-blocking on the flagged line or within the 3 lines above."""
    lo = max(0, i - 4)
    return any(_ALLOW.search(lines[j]) for j in range(lo, i))


def scan(root: Path = ROOT) -> list:
    bad = []
    for d in SCAN_DIRS:
        for path in sorted((root / d).rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            lines = path.read_text(encoding="utf-8").splitlines()
            for i, line in enumerate(lines, 1):
                key = f"{rel}:{i}"
                if key in ALLOWLIST or _annotated(lines, i):
                    continue
                if _SETTIMEOUT_NONE.search(line):
                    bad.append((key, "settimeout(None): unbounded socket"))
                    continue
                m = _ZERO_ARG_WAIT.search(line)
                if m:
                    bad.append(
                        (key, f".{m.group(1)}() with no timeout: "
                         "unbounded wait"))
                    continue
                if _CREATE_CONN.search(line):
                    # the call may span lines; look for timeout= in the
                    # statement (this line + the next two)
                    stmt = " ".join(lines[i - 1:i + 2])
                    if "timeout=" not in stmt:
                        bad.append(
                            (key, "create_connection without timeout="))
    return bad


def main() -> int:
    bad = scan()
    for key, why in bad:
        print(f"{key}: {why}")
    if bad:
        print(f"\n{len(bad)} unbounded blocking call(s); add a timeout, "
              "or annotate '# allow-blocking: <reason>' if the block is "
              "deliberate and interruptible "
              "(tools/check_blocking_timeouts.py)")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
