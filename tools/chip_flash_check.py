#!/usr/bin/env python
"""Real-chip validation of the Pallas flash-attention kernels.

VERDICT r4 item 6: flash / ring-flash / flash-grad are proven in
interpret mode on the virtual CPU mesh (tests/test_flash_attention.py);
this tool runs the REAL kernel on the TPU — forward (causal + full) and
custom-vjp grad, each checked against the kernel-free oracle
(reference_attention_lse / jax autodiff) — and records one JSON row.

Safe under the tunnel protocol: probe runs in a throwaway subprocess,
the measurement child self-terminates between device ops (no external
kill wrappers; see bench.py's post-mortems).

Usage: python tools/chip_flash_check.py  (writes CHIP_FLASH.json too)
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_T0 = time.time()


def child_main() -> None:
    import jax

    if os.environ.get("BENCH_PLATFORM") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    # FLASH_CHECK_INTERPRET=1: run the kernel in the Pallas interpreter
    # (CPU dry-test of this script; the chip run leaves it unset so the
    # REAL kernel is what's validated)
    interp = os.environ.get("FLASH_CHECK_INTERPRET", "") in ("1", "true")
    import jax.numpy as jnp
    import numpy as np

    from nnstreamer_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_grad,
        reference_attention_lse,
    )

    dev = jax.devices()[0]
    B, T, H, D = 2, 512, 4, 64
    rng = np.random.default_rng(0)
    q, k, v = (
        jax.device_put(
            rng.normal(0, 1, (B, T, H, D)).astype(np.float32), dev
        ).astype(jnp.bfloat16)
        for _ in range(3)
    )

    checks = {}
    for causal in (True, False):
        out = jax.jit(
            lambda q, k, v, c=causal: flash_attention(
                q, k, v, causal=c, interpret=interp or None
            )
        )(q, k, v)
        ref, _ = reference_attention_lse(q, k, v, causal=causal)
        err = float(
            jnp.max(jnp.abs(out.astype(jnp.float32) - ref.astype(jnp.float32)))
        )
        checks[f"fwd_{'causal' if causal else 'full'}_max_err"] = round(err, 5)

    # grad: kernel-forward custom_vjp vs full autodiff of the oracle
    def loss_kernel(q, k, v):
        return jnp.sum(
            flash_attention_grad(
                q, k, v, causal=True, interpret=interp or None
            ).astype(jnp.float32) ** 2
        )

    def loss_ref(q, k, v):
        out, _ = reference_attention_lse(q, k, v, causal=True)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    gk = jax.jit(jax.grad(loss_kernel, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for name, a, b in zip("qkv", gk, gr):
        af, bf = a.astype(jnp.float32), b.astype(jnp.float32)
        denom = float(jnp.max(jnp.abs(bf))) or 1.0
        checks[f"grad_{name}_rel_err"] = round(
            float(jnp.max(jnp.abs(af - bf))) / denom, 5
        )

    # bf16 on the MXU with f32 accumulation: forward ~1e-2 class, grads a
    # touch looser through the recompute
    ok = all(
        e <= (0.06 if key.startswith("grad") else 0.04)
        for key, e in checks.items()
    )

    # bonus: kernel vs fused-XLA oracle wall time at a serving shape
    def timeit(fn, n=20):
        fn()  # compile
        t = time.perf_counter()
        for _ in range(n):
            r = fn()
        jax.block_until_ready(r)
        return (time.perf_counter() - t) / n

    jf = jax.jit(
        lambda: flash_attention(q, k, v, causal=True, interpret=interp or None)
    )
    jr = jax.jit(lambda: reference_attention_lse(q, k, v, causal=True)[0])
    checks["kernel_ms"] = round(timeit(jf) * 1e3, 3)
    checks["oracle_ms"] = round(timeit(jr) * 1e3, 3)

    row = {
        "metric": "flash_attention_chip_check",
        "value": 1.0 if ok else 0.0,
        "unit": "ok",
        "vs_baseline": None,
        "ok": ok,
        "shape": f"B{B}xT{T}xH{H}xD{D}",
        "dtype": "bfloat16",
        "platform": dev.platform,
        **checks,
    }
    print("CHECKROW " + json.dumps(row), flush=True)


def main() -> int:
    sys.path.insert(0, ROOT)
    from bench import probe_backend

    err = ""
    if os.environ.get("BENCH_PLATFORM") != "cpu":
        err, _plat = probe_backend(
            tries=int(os.environ.get("BENCH_PROBE_TRIES", "1")),
            timeout_s=float(os.environ.get("BENCH_PROBE_TIMEOUT", "90")),
        )
    if err:
        row = {
            "metric": "flash_attention_chip_check", "value": None,
            "unit": "ok", "vs_baseline": None,
            "error": f"accelerator backend unavailable: {err}",
        }
        print(json.dumps(row), flush=True)
        return 0
    deadline = float(os.environ.get("BENCH_DEADLINE", "300"))
    import tempfile

    with tempfile.TemporaryFile(mode="w+t") as out:
        try:
            subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                stdout=out, timeout=deadline + 60.0,
            )
        except subprocess.TimeoutExpired:
            pass
        out.seek(0)
        lines = out.read().splitlines()
    row = None
    for line in reversed(lines):
        if line.startswith("CHECKROW "):
            row = json.loads(line[len("CHECKROW "):])
            break
    if row is None:
        row = {
            "metric": "flash_attention_chip_check", "value": None,
            "unit": "ok", "vs_baseline": None,
            "error": f"child produced no row "
                     f"({lines[-1] if lines else 'no output'})",
        }
    print(json.dumps(row), flush=True)
    if row.get("platform") not in (None, "cpu"):
        # the artifact claims CHIP evidence: never write it from a CPU
        # dry-test (FLASH_CHECK_INTERPRET / BENCH_PLATFORM=cpu)
        try:
            with open(os.path.join(ROOT, "CHIP_FLASH.json"), "w") as f:
                json.dump(row, f, indent=1)
        except OSError:
            pass
    return 0


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main()
    else:
        sys.exit(main())
