#!/usr/bin/env python
"""Deterministic fleet chaos harness: scripted membership churn under
continuous multi-tenant load.

Drives N query servers + M tenant clients through the failure classes a
serving fleet actually sees — hard kill, rolling restart (GOAWAY drain,
PR-5), server join, hot-tenant burst — and computes an exact verdict:
zero lost/duplicated frames, per-tenant delivered/shed accounting,
breaker-trip census, and consistent-hash affinity remap counts.

Everything is scripted and event-ordered (actions run between push
waves, never on wall-clock timers), so the same script asserts the same
contracts in CI (the chaos-marked e2e in ``tests/test_fleet.py``) and at
the terminal::

    python tools/chaos_fleet.py            # default 3-server script
    python tools/chaos_fleet.py --servers 4 --keys 200 --frames 30

Fleet membership travels over the hybrid MQTT discovery plane (an
in-process :class:`MiniBroker`): servers announce retained endpoints
(with their live ``draining`` state — Documentation/resilience.md),
clients resolve the pool from the broker.  Because this is a CHAOS
harness, membership refreshes can also be forced between waves
(:meth:`FleetHarness.refresh_client`) instead of waiting for a failure
wave to trigger elastic rediscovery — scripted churn must not depend on
luck."""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[len(s) // 2]


class ClientHandle:
    """One tenant's client pipeline: appsrc -> tensor_query_client ->
    tensor_sink, plus the exact push ledger the verdict checks against."""

    def __init__(self, harness: "FleetHarness", name: str, pipe,
                 tenant: str):
        self._h = harness
        self.name = name
        self.tenant = tenant
        self.pipe = pipe
        self.pushed: List[float] = []

    @property
    def element(self):
        return self.pipe["q"]

    def push(self, value: float, key: Optional[str] = None,
             meta: Optional[Dict[str, Any]] = None) -> None:
        import numpy as np

        from nnstreamer_tpu.core.buffer import TensorFrame

        m = dict(meta or {})
        if key is not None:
            m[self._h.affinity_key] = key
        self.pipe["src"].push(TensorFrame([np.float32([value])], meta=m))
        self.pushed.append(float(value))

    def settle(self, timeout: float = 30.0) -> None:
        """Wait until every pushed frame has been answered (or counted
        degraded) WITHOUT ending the stream — the load stays continuous
        across chaos actions, and phase-boundary counters are exact."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            answered = len(self.pipe["out"].frames)
            degraded = int(self.health().get("degraded_frames", 0))
            if answered + degraded >= len(self.pushed):
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"client {self.name}: {len(self.pushed)} pushed but only "
            f"{len(self.pipe['out'].frames)} answered after {timeout}s")

    def values(self) -> List[float]:
        return [float(f.tensors[0][0]) for f in self.pipe["out"].frames]

    def spans_ms(self) -> List[float]:
        """Per-answer end-to-end latencies from the trace-span meta."""
        from nnstreamer_tpu.core.telemetry import SPAN_META

        out = []
        for f in self.pipe["out"].frames:
            span = f.meta.get(SPAN_META)
            if span:
                out.append(float(span["total"]) * 1e3)
        return out

    def health(self) -> Dict[str, Any]:
        return self.pipe.health()["q"]

    def finish(self, timeout: float = 60.0) -> None:
        self.pipe["src"].end_of_stream()
        self.pipe.wait(timeout=timeout)

    def stop(self) -> None:
        self.pipe.stop()


class GenClientHandle:
    """One tenant's long-lived GENERATION-STREAM client (continuous
    batching, PR-9): ``appsrc -> tensor_query_client stream=true ->
    tensor_sink``; each pushed prompt opens one server-streaming
    request whose token chunks flow back until a final-flagged frame.

    Exactness: every COMPLETED stream's concatenated tokens must equal
    the sim oracle for its prompt (token 1 = sum(prompt) % vocab, then
    the fixed recurrence — the servers run the async-sim generator), so
    cross-slot contamination or duplicated/lost chunks are exact-fail.
    Streams are grouped by trace id (unique per request; stream_seq can
    collide across servers)."""

    def __init__(self, harness: "FleetHarness", name: str, pipe,
                 tenant: str):
        self._h = harness
        self.name = name
        self.tenant = tenant
        self.pipe = pipe
        self.prompts: Dict[str, Any] = {}  # trace id -> prompt array
        self._seq = 0

    @property
    def element(self):
        return self.pipe["q"]

    def push_prompt(self, key: Optional[str] = None, prompt=None):
        import numpy as np

        from nnstreamer_tpu.core.buffer import TensorFrame
        from nnstreamer_tpu.core.telemetry import TRACE_ID_META, new_trace_id

        self._seq += 1
        if prompt is None:
            prompt = (np.arange(4, dtype=np.int32)[None] * 13
                      + self._seq) % self._h.gen_vocab
        trace = new_trace_id()
        meta: Dict[str, Any] = {TRACE_ID_META: trace}
        if key is not None:
            meta[self._h.affinity_key] = key
        self.pipe["src"].push(TensorFrame([prompt], meta=meta))
        self.prompts[trace] = prompt
        return trace

    def _by_trace(self) -> Dict[str, list]:
        from nnstreamer_tpu.core.telemetry import TRACE_ID_META

        out: Dict[str, list] = {}
        for f in self.pipe["out"].frames:
            out.setdefault(f.meta.get(TRACE_ID_META), []).append(f)
        return out

    def finished(self) -> int:
        return sum(
            1 for frames in self._by_trace().values()
            if any(f.meta.get("final") for f in frames))

    def settle(self, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.finished() >= len(self.prompts):
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"gen client {self.name}: {len(self.prompts)} streams pushed "
            f"but only {self.finished()} finished after {timeout}s")

    def check_exact(self) -> Dict[str, Any]:
        """Per-stream verdict: every stream's tokens equal its oracle
        EXACTLY, chunk meta coherent, zero duplicated chunks."""
        import numpy as np

        from nnstreamer_tpu.core.slots import SimSlotModel

        sim = SimSlotModel(1, vocab=self._h.gen_vocab)
        ok = bad = 0
        tokens = 0
        by_trace = self._by_trace()  # ONE index pass for every stream
        for trace, prompt in self.prompts.items():
            frames = sorted(by_trace.get(trace, []),
                            key=lambda f: f.meta["chunk_index"])
            idxs = [f.meta["chunk_index"] for f in frames]
            if idxs != list(range(len(frames))) or not frames or (
                    not frames[-1].meta.get("final")):
                bad += 1
                continue
            parts = [np.asarray(f.tensors[0]) for f in frames
                     if f.tensors]
            # an eviction before the first token answers with ONE
            # tensor-less typed-expiry frame: zero tokens, counted
            # below as a mismatched (incomplete) stream, never a crash
            toks = (np.concatenate(parts, axis=1) if parts
                    else np.zeros((1, 0), np.int32))
            t = int(prompt.sum()) % sim.vocab
            want = [t]
            for _ in range(self._h.gen_max_new - 1):
                t = sim.step_token(t)
                want.append(t)
            if toks.tolist() == [want]:
                ok += 1
                tokens += toks.shape[1]
            else:
                bad += 1
        return {"streams": len(self.prompts), "exact": ok,
                "mismatched": bad, "tokens": tokens}

    def tokens_done(self, trace: str) -> int:
        """Tokens delivered so far for one stream (drives the seeded
        mid-decode chaos points: act once every stream crossed a token
        threshold, never on wall-clock luck)."""
        frames = self._by_trace().get(trace, [])
        return max(
            (int(f.meta.get("tokens_done", 0)) for f in frames), default=0)

    def health(self) -> Dict[str, Any]:
        return self.pipe.health()["q"]

    def finish(self, timeout: float = 120.0) -> None:
        self.pipe["src"].end_of_stream()
        self.pipe.wait(timeout=timeout)

    def stop(self) -> None:
        self.pipe.stop()


class FleetHarness:
    """N query servers + M tenant clients on one hybrid discovery plane.

    Servers are ``serversrc ! identity sleep= ! scaler x2 !
    serversink`` pipelines announcing on ``nns/query/<topic>/``;
    clients resolve the pool from the broker.  ``expected(values)`` for
    every answered frame is ``value * 2``.

    ``mode="generate"`` swaps the server graph for a continuous-batching
    generator (``serversrc ! tensor_generator slots=N custom=sim:... !
    serversink``) and clients for :class:`GenClientHandle` long-lived
    streams — rolling-restart / kill verdicts then cover STATEFUL
    streams with PR-8 session affinity."""

    def __init__(self, topic: str = "chaosfleet", connect_type: str = "tcp",
                 server_sleep: float = 0.01, max_inflight: int = 32,
                 tenant_quotas: str = "", shed_window_s: float = 5.0,
                 affinity_key: str = "sess", base_id: int = 9600,
                 mode: str = "unary", gen_slots: int = 2,
                 gen_max_new: int = 24, gen_vocab: int = 997,
                 gen_step_ms: float = 1.0, digest_interval: float = 0.0,
                 gen_slo: str = "", gen_extra: str = ""):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        self.topic = topic
        self.connect_type = connect_type
        self.server_sleep = server_sleep
        self.max_inflight = max_inflight
        self.tenant_quotas = tenant_quotas
        self.shed_window_s = shed_window_s
        self.affinity_key = affinity_key
        self.base_id = base_id
        self.mode = mode
        self.gen_slots = gen_slots
        self.gen_max_new = gen_max_new
        self.gen_vocab = gen_vocab
        self.gen_step_ms = gen_step_ms
        # fleet observatory (core/fleet.py): >0 arms the servers'
        # telemetry-digest publishers; gen_slo adds slo-* props on the
        # generator (e.g. "slo-ttft-p95=10 slo-availability=0.9")
        self.digest_interval = digest_interval
        self.gen_slo = gen_slo
        # extra generator props appended verbatim (mode="generate"
        # only) — the prefix chaos arms "prefix-cache=on ..." here
        self.gen_extra = gen_extra
        self.observatory = None
        self.broker = MiniBroker()
        self.servers: Dict[int, Any] = {}   # idx -> pipeline (live only)
        self.ports: Dict[int, int] = {}     # idx -> port (survives kills)
        self.clients: List[ClientHandle] = []
        self.gen_clients: List[GenClientHandle] = []
        # per-tenant counters of servers that LEFT the fleet, captured at
        # kill time so fleet-wide accounting stays exact across churn
        self.retired_tenants: List[Dict[str, Any]] = []
        # generator counters of retired servers (mode="generate"): the
        # resume/migration invariants sum over every engine that ever
        # decoded a token, including killed/rolled ones
        self.retired_gen: List[Dict[str, Any]] = []
        # global admission counters of retired servers (the observatory
        # cross-check sums admitted/shed over every server that ever
        # served, exactly like the per-tenant rows above)
        self.retired_admission: List[Dict[str, int]] = []
        self.server_starts = 0
        self._blackhole: Optional[int] = None  # dead port (partitions)

    # -- servers ------------------------------------------------------------
    def start_server(self, idx: int, port: int = 0):
        from nnstreamer_tpu.pipeline.parser import parse_pipeline

        quotas = (f"tenant-quotas={self.tenant_quotas} "
                  if self.tenant_quotas else "")
        slo = f"{self.gen_slo} " if self.gen_slo else ""
        if self.mode == "generate":
            # continuous-batching generator fleet: each server
            # multiplexes concurrent token streams into shared slots
            # over the deterministic async-sim model
            core = (
                f"tensor_generator name=gen slots={self.gen_slots} "
                f"custom=sim:1,sim_step_ms:{self.gen_step_ms},"
                f"sim_per_slot_ms:0.05,sim_prefill_ms:0.02,"
                f"vocab:{self.gen_vocab} "
                f"max-new={self.gen_max_new} chunk=4 {slo}"
                + (f"{self.gen_extra} " if self.gen_extra else "")
                + "! "
            )
        else:
            core = (
                f"identity sleep={self.server_sleep} ! "
                "tensor_filter framework=scaler custom=factor:2 ! "
            )
        digest = (f"digest-interval={self.digest_interval} "
                  if self.digest_interval > 0 else "digest-interval=0 ")
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={self.base_id + idx} "
            f"port={port} connect-type={self.connect_type} "
            f"topic={self.topic} dest-host=127.0.0.1 "
            f"dest-port={self.broker.port} {digest}"
            f"max-inflight={self.max_inflight} {quotas}"
            f"shed-window={self.shed_window_s} ! "
            f"{core}"
            f"tensor_query_serversink id={self.base_id + idx}",
            name=f"server{idx}",
        )
        pipe.start()
        self.servers[idx] = pipe
        self.ports[idx] = pipe["ssrc"].props["port"]
        self.server_starts += 1
        return pipe

    def _retire_rows(self, pipe) -> None:
        self.retired_tenants.append(self.server_tenant_rows(pipe))
        self.retired_gen.append(self.server_gen_row(pipe))
        self.retired_admission.append(self.server_admission_row(pipe))

    def kill_server(self, idx: int) -> None:
        """Hard stop: no drain, no GOAWAY — in-flight requests die with
        their sockets (the announce is tombstoned by element stop)."""
        pipe = self.servers.pop(idx)
        self._retire_rows(pipe)
        pipe.stop()

    def crash_server(self, idx: int) -> None:
        """Crash simulation for the OBSERVATORY's staleness contract: the
        process dies without tombstoning its retained announce (a real
        SIGKILL never runs ``stop()``'s clear), so the stale digest must
        be TTL-evicted by the observatory, not retired by a tombstone.
        The last force-published digest still carries the final
        counters, so fleet totals stay exact."""
        pipe = self.servers.pop(idx)
        self._retire_rows(pipe)
        ssrc = pipe["ssrc"]
        if ssrc._digest is not None:
            ssrc._digest.poll(force=True)
        # detach the announce BEFORE stop: clear() then has nothing to
        # tombstone — exactly a crashed process's broker state (the
        # retained digest stays).  The mqtt client itself must still be
        # CLOSED: its reconnect-enabled reader/ping threads would
        # otherwise outlive the harness (and trip the test suite's
        # framework-thread quiesce guard for the rest of the session)
        ann = ssrc._announcement
        if ann is not None:
            client, ann._client = ann._client, None
            ssrc._announcement = None
            if client is not None:
                client.close()
        pipe.stop()

    def rolling_restart(self, idx: int, drain_timeout: float = 15.0) -> Dict[str, Any]:
        """PR-5 zero-downtime roll: drain (GOAWAY to new requests,
        in-flight finish), stop, restart on the SAME port."""
        pipe = self.servers[idx]
        res = pipe.drain(timeout=drain_timeout)
        health = pipe.health()["ssrc"]
        gen_health = self.server_gen_row(pipe)
        self._retire_rows(pipe)
        pipe.stop()
        self.servers.pop(idx)
        self.start_server(idx, port=self.ports[idx])
        return {"drain": res, "health": health, "gen": gen_health}

    def add_server(self) -> int:
        idx = (max(self.ports) + 1) if self.ports else 0
        self.start_server(idx)
        return idx

    def inject_device_loss(self, idx: int) -> None:
        """Kill one mesh member of server ``idx``'s sim model on its
        NEXT decode attempt (mode="generate" only): the engine hands
        every live stream off with resume state, rebuilds on the
        "survivors", and the server announces degraded:true — the
        degrade-don't-die ladder under scripted, not wall-clock,
        timing."""
        self.servers[idx]["gen"]._engine.model.fail_next("lost")

    def wait_device_lost(self, idx: int, timeout: float = 30.0) -> Dict[str, Any]:
        """Block until server ``idx`` survived a device loss (engine
        counter visible in health); returns its gen health row."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            row = self.server_gen_row(self.servers[idx])
            if int(row.get("gen_device_lost", 0)) >= 1:
                return row
            time.sleep(0.01)
        raise TimeoutError(
            f"server {idx} never reported a device loss in {timeout}s")

    @staticmethod
    def server_gen_row(pipe) -> Dict[str, Any]:
        """Numeric generator counters of one server (empty outside
        mode="generate")."""
        return {
            k: v for k, v in pipe.health().get("gen", {}).items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }

    def fleet_gen(self) -> Dict[str, float]:
        """Generator counters summed over every server that is or ever
        was in the fleet (retired engines contribute their
        last-observed counters)."""
        total: Dict[str, float] = {}
        rows = [self.server_gen_row(p) for p in self.servers.values()]
        rows.extend(self.retired_gen)
        for row in rows:
            for k, v in row.items():
                total[k] = total.get(k, 0) + v
        return total

    def fleet_tokens(self) -> int:
        return int(self.fleet_gen().get("gen_tokens", 0))

    @staticmethod
    def server_tenant_rows(pipe) -> Dict[str, Any]:
        return {
            t: dict(row)
            for t, row in pipe.health()["ssrc"].get("tenants", {}).items()
        }

    def fleet_tenants(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant {admitted, shed} summed over every server that is
        or ever was in the fleet (retired servers contribute their
        last-observed counters)."""
        total: Dict[str, Dict[str, int]] = {}
        rows = [self.server_tenant_rows(p) for p in self.servers.values()]
        rows.extend(self.retired_tenants)
        for by_tenant in rows:
            for t, row in by_tenant.items():
                agg = total.setdefault(t, {"admitted": 0, "shed": 0})
                agg["admitted"] += int(row.get("admitted", 0))
                agg["shed"] += int(row.get("shed", 0))
        return total

    @staticmethod
    def server_admission_row(pipe) -> Dict[str, int]:
        h = pipe.health()["ssrc"]
        return {"admitted": int(h.get("admitted", 0)),
                "shed": int(h.get("load_shed", 0))}

    def fleet_admission(self) -> Dict[str, int]:
        """Global {admitted, shed} over every server that ever served."""
        total = {"admitted": 0, "shed": 0}
        rows = [self.server_admission_row(p) for p in self.servers.values()]
        rows.extend(self.retired_admission)
        for r in rows:
            total["admitted"] += r["admitted"]
            total["shed"] += r["shed"]
        return total

    # -- control-plane chaos (broker death / network partition) -------------
    def blackhole_port(self) -> int:
        """A bound-then-released localhost port: dialing it is REFUSED
        immediately (no listener), so pointing a client's broker list at
        it is a deterministic, timeout-free network partition."""
        if self._blackhole is None:
            import socket as _socket

            s = _socket.socket()
            s.bind(("127.0.0.1", 0))
            self._blackhole = s.getsockname()[1]
            s.close()
        return self._blackhole

    @staticmethod
    def sever_client(client, port: int) -> None:
        """Partition one MqttClient: point its failover list at a dead
        port and cut the live socket — its reconnect loop dials the
        void until :meth:`restore_client`.  Only the CONTROL plane is
        touched; data-plane TCP connections are not this client's."""
        import socket as _socket

        client._brokers = [("127.0.0.1", int(port))]
        client._broker_i = 0
        with client._wlock:
            sock = client._sock
        if sock is not None:
            try:
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass

    @staticmethod
    def restore_client(client, host: str, port: int) -> None:
        """Heal a partition made by :meth:`sever_client`: the reconnect
        loop's next dial (bounded by its 2s backoff cap) reconnects,
        resumes the session, and fires the re-announce hooks."""
        client._brokers = [(host, int(port))]
        client._broker_i = 0

    def kill_broker(self) -> None:
        """Broker process death: every connection is torn down and the
        retained store dies with the process (amnesia — only persistent
        QoS-1 sessions survive via the port-keyed store).  Every client
        enters its reconnect loop; :meth:`revive_broker` rebinds the
        SAME port, standing in for a restarted or failed-over broker."""
        self.broker.close()

    def revive_broker(self) -> None:
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        self.broker = MiniBroker(port=self.broker.port)

    def partition_server(self, idx: int) -> None:
        """Cut server ``idx``'s announce/digest client off the broker
        (its clients keep serving: the DATA plane is untouched)."""
        ann = self.servers[idx]["ssrc"]._announcement
        self.sever_client(ann._client, self.blackhole_port())

    def heal_server(self, idx: int, timeout: float = 10.0) -> None:
        """Heal ``idx``'s partition and wait until it re-announced."""
        ann = self.servers[idx]["ssrc"]._announcement
        before = ann.reannounces
        self.restore_client(ann._client, "127.0.0.1", self.broker.port)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if ann.connected and ann.reannounces > before:
                return
            time.sleep(0.02)
        raise TimeoutError(f"server {idx} never re-announced after heal")

    # -- fleet observatory --------------------------------------------------
    def attach_observatory(self, ttl_s: float = 10.0):
        """Subscribe a :class:`FleetObservatory` to this harness's
        broker (requires ``digest_interval`` > 0 on the servers)."""
        from nnstreamer_tpu.core.fleet import FleetObservatory

        self.observatory = FleetObservatory(
            topic=self.topic, default_ttl_s=ttl_s,
        ).start("127.0.0.1", self.broker.port)
        return self.observatory

    def publish_digests(self) -> None:
        """Force a digest publish on every LIVE server NOW (scripted
        verdict points must not wait out the publish interval)."""
        for pipe in self.servers.values():
            pipe["ssrc"].publish_digest(force=True)

    def idx_for_topic(self, topic: str) -> int:
        """Map an observatory row's announce topic back to the live
        server index (the autoscale actuator's drain/resize targets are
        announce topics, not harness indices)."""
        for idx, pipe in self.servers.items():
            ann = pipe["ssrc"]._announcement
            if ann is not None and ann.topic == topic:
                return idx
        raise KeyError(f"no live server announces {topic!r}")

    def observatory_settled(self, timeout: float = 10.0) -> None:
        """Block until the observatory ingested every live server's
        LATEST published digest (by seq) — the verdict must compare
        final ledgers against final digests, not in-flight ones."""
        want = {}
        for pipe in self.servers.values():
            ssrc = pipe["ssrc"]
            if ssrc._digest is not None and ssrc._announcement is not None:
                want[ssrc._announcement.topic] = ssrc._digest.seq
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            rows = {r["topic"]: r for r in self.observatory.servers()}
            if all(
                t in rows and int(rows[t].get("seq", 0)) >= seq
                for t, seq in want.items()
            ):
                return
            time.sleep(0.02)
        raise TimeoutError(
            f"observatory never caught up to {want} (has "
            f"{[(r['topic'], r.get('seq')) for r in self.observatory.servers()]})")

    def observatory_crosscheck(self) -> Dict[str, Any]:
        """The acceptance cross-check: the observatory's fleet rollups
        must EXACTLY equal the sum of per-server ledgers, retired
        servers included.  Call after :meth:`publish_digests` +
        :meth:`observatory_settled` at a quiescent point."""
        roll = self.observatory.rollup()
        ledger_tenants = {
            t: {"admitted": r["admitted"], "shed": r["shed"]}
            for t, r in self.fleet_tenants().items()
        }
        ledger_adm = self.fleet_admission()
        tokens_exact = roll["tokens"] == self.fleet_tokens()
        admitted_exact = roll["admitted"] == ledger_adm["admitted"]
        shed_exact = roll["shed"] == ledger_adm["shed"]
        tenants_exact = roll["tenants"] == ledger_tenants
        # shared-prefix cache counters (PR 18): integer-exact against
        # the summed engine ledgers, retired servers included; fleets
        # with the cache unarmed compare 0 == 0
        gen = self.fleet_gen()
        prefix_exact = (
            int(roll.get("prefix_hits", 0))
            == int(gen.get("prefix_hits", 0))
            and int(roll.get("prefix_misses", 0))
            == int(gen.get("prefix_misses", 0)))
        return {
            "rollup_tokens": roll["tokens"],
            "ledger_tokens": self.fleet_tokens(),
            "rollup_admitted": roll["admitted"],
            "ledger_admitted": ledger_adm["admitted"],
            "rollup_shed": roll["shed"],
            "ledger_shed": ledger_adm["shed"],
            "rollup_tenants": roll["tenants"],
            "ledger_tenants": ledger_tenants,
            "servers_seen": self.observatory.servers_seen,
            "server_starts": self.server_starts,
            "stale_evicted": roll["stale_evicted"],
            "retired": roll["retired"],
            "slo_burn": roll["slo_burn"],
            "rollup_prefix_hits": int(roll.get("prefix_hits", 0)),
            "ledger_prefix_hits": int(gen.get("prefix_hits", 0)),
            "rollup_prefix_misses": int(roll.get("prefix_misses", 0)),
            "ledger_prefix_misses": int(gen.get("prefix_misses", 0)),
            "exact": bool(tokens_exact and admitted_exact and shed_exact
                          and tenants_exact and prefix_exact),
        }

    # -- clients ------------------------------------------------------------
    def make_client(self, name: str, tenant: str = "",
                    routing: str = "least-inflight", priority: int = 3,
                    affinity: bool = False, retries: int = 3,
                    busy_retries: int = 8, breaker_threshold: int = 8,
                    max_in_flight: int = 4, timeout: float = 5.0,
                    degrade: str = "error",
                    discovery_timeout: float = 10.0,
                    static_hosts: bool = False) -> ClientHandle:
        from nnstreamer_tpu.pipeline.parser import parse_pipeline

        akey = f"affinity-key={self.affinity_key} " if affinity else ""
        tprop = f"tenant={tenant} " if tenant else ""
        if static_hosts:
            # pinned membership (no discovery, no elastic rediscovery):
            # the burst client of the e2e uses this so every push maps
            # to EXACTLY one admission attempt — exact shed accounting
            hosts = ",".join(
                f"localhost:{self.ports[i]}" for i in sorted(self.servers))
            plane = f"hosts={hosts} "
        else:
            plane = (
                f"topic={self.topic} dest-host=127.0.0.1 "
                f"dest-port={self.broker.port} "
                f"discovery-timeout={discovery_timeout} ")
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=1024 ! "
            f"tensor_query_client name=q connect-type={self.connect_type} "
            f"{plane}"
            f"routing={routing} {akey}{tprop}priority={priority} "
            f"retries={retries} busy-retries={busy_retries} "
            f"breaker-threshold={breaker_threshold} retry-backoff=0.02 "
            f"max-in-flight={max_in_flight} timeout={timeout} "
            f"degrade={degrade} ! "
            "tensor_sink name=out",
            name=f"client-{name}",
        )
        pipe.start()
        handle = ClientHandle(self, name, pipe, tenant)
        self.clients.append(handle)
        return handle

    def make_gen_client(self, name: str, tenant: str = "",
                        routing: str = "least-inflight",
                        affinity: bool = False, retries: int = 3,
                        busy_retries: int = 8,
                        breaker_threshold: int = 8,
                        timeout: float = 60.0,
                        discovery_timeout: float = 10.0
                        ) -> GenClientHandle:
        """A long-lived generation-STREAM client (``stream=true``): each
        pushed prompt holds one server-streaming request until its final
        chunk; PR-8 affinity pins a session's streams to one server."""
        from nnstreamer_tpu.pipeline.parser import parse_pipeline

        akey = f"affinity-key={self.affinity_key} " if affinity else ""
        tprop = f"tenant={tenant} " if tenant else ""
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=1024 ! "
            f"tensor_query_client name=q connect-type={self.connect_type} "
            f"topic={self.topic} dest-host=127.0.0.1 "
            f"dest-port={self.broker.port} "
            f"discovery-timeout={discovery_timeout} "
            f"stream=true routing={routing} {akey}{tprop}"
            f"retries={retries} busy-retries={busy_retries} "
            f"breaker-threshold={breaker_threshold} retry-backoff=0.02 "
            f"timeout={timeout} ! "
            "tensor_sink name=out",
            name=f"genclient-{name}",
        )
        pipe.start()
        handle = GenClientHandle(self, name, pipe, tenant)
        self.gen_clients.append(handle)
        return handle

    def refresh_client(self, handle: ClientHandle) -> bool:
        """Force one elastic rediscovery NOW (scripted membership churn;
        production clients refresh on failure waves instead).  Returns
        True when the pool actually swapped."""
        el = handle.element
        el._last_discovery_ts = float("-inf")  # skip the churn cooldown
        return el._rediscover(el._pstate)

    # -- verdict ------------------------------------------------------------
    @staticmethod
    def check_exact(handle: ClientHandle) -> Dict[str, Any]:
        """Zero-lost / zero-duplicated check for one client: every pushed
        value answered exactly once as value*2 (minus frames the client
        itself dropped under degrade=skip, which it counts)."""
        got = sorted(handle.values())
        degraded = int(handle.health().get("degraded_frames", 0))
        want = sorted(v * 2.0 for v in handle.pushed)
        lost = dup = 0
        if degraded == 0:
            from collections import Counter

            cw, cg = Counter(want), Counter(got)
            lost = sum((cw - cg).values())
            dup = sum((cg - cw).values())
        else:
            # degrade=skip clients: delivered subset must still be
            # duplicate-free and correct
            from collections import Counter

            cg = Counter(got)
            cw = Counter(want)
            dup = sum((cg - cw).values())
            lost = sum((cw - cg).values()) - degraded
        return {
            "pushed": len(handle.pushed), "answered": len(got),
            "degraded": degraded, "lost": lost, "duplicated": dup,
        }

    def breaker_trips(self) -> int:
        trips = 0
        for c in list(self.clients) + list(self.gen_clients):
            h = c.health()
            trips += int(h.get("breaker_trips_evicted", 0))
            for snap in h.get("breakers", {}).values():
                trips += int(snap.get("trips", 0))
        return trips

    def verdict(self) -> Dict[str, Any]:
        per_client = {c.name: self.check_exact(c) for c in self.clients}
        p50 = {
            c.name: round(_median(c.spans_ms()), 3) for c in self.clients
        }
        return {
            "clients": per_client,
            "p50_ms": p50,
            "tenants": self.fleet_tenants(),
            "breaker_trips": self.breaker_trips(),
            "goaway_replies": sum(
                int(c.health().get("goaway_replies", 0))
                for c in self.clients),
            "affinity_remaps": {
                c.name: int(c.health().get("affinity_remaps", 0))
                for c in self.clients
            },
            "lost": sum(r["lost"] for r in per_client.values()),
            "duplicated": sum(r["duplicated"] for r in per_client.values()),
        }

    def stop_all(self) -> None:
        for c in list(self.clients) + list(self.gen_clients):
            try:
                c.stop()
            except Exception:  # allow-silent: teardown best-effort
                pass
        for pipe in list(self.servers.values()):
            try:
                pipe.stop()
            except Exception:  # allow-silent: teardown best-effort
                pass
        self.servers.clear()
        if self.observatory is not None:
            try:
                self.observatory.stop()
            except Exception:  # allow-silent: teardown best-effort
                pass
            self.observatory = None
        self.broker.close()


# ---------------------------------------------------------------------------
# The default script (CLI mode; the e2e in tests/test_fleet.py pins the
# same phases with exact assertions)
# ---------------------------------------------------------------------------
def run_default_script(servers: int = 3, frames: int = 30,
                       keys: int = 120) -> Dict[str, Any]:
    import math

    h = FleetHarness(tenant_quotas="A:6,B:2", server_sleep=0.01)
    try:
        for i in range(servers):
            h.start_server(i)
        ca = h.make_client("A", tenant="A", routing="least-inflight")
        cb = h.make_client("B", tenant="B", routing="ewma", busy_retries=12)
        ck = h.make_client("K", affinity=True, routing="rotate")
        seq = iter(range(10**6))
        key_names = [f"sess-{k}" for k in range(keys)]

        def wave(tag: str, n: int = frames) -> None:
            for _ in range(n):
                ca.push(next(seq))
                cb.push(10_000 + next(seq))
            for k in key_names:
                ck.push(20_000 + next(seq), key=k)
            for c in (ca, cb, ck):
                c.settle()

        wave("baseline")
        roll = h.rolling_restart(0)
        wave("after-roll")
        joined = h.add_server()
        h.refresh_client(ck)
        remaps_before = ck.health()["affinity_remaps"]
        wave("after-join")
        remap_join = ck.health()["affinity_remaps"] - remaps_before
        h.kill_server(servers - 1)
        for c in (ca, cb, ck):
            h.refresh_client(c)
        wave("after-kill")
        for c in (ca, cb, ck):
            c.finish()
        v = h.verdict()
        v["rolling_restart"] = {
            "goaway_sent": roll["health"].get("goaway_sent", 0),
            "drain_dropped": roll["drain"]["dropped"],
        }
        v["remap_join"] = remap_join
        v["remap_join_bound"] = math.ceil(keys / max(1, len(h.servers)))
        v["joined_server"] = joined
        v["ok"] = (
            v["lost"] == 0 and v["duplicated"] == 0
            and v["breaker_trips"] == 0
            and remap_join <= v["remap_join_bound"]
        )
        return v
    finally:
        h.stop_all()


def run_generate_script(servers: int = 2, streams: int = 12) -> Dict[str, Any]:
    """Generation-STREAM chaos (continuous batching, PR-9): long-lived
    token streams multiplexed into shared slots across the fleet, with
    PR-8 session affinity, surviving a rolling restart mid-wave — the
    drain lets in-flight streams FINISH (they hold their admission slot
    until the final chunk) while new streams fail over on GOAWAY."""
    h = FleetHarness(mode="generate", gen_slots=2, gen_max_new=24,
                     gen_step_ms=1.0, base_id=9700,
                     topic="chaosgen")
    try:
        for i in range(servers):
            h.start_server(i)
        ca = h.make_gen_client("A", tenant="A")
        ck = h.make_gen_client("K", affinity=True, routing="rotate")
        total = 2 * (streams // 2)  # pushed per client across both waves

        # wave 1: concurrent streams share slots, exact tokens
        for j in range(streams // 2):
            ca.push_prompt()
            ck.push_prompt(key=f"sess-{j % 4}")
        ca.settle()
        ck.settle()

        # wave 2 pushed, then a rolling restart lands MID-WAVE: stateful
        # streams on the draining server complete (zero loss), affinity
        # sessions re-pin once the server returns on the same port
        for j in range(streams // 2):
            ca.push_prompt()
            ck.push_prompt(key=f"sess-{j % 4}")
        roll = h.rolling_restart(0)
        ca.settle()
        ck.settle()
        for c in (ca, ck):
            c.finish()
        va, vk = ca.check_exact(), ck.check_exact()
        gen_totals = {}
        for pipe in h.servers.values():
            for k, val in pipe.health().get("gen", {}).items():
                if isinstance(val, (int, float)):
                    gen_totals[k] = gen_totals.get(k, 0) + val
        v = {
            "clients": {"A": va, "K": vk},
            "rolling_restart": {
                "goaway_sent": roll["health"].get("goaway_sent", 0),
                "drain_dropped": roll["drain"]["dropped"],
            },
            "goaway_replies": sum(
                int(c.health().get("goaway_replies", 0))
                for c in (ca, ck)),
            "breaker_trips": h.breaker_trips(),
            "gen": {k: gen_totals.get(k, 0) for k in (
                "gen_joins", "gen_completed", "gen_evicted",
                "gen_cancelled", "gen_tokens")},
        }
        v["ok"] = (
            va["mismatched"] == 0 and vk["mismatched"] == 0
            and va["exact"] == total and vk["exact"] == total
            and roll["drain"]["dropped"] == 0
            and v["breaker_trips"] == 0
        )
        return v
    finally:
        h.stop_all()


def run_generate_resume_script(servers: int = 3, streams: int = 8,
                               seed: int = 0) -> Dict[str, Any]:
    """Durable-stream chaos (stream continuity, Documentation/
    resilience.md): N concurrent LONG generation streams survive a hard
    server kill AND a rolling restart, both landing at seeded random
    decode points mid-stream.  The kill exercises checkpointed RESUME
    (mid-stream transport break -> re-prefill on a healthy server); the
    roll exercises live MIGRATION (resumable GOAWAY handoff chunks).

    Exactness contract: every stream's concatenated tokens equal the
    sim oracle bit-for-bit (zero lost, zero duplicated), client
    ``stream_resumes`` equals the streams broken by the kill, client
    ``stream_migrations`` equals the rolled engine's
    ``gen_goaway_evicted``, fleet ``gen_resumes`` equals resumes +
    migrations (every attempt landed exactly once), zero resume
    failures, and zero breaker trips beyond the killed host.

    One stream per client (streams inside one client element are
    sequential by design), so ``streams`` clients run concurrently.
    Fresh clients deterministically rank the lowest-addressed server
    first under least-inflight with zero load, so wave placement — and
    therefore the kill's exact resume count — is scripted, not luck."""
    import random

    h = FleetHarness(mode="generate", gen_slots=max(8, streams),
                     gen_max_new=96, gen_step_ms=3.0, base_id=9800,
                     topic="chaosgenres")
    rng = random.Random(seed)
    try:
        for i in range(servers):
            h.start_server(i)
        clients = [
            h.make_gen_client(f"C{i}", routing="least-inflight",
                              timeout=120.0)
            for i in range(streams)
        ]
        traces = [c.push_prompt() for c in clients]

        def wait_tokens_each(n: int, timeout: float = 60.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if all(c.tokens_done(t) >= n
                       for c, t in zip(clients, traces)):
                    return
                time.sleep(0.005)
            raise TimeoutError(
                f"streams never all reached {n} delivered tokens")

        def min_port_live() -> int:
            return min(h.servers, key=lambda i: h.ports[i])

        # seeded random decode points (chunk multiples, comfortably
        # inside the 96-token streams so both events land MID-decode)
        t_kill = 4 * rng.randint(1, 3)
        t_roll = t_kill + 4 * rng.randint(4, 8)

        # hard kill: every fresh client ranked the same lowest-address
        # server first, so ALL streams are on it — resumes are exact
        killed = min_port_live()
        killed_addr = f"127.0.0.1:{h.ports[killed]}"
        wait_tokens_each(t_kill)
        h.kill_server(killed)

        # rolling restart mid-decode: roll whichever live server holds
        # the most resumed streams (occupancy read from health, so the
        # roll provably lands on active streams)
        wait_tokens_each(t_roll)
        rolled = max(
            h.servers,
            key=lambda i: h.servers[i].health()["gen"].get(
                "gen_occupied", 0))
        roll = h.rolling_restart(rolled)

        for c in clients:
            c.settle(timeout=120.0)
        for c in clients:
            c.finish()

        checks = [c.check_exact() for c in clients]
        exact = sum(r["exact"] for r in checks)
        mismatched = sum(r["mismatched"] for r in checks)
        res = {
            k: sum(int(c.health().get(k, 0)) for c in clients)
            for k in ("stream_resumes", "stream_migrations",
                      "duplicate_tokens_dropped", "resume_failures",
                      "goaway_replies")
        }
        gen = h.fleet_gen()
        # breaker census: trips are allowed ONLY against the killed
        # host (evicted-breaker trips belong to it too — it is the one
        # endpoint rediscovery dropped)
        foreign_trips = 0
        for c in clients:
            for addr, snap in c.health().get("breakers", {}).items():
                if addr != killed_addr:
                    foreign_trips += int(snap.get("trips", 0))
        migrated = int(roll["gen"].get("gen_goaway_evicted", 0))
        v = {
            "streams": streams,
            "exact": exact,
            "mismatched": mismatched,
            "tokens": sum(r["tokens"] for r in checks),
            "seed": seed,
            "decode_points": {"kill": t_kill, "roll": t_roll},
            "killed": killed_addr,
            "rolled_goaway_evicted": migrated,
            "rolling_restart": {
                "goaway_sent": roll["health"].get("goaway_sent", 0),
                "drain_dropped": roll["drain"]["dropped"],
            },
            "resumes": res,
            "gen": {k: int(gen.get(k, 0)) for k in (
                "gen_joins", "gen_completed", "gen_resumes",
                "gen_goaway_evicted", "gen_evicted", "gen_cancelled",
                "gen_tokens")},
            "foreign_breaker_trips": foreign_trips,
        }
        v["ok"] = bool(
            mismatched == 0 and exact == streams
            # the kill broke every stream mid-decode: each resumed once
            and res["stream_resumes"] == streams
            # every handoff the rolled engine emitted was migrated by
            # exactly one client, and the roll landed on live streams
            and res["stream_migrations"] == migrated
            and migrated >= 1
            # every resume/migration attempt landed exactly once
            and gen.get("gen_resumes", 0)
            == res["stream_resumes"] + res["stream_migrations"]
            and res["resume_failures"] == 0
            and foreign_trips == 0
            and roll["drain"]["dropped"] == 0
        )
        return v
    finally:
        h.stop_all()


def run_observatory_script(servers: int = 3, streams: int = 8) -> Dict[str, Any]:
    """Fleet-observatory chaos acceptance (Documentation/observability.md
    "Fleet observatory"): a generate-mode fleet publishing telemetry
    digests survives a rolling restart mid-wave, a hot-tenant burst over
    quota, and a tombstone-less CRASH — and at every quiescent point the
    observatory's fleet rollups (tokens, admitted, shed, per-tenant
    rows) are EXACTLY equal to the sum of per-server ledgers, retired
    servers included.

    Contract pinned by the verdict: digests observed from every server
    that ever started, the crashed server's stale digest TTL-evicted
    (its counters retired exactly), per-tenant SLO burn gauges and
    ``nns.fleet.*`` rollups visible in ``/metrics``, zero lost streams,
    zero breaker trips (the crash lands after clients finished)."""
    from urllib.request import urlopen

    h = FleetHarness(mode="generate", gen_slots=4, gen_max_new=24,
                     gen_step_ms=1.0, base_id=10000, topic="chaosobs",
                     tenant_quotas="B:1", digest_interval=0.25,
                     gen_slo=("slo-ttft-p95=30 slo-token-p99=5 "
                              "slo-availability=0.5"))
    try:
        for i in range(servers):
            h.start_server(i)
        obs = h.attach_observatory(ttl_s=5.0)
        mport = obs.serve_metrics(0)
        ca = [h.make_gen_client(f"A{i}", tenant="A") for i in range(2)]

        # wave 1: steady 2-client tenant-A load
        for _ in range(max(1, streams // 2)):
            for c in ca:
                c.push_prompt()
        for c in ca:
            c.settle(timeout=120.0)

        # wave 2 pushed, rolling restart lands MID-WAVE (digesting
        # server drains: streams migrate, its final digest retires its
        # exact counters, the restarted instance digests from zero)
        for _ in range(max(1, streams // 2)):
            for c in ca:
                c.push_prompt()
        roll = h.rolling_restart(0)
        for c in ca:
            c.settle(timeout=120.0)

        # hot-tenant burst: 3 concurrent tenant-B streams against a
        # B:1 quota — fresh least-inflight clients all rank the same
        # lowest-address server first, so quota sheds are guaranteed;
        # busy-retries spread the losers to other servers (all finish)
        cb = [
            h.make_gen_client(f"B{i}", tenant="B", busy_retries=40)
            for i in range(3)
        ]
        for c in cb:
            c.push_prompt()
        for c in cb:
            c.settle(timeout=120.0)

        for c in ca + cb:
            c.finish()
        checks = [c.check_exact() for c in ca + cb]

        # quiescent verdict point 1: force digests, wait for ingest,
        # cross-check rollups vs ledgers EXACTLY (retired roll incl.)
        h.publish_digests()
        h.observatory_settled()
        cc_pre = h.observatory_crosscheck()

        # crash (no tombstone): the observatory must TTL-evict the
        # stale row and retire its exact final counters
        h.crash_server(max(h.servers))
        stale_deadline = time.monotonic() + 15.0
        while (h.observatory.rollup()["stale_evicted"] < 1
               and time.monotonic() < stale_deadline):
            time.sleep(0.05)
        cc_post = h.observatory_crosscheck()

        body = urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5).read().decode()
        metrics_ok = all(
            frag in body for frag in (
                "nns_fleet_tokens", "nns_fleet_servers",
                "nns_fleet_tenant_shed", "nns_fleet_slo_burn",
                "nns_slo_availability_burn",
            ))
        shed_b = h.fleet_tenants().get("B", {}).get("shed", 0)
        v = {
            "clients": {c.name: r for c, r in zip(ca + cb, checks)},
            "exact": sum(r["exact"] for r in checks),
            "mismatched": sum(r["mismatched"] for r in checks),
            "rolling_restart": {
                "goaway_sent": roll["health"].get("goaway_sent", 0),
                "drain_dropped": roll["drain"]["dropped"],
            },
            "burst_shed_B": shed_b,
            "crosscheck_pre_crash": cc_pre,
            "crosscheck_post_crash": cc_post,
            "metrics_endpoint_ok": metrics_ok,
            "breaker_trips": h.breaker_trips(),
        }
        v["ok"] = bool(
            v["mismatched"] == 0
            and cc_pre["exact"] and cc_post["exact"]
            and cc_post["servers_seen"] == h.server_starts
            and cc_post["stale_evicted"] >= 1
            and shed_b > 0
            and roll["drain"]["dropped"] == 0
            and metrics_ok
            and v["breaker_trips"] == 0
        )
        return v
    finally:
        h.stop_all()


def run_prefix_script(servers: int = 3, clients: int = 6,
                      seed: int = 0) -> Dict[str, Any]:
    """Shared-prefix cache chaos (PR 18, Documentation/performance.md
    "Shared prefix cache"): N clients share one prompt prefix;
    ``affinity-key=prefix`` routes them all to the one rendezvous owner
    whose prefix KV pages are warm.  A rolling restart of that owner
    lands MID-decode: live streams migrate to cache-cold servers and
    must stay bit-exact, the restarted owner comes back deliberately
    cache-cold, and one re-warm wave restores the hit path.

    Exactness contract: every stream's tokens equal the sim oracle
    bit-for-bit (zero lost, zero duplicated — a stale or cross-slot
    prefix page is exact-fail); after the warm wave the fleet ledger
    shows EXACTLY one miss and ``clients-1`` hits at 64 cached tokens
    each; the observatory's fleet prefix_hits/prefix_misses rollup is
    integer-exact against the summed per-server ledgers (retired rows
    included); the final fleet hit ratio clears 0.5 despite the
    cache-cold failovers; zero drain drops, zero breaker trips."""
    import numpy as np

    # the shared prefix must span the WIRE grain (PREFIX_GRAIN=64): the
    # client's route key is the first-grain chain digest, so shorter
    # "shared" prefixes would fall back to full-prompt digests and
    # scatter the clients; the server caches at a finer 8-token grain
    # (prompts are 67 tokens -> 64 cached tokens per warm hit)
    grain, prefix_len = 8, 64
    h = FleetHarness(mode="generate", gen_slots=max(4, clients),
                     gen_max_new=48, gen_step_ms=3.0, base_id=10400,
                     topic="chaospfx", affinity_key="prefix",
                     digest_interval=0.25,
                     gen_extra=(f"prefix-cache=on prefix-grain={grain} "
                                "prefill-chunk=4"))
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, h.gen_vocab, (1, prefix_len)).astype(np.int32)

    def mk_prompt(i: int):
        # shared prefix + unique 3-token suffix: route keys collide
        # (same first grains), oracles do NOT (suffix changes the sum)
        suffix = np.int32([[(7 + 13 * i) % h.gen_vocab,
                            (3 * i + 1) % h.gen_vocab,
                            (i * i + 5) % h.gen_vocab]])
        return np.concatenate([shared, suffix], axis=1)

    try:
        for i in range(servers):
            h.start_server(i)
        h.attach_observatory(ttl_s=10.0)
        cs = [h.make_gen_client(f"P{i}", affinity=True, timeout=120.0)
              for i in range(clients)]

        # -- phase A: prime — the first stream misses and publishes ----
        cs[0].push_prompt(prompt=mk_prompt(0))
        cs[0].settle(timeout=120.0)

        # -- phase B: warm wave — every other client hits the cache ----
        for i in range(1, clients):
            cs[i].push_prompt(prompt=mk_prompt(i))
        for c in cs:
            c.settle(timeout=120.0)
        warm = h.fleet_gen()
        warm_snap = {k: int(warm.get(k, 0)) for k in (
            "prefix_hits", "prefix_misses", "prefix_hit_tokens",
            "prefix_publishes")}

        # -- phase C: roll the warm owner mid-decode -------------------
        traces = [c.push_prompt(prompt=mk_prompt(100 + i))
                  for i, c in enumerate(cs)]
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(c.tokens_done(t) >= 8 for c, t in zip(cs, traces)):
                break
            time.sleep(0.005)
        owner = max(
            h.servers,
            key=lambda i: h.servers[i].health()["gen"].get(
                "gen_occupied", 0))
        roll = h.rolling_restart(owner)
        for c in cs:
            c.settle(timeout=120.0)

        # -- phase D: re-warm the deliberately cache-cold owner --------
        cs[0].push_prompt(prompt=mk_prompt(200))
        cs[0].settle(timeout=120.0)
        for i in range(1, clients):
            cs[i].push_prompt(prompt=mk_prompt(200 + i))
        for c in cs:
            c.settle(timeout=120.0)
        for c in cs:
            c.finish()

        checks = [c.check_exact() for c in cs]
        exact = sum(r["exact"] for r in checks)
        mismatched = sum(r["mismatched"] for r in checks)
        res = {
            k: sum(int(c.health().get(k, 0)) for c in cs)
            for k in ("stream_resumes", "stream_migrations",
                      "resume_failures")
        }
        h.publish_digests()
        h.observatory_settled()
        cc = h.observatory_crosscheck()
        gen = h.fleet_gen()
        pfx = {k: int(gen.get(k, 0)) for k in (
            "prefix_hits", "prefix_misses", "prefix_hit_tokens",
            "prefix_publishes", "prefix_evictions")}
        lookups = pfx["prefix_hits"] + pfx["prefix_misses"]
        ratio = (pfx["prefix_hits"] / lookups) if lookups else 0.0
        v = {
            "clients": clients,
            "streams": sum(r["streams"] for r in checks),
            "exact": exact,
            "mismatched": mismatched,
            "tokens": sum(r["tokens"] for r in checks),
            "warm_wave": warm_snap,
            "fleet_prefix": pfx,
            "hit_ratio": round(ratio, 4),
            "migrations": res["stream_migrations"],
            "resumes": res["stream_resumes"],
            "resume_failures": res["resume_failures"],
            "rolling_restart": {
                "goaway_sent": roll["health"].get("goaway_sent", 0),
                "drain_dropped": roll["drain"]["dropped"],
            },
            "crosscheck": cc,
            "breaker_trips": h.breaker_trips(),
        }
        v["ok"] = bool(
            mismatched == 0 and exact == v["streams"]
            # warm-wave ledger is EXACT: one publish-miss, then a hit
            # at 16 cached tokens for every other client
            and warm_snap["prefix_misses"] == 1
            and warm_snap["prefix_hits"] == clients - 1
            and warm_snap["prefix_hit_tokens"]
            == (clients - 1) * prefix_len
            and warm_snap["prefix_publishes"] >= 1
            # the roll landed on live streams and every handoff resumed
            and res["stream_migrations"] >= 1
            and res["resume_failures"] == 0
            and roll["drain"]["dropped"] == 0
            # cache-cold failovers tolerated, but the fleet still
            # serves mostly warm
            and ratio >= 0.5
            and cc["exact"]
            and v["breaker_trips"] == 0
        )
        return v
    finally:
        h.stop_all()


def run_device_loss_script(servers: int = 3, streams: int = 8,
                           seed: int = 0) -> Dict[str, Any]:
    """Device-loss chaos (degrade, don't die — Documentation/
    resilience.md "Resource pressure & device loss"): N concurrent
    slotted generation streams are decoding on one server when a mesh
    member DIES mid-scan.  The engine hands every live stream off as a
    resumable continuity chunk, rebuilds its model on the survivors
    (re-mesh), and the server announces ``degraded:true`` — clients
    migrate the streams (possibly straight back to the degraded server:
    the resume signature excludes the mesh, so tokens stay bit-exact)
    and fleet routing deprioritizes the wounded host from the broker
    state alone.

    Exactness contract: every stream's concatenated tokens equal the
    sim oracle bit-for-bit, client ``stream_migrations`` equals the
    wounded engine's ``gen_device_lost_evicted`` (every handoff landed
    exactly once), ``gen_device_lost == 1`` / ``gen_remeshes == 1``,
    zero frame loss, zero resume failures, ZERO breaker trips anywhere
    (no server died — the chip did), and the degraded announce is
    observed client-side after one rediscovery."""
    import random

    h = FleetHarness(mode="generate", gen_slots=max(8, streams),
                     gen_max_new=96, gen_step_ms=3.0, base_id=9900,
                     topic="chaosdevloss")
    rng = random.Random(seed)
    try:
        for i in range(servers):
            h.start_server(i)
        clients = [
            h.make_gen_client(f"C{i}", routing="least-inflight",
                              timeout=120.0)
            for i in range(streams)
        ]
        traces = [c.push_prompt() for c in clients]

        def wait_tokens_each(n: int, timeout: float = 60.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if all(c.tokens_done(t) >= n
                       for c, t in zip(clients, traces)):
                    return
                time.sleep(0.005)
            raise TimeoutError(
                f"streams never all reached {n} delivered tokens")

        # seeded mid-decode loss point (chunk multiple, well inside the
        # 96-token streams); every fresh client ranked the same
        # lowest-address server first, so all streams share one victim
        t_loss = 4 * rng.randint(2, 6)
        wait_tokens_each(t_loss)
        victim = max(
            h.servers,
            key=lambda i: h.servers[i].health()["gen"].get(
                "gen_occupied", 0))
        victim_addr = f"127.0.0.1:{h.ports[victim]}"
        h.inject_device_loss(victim)
        loss_row = h.wait_device_lost(victim)

        # the degraded announce: visible to any client after ONE
        # rediscovery (production clients refresh on failure waves; the
        # script forces it so the observation is deterministic)
        h.refresh_client(clients[0])
        hints = dict(clients[0].element._endpoint_hints)
        degraded_seen = bool(hints.get(victim_addr, {}).get("degraded"))

        for c in clients:
            c.settle(timeout=120.0)
        for c in clients:
            c.finish()

        checks = [c.check_exact() for c in clients]
        exact = sum(r["exact"] for r in checks)
        mismatched = sum(r["mismatched"] for r in checks)
        res = {
            k: sum(int(c.health().get(k, 0)) for c in clients)
            for k in ("stream_resumes", "stream_migrations",
                      "duplicate_tokens_dropped", "resume_failures")
        }
        gen = h.fleet_gen()
        victim_health = h.servers[victim].health()
        handed_off = int(
            victim_health["gen"].get("gen_device_lost_evicted", 0))
        v = {
            "streams": streams,
            "exact": exact,
            "mismatched": mismatched,
            "tokens": sum(r["tokens"] for r in checks),
            "seed": seed,
            "loss_point": t_loss,
            "victim": victim_addr,
            "handed_off": handed_off,
            "degraded_announce_seen": degraded_seen,
            "victim_degraded_health": int(
                victim_health["ssrc"].get("degraded", 0)),
            "resumes": res,
            "gen": {k: int(gen.get(k, 0)) for k in (
                "gen_joins", "gen_completed", "gen_device_lost",
                "gen_device_lost_evicted", "gen_remeshes",
                "gen_resumes", "gen_tokens")},
            # no server process died: trips anywhere are a failure
            "breaker_trips": h.breaker_trips(),
        }
        v["ok"] = bool(
            mismatched == 0 and exact == streams
            and int(loss_row.get("gen_device_lost", 0)) == 1
            and int(gen.get("gen_remeshes", 0)) == 1
            # every handoff the wounded engine emitted was migrated by
            # exactly one client, and the loss landed on live streams
            and res["stream_migrations"] == handed_off
            and handed_off >= 1
            and res["resume_failures"] == 0
            and degraded_seen
            and v["victim_degraded_health"] == 1
            and v["breaker_trips"] == 0
        )
        return v
    finally:
        h.stop_all()


class HarnessActuator:
    """The reference :class:`~nnstreamer_tpu.core.autoscale.FleetActuator`:
    closes the controller loop onto a :class:`FleetHarness`.

    spawn  → start a NEW server on the discovery plane
    drain  → zero-loss decommission: GOAWAY drain (live streams hand
             off resumably), exact ledger retirement, stop — NO restart
    resize → live slot-width rebuild (``tensor_generator``
             ``request_resize``: dispatch-thread swap at an idle
             boundary, ledgers adopted, streams migrate bit-identically)

    Every verb returns immediately; a worker thread resolves the
    :class:`ActionTicket` with the outcome — the controller's decision
    loop never blocks on actuation (the FleetActuator contract).

    Every verb carries the issuing controller's lease ``epoch`` (PR-17
    fencing): the drain entry goes through the serversrc's fenced
    ``request_drain`` and the resize through the generator's fenced
    ``request_resize``, so a stale-epoch command from a deposed
    controller is REFUSED by the target with a typed
    :class:`StaleEpochError` — visible in the resolved event."""

    def __init__(self, harness: FleetHarness):
        self.h = harness
        self.events: List[Dict[str, Any]] = []   # resolved verbs, in order
        self.drains: List[Dict[str, Any]] = []   # per-drain evidence rows

    def _spawn_ticket(self):
        from nnstreamer_tpu.core.autoscale import ActionTicket

        return ActionTicket()

    def _run(self, kind: str, target: str, epoch: int, fn) -> "Any":
        ticket = self._spawn_ticket()

        def worker() -> None:
            try:
                ok, detail = fn()
            except Exception as exc:  # noqa: BLE001 — outcome goes to the ticket
                ok, detail = False, f"{type(exc).__name__}: {exc}"
            self.events.append({"kind": kind, "target": target,
                                "epoch": int(epoch),
                                "ok": bool(ok), "detail": detail})
            ticket.resolve(ok, detail)

        threading.Thread(target=worker, daemon=True,
                         name=f"chaos-actuate-{kind}").start()
        return ticket

    def spawn(self, epoch: int = 0):
        def do() -> tuple:
            idx = self.h.add_server()
            return True, f"server{idx} port={self.h.ports[idx]}"

        return self._run("scale_up", "", epoch, do)

    def drain(self, target: str, epoch: int = 0):
        def do() -> tuple:
            idx = self.h.idx_for_topic(target)
            pipe = self.h.servers[idx]
            # the fenced drain entry FIRST: a stale epoch raises here,
            # before any stream is touched
            pipe["ssrc"].request_drain(epoch=epoch)
            res = pipe.drain(timeout=30.0)
            ssrc = pipe["ssrc"]
            # the element-level actuation probe: frames() must have
            # walked serving → draining → stopped
            deadline = time.monotonic() + 5.0
            while not ssrc.drain_complete and time.monotonic() < deadline:
                time.sleep(0.01)
            rec = {
                "idx": idx,
                "target": target,
                "dropped": int(res.get("dropped", 0)),
                "drain_complete": bool(ssrc.drain_complete),
                "goaway_sent": int(
                    pipe.health()["ssrc"].get("goaway_sent", 0)),
                "gen": self.h.server_gen_row(pipe),
            }
            self.h._retire_rows(pipe)
            pipe.stop()
            self.h.servers.pop(idx, None)
            self.drains.append(rec)
            ok = rec["dropped"] == 0 and rec["drain_complete"]
            return ok, (f"drained server{idx}: dropped={rec['dropped']} "
                        f"goaway_evicted="
                        f"{rec['gen'].get('gen_goaway_evicted', 0)}")

        return self._run("scale_down", target, epoch, do)

    def resize(self, target: str, slots: int, epoch: int = 0):
        def do() -> tuple:
            idx = self.h.idx_for_topic(target)
            gen = self.h.servers[idx]["gen"]
            gen.request_resize(slots, epoch=epoch)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                row = self.h.server_gen_row(self.h.servers[idx])
                if (not gen.resize_pending
                        and int(row.get("gen_slots", 0)) == slots):
                    return True, f"server{idx} resized to {slots} slots"
                time.sleep(0.01)
            return False, f"server{idx} resize to {slots} never completed"

        return self._run("resize", target, epoch, do)


def run_autoscale_script(servers: int = 1, streams: int = 4) -> Dict[str, Any]:
    """Predictive-autoscaler chaos acceptance (Documentation/
    resilience.md "Fleet autoscaling"): a generate-mode fleet under a
    live :class:`FleetController` closes the loop observatory →
    ``plan()`` → :class:`HarnessActuator` through three scripted
    phases, with the zero-loss invariants checked exactly:

    1. **Ramp** — saturating tenant-A load on a 1-server fleet drives
       reactive scale-up (hysteresis streak, then spawn).
    2. **Hot-tenant burst** — a tenant-B burst saturates the grown
       fleet; the controller scales up again and the VICTIM tenant's
       goodput stays >= 90% of its no-burst baseline (tenant ledgers
       prove it).
    3. **Forced scale-down under live load** — the operator shrinks
       ``max_servers``; the envelope rule drains the least-loaded
       server while every server holds live streams, so the drain
       migrates them: client ``stream_migrations`` must equal the
       drained engine's ``gen_goaway_evicted`` and every token stream
       stays bit-identical to the sim oracle.

    Verdict: zero lost/duplicated streams, zero breaker trips, drain
    dropped nothing, observatory rollups exactly equal the per-server
    ledgers (retired servers included), and the controller's
    ``nns.autoscale.*`` counters exactly match the actuation record.

    ``max_inflight == gen_slots`` makes placement deterministic:
    admission sheds BUSY beyond the slot count, so saturating waves
    spread across the fleet by busy-retry instead of queueing on the
    lowest-address server — occupancy (not luck) drives the plan."""
    from urllib.request import urlopen

    from nnstreamer_tpu.core.autoscale import FleetController, FleetPolicy

    h = FleetHarness(mode="generate", gen_slots=2, max_inflight=2,
                     gen_max_new=96, gen_step_ms=4.0, base_id=10100,
                     topic="chaosauto", digest_interval=0.25,
                     gen_slo=("slo-ttft-p95=30 slo-token-p99=5 "
                              "slo-availability=0.5"))
    ctrl = None
    try:
        for i in range(max(1, servers)):
            h.start_server(i)
        obs = h.attach_observatory(ttl_s=5.0)
        mport = obs.serve_metrics(0)
        act = HarnessActuator(h)
        pol = FleetPolicy(min_servers=1, max_servers=3,
                          occupancy_high=0.75, occupancy_low=0.2,
                          up_streak=2, down_streak=3,
                          cooldown_up_s=0.2, cooldown_down_s=0.2,
                          burn_high=5.0)
        ctrl = FleetController(obs, act, policy=pol).start()

        def occupied_total() -> int:
            return sum(
                int(h.server_gen_row(p).get("gen_occupied", 0))
                for p in list(h.servers.values()))

        def wait_occupied(n: int, timeout: float = 20.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if occupied_total() >= n:
                    return
                time.sleep(0.005)
            raise TimeoutError(
                f"fleet never reached {n} occupied slots "
                f"(at {occupied_total()})")

        def wait_all_loaded(timeout: float = 20.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if all(
                    int(h.server_gen_row(p).get("gen_occupied", 0)) >= 1
                    for p in list(h.servers.values())
                ):
                    return
                time.sleep(0.005)
            raise TimeoutError("load never spread to every server")

        def tick() -> list:
            h.publish_digests()
            h.observatory_settled()
            return ctrl.tick()

        def tick_until(kind: str, timeout: float = 15.0) -> list:
            """Tick the controller until it dispatches ``kind`` — the
            OUTCOME is pinned (hysteresis guarantees >= up_streak
            pressure observations first); the exact tick count is
            timing, not contract."""
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                acts = tick()
                if any(a.kind == kind for a in acts):
                    return acts
                time.sleep(0.03)
            raise TimeoutError(f"controller never dispatched {kind}")

        def wait_fleet(n: int, timeout: float = 30.0) -> None:
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                if len(h.servers) == n:
                    return
                time.sleep(0.01)
            raise TimeoutError(
                f"fleet never reached {n} servers (at {len(h.servers)})")

        n0 = len(h.servers)
        pol.max_servers = n0 + 2

        # -- phase 1: ramp → reactive scale-up ---------------------------
        # backlog of 2x the slot count: busy-retriers refill slots the
        # moment streams finish, so saturation OUTLIVES the hysteresis
        # streak no matter how ticks interleave with stream completions
        ramp = [
            h.make_gen_client(f"A{i}", tenant="A", busy_retries=60,
                              timeout=120.0)
            for i in range(4 * n0 + 2)
        ]
        for c in ramp:
            c.push_prompt()
        wait_occupied(2 * n0)
        acts1 = tick_until("scale_up")
        wait_fleet(n0 + 1)
        for c in ramp:
            c.settle(timeout=120.0)
        baseline_checks = [c.check_exact() for c in ramp]
        baseline_goodput = (
            sum(r["exact"] for r in baseline_checks) / max(1, len(ramp)))

        # -- phase 2: hot-tenant burst → scale-up absorbs it -------------
        victims = [
            h.make_gen_client(f"V{i}", tenant="A", busy_retries=60,
                              timeout=120.0)
            for i in range(2)
        ]
        burst = [
            h.make_gen_client(f"B{i}", tenant="B", busy_retries=60,
                              timeout=120.0)
            for i in range(4 * (n0 + 1) - 2)
        ]
        for c in victims + burst:
            c.push_prompt()
        wait_occupied(2 * (n0 + 1))
        acts2 = tick_until("scale_up")
        wait_fleet(n0 + 2)
        for c in victims + burst:
            c.settle(timeout=120.0)
        victim_checks = [c.check_exact() for c in victims]
        victim_goodput = (
            sum(r["exact"] for r in victim_checks) / max(1, len(victims)))

        # -- phase 3: envelope shrink → scale-down under live load -------
        # streams are SHORT (they dry up in under a second), so a
        # static wave cannot keep the fleet loaded long enough for the
        # drain decision to land on a busy server — a pump tops up
        # every client's in-flight streams instead, keeping the fleet
        # saturated (2x clients >> fleet slots, busy-retries spill the
        # excess onto whichever server has a free slot)
        down = [
            h.make_gen_client(f"D{i}", busy_retries=60, timeout=120.0)
            for i in range(2 * (n0 + 2))
        ]
        refill = [
            h.make_gen_client(f"R{i}", busy_retries=60, timeout=120.0)
            for i in range(4)
        ]
        pumps = down + refill

        def pump() -> None:
            for c in pumps:
                while len(c.prompts) - c.finished() < 2:
                    c.push_prompt()

        pump()
        wait_all_loaded()
        ctrl.policy.max_servers = n0 + 1  # the operator shrinks the bound
        # envelope rule: drain NOW — but only take the decision tick
        # while EVERY server holds live streams ("drain under live
        # load" is the contract; a momentarily idle server would be
        # picked as least-loaded and hand off nothing)
        deadline = time.monotonic() + 30.0
        while True:
            pump()
            wait_all_loaded()
            acts3 = tick()
            if any(a.kind == "scale_down" for a in acts3):
                break
            if time.monotonic() >= deadline:
                raise TimeoutError("controller never dispatched scale_down")
            time.sleep(0.03)
        wait_fleet(n0 + 1)
        for c in down + refill:
            c.settle(timeout=120.0)
        tick()                            # reap the drain ticket

        # -- verdict ------------------------------------------------------
        for c in h.gen_clients:
            c.finish()
        checks = {c.name: c.check_exact() for c in h.gen_clients}
        exact = sum(r["exact"] for r in checks.values())
        mismatched = sum(r["mismatched"] for r in checks.values())
        total_streams = sum(r["streams"] for r in checks.values())
        migrations = sum(
            int(c.health().get("stream_migrations", 0))
            for c in h.gen_clients)
        drain_rec = act.drains[0] if act.drains else {}
        handed_off = int(drain_rec.get("gen", {}).get(
            "gen_goaway_evicted", 0))

        h.publish_digests()
        h.observatory_settled()
        cc = h.observatory_crosscheck()

        body = urlopen(
            f"http://127.0.0.1:{mport}/metrics", timeout=5).read().decode()
        metrics_ok = all(
            frag in body for frag in (
                "nns_autoscale_ticks", "nns_autoscale_scale_ups",
                "nns_autoscale_scale_downs", "nns_autoscale_decisions",
                "nns_fleet_servers",
            ))
        accounting_ok = (
            ctrl.scale_ups == sum(
                1 for e in act.events if e["kind"] == "scale_up")
            and ctrl.scale_downs == sum(
                1 for e in act.events if e["kind"] == "scale_down")
            and ctrl.state.decisions
            == ctrl.scale_ups + ctrl.scale_downs + ctrl.resizes
            and all(e["ok"] for e in act.events))

        v = {
            "clients": checks,
            "exact": exact,
            "mismatched": mismatched,
            "streams": total_streams,
            "scale_ups": ctrl.scale_ups,
            "scale_downs": ctrl.scale_downs,
            "actions_failed": ctrl.actions_failed,
            "decisions": ctrl.state.decisions,
            "hysteresis_holds": ctrl.state.hysteresis_holds,
            "actions": [(e["kind"], e["ok"], e["detail"])
                        for e in act.events],
            "phase_actions": [[a.kind for a in acts]
                              for acts in (acts1, acts2, acts3)],
            "baseline_goodput": baseline_goodput,
            "victim_goodput": victim_goodput,
            "tenants": h.fleet_tenants(),
            "drain": {k: drain_rec.get(k) for k in
                      ("target", "dropped", "drain_complete",
                       "goaway_sent")},
            "handed_off": handed_off,
            "migrations": migrations,
            "model_samples": len(ctrl.model),
            "crosscheck": cc,
            "metrics_endpoint_ok": metrics_ok,
            "accounting_ok": accounting_ok,
            "breaker_trips": h.breaker_trips(),
            "inflight": ctrl.inflight(),
        }
        v["ok"] = bool(
            mismatched == 0 and exact == total_streams
            and ctrl.scale_ups == 2 and ctrl.scale_downs == 1
            and ctrl.actions_failed == 0
            and drain_rec.get("dropped", 1) == 0
            and drain_rec.get("drain_complete") is True
            and handed_off >= 1
            and migrations == handed_off
            and baseline_goodput > 0
            and victim_goodput >= 0.9 * baseline_goodput
            and cc["exact"]
            and metrics_ok
            and accounting_ok
            and v["breaker_trips"] == 0
            and not v["inflight"]
        )
        return v
    finally:
        if ctrl is not None:
            ctrl.stop()
        h.stop_all()


def run_partition_script(servers: int = 3, streams: int = 6,
                         seed: int = 0,
                         lease_ttl: float = 4.0) -> Dict[str, Any]:
    """Fail-static control-plane chaos (Documentation/resilience.md
    "Control-plane resilience"): the discovery/control plane is killed,
    blinded, partitioned, and duplicated while a generate-mode fleet
    keeps serving — and the dataplane is provably untouched.

    Script, with TWO live controllers throughout:

    1. **Election** — two leased controllers on one retained lease
       topic: exactly one acquires (epoch 1), the standby's refusals
       are counted.
    2. **Broker death mid-load** — the broker dies at a seeded decode
       point and later restarts on the same port with amnesia.  While
       it is down, the leader's view degrades to BLIND and the
       fail-static ladder freezes BOTH a tempted ceiling drain
       (``broker_disconnected``) and the cold-controller floor spawn
       (``no_fresh_rows``); streams keep decoding over direct TCP.
       After the restart every server re-announces and the fleet
       rollups are integer-exact again.
    3. **Partition** — one server's control-plane link is severed; its
       digest goes stale and is TTL-evicted while the server keeps
       serving.  A tempted ceiling drain is frozen (``below_quorum``):
       zero drains while part of the fleet is alive but invisible.
       After the heal the rollups are integer-exact (resurrection
       reversal), and only then does the envelope drain actually run —
       carrying the leader's epoch.
    4. **Fencing** — the leader is partitioned off the lease topic: it
       self-fences within one TTL, the standby promotes with epoch 2,
       actuates a fenced resize, and the old epoch's resize is REFUSED
       by the target with a typed stale-epoch reject — ledgers and
       slot width bit-untouched.

    Verdict (exact): zero lost/duplicated tokens, zero drains of
    alive-but-invisible servers, exactly one epoch's actions applied,
    stale-epoch rejects counted, fleet rollups integer-exact after
    every heal."""
    import random

    from nnstreamer_tpu.core.autoscale import (
        FleetController, FleetPolicy, LeaderLease, LeaseChannel,
        StaleEpochError,
    )

    h = FleetHarness(mode="generate", gen_slots=max(4, streams),
                     gen_max_new=64, gen_step_ms=3.0, base_id=10200,
                     topic="chaospart", digest_interval=0.25)
    rng = random.Random(seed)
    chan1 = chan2 = None
    try:
        for i in range(max(2, servers)):
            h.start_server(i)
        obs = h.attach_observatory(ttl_s=2.0)
        act1, act2 = HarnessActuator(h), HarnessActuator(h)
        # reactive rules disabled (streaks unreachable): every scale
        # impulse in this script is a scripted envelope change, so the
        # freeze/act counts are exact, not timing-dependent
        pol = FleetPolicy(min_servers=1, max_servers=len(h.servers),
                          up_streak=99, down_streak=99,
                          cooldown_up_s=0.05, cooldown_down_s=0.05,
                          plane_quorum_fraction=0.9)
        lease1 = LeaderLease("ctl-a", ttl_s=lease_ttl)
        lease2 = LeaderLease("ctl-b", ttl_s=lease_ttl)
        chan1 = LeaseChannel("127.0.0.1", h.broker.port, h.topic, lease1)
        chan2 = LeaseChannel("127.0.0.1", h.broker.port, h.topic, lease2)
        ctrl1 = FleetController(obs, act1, policy=pol, lease=lease1)
        ctrl2 = FleetController(obs, act2, policy=pol, lease=lease2)

        def tick_ctrl(ctrl) -> list:
            h.publish_digests()
            return ctrl.tick()

        # -- phase 1: election -------------------------------------------
        deadline = time.monotonic() + lease_ttl + 10.0
        while not lease1.held and time.monotonic() < deadline:
            ctrl1.tick()  # vacancy watch: acquires after one full TTL
            time.sleep(0.05)
        for _ in range(3):
            ctrl2.tick()  # standby: sees the fresh lease, refuses
            time.sleep(0.02)
        epoch1 = lease1.epoch

        # -- phase 2: broker death mid-generate-load ---------------------
        clients = [
            h.make_gen_client(f"C{i}", routing="least-inflight",
                              timeout=120.0)
            for i in range(max(2, streams))
        ]
        traces = [c.push_prompt() for c in clients]
        t_kill = 4 * rng.randint(1, 3)  # seeded mid-decode kill point
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if all(c.tokens_done(t) >= t_kill
                   for c, t in zip(clients, traces)):
                break
            time.sleep(0.005)
        ctrl1.tick()  # fresh lease renewal right before the outage
        frozen0 = ctrl1.state.frozen
        h.kill_broker()
        # wait until the plane loss is SENSED everywhere (observatory
        # gauge + every server's announce client)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if (not obs.plane_connected and not any(
                    p["ssrc"]._announcement.connected
                    for p in h.servers.values())):
                break
            time.sleep(0.02)
        plane_lost_sensed = not obs.plane_connected
        # digest publishes during the outage fail EXACTLY (counted per
        # missed interval, never queued blindly)
        h.publish_digests()
        pf_outage = sum(
            p["ssrc"]._digest.publish_failures
            for p in h.servers.values())
        # tempted ceiling drain while disconnected -> frozen, DEGRADED
        pol.max_servers = len(h.servers) - 1
        ctrl1.tick()
        # wait out the observatory TTL: full blindness, where even the
        # floor-spawn impulse of an (apparently) empty fleet is frozen
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            snap = obs.snapshot()
            if not [r for r in snap.get("servers", ())
                    if not r.get("stale")]:
                break
            time.sleep(0.05)
        pol.min_servers = 1
        ctrl1.tick()
        frozen_outage = ctrl1.state.frozen - frozen0
        frozen_reasons = dict(ctrl1.state.frozen_by_reason)
        blind_level = ctrl1.plane.level
        pol.max_servers = len(h.servers)  # disarm before the heal
        # streams decoded through the whole outage: dataplane untouched
        for c in clients:
            c.settle(timeout=120.0)

        h.revive_broker()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            if (obs.plane_connected and all(
                    p["ssrc"]._announcement.reannounces >= 1
                    and p["ssrc"]._announcement.connected
                    for p in h.servers.values())):
                break
            time.sleep(0.05)
        reannounces = {
            idx: p["ssrc"]._announcement.reannounces
            for idx, p in h.servers.items()}
        reconnects = {
            idx: p["ssrc"]._announcement.reconnects
            for idx, p in h.servers.items()}
        h.publish_digests()
        h.observatory_settled()
        cc_outage = h.observatory_crosscheck()

        # -- phase 3: partition one server, freeze, heal, then drain -----
        victim = max(h.servers)
        victim_topic = h.servers[victim]["ssrc"]._announcement.topic
        frozen1 = ctrl1.state.frozen
        h.partition_server(victim)
        # second wave lands WHILE the victim is invisible — it must
        # keep serving (clients still hold its direct TCP endpoint)
        for c in clients:
            c.push_prompt()
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            rows = {r["topic"]: r for r in obs.servers()}
            row = rows.get(victim_topic)
            if row is None or row.get("stale"):
                break
            h.publish_digests()
            time.sleep(0.05)
        # tempt a ceiling drain BELOW the visible coverage (2 fresh
        # rows): without the ladder this would shrink a fleet the
        # controller can only half see
        pol.max_servers = 1
        tick_ctrl(ctrl1)
        frozen_partition = ctrl1.state.frozen - frozen1
        partition_reasons = dict(ctrl1.state.frozen_by_reason)
        drains_during_partition = len(act1.drains)
        for c in clients:
            c.settle(timeout=120.0)
        h.heal_server(victim)
        pol.max_servers = len(h.servers) - 1  # the legit envelope drain
        h.publish_digests()
        h.observatory_settled()
        cc_heal = h.observatory_crosscheck()
        # the envelope drain may now actually run — carrying epoch 1
        deadline = time.monotonic() + 20.0
        acts = []
        while time.monotonic() < deadline:
            acts = tick_ctrl(ctrl1)
            if any(a.kind == "scale_down" for a in acts):
                break
            time.sleep(0.05)
        deadline = time.monotonic() + 30.0
        while len(h.servers) > pol.max_servers and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        tick_ctrl(ctrl1)  # reap the drain ticket
        drain_rec = act1.drains[0] if act1.drains else {}

        # -- phase 4: depose the leader; fenced takeover -----------------
        h.sever_client(chan1._client, h.blackhole_port())
        deadline = time.monotonic() + 3.0 * lease_ttl + 10.0
        while (not (lease2.held and lease1.self_fences >= 1)
               and time.monotonic() < deadline):
            ctrl1.tick()  # self-fences once renewals go unconfirmed
            ctrl2.tick()  # promotes after the seen lease expires
            time.sleep(0.05)
        epoch2 = lease2.epoch
        # the new leader actuates a fenced resize; the OLD epoch's
        # command is then refused by the target, width untouched
        tgt = min(h.servers)
        gen = h.servers[tgt]["gen"]
        slots0 = int(h.server_gen_row(h.servers[tgt]).get("gen_slots", 0))
        gen.request_resize(slots0 + 2, epoch=epoch2)
        deadline = time.monotonic() + 15.0
        while gen.resize_pending and time.monotonic() < deadline:
            time.sleep(0.01)
        stale_rejected = False
        try:
            gen.request_resize(slots0, epoch=epoch1)
        except StaleEpochError:
            stale_rejected = True
        tgt_row = h.server_gen_row(h.servers[tgt])

        # -- verdict ------------------------------------------------------
        for c in clients:
            c.finish()
        checks = [c.check_exact() for c in clients]
        exact = sum(r["exact"] for r in checks)
        mismatched = sum(r["mismatched"] for r in checks)
        total = 2 * len(clients)  # two prompts per client
        h.publish_digests()
        h.observatory_settled()
        cc_final = h.observatory_crosscheck()
        v = {
            "streams": total,
            "exact": exact,
            "mismatched": mismatched,
            "tokens": sum(r["tokens"] for r in checks),
            "seed": seed,
            "kill_point": t_kill,
            "election": {
                "leader": lease1.owner, "epoch1": epoch1,
                "standby_refusals": lease2.refusals,
                "standby_ticks": ctrl2.standby_ticks,
            },
            "broker_outage": {
                "plane_lost_sensed": plane_lost_sensed,
                "digest_publish_failures": pf_outage,
                "frozen": frozen_outage,
                "frozen_reasons": frozen_reasons,
                "blind_level": blind_level,
                "reconnects": reconnects,
                "reannounces": reannounces,
                "crosscheck_exact": cc_outage["exact"],
            },
            "partition": {
                "victim": victim_topic,
                "frozen": frozen_partition,
                "frozen_reasons": partition_reasons,
                "drains_while_invisible": drains_during_partition,
                "crosscheck_after_heal": cc_heal["exact"],
            },
            "scale_down": {
                "target": drain_rec.get("target"),
                "dropped": drain_rec.get("dropped"),
                "drain_complete": drain_rec.get("drain_complete"),
                "epochs": [e["epoch"] for e in act1.events],
            },
            "fencing": {
                "epoch2": epoch2,
                "steals": lease2.steals,
                "self_fences": lease1.self_fences,
                "stale_reject": stale_rejected,
                "gen_stale_epoch_rejects": int(
                    tgt_row.get("gen_stale_epoch_rejects", 0)),
                "slots_after": int(tgt_row.get("gen_slots", 0)),
            },
            "standby_actions": len(act2.events),
            "crosscheck_final": cc_final["exact"],
            "breaker_trips": h.breaker_trips(),
        }
        v["ok"] = bool(
            mismatched == 0 and exact == total
            and epoch1 == 1 and epoch2 == 2
            and lease2.refusals >= 1
            and lease1.self_fences == 1 and lease2.steals == 1
            and plane_lost_sensed
            and pf_outage >= 1
            and frozen_outage >= 2
            and "broker_disconnected" in frozen_reasons
            and "no_fresh_rows" in frozen_reasons
            and blind_level == "blind"
            and all(n >= 1 for n in reannounces.values())
            and cc_outage["exact"]
            and frozen_partition >= 1
            and "below_quorum" in partition_reasons
            and drains_during_partition == 0
            and cc_heal["exact"]
            and drain_rec.get("dropped", 1) == 0
            and drain_rec.get("drain_complete") is True
            and all(e == epoch1 for e in v["scale_down"]["epochs"])
            and stale_rejected
            and v["fencing"]["gen_stale_epoch_rejects"] == 1
            and v["fencing"]["slots_after"] == slots0 + 2
            and len(act2.events) == 0
            and cc_final["exact"]
        )
        return v
    finally:
        for chan in (chan1, chan2):
            if chan is not None:
                try:
                    chan.close()
                except Exception:  # allow-silent: teardown best-effort
                    pass
        h.stop_all()


def run_train_script(seed: int = 0) -> Dict[str, Any]:
    """Continuous-learning chaos (the crash-safe in-pipeline training
    contract, Documentation/resilience.md "Continuous learning"):

    * **kill mid-epoch → exact-step resume** — a ``trainer.step`` fault
      kills the training thread mid-epoch-2; the durable (marker-
      committed) epoch-1 checkpoint is the resume point, the replayed
      stream fast-forwards by the cursor (zero samples retrained), and
      the final checkpoint is BIT-IDENTICAL to an uninterrupted control
      run (every param leaf, exact compare).
    * **gated promotion** — the closed loop in ONE pipeline (datareposrc
      → tensor_trainer → model_validator ∥ appsrc → tensor_filter):
      the validator scores the newest durable checkpoint on a held-out
      split and promotes it into the co-hosted serving filter through
      the staged hot swap; a regressed candidate is REFUSED (counted,
      model untouched); a candidate that validates clean but error-
      bursts in serving (``filter.reload.post`` faults) rolls back
      inside the observation window with zero frame loss.
    * **memory pressure → resumable pause** — injected watermark
      pressure pauses train steps (counted, incident) while the
      co-hosted filter keeps serving; pressure clears, training resumes
      and finishes with every sample incorporated.
    """
    import shutil
    import tempfile

    import numpy as np

    from nnstreamer_tpu import models as zoo
    from nnstreamer_tpu.core import checkpoint as ckpt
    from nnstreamer_tpu.core.buffer import TensorFrame
    from nnstreamer_tpu.core.checkpoint import atomic_write_bytes
    from nnstreamer_tpu.core.resilience import FAULTS
    from nnstreamer_tpu.pipeline import parse_pipeline
    from nnstreamer_tpu.trainer.jax_trainer import JaxTrainer

    n_train, n_hold, classes, batch, epochs = 32, 16, 4, 8, 3
    steps_per_epoch = n_train // batch
    tmp = tempfile.mkdtemp(prefix="nns_chaos_train_")
    v: Dict[str, Any] = {"mode": "train"}
    checks: Dict[str, bool] = {}
    try:
        # -- deterministic learnable dataset (banded images, datarepo) -------
        rng = np.random.default_rng(seed)
        data_path = os.path.join(tmp, "data.bin")
        json_path = os.path.join(tmp, "data.json")
        frames = []
        for i in range(n_train + n_hold):
            label = i % classes
            img = rng.normal(0.2, 0.05, (28, 28, 1)).astype(np.float32)
            img[label * 5 : label * 5 + 4, :, :] += 0.8
            frames.append((img, np.int32([label])))
        wpipe = parse_pipeline(
            f"appsrc name=src ! datareposink location={data_path} "
            f"json={json_path}"
        )
        wpipe.start()
        for img, label in frames:
            wpipe["src"].push([img, label])
        wpipe["src"].end_of_stream()
        wpipe.wait(timeout=60)
        wpipe.stop()

        cfg = {
            "arch": "mnist_cnn", "arch_props": {"classes": str(classes)},
            "optimizer": "adam", "learning_rate": 3e-3,
            "batch_size": batch, "loss": "softmax_ce",
        }
        cfg_path = os.path.join(tmp, "cfg.json")
        with open(cfg_path, "w") as f:
            json.dump(cfg, f)

        def backend_props(ck_dir: str, resume: bool = False):
            return {
                "model-config": json.dumps(cfg), "num-inputs": 1,
                "num-labels": 1, "num-training-samples": n_train,
                "num-validation-samples": 0, "epochs": epochs,
                "checkpoint-path": ck_dir, "checkpoint-interval": 1,
                "checkpoint-keep": 0, "resume": resume,
            }

        def feed(tr) -> None:
            # the deterministic datarepo replay, at API grain: every
            # frame carries the (epoch, sample_index) meta the resume
            # fast-forward keys on
            for ep in range(epochs):
                for i in range(n_train):
                    fr = TensorFrame([frames[i][0], frames[i][1]])
                    fr.meta["epoch"] = ep
                    fr.meta["sample_index"] = i
                    tr.push_data(fr)
            tr.end_of_data()

        def run_backend(ck_dir: str, resume: bool = False) -> JaxTrainer:
            tr = JaxTrainer()
            tr.create(backend_props(ck_dir, resume))
            tr.start()
            feed(tr)
            tr._thread.join(timeout=300)
            return tr

        # -- phase 1: kill mid-epoch, resume exactly -------------------------
        ck_ctl, ck_chaos = os.path.join(tmp, "ck_ctl"), os.path.join(tmp, "ck")
        control = run_backend(ck_ctl)
        checks["control_clean"] = (
            control.error is None and control.status.epoch_count == epochs
            and ckpt.latest_step(ck_ctl) == epochs
        )
        # fire on the 6th optimizer step: mid-epoch-2, after the epoch-1
        # checkpoint committed — the torn tail past it must be discarded
        FAULTS.arm("trainer.step", exc=RuntimeError("chaos: kill mid-epoch"),
                   after=steps_per_epoch + 1, times=1)
        killed = run_backend(ck_chaos)
        FAULTS.reset()
        durable = ckpt.latest_step(ck_chaos)
        checks["killed_mid_epoch"] = killed.error is not None
        checks["durable_is_epoch1"] = durable == 1
        resumed = run_backend(ck_chaos, resume=True)
        checks["resume_clean"] = (
            resumed.error is None and resumed.resumes == 1
            and resumed.status.epoch_count == epochs
            and ckpt.latest_step(ck_chaos) == epochs
        )
        # zero samples retrained: epoch 1 is skipped via the cursor, and
        # the (epoch, sample_index) ledger holds no duplicates
        checks["replay_exact"] = (
            resumed.replay_skipped == n_train
            and resumed.gap_samples == 0
            and len(resumed.trained_log) == len(set(resumed.trained_log))
            and all(ep >= 1 for ep, _ in resumed.trained_log)
        )
        # bit-identical at checkpoint grain: restore the final state of
        # both runs and compare every leaf exactly
        import jax
        import optax

        fn0, template, _, _ = zoo.build("mnist_cnn",
                                        {"classes": str(classes)})
        opt_template = jax.jit(optax.adam(cfg["learning_rate"]).init)(template)
        tpl = {"params": template, "opt_state": opt_template}
        leaves_a = jax.tree_util.tree_leaves(
            ckpt.restore_state(ck_ctl, epochs, tpl))
        leaves_b = jax.tree_util.tree_leaves(
            ckpt.restore_state(ck_chaos, epochs, tpl))
        bitwise = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(leaves_a, leaves_b)
        ) and len(leaves_a) == len(leaves_b)
        checks["params_bit_identical"] = bitwise
        v["resume"] = {
            "durable_step_after_kill": durable,
            "resumed_at_step": resumed.resumed_at,
            "replay_skipped": resumed.replay_skipped,
            "final_steps": resumed.steps,
            "params_bit_identical": bitwise,
        }

        # -- phase 2: the closed loop — gate, promote, refuse, roll back -----
        base_path = os.path.join(tmp, "base.msgpack")
        from flax import serialization

        atomic_write_bytes(base_path, serialization.to_bytes(template))
        ck_loop = os.path.join(tmp, "ck_loop")
        promoted_path = os.path.join(tmp, "promoted.msgpack")
        pipe = parse_pipeline(
            f"datareposrc name=data location={data_path} json={json_path} "
            f"stop-sample-index={n_train - 1} epochs={epochs} ! "
            f"tensor_trainer name=train framework=jax model-config={cfg_path} "
            f"num-inputs=1 num-labels=1 num-training-samples={n_train} "
            f"epochs={epochs} checkpoint-path={ck_loop} "
            "checkpoint-interval=1 checkpoint-keep=0 ! "
            f"model_validator name=gate checkpoint-path={ck_loop} "
            f"model-config={cfg_path} data-location={data_path} "
            f"data-json={json_path} holdout-start={n_train} metric=accuracy "
            f"target=serve promote-path={promoted_path} ! "
            "tensor_sink name=tstats "
            f"appsrc name=src ! tensor_filter name=serve framework=jax-xla "
            f"model={base_path} custom=arch:mnist_cnn,classes:{classes} "
            "is-updatable=true staged-reload=true observation-window=3 "
            "rollback-error-burst=3 ! tensor_sink name=out"
        )
        pipe.start()
        pushed = 0

        def pump(until, deadline_s: float, tag: str) -> None:
            nonlocal pushed
            deadline = time.monotonic() + deadline_s
            while time.monotonic() < deadline:
                pipe["src"].push(frames[pushed % len(frames)][0])
                pushed += 1
                if until():
                    return
                time.sleep(0.02)
            raise TimeoutError(f"train chaos: {tag} not reached")

        gate, serve = pipe["gate"], pipe["serve"]
        # ...and let the post-swap observation window close on clean
        # frames — the NEXT swap must not inherit an open window
        pump(lambda: gate.promotions >= 1
             and serve.health_info()["model_version"] >= 1
             and serve.health_info()["swap_state"] == "idle",
             180.0, "good promotion")
        h = serve.health_info()
        checks["good_promotion"] = (
            gate.validations >= 1 and gate.promotions == 1
            and h["model_version"] == 1 and h["rollbacks"] == 0
        )
        good_score = gate.best_score
        v["promotion"] = {
            "validations": gate.validations, "score": good_score,
            "model_version": h["model_version"],
        }
        # a regressed candidate: the UNTRAINED params, planted as a newer
        # durable checkpoint — the gate must refuse it
        ckpt.save_state(ck_loop, 90, {"params": template,
                                      "opt_state": opt_template},
                        meta={"cursor": {"unit": "epoch", "step": 0,
                                         "epoch": 90}})
        gate.handle_frame(None, TensorFrame([np.zeros(5, np.float64)]))
        h = serve.health_info()
        checks["regression_refused"] = (
            gate.promotions_refused == 1 and gate.promotions == 1
            and h["model_version"] == 1
        )
        v["refusal"] = {"refused": gate.promotions_refused,
                        "score": gate.val_score, "best": gate.best_score}
        # a candidate that validates clean but error-bursts in serving:
        # re-plant the promoted (good) params as a newer checkpoint, arm
        # the post-swap observation fault — the window must roll back,
        # and the retained old model must serve every faulted frame
        with open(promoted_path, "rb") as f:
            good_params = serialization.from_bytes(template, f.read())
        ckpt.save_state(ck_loop, 91, {"params": good_params,
                                      "opt_state": opt_template},
                        meta={"cursor": {"unit": "epoch", "step": 0,
                                         "epoch": 91}})
        FAULTS.arm("filter.reload.post",
                   exc=RuntimeError("chaos: bad rollout"), times=3)
        gate.handle_frame(None, TensorFrame([np.zeros(5, np.float64)]))
        pump(lambda: serve.health_info()["rollbacks"] >= 1,
             120.0, "rollback")
        FAULTS.reset()
        # settle the serving chain, then the ledger must balance exactly
        pipe["src"].end_of_stream()
        pipe.wait(timeout=120)
        h = serve.health_info()
        served = len(pipe["out"].frames)
        train_h = pipe["train"].health_info()
        checks["rollback_exact"] = (
            h["rollbacks"] == 1 and h["swaps"] == 2
            and h["model_version"] == 1 and gate.promotions == 2
        )
        checks["zero_frame_loss"] = served == pushed
        checks["trainer_accounting"] = (
            train_h["train_epochs"] == epochs
            and train_h["train_steps"] == epochs * steps_per_epoch
            and train_h["train_checkpoints"] == epochs
            and train_h["train_samples"] == epochs * n_train
        )
        v["rollback"] = {"rollbacks": h["rollbacks"], "swaps": h["swaps"],
                         "model_version": h["model_version"],
                         "served": served, "pushed": pushed}
        pipe.stop()

        # -- phase 3: memory pressure pauses training, serving lives on ------
        ck_p = os.path.join(tmp, "ck_pause")
        pressure = {"on": True}
        pipe2 = parse_pipeline(
            f"datareposrc name=data location={data_path} json={json_path} "
            f"stop-sample-index={n_train - 1} epochs=2 ! "
            f"tensor_trainer name=train framework=jax model-config={cfg_path} "
            f"num-inputs=1 num-labels=1 num-training-samples={n_train} "
            f"epochs=2 checkpoint-path={ck_p} checkpoint-interval=1 ! "
            "tensor_sink name=tsink "
            f"appsrc name=src ! tensor_filter name=serve framework=jax-xla "
            f"model={base_path} custom=arch:mnist_cnn,classes:{classes} ! "
            "tensor_sink name=out"
        )
        pipe2.enable_memory_monitor(
            high=0.90, low=0.75, sustain_s=0.0, min_poll_s=0.05,
            sample=lambda: ((95, 100, 0) if pressure["on"] else (10, 100, 0)),
        )
        pipe2.start()
        trainer2 = pipe2["train"]
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            if trainer2.health_info()["train_paused"]:
                break
            time.sleep(0.02)
        th = trainer2.health_info()
        checks["pressure_paused"] = th["train_paused"] == 1 and th["train_pauses"] == 1
        steps_frozen = th["train_steps"]
        served_during_pause = 0
        for _ in range(30):  # co-hosted serving must not starve
            pipe2["src"].push(frames[0][0])
            served_during_pause += 1
            time.sleep(0.01)
        deadline = time.monotonic() + 30
        while (len(pipe2["out"].frames) < served_during_pause
               and time.monotonic() < deadline):
            time.sleep(0.02)
        th = trainer2.health_info()
        checks["paused_is_frozen"] = (
            th["train_steps"] == steps_frozen and th["train_paused"] == 1
        )
        checks["serving_alive_under_pressure"] = (
            len(pipe2["out"].frames) == served_during_pause
        )
        pressure["on"] = False  # clears: training resumes, zero loss
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            th = trainer2.health_info()
            if th["train_epochs"] == 2 and not th["train_alive"]:
                break
            time.sleep(0.05)
        checks["pause_resumed_zero_loss"] = (
            th["train_epochs"] == 2 and th["train_paused"] == 0
            and th["train_samples"] == 2 * n_train
            and th["train_pauses"] == 1
        )
        v["pressure"] = {
            "pauses": th["train_pauses"],
            "steps_at_pause": steps_frozen,
            "served_while_paused": served_during_pause,
            "samples_trained": th["train_samples"],
        }
        pipe2["src"].end_of_stream()
        pipe2.wait(timeout=60)
        pipe2.stop()

        v["checks"] = checks
        v["ok"] = all(checks.values())
        return v
    finally:
        FAULTS.reset()
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    import argparse

    import jax

    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--servers", type=int, default=3)
    ap.add_argument("--frames", type=int, default=30,
                    help="frames per tenant per wave")
    ap.add_argument("--keys", type=int, default=120,
                    help="distinct affinity sessions")
    ap.add_argument("--mode",
                    choices=("unary", "generate", "generate-resume",
                             "device-loss", "observatory", "autoscale",
                             "partition", "prefix", "train"),
                    default="unary",
                    help="unary request fleet (default), long-lived "
                    "generation-stream fleet (continuous batching), "
                    "the durable-stream chaos: hard kill + rolling "
                    "restart at seeded random decode points with "
                    "checkpointed resume / live migration, the "
                    "device-loss chaos: a mesh member dies mid-decode "
                    "— streams hand off resumably, the engine "
                    "re-meshes, the server announces degraded, or the "
                    "observatory chaos: digest-publishing fleet under "
                    "rolling restart + hot-tenant burst + crash, with "
                    "exact fleet-rollup-vs-ledger cross-checks, or the "
                    "autoscale chaos: a live FleetController closes the "
                    "loop — load ramp + hot-tenant burst drive scale-up, "
                    "an envelope shrink forces a zero-loss scale-down "
                    "under live load (streams migrate bit-identically), "
                    "or the partition chaos: broker death/restart "
                    "mid-load, a partitioned server subset, and two "
                    "leased controllers — fail-static freezes, fenced "
                    "takeover, exact stale-epoch rejects, or the "
                    "shared-prefix cache chaos: N clients share one "
                    "prompt prefix, prefix-affinity routes them to the "
                    "warm owner, a mid-decode rolling restart forces "
                    "bit-exact cache-cold failover and a re-warm, with "
                    "exact hit/miss ledgers and observatory rollups, or "
                    "the continuous-learning chaos: a trainer killed "
                    "mid-epoch resumes bit-exactly from the durable "
                    "checkpoint, the validation gate refuses a regressed "
                    "candidate, a bad promotion rolls back with zero "
                    "frame loss, and injected memory pressure pauses "
                    "training while co-hosted serving lives on")
    ap.add_argument("--streams", type=int, default=12,
                    help="generation streams per client (--mode "
                    "generate) or concurrent streams (generate-resume)")
    ap.add_argument("--seed", type=int, default=0,
                    help="seed for the generate-resume decode points")
    args = ap.parse_args()
    if args.mode == "generate":
        verdict = run_generate_script(max(1, min(args.servers, 4)),
                                      args.streams)
    elif args.mode == "generate-resume":
        verdict = run_generate_resume_script(
            max(2, min(args.servers, 4)), max(2, args.streams),
            args.seed)
    elif args.mode == "device-loss":
        verdict = run_device_loss_script(
            max(2, min(args.servers, 4)), max(2, args.streams),
            args.seed)
    elif args.mode == "observatory":
        verdict = run_observatory_script(
            max(2, min(args.servers, 4)), max(2, args.streams))
    elif args.mode == "autoscale":
        verdict = run_autoscale_script(1, max(2, args.streams))
    elif args.mode == "partition":
        verdict = run_partition_script(
            max(2, min(args.servers, 4)), max(2, min(args.streams, 8)),
            args.seed)
    elif args.mode == "prefix":
        verdict = run_prefix_script(
            max(2, min(args.servers, 4)), max(2, min(args.streams, 12)),
            args.seed)
    elif args.mode == "train":
        verdict = run_train_script(args.seed)
    else:
        verdict = run_default_script(args.servers, args.frames, args.keys)
    print(json.dumps(verdict, indent=1, sort_keys=True))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
