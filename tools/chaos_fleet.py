#!/usr/bin/env python
"""Deterministic fleet chaos harness: scripted membership churn under
continuous multi-tenant load.

Drives N query servers + M tenant clients through the failure classes a
serving fleet actually sees — hard kill, rolling restart (GOAWAY drain,
PR-5), server join, hot-tenant burst — and computes an exact verdict:
zero lost/duplicated frames, per-tenant delivered/shed accounting,
breaker-trip census, and consistent-hash affinity remap counts.

Everything is scripted and event-ordered (actions run between push
waves, never on wall-clock timers), so the same script asserts the same
contracts in CI (the chaos-marked e2e in ``tests/test_fleet.py``) and at
the terminal::

    python tools/chaos_fleet.py            # default 3-server script
    python tools/chaos_fleet.py --servers 4 --keys 200 --frames 30

Fleet membership travels over the hybrid MQTT discovery plane (an
in-process :class:`MiniBroker`): servers announce retained endpoints
(with their live ``draining`` state — Documentation/resilience.md),
clients resolve the pool from the broker.  Because this is a CHAOS
harness, membership refreshes can also be forced between waves
(:meth:`FleetHarness.refresh_client`) instead of waiting for a failure
wave to trigger elastic rediscovery — scripted churn must not depend on
luck."""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _median(xs: List[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[len(s) // 2]


class ClientHandle:
    """One tenant's client pipeline: appsrc -> tensor_query_client ->
    tensor_sink, plus the exact push ledger the verdict checks against."""

    def __init__(self, harness: "FleetHarness", name: str, pipe,
                 tenant: str):
        self._h = harness
        self.name = name
        self.tenant = tenant
        self.pipe = pipe
        self.pushed: List[float] = []

    @property
    def element(self):
        return self.pipe["q"]

    def push(self, value: float, key: Optional[str] = None,
             meta: Optional[Dict[str, Any]] = None) -> None:
        import numpy as np

        from nnstreamer_tpu.core.buffer import TensorFrame

        m = dict(meta or {})
        if key is not None:
            m[self._h.affinity_key] = key
        self.pipe["src"].push(TensorFrame([np.float32([value])], meta=m))
        self.pushed.append(float(value))

    def settle(self, timeout: float = 30.0) -> None:
        """Wait until every pushed frame has been answered (or counted
        degraded) WITHOUT ending the stream — the load stays continuous
        across chaos actions, and phase-boundary counters are exact."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            answered = len(self.pipe["out"].frames)
            degraded = int(self.health().get("degraded_frames", 0))
            if answered + degraded >= len(self.pushed):
                return
            time.sleep(0.01)
        raise TimeoutError(
            f"client {self.name}: {len(self.pushed)} pushed but only "
            f"{len(self.pipe['out'].frames)} answered after {timeout}s")

    def values(self) -> List[float]:
        return [float(f.tensors[0][0]) for f in self.pipe["out"].frames]

    def spans_ms(self) -> List[float]:
        """Per-answer end-to-end latencies from the trace-span meta."""
        from nnstreamer_tpu.core.telemetry import SPAN_META

        out = []
        for f in self.pipe["out"].frames:
            span = f.meta.get(SPAN_META)
            if span:
                out.append(float(span["total"]) * 1e3)
        return out

    def health(self) -> Dict[str, Any]:
        return self.pipe.health()["q"]

    def finish(self, timeout: float = 60.0) -> None:
        self.pipe["src"].end_of_stream()
        self.pipe.wait(timeout=timeout)

    def stop(self) -> None:
        self.pipe.stop()


class FleetHarness:
    """N query servers + M tenant clients on one hybrid discovery plane.

    Servers are ``serversrc ! identity sleep= ! scaler x2 !
    serversink`` pipelines announcing on ``nns/query/<topic>/``;
    clients resolve the pool from the broker.  ``expected(values)`` for
    every answered frame is ``value * 2``."""

    def __init__(self, topic: str = "chaosfleet", connect_type: str = "tcp",
                 server_sleep: float = 0.01, max_inflight: int = 32,
                 tenant_quotas: str = "", shed_window_s: float = 5.0,
                 affinity_key: str = "sess", base_id: int = 9600):
        from nnstreamer_tpu.distributed.mqtt import MiniBroker

        self.topic = topic
        self.connect_type = connect_type
        self.server_sleep = server_sleep
        self.max_inflight = max_inflight
        self.tenant_quotas = tenant_quotas
        self.shed_window_s = shed_window_s
        self.affinity_key = affinity_key
        self.base_id = base_id
        self.broker = MiniBroker()
        self.servers: Dict[int, Any] = {}   # idx -> pipeline (live only)
        self.ports: Dict[int, int] = {}     # idx -> port (survives kills)
        self.clients: List[ClientHandle] = []
        # per-tenant counters of servers that LEFT the fleet, captured at
        # kill time so fleet-wide accounting stays exact across churn
        self.retired_tenants: List[Dict[str, Any]] = []

    # -- servers ------------------------------------------------------------
    def start_server(self, idx: int, port: int = 0):
        from nnstreamer_tpu.pipeline.parser import parse_pipeline

        quotas = (f"tenant-quotas={self.tenant_quotas} "
                  if self.tenant_quotas else "")
        pipe = parse_pipeline(
            f"tensor_query_serversrc name=ssrc id={self.base_id + idx} "
            f"port={port} connect-type={self.connect_type} "
            f"topic={self.topic} dest-host=127.0.0.1 "
            f"dest-port={self.broker.port} "
            f"max-inflight={self.max_inflight} {quotas}"
            f"shed-window={self.shed_window_s} ! "
            f"identity sleep={self.server_sleep} ! "
            "tensor_filter framework=scaler custom=factor:2 ! "
            f"tensor_query_serversink id={self.base_id + idx}",
            name=f"server{idx}",
        )
        pipe.start()
        self.servers[idx] = pipe
        self.ports[idx] = pipe["ssrc"].props["port"]
        return pipe

    def kill_server(self, idx: int) -> None:
        """Hard stop: no drain, no GOAWAY — in-flight requests die with
        their sockets (the announce is tombstoned by element stop)."""
        pipe = self.servers.pop(idx)
        self.retired_tenants.append(self.server_tenant_rows(pipe))
        pipe.stop()

    def rolling_restart(self, idx: int, drain_timeout: float = 15.0) -> Dict[str, Any]:
        """PR-5 zero-downtime roll: drain (GOAWAY to new requests,
        in-flight finish), stop, restart on the SAME port."""
        pipe = self.servers[idx]
        res = pipe.drain(timeout=drain_timeout)
        health = pipe.health()["ssrc"]
        self.retired_tenants.append(self.server_tenant_rows(pipe))
        pipe.stop()
        self.servers.pop(idx)
        self.start_server(idx, port=self.ports[idx])
        return {"drain": res, "health": health}

    def add_server(self) -> int:
        idx = (max(self.ports) + 1) if self.ports else 0
        self.start_server(idx)
        return idx

    @staticmethod
    def server_tenant_rows(pipe) -> Dict[str, Any]:
        return {
            t: dict(row)
            for t, row in pipe.health()["ssrc"].get("tenants", {}).items()
        }

    def fleet_tenants(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant {admitted, shed} summed over every server that is
        or ever was in the fleet (retired servers contribute their
        last-observed counters)."""
        total: Dict[str, Dict[str, int]] = {}
        rows = [self.server_tenant_rows(p) for p in self.servers.values()]
        rows.extend(self.retired_tenants)
        for by_tenant in rows:
            for t, row in by_tenant.items():
                agg = total.setdefault(t, {"admitted": 0, "shed": 0})
                agg["admitted"] += int(row.get("admitted", 0))
                agg["shed"] += int(row.get("shed", 0))
        return total

    # -- clients ------------------------------------------------------------
    def make_client(self, name: str, tenant: str = "",
                    routing: str = "least-inflight", priority: int = 3,
                    affinity: bool = False, retries: int = 3,
                    busy_retries: int = 8, breaker_threshold: int = 8,
                    max_in_flight: int = 4, timeout: float = 5.0,
                    degrade: str = "error",
                    discovery_timeout: float = 10.0,
                    static_hosts: bool = False) -> ClientHandle:
        from nnstreamer_tpu.pipeline.parser import parse_pipeline

        akey = f"affinity-key={self.affinity_key} " if affinity else ""
        tprop = f"tenant={tenant} " if tenant else ""
        if static_hosts:
            # pinned membership (no discovery, no elastic rediscovery):
            # the burst client of the e2e uses this so every push maps
            # to EXACTLY one admission attempt — exact shed accounting
            hosts = ",".join(
                f"localhost:{self.ports[i]}" for i in sorted(self.servers))
            plane = f"hosts={hosts} "
        else:
            plane = (
                f"topic={self.topic} dest-host=127.0.0.1 "
                f"dest-port={self.broker.port} "
                f"discovery-timeout={discovery_timeout} ")
        pipe = parse_pipeline(
            "appsrc name=src max-buffers=1024 ! "
            f"tensor_query_client name=q connect-type={self.connect_type} "
            f"{plane}"
            f"routing={routing} {akey}{tprop}priority={priority} "
            f"retries={retries} busy-retries={busy_retries} "
            f"breaker-threshold={breaker_threshold} retry-backoff=0.02 "
            f"max-in-flight={max_in_flight} timeout={timeout} "
            f"degrade={degrade} ! "
            "tensor_sink name=out",
            name=f"client-{name}",
        )
        pipe.start()
        handle = ClientHandle(self, name, pipe, tenant)
        self.clients.append(handle)
        return handle

    def refresh_client(self, handle: ClientHandle) -> bool:
        """Force one elastic rediscovery NOW (scripted membership churn;
        production clients refresh on failure waves instead).  Returns
        True when the pool actually swapped."""
        el = handle.element
        el._last_discovery_ts = float("-inf")  # skip the churn cooldown
        return el._rediscover(el._pstate)

    # -- verdict ------------------------------------------------------------
    @staticmethod
    def check_exact(handle: ClientHandle) -> Dict[str, Any]:
        """Zero-lost / zero-duplicated check for one client: every pushed
        value answered exactly once as value*2 (minus frames the client
        itself dropped under degrade=skip, which it counts)."""
        got = sorted(handle.values())
        degraded = int(handle.health().get("degraded_frames", 0))
        want = sorted(v * 2.0 for v in handle.pushed)
        lost = dup = 0
        if degraded == 0:
            from collections import Counter

            cw, cg = Counter(want), Counter(got)
            lost = sum((cw - cg).values())
            dup = sum((cg - cw).values())
        else:
            # degrade=skip clients: delivered subset must still be
            # duplicate-free and correct
            from collections import Counter

            cg = Counter(got)
            cw = Counter(want)
            dup = sum((cg - cw).values())
            lost = sum((cw - cg).values()) - degraded
        return {
            "pushed": len(handle.pushed), "answered": len(got),
            "degraded": degraded, "lost": lost, "duplicated": dup,
        }

    def breaker_trips(self) -> int:
        trips = 0
        for c in self.clients:
            h = c.health()
            trips += int(h.get("breaker_trips_evicted", 0))
            for snap in h.get("breakers", {}).values():
                trips += int(snap.get("trips", 0))
        return trips

    def verdict(self) -> Dict[str, Any]:
        per_client = {c.name: self.check_exact(c) for c in self.clients}
        p50 = {
            c.name: round(_median(c.spans_ms()), 3) for c in self.clients
        }
        return {
            "clients": per_client,
            "p50_ms": p50,
            "tenants": self.fleet_tenants(),
            "breaker_trips": self.breaker_trips(),
            "goaway_replies": sum(
                int(c.health().get("goaway_replies", 0))
                for c in self.clients),
            "affinity_remaps": {
                c.name: int(c.health().get("affinity_remaps", 0))
                for c in self.clients
            },
            "lost": sum(r["lost"] for r in per_client.values()),
            "duplicated": sum(r["duplicated"] for r in per_client.values()),
        }

    def stop_all(self) -> None:
        for c in self.clients:
            try:
                c.stop()
            except Exception:  # allow-silent: teardown best-effort
                pass
        for pipe in list(self.servers.values()):
            try:
                pipe.stop()
            except Exception:  # allow-silent: teardown best-effort
                pass
        self.servers.clear()
        self.broker.close()


# ---------------------------------------------------------------------------
# The default script (CLI mode; the e2e in tests/test_fleet.py pins the
# same phases with exact assertions)
# ---------------------------------------------------------------------------
def run_default_script(servers: int = 3, frames: int = 30,
                       keys: int = 120) -> Dict[str, Any]:
    import math

    h = FleetHarness(tenant_quotas="A:6,B:2", server_sleep=0.01)
    try:
        for i in range(servers):
            h.start_server(i)
        ca = h.make_client("A", tenant="A", routing="least-inflight")
        cb = h.make_client("B", tenant="B", routing="ewma", busy_retries=12)
        ck = h.make_client("K", affinity=True, routing="rotate")
        seq = iter(range(10**6))
        key_names = [f"sess-{k}" for k in range(keys)]

        def wave(tag: str, n: int = frames) -> None:
            for _ in range(n):
                ca.push(next(seq))
                cb.push(10_000 + next(seq))
            for k in key_names:
                ck.push(20_000 + next(seq), key=k)
            for c in (ca, cb, ck):
                c.settle()

        wave("baseline")
        roll = h.rolling_restart(0)
        wave("after-roll")
        joined = h.add_server()
        h.refresh_client(ck)
        remaps_before = ck.health()["affinity_remaps"]
        wave("after-join")
        remap_join = ck.health()["affinity_remaps"] - remaps_before
        h.kill_server(servers - 1)
        for c in (ca, cb, ck):
            h.refresh_client(c)
        wave("after-kill")
        for c in (ca, cb, ck):
            c.finish()
        v = h.verdict()
        v["rolling_restart"] = {
            "goaway_sent": roll["health"].get("goaway_sent", 0),
            "drain_dropped": roll["drain"]["dropped"],
        }
        v["remap_join"] = remap_join
        v["remap_join_bound"] = math.ceil(keys / max(1, len(h.servers)))
        v["joined_server"] = joined
        v["ok"] = (
            v["lost"] == 0 and v["duplicated"] == 0
            and v["breaker_trips"] == 0
            and remap_join <= v["remap_join_bound"]
        )
        return v
    finally:
        h.stop_all()


def main() -> int:
    import argparse

    import jax

    jax.config.update("jax_platforms", "cpu")
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--servers", type=int, default=3)
    ap.add_argument("--frames", type=int, default=30,
                    help="frames per tenant per wave")
    ap.add_argument("--keys", type=int, default=120,
                    help="distinct affinity sessions")
    args = ap.parse_args()
    verdict = run_default_script(args.servers, args.frames, args.keys)
    print(json.dumps(verdict, indent=1, sort_keys=True))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
