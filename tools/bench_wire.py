#!/usr/bin/env python
"""Measure the wire-integrity tax: checksum-on vs checksum-off overhead.

Emits one row per payload size comparing the three envelope modes:

* ``v1``        — legacy frames, no checksum (encode + decode);
* ``v2``        — checksummed frames, verify ON at decode (the default
                  data plane after ISSUE 4);
* ``v2_noverify`` — checksummed encode, verification skipped at decode
                  (the ``verify-checksum=false`` element property).

Reported as round trips/s plus the derived integrity tax (percent
throughput lost v1 -> v2) and the effective CRC bandwidth, so the cost
is measured, not guessed (Documentation/wire-protocol.md "Cost").
BENCH_WIRE_FRAMES / BENCH_WIRE_SIZES override the defaults; --out
writes the rows as JSON (BENCH_WIRE.json convention).

The decode path is zero-copy, so the checksum pass dominates at large
payloads — the honest framing of this number is GB/s of CRC, not a
relative slowdown of an otherwise-nearly-free decode.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nnstreamer_tpu.core.buffer import TensorFrame  # noqa: E402
from nnstreamer_tpu.distributed import wire  # noqa: E402


def _roundtrip_rate(frame, version: int, verify: bool, n: int) -> float:
    buf = wire.encode_frame(frame, version=version)
    # warm-up (allocator, caches)
    for _ in range(3):
        wire.decode_frame(wire.encode_frame(frame, version=version),
                          verify=verify)
    t0 = time.perf_counter()
    for _ in range(n):
        buf = wire.encode_frame(frame, version=version)
        wire.decode_frame(buf, verify=verify)
    dt = time.perf_counter() - t0
    return n / dt, len(buf)


def run(sizes, n_frames) -> list:
    rows = []
    for size in sizes:
        elems = max(1, size // 4)
        frame = TensorFrame(
            [np.arange(elems, dtype=np.float32)], pts=0.5, meta={"b": 1})
        n = max(20, min(n_frames, int(4e8 // max(size, 1))))
        v1_fps, nbytes = _roundtrip_rate(frame, 1, True, n)
        v2_fps, _ = _roundtrip_rate(frame, 2, True, n)
        v2nv_fps, _ = _roundtrip_rate(frame, 2, False, n)
        # two CRC passes per round trip (encode + verify)
        crc_s = (1.0 / v2_fps) - (1.0 / v2nv_fps)  # verify pass alone
        rows.append({
            "payload_bytes": nbytes,
            "iters": n,
            "v1_rps": round(v1_fps, 1),
            "v2_rps": round(v2_fps, 1),
            "v2_noverify_rps": round(v2nv_fps, 1),
            "integrity_tax_pct": round(100.0 * (1.0 - v2_fps / v1_fps), 2),
            "verify_crc_mb_s": (
                round(nbytes / crc_s / 1e6, 1) if crc_s > 1e-9 else None),
        })
    return rows


def measure_crc_bandwidth(size: int = 1 << 20, n_frames: int = 400) -> float:
    """Effective verify-pass CRC bandwidth in MB/s at one payload size
    (default 1 MiB, where the checksum pass dominates the zero-copy
    decode).  Shared by the wire-integrity bench rows and the perf-truth
    baseline (tools/perf_truth.py), so the published number and the
    regression-gated one measure the SAME harness.  Returns 0.0 when the
    verify pass is too cheap to resolve."""
    rows = run([int(size)], n_frames)
    return float(rows[0]["verify_crc_mb_s"] or 0.0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="", help="write rows as JSON here")
    args = ap.parse_args(argv)
    sizes = [int(s) for s in os.environ.get(
        "BENCH_WIRE_SIZES", "4096,153600,1048576").split(",")]
    n_frames = int(os.environ.get("BENCH_WIRE_FRAMES", "2000"))
    rows = run(sizes, n_frames)
    for r in rows:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"bench": "wire_checksum_overhead", "rows": rows}, f,
                      indent=2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
