#!/usr/bin/env python
"""Generate Documentation/element-reference.md from the element registry.

≙ the reference's hand-written ``Documentation/component-description.md``,
but derived from the live Property tables so it cannot drift (CI re-runs
this and fails on diff).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # never dial an accelerator


def main(out_path: str) -> None:
    from nnstreamer_tpu import elements  # noqa: F401 — registers factories
    from nnstreamer_tpu.pipeline.element import ELEMENT_TYPES

    lines = [
        "# Element reference",
        "",
        "Every pipeline element and its properties, generated from the",
        "registry (`tools/gen_element_docs.py`; do not edit by hand).",
        "Reference analog: `Documentation/component-description.md`.",
        "",
    ]
    by_factory = {}
    aliases = {}
    for name, cls in sorted(ELEMENT_TYPES.items()):
        if cls.FACTORY_NAME == name:
            by_factory[name] = cls
        else:
            aliases.setdefault(cls.FACTORY_NAME, []).append(name)
    for name, cls in sorted(by_factory.items()):
        header = f"## `{name}`"
        if name in aliases:
            header += "  (aliases: " + ", ".join(
                f"`{a}`" for a in sorted(aliases[name])
            ) + ")"
        lines.append(header)
        lines.append("")
        doc = (cls.__doc__ or "").strip().splitlines()
        if doc:
            lines.append(doc[0].strip())
            lines.append("")
        props = getattr(cls, "PROPERTIES", {})
        if props:
            lines.append("| property | type | default | description |")
            lines.append("|---|---|---|---|")
            for pname, prop in props.items():
                desc = (prop.doc or "").replace("|", "\\|")
                default = repr(prop.default)
                lines.append(
                    f"| `{pname}` | {prop.type.__name__} | {default} | {desc} |"
                )
            lines.append("")
        else:
            lines.append("(no properties)")
            lines.append("")
    with open(out_path, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out_path}: {len(by_factory)} elements")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "Documentation/element-reference.md")
