#!/usr/bin/env python
"""fleet_top: live fleet dashboard over the discovery-plane telemetry
digests (Documentation/observability.md "Fleet observatory").

Subscribes a :class:`FleetObservatory` to the retained announces under
``nns/query/<topic>/#`` and renders the fleet: one row per live server
(state, digest seq/staleness, inflight, slot occupancy, tokens/s,
memory headroom, per-server shed) under a rollup header (aggregate
tokens/s, weighted occupancy, admittable-slot headroom, per-tenant
admitted/shed, SLO burn).  No server-side changes needed — servers
publish digests whenever ``digest-interval`` > 0 and they announce.

Modes::

    python tools/fleet_top.py --broker-port 1883 --topic prod           # one-shot table
    python tools/fleet_top.py --broker-port 1883 --topic prod --json    # one-shot JSON (scripts)
    python tools/fleet_top.py --broker-port 1883 --topic prod --watch   # live terminal view
    python tools/fleet_top.py ... --metrics-port 9464                   # + Prometheus endpoint
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, Dict, List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _fmt_bytes(n: int) -> str:
    v = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(v) < 1024 or unit == "TiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}TiB"


def _server_state(row: Dict[str, Any]) -> str:
    if row.get("stale"):
        # flagged by the observatory: this digest outlived the publish
        # cadence — every other field in the row is old news, and the
        # autoscale controller already excludes it from headroom math
        return "stale"
    if row.get("draining"):
        return "draining"
    if row.get("degraded"):
        return "degraded"
    if row.get("swap", "idle") != "idle":
        return f"swap:{row['swap']}"
    if row.get("mem_pressure"):
        return "mem-pressure"
    return "serving"


def render(snapshot: Dict[str, Any], topic: str) -> str:
    """The terminal view: rollup header + one aligned row per server.
    Pure function of the snapshot (unit-testable without a broker)."""
    roll = snapshot["rollup"]
    servers: List[Dict[str, Any]] = snapshot["servers"]
    lines = [
        f"fleet '{topic or '#'}' — {roll['servers']} server(s) live, "
        f"{roll['draining']} draining, {roll['degraded']} degraded, "
        f"{roll.get('stale', 0)} stale, "
        f"{roll['retired']} retired, {roll['stale_evicted']} stale-evicted",
        f"tokens/s {roll['tokens_per_s']:.1f}   occupancy "
        f"{roll['occupancy']:.2f} ({roll['occupied']}/{roll['slots']})   "
        f"slot headroom {roll['slot_headroom']}   mem headroom "
        f"{_fmt_bytes(roll['mem_headroom_bytes'])}   inflight "
        f"{roll['inflight']}",
        f"totals (retired incl.): tokens {roll['tokens']}  admitted "
        f"{roll['admitted']}  shed {roll['shed']}",
    ]
    # control plane: is the view itself trustworthy?  Broker link state
    # + ingest age come from the observatory rollup; lease/freeze state
    # rides the autoscale block when a controller owns this snapshot.
    plane = ("up" if roll.get("plane_connected", 1) else "DOWN")
    cp = (f"control plane: broker {plane}  last ingest "
          f"{roll.get('plane_ingest_age_s', 0.0):.1f}s ago  reconnects "
          f"{roll.get('plane_reconnects', 0)}")
    a = snapshot.get("autoscale") or {}
    if a:
        lease = a.get("lease")
        if lease:
            held = "leader" if lease.get("held") else "standby"
            cp += (f"  lease {lease.get('owner', '?')} "
                   f"epoch {lease.get('epoch', 0)} ({held})")
        level = a.get("plane_level", "ok")
        if level != "ok" or a.get("frozen", 0):
            reasons = ",".join(a.get("plane_reasons", [])) or "-"
            cp += (f"  [{level.upper()}: {reasons}  frozen "
                   f"{a.get('frozen', 0)}]")
    lines.append(cp)
    if roll.get("tenants"):
        parts = [
            f"{t or '<unnamed>'}: {r['admitted']}/{r['shed']}"
            for t, r in sorted(roll["tenants"].items())
        ]
        lines.append("tenants (admitted/shed): " + "  ".join(parts))
    if roll.get("slo_burn"):
        parts = [
            f"{t or '<unnamed>'}: {b:.2f}"
            for t, b in sorted(roll["slo_burn"].items())
        ]
        lines.append("slo burn (worst per tenant): " + "  ".join(parts))
    if roll.get("ttft_p95_ms"):
        lines.append(
            f"ttft p95 (worst tenant, fresh rows): "
            f"{roll['ttft_p95_ms']:.1f}ms")
    if snapshot.get("autoscale"):
        # the controller's decision column (FleetController.snapshot())
        a = snapshot["autoscale"]
        lines.append(
            f"autoscale: target {a.get('target_servers', 0)} server(s)  "
            f"decisions {a.get('decisions', 0)}  inflight "
            f"{len(a.get('inflight', {}))}  model "
            f"{'ready' if a.get('model_ready') else 'warming'} "
            f"({a.get('model_samples', 0)} samples)")
        for d in a.get("recent", []):
            tgt = d.get("target") or "<new>"
            tag = "predictive" if d.get("predictive") else "reactive"
            lines.append(
                f"  [{d.get('status', '?')}] {d.get('kind')} {tgt} "
                f"({tag}) {d.get('reason', '')}")
    lines.append("")
    hdr = (f"{'ADDR':<22}{'STATE':<14}{'SEQ':>6}{'AGE':>7}{'INFL':>6}"
           f"{'SLOTS':>8}{'TOK/S':>9}{'SHED':>7}{'HEADROOM':>10}")
    lines.append(hdr)
    for row in servers:
        occ = (f"{row.get('occupied', 0)}/{row.get('slots', 0)}"
               if row.get("slots") else "-")
        lines.append(
            f"{row['addr']:<22}{_server_state(row):<14}"
            f"{row.get('seq', 0):>6}{row.get('seen_s', 0.0):>6.1f}s"
            f"{row.get('inflight', 0):>6}{occ:>8}"
            f"{row.get('tokens_per_s', 0.0):>9.1f}"
            f"{row.get('shed', 0):>7}"
            f"{_fmt_bytes(row.get('mem_headroom_bytes', 0)):>10}"
        )
    if not servers:
        lines.append("(no live digests — servers down, digests off, or "
                     "wrong topic)")
    return "\n".join(lines)


def main() -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--broker-host", default="localhost")
    ap.add_argument("--broker-port", type=int, required=True)
    ap.add_argument("--topic", default="",
                    help="announce topic (empty = every topic)")
    ap.add_argument("--json", action="store_true",
                    help="print the snapshot as JSON and exit (scripts)")
    ap.add_argument("--watch", action="store_true",
                    help="live terminal view (redraw every --interval)")
    ap.add_argument("--interval", type=float, default=2.0,
                    help="--watch redraw interval, seconds")
    ap.add_argument("--settle", type=float, default=1.0,
                    help="seconds to gather retained announces before "
                    "the first render")
    ap.add_argument("--ttl", type=float, default=10.0,
                    help="fallback staleness TTL for digests that carry "
                    "none, seconds")
    ap.add_argument("--metrics-port", type=int, default=-1,
                    help="also serve /metrics (Prometheus) on this port "
                    "(0 = ephemeral; -1 = off)")
    args = ap.parse_args()

    from nnstreamer_tpu.core.fleet import FleetObservatory

    obs = FleetObservatory(topic=args.topic, default_ttl_s=args.ttl)
    obs.start(args.broker_host, args.broker_port)
    try:
        if args.metrics_port >= 0:
            port = obs.serve_metrics(args.metrics_port)
            print(f"# /metrics on http://127.0.0.1:{port}/metrics",
                  file=sys.stderr)
        time.sleep(max(0.0, args.settle))
        if args.json:
            print(json.dumps(obs.snapshot(), indent=1, sort_keys=True))
            return 0
        if not args.watch:
            print(render(obs.snapshot(), args.topic))
            return 0
        while True:
            # ANSI home+clear-below: redraw without scrollback spam
            sys.stdout.write("\x1b[H\x1b[2J")
            print(time.strftime("%H:%M:%S"))
            print(render(obs.snapshot(), args.topic))
            sys.stdout.flush()
            time.sleep(max(0.2, args.interval))
    except KeyboardInterrupt:
        return 0
    finally:
        obs.stop()


if __name__ == "__main__":
    sys.exit(main())
