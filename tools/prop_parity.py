#!/usr/bin/env python
"""Generate Documentation/prop-parity.md: reference element properties vs
this framework's, with curated annotations for intentional differences.

Reference props are extracted from the reference sources' g_param_spec_*
installs; ours from each element class's PROPERTIES (+COMMON_PROPERTIES).
Run: python tools/prop_parity.py [--check]   (--check: exit 1 if an
unannotated gap appears — used as a CI-style guard)
"""

import os
import re
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REF = "/root/reference"
sys.path.insert(0, ROOT)

# reference element -> source files holding its g_param_spec installs
REF_SOURCES = {
    "tensor_filter": [
        "gst/nnstreamer/tensor_filter/tensor_filter_common.c",
        "gst/nnstreamer/tensor_filter/tensor_filter.c",
    ],
    "tensor_converter": ["gst/nnstreamer/elements/gsttensor_converter.c"],
    "tensor_transform": ["gst/nnstreamer/elements/gsttensor_transform.c"],
    "tensor_decoder": ["gst/nnstreamer/elements/gsttensor_decoder.c"],
    "tensor_if": ["gst/nnstreamer/elements/gsttensor_if.c"],
    "tensor_aggregator": ["gst/nnstreamer/elements/gsttensor_aggregator.c"],
    "tensor_rate": ["gst/nnstreamer/elements/gsttensor_rate.c"],
    "tensor_crop": ["gst/nnstreamer/elements/gsttensor_crop.c"],
    "tensor_mux": ["gst/nnstreamer/elements/gsttensor_mux.c"],
    "tensor_demux": ["gst/nnstreamer/elements/gsttensor_demux.c"],
    "tensor_merge": ["gst/nnstreamer/elements/gsttensor_merge.c"],
    "tensor_split": ["gst/nnstreamer/elements/gsttensor_split.c"],
    "tensor_sink": ["gst/nnstreamer/elements/gsttensor_sink.c"],
    "tensor_query_client": [
        "gst/nnstreamer/tensor_query/tensor_query_client.c"],
    "tensor_query_serversrc": [
        "gst/nnstreamer/tensor_query/tensor_query_serversrc.c"],
    "tensor_query_serversink": [
        "gst/nnstreamer/tensor_query/tensor_query_serversink.c"],
    "tensor_trainer": ["gst/nnstreamer/elements/gsttensor_trainer.c"],
    "datareposrc": ["gst/datarepo/gstdatareposrc.c"],
    "datareposink": ["gst/datarepo/gstdatareposink.c"],
    "edgesink": ["gst/edge/edge_sink.c"],
    "edgesrc": ["gst/edge/edge_src.c"],
    "tensor_sparse_enc": ["gst/nnstreamer/elements/gsttensor_sparseenc.c"],
    "tensor_sparse_dec": ["gst/nnstreamer/elements/gsttensor_sparsedec.c"],
    "tensor_reposink": ["gst/nnstreamer/elements/gsttensor_reposink.c"],
    "tensor_reposrc": ["gst/nnstreamer/elements/gsttensor_reposrc.c"],
    "mqttsink": ["gst/mqtt/mqttsink.c"],
    "mqttsrc": ["gst/mqtt/mqttsrc.c"],
    "tensor_src_iio": ["gst/nnstreamer/elements/gsttensor_srciio.c"],
}

# reference prop -> (our name | None, note).  None = intentionally not a
# property here; the note says where the capability lives instead.
ANNOTATIONS = {
    ("*", "sub-plugins"): (
        None, "read-only discovery list -> `nns-tpu-check` CLI (confchk)"),
    ("tensor_filter", "inputranks"): ("inputranks", "declarative rank fix"),
    ("tensor_filter", "outputranks"): ("outputranks", "declarative rank fix"),
    ("tensor_filter", "inputlayout"): (
        "inputlayout", "validated + recorded; XLA owns physical layout"),
    ("tensor_filter", "outputlayout"): (
        "outputlayout", "validated + recorded; XLA owns physical layout"),
    ("tensor_transform", "transpose-rank-limit"): (
        None, "no rank cap here: transpose handles any rank <= 16"),
    ("tensor_query_client", "dest-host"): (
        None, "broker-discovery addressing; direct host:port + hosts= "
        "round-robin cover the capability (hybrid discovery via edge "
        "elements)"),
    ("tensor_query_client", "dest-port"): (None, "see dest-host"),
    ("tensor_query_client", "topic"): (None, "see dest-host"),
    ("tensor_query_serversrc", "dest-host"): (None, "see client dest-host"),
    ("tensor_query_serversrc", "dest-port"): (None, "see client dest-host"),
    ("tensor_query_serversrc", "topic"): (None, "see client dest-host"),
    ("tensor_query_serversrc", "timeout"): (
        None, "ingress is push-based here; client timeout + server "
        "deadline (gRPC context) bound waits"),
    ("tensor_query_serversrc", "is-live"): (
        None, "always live (pushsrc semantics built in)"),
    ("tensor_query_serversink", "connect-type"): (
        None, "transport chosen by the serversrc pair"),
    ("tensor_query_serversink", "timeout"): (
        None, "answers resolve in-process; RPC deadline governs"),
    ("mqttsink", "pub-wait-timeout"): (
        None, "QoS-1 drain window on stop() (bounded) covers the intent"),
    ("mqttsrc", "debug"): ("debug", None),
    ("tensor_src_iio", "poll-timeout"): ("poll-timeout", None),
    ("edgesink", "wait-connection"): (
        None, "pub/sub broker holds the stream; subscribers attach "
        "anytime (no blocking-for-first-subscriber mode)"),
    ("edgesink", "connection-timeout"): (None, "see wait-connection"),
    ("edgesrc", "host"): (
        None, "subscriber dials dest-host/dest-port (broker); a local "
        "bind address is not needed"),
    ("edgesrc", "port"): (None, "see host"),
    ("datareposrc", "caps"): (
        None, "schema comes from the JSON meta (self-describing dataset)"),
    ("tensor_reposrc", "caps"): (
        None, "repo slots carry their schema; negotiated downstream"),
    ("mqttsink", "num-buffers"): ("num-buffers", None),
    ("tensor_converter", "mode"): ("mode", None),
}

# our-name aliases: reference name -> our spelling
ALIASES = {
    "inputtype": "input-type",
    "outputtype": "output-type",
    "compared-value-option": "compared-value-option",
    "cleansession": "cleansession",
    "mqtt-qos": "mqtt-qos",
    "clean-session": "clean-session",
    "emit-signal": "emit-signal",
}

OUR_NAME = {
    "tensor_sparse_enc": "tensor_sparse_enc",
    "tensor_sparse_dec": "tensor_sparse_dec",
}


def ref_props(element):
    pat = re.compile(r'g_param_spec_\w+\s*\(\s*"([^"]+)"')
    props = []
    for rel in REF_SOURCES[element]:
        path = os.path.join(REF, rel)
        with open(path, errors="replace") as f:
            props += pat.findall(f.read())
    return list(dict.fromkeys(props))


def our_props(element):
    from nnstreamer_tpu.pipeline.element import (
        COMMON_PROPERTIES,
        ELEMENT_TYPES,
    )

    cls = ELEMENT_TYPES[OUR_NAME.get(element, element)]
    return set(cls.PROPERTIES) | set(COMMON_PROPERTIES)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    # importing the element packages triggers registration
    import nnstreamer_tpu.elements  # noqa: F401

    check = "--check" in sys.argv
    lines = [
        "# Property parity: reference elements vs nnstreamer_tpu",
        "",
        "Generated by `python tools/prop_parity.py` (re-run after adding",
        "element properties).  Reference props are extracted from the",
        "`g_param_spec_*` installs in the reference sources; `covered by`",
        "names the mechanism when the capability intentionally lives",
        "elsewhere than a same-named property.",
        "",
    ]
    unannotated = []
    for el in REF_SOURCES:
        ours = our_props(el)
        rows = []
        n_same = 0
        for p in ref_props(el):
            note = ANNOTATIONS.get((el, p)) or ANNOTATIONS.get(("*", p))
            if p in ours or p.replace("_", "-") in ours:
                n_same += 1
                continue
            if ALIASES.get(p) in ours:
                rows.append(f"| `{p}` | `{ALIASES[p]}` | renamed |")
            elif note is not None:
                target, text = note
                if target and target in ours:
                    rows.append(f"| `{p}` | `{target}` | {text or ''} |")
                    n_same += 1
                    continue
                rows.append(f"| `{p}` | — | covered by: {text} |")
            else:
                rows.append(f"| `{p}` | — | **GAP (unannotated)** |")
                unannotated.append((el, p))
        lines.append(f"## {el}")
        lines.append("")
        lines.append(
            f"{n_same} reference props present under the same name."
        )
        if rows:
            lines.append("")
            lines.append("| reference prop | ours | note |")
            lines.append("|---|---|---|")
            lines.extend(rows)
        lines.append("")
    out = os.path.join(ROOT, "Documentation", "prop-parity.md")
    with open(out, "w") as f:
        f.write("\n".join(lines) + "\n")
    print(f"wrote {out}")
    if unannotated:
        print(f"{len(unannotated)} unannotated gap(s):")
        for el, p in unannotated:
            print(f"  {el}.{p}")
        if check:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
