#!/usr/bin/env python
"""Endurance soak: five concurrent pipelines under sustained load.

Runs (for SOAK_MINUTES, default 20):
  * an in-process jax-xla inference pipeline (micro-batched, dispatch
    window active) fed continuously;
  * a block-ingest (BatchFrame) variant of the same;
  * an MQTT QoS-1 leg through the in-repo broker with a broker
    kill+rebind every ~SOAK_KILL_S seconds;
  * a raw-TCP query offload leg (echo server subprocess) with wire
    batching;
  * an ELASTIC hybrid-query leg: topic-discovered server pod, blue-green
    HARD-killed and replaced every ~SOAK_KILL_S — the client must ride
    stale-announce probing + re-discovery + retries=1 resend.

Asserts: no frame loss on the lossless legs (exactly-once in-proc/tcp,
at-least-once distinct on MQTT), PROGRESS after every pod replacement on
the elastic leg (at-least-once across a replacement window is not
provably lossless — losses are REPORTED, not asserted zero), thread
population back to baseline.  Writes one JSON artifact (default
SOAK.json) with per-leg counts/rates.

≙ the reference's soak/longevity practice (SSAT repeated pipelines,
gst leak checks) — condensed into one self-checking harness.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from nnstreamer_tpu.backends.jax_xla import register_jax_model
    from nnstreamer_tpu.distributed.mqtt import MiniBroker
    from nnstreamer_tpu.pipeline import parse_pipeline

    minutes = float(os.environ.get("SOAK_MINUTES", "20"))
    kill_s = float(os.environ.get("SOAK_KILL_S", "120"))
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SOAK.json"
    deadline = time.monotonic() + minutes * 60
    baseline_threads = {t.ident for t in threading.enumerate()}
    errors: list = []

    # -- leg 1: in-process inference ---------------------------------------
    register_jax_model("soak_m", lambda p, xs: [xs[0] * 2.0 + 1.0], None)
    infer = parse_pipeline(
        "appsrc name=src max-buffers=256 ! "
        "tensor_filter framework=jax-xla model=soak_m max-batch=16 "
        "batch-timeout=5 dispatch-depth=4 ! tensor_sink name=out "
        "max-stored=1")
    infer_count = {"n": 0}
    infer.start()
    infer["out"].connect_new_data(
        lambda f: infer_count.__setitem__("n", infer_count["n"] + 1))

    def infer_feeder():
        i = 0
        while time.monotonic() < deadline:
            try:
                infer["src"].push(np.full((64,), float(i % 97), np.float32))
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(("infer", repr(e)))
                return
            time.sleep(0.002)
        infer_count["pushed"] = i

    # -- leg 1b: block-ingest inference (BatchFrame path endurance) ---------
    blk = parse_pipeline(
        "appsrc name=src max-buffers=64 ! "
        "tensor_filter framework=jax-xla model=soak_m max-batch=32 "
        "dispatch-depth=4 ! tensor_sink name=out max-stored=1")
    blk_count = {"n": 0}
    blk.start()
    blk["out"].connect_new_data(
        lambda f: blk_count.__setitem__("n", blk_count["n"] + 1))

    def blk_feeder():
        i = 0
        while time.monotonic() < deadline:
            try:
                block = np.arange(
                    i, i + 32, dtype=np.float32
                )[:, None] % 251
                blk["src"].push_block(block)
                i += 32
            except Exception as e:  # noqa: BLE001
                errors.append(("block", repr(e)))
                return
            time.sleep(0.01)
        blk_count["pushed"] = i

    # -- leg 2: MQTT QoS-1 with broker chaos --------------------------------
    broker = MiniBroker(retransmit_s=0.3)
    port = broker.port
    rx = parse_pipeline(
        f"mqttsrc host=127.0.0.1 port={port} sub-topic=soak/t "
        "client-id=soak-rx clean-session=false qos=1 sub-timeout=60000 ! "
        "tensor_sink name=out max-stored=1")
    rx.start()
    mqtt_seen: set = set()
    rx["out"].connect_new_data(
        lambda f: mqtt_seen.add(int(round(f.pts)))
        if f.pts is not None else None)
    tx = parse_pipeline(
        "appsrc name=src ! "
        f"mqttsink name=snk host=127.0.0.1 port={port} pub-topic=soak/t "
        "qos=1 client-id=soak-tx")
    tx.start()
    assert broker.wait_subscriber("soak/t", 15), "mqtt sub never landed"

    mqtt_state = {"pushed": 0, "broker": broker}

    def mqtt_feeder():
        i = 0
        last_chaos = time.monotonic()
        while time.monotonic() < deadline:
            try:
                tx["src"].push(np.full((8,), float(i % 251), np.float32),
                               pts=float(i))
                i += 1
                if time.monotonic() - last_chaos > kill_s:
                    # chaos: kill + rebind the broker under load
                    mqtt_state["broker"].close()
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < 20:
                        try:
                            mqtt_state["broker"] = MiniBroker(
                                port=port, retransmit_s=0.3)
                            break
                        except OSError:
                            time.sleep(0.2)
                    last_chaos = time.monotonic()
            except Exception as e:  # noqa: BLE001
                errors.append(("mqtt", repr(e)))
                return
            time.sleep(0.02)
        mqtt_state["pushed"] = i

    # -- leg 3: raw-TCP query offload ---------------------------------------
    # ONE echo-server template serves both query legs (static and
    # elastic); only the serversrc properties differ
    def _query_server_script(src_props: str) -> str:
        return f"""
import sys; sys.path.insert(0, {ROOT!r})
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, time
from nnstreamer_tpu.backends.custom_easy import register_custom_easy
from nnstreamer_tpu.pipeline import parse_pipeline
register_custom_easy("soak_echo", lambda xs: [np.asarray(xs[0])])
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 connect-type=tcp {src_props} ! "
    "tensor_filter framework=custom-easy model=soak_echo ! "
    "tensor_query_serversink")
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep({minutes * 60 + 120})
"""

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)

    def _spawn_query_server(src_props: str):
        p = subprocess.Popen(
            [sys.executable, "-c", _query_server_script(src_props)],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        line = p.stdout.readline()
        assert line.startswith("PORT "), (
            f"query server died during startup: {line!r}"
        )
        return p, int(line.split()[1])

    srv, qport = _spawn_query_server("")
    qcli = parse_pipeline(
        f"appsrc name=src max-buffers=128 ! "
        f"tensor_query_client port={qport} connect-type=tcp timeout=30 "
        "wire-batch=8 max-in-flight=8 ! tensor_sink name=out max-stored=1")
    q_count = {"n": 0}
    qcli.start()
    qcli["out"].connect_new_data(
        lambda f: q_count.__setitem__("n", q_count["n"] + 1))

    def query_feeder():
        i = 0
        payload = np.zeros((4096,), np.float32)  # 16 KB
        while time.monotonic() < deadline:
            try:
                qcli["src"].push(payload)
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(("query", repr(e)))
                return
            time.sleep(0.005)
        q_count["pushed"] = i

    # -- leg 4: elastic hybrid query (pod replacement under load) -----------
    # A STABLE discovery broker (the chaos broker above loses retained
    # announces on kill — servers would have to re-announce); servers are
    # HARD-killed (no tombstone) and respawned on fresh ports every
    # kill_s, so the client must ride stale-announce probing + topic
    # re-discovery + at-least-once resend (retries=1) across every
    # replacement.  Success = continued delivery after each replacement;
    # a brief pod-down window may drop in-flight requests (at-least-once
    # is not lossless when NO server exists), so the assertion is
    # progress, not zero-loss.
    disc_broker = MiniBroker()

    def spawn_elastic_server():
        p, _port = _spawn_query_server(
            f"topic=soak-elastic dest-host=127.0.0.1 "
            f"dest-port={disc_broker.port}"
        )
        return p

    e_state = {"srv": None, "replacements": 0, "progress": []}
    e_count = {"n": 0}

    def elastic_feeder():
        ecli = None
        try:
            # setup INSIDE the try: a spawn/start crash must land in
            # `errors`, not die silently on a daemon thread
            e_state["srv"] = spawn_elastic_server()
            ecli = parse_pipeline(
                "appsrc name=src max-buffers=64 ! "
                "tensor_query_client topic=soak-elastic dest-host=127.0.0.1 "
                f"dest-port={disc_broker.port} discovery-timeout=15 "
                "retries=1 connect-type=tcp timeout=10 ! "
                "tensor_sink name=out max-stored=1")
            ecli.start()
            ecli["out"].connect_new_data(
                lambda f: e_count.__setitem__("n", e_count["n"] + 1))
            i = 0
            last_kill = time.monotonic()
            e_state["active_from"] = last_kill  # post-setup: spawn+start
            payload = np.zeros((512,), np.float32)
            while time.monotonic() < deadline:
                try:
                    ecli["src"].push(payload)
                    i += 1
                    if time.monotonic() - last_kill > kill_s:
                        # blue-green pod replacement: the NEW server is
                        # announced BEFORE the old is HARD-killed (its
                        # stale announce stays — probing must skip it);
                        # in-flight requests on the old server fail and
                        # ride re-discovery + retries=1 resend
                        before = e_count["n"]
                        new_srv = spawn_elastic_server()
                        e_state["srv"].kill()
                        e_state["srv"].wait(timeout=10)
                        e_state["srv"] = new_srv
                        e_state["replacements"] += 1
                        e_state["progress"].append(before)
                        last_kill = time.monotonic()
                except Exception as e:  # noqa: BLE001
                    errors.append(("elastic", repr(e)))
                    return
                time.sleep(0.02)
            e_count["pushed"] = i
            e_state["active_s"] = time.monotonic() - e_state["active_from"]
            ecli["src"].end_of_stream()
            ecli.wait(timeout=120)
            e_count["final"] = e_count["n"]
        except Exception as e:  # noqa: BLE001 — setup/teardown failures
            errors.append(("elastic", repr(e)))
        finally:
            if ecli is not None:
                ecli.stop()

    feeders = [threading.Thread(target=f, daemon=True)
               for f in (infer_feeder, blk_feeder, mqtt_feeder, query_feeder,
                         elastic_feeder)]
    t0 = time.monotonic()
    for t in feeders:
        t.start()
    while any(t.is_alive() for t in feeders):
        time.sleep(5)
        el = time.monotonic() - t0
        print(f"[soak] {el/60:5.1f}m  infer={infer_count['n']} "
              f"block={blk_count['n']} "
              f"mqtt={len(mqtt_seen)} query={q_count['n']} "
              f"elastic={e_count['n']}/{e_state['replacements']}repl "
              f"errors={len(errors)}", flush=True)

    # drain: EOS every leg, bounded waits
    infer["src"].end_of_stream()
    infer.wait(timeout=60)
    blk["src"].end_of_stream()
    blk.wait(timeout=60)
    tx["src"].end_of_stream()
    tx.wait(timeout=60)
    unacked = (tx["snk"]._client.drain(30.0)
               if tx["snk"]._client is not None else 0)
    qcli["src"].end_of_stream()
    qcli.wait(timeout=120)
    dt = time.monotonic() - t0

    infer_done = infer_count["n"]
    blk_done = blk_count["n"]
    q_done = q_count["n"]
    deadline2 = time.time() + 60
    while len(mqtt_seen) < mqtt_state.get("pushed", 0) and \
            time.time() < deadline2:
        time.sleep(0.2)

    infer.stop()
    blk.stop()
    tx.stop()
    rx.stop()
    qcli.stop()
    mqtt_state["broker"].close()
    srv.kill()
    srv.wait(timeout=10)
    if e_state["srv"] is not None:
        e_state["srv"].kill()
        e_state["srv"].wait(timeout=10)
    disc_broker.close()

    # leak check
    leak_deadline = time.time() + 30
    leaked = []
    while time.time() < leak_deadline:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and t.ident not in baseline_threads]
        if not leaked:
            break
        time.sleep(0.5)

    mqtt_pushed = mqtt_state.get("pushed", 0)
    mqtt_missing = (
        [i for i in range(mqtt_pushed) if i not in mqtt_seen]
        if mqtt_pushed else [])
    result = {
        "metric": "soak_endurance",
        "minutes": round(dt / 60, 2),
        "legs": {
            "infer": {"pushed": infer_count.get("pushed"),
                      "delivered": infer_done,
                      "fps": round(infer_done / dt, 1)},
            "block_infer": {"pushed": blk_count.get("pushed"),
                            "delivered": blk_done,
                            "fps": round(blk_done / dt, 1)},
            "mqtt_qos1": {"pushed": mqtt_pushed,
                          "delivered_distinct": len(mqtt_seen),
                          "missing": len(mqtt_missing),
                          "unacked_at_eos": unacked,
                          "broker_kills": max(0, int(dt // kill_s))},
            "tcp_query": {"pushed": q_count.get("pushed"),
                          "delivered": q_done,
                          "fps": round(q_done / dt, 1)},
            "elastic_hybrid": {
                "pushed": e_count.get("pushed"),
                "delivered": e_count.get("final", e_count["n"]),
                "replacements": e_state["replacements"],
                # at-least-once across replacement windows: losses and
                # resend duplicates are REPORTED, not asserted away
                "lost": max(
                    0,
                    (e_count.get("pushed") or 0)
                    - e_count.get("final", e_count["n"]),
                ),
                "duplicates": max(
                    0,
                    e_count.get("final", e_count["n"])
                    - (e_count.get("pushed") or 0),
                ),
                "progress_at_kill": e_state["progress"],
            },
        },
        "errors": errors,
        "leaked_threads": [t.name for t in leaked],
        "ok": (not errors and not leaked and not mqtt_missing
               and unacked == 0
               and infer_done == infer_count.get("pushed")
               and blk_done == blk_count.get("pushed")
               and q_done == q_count.get("pushed")
               # elastic leg contract = PROGRESS through replacements
               # (delivery strictly advances between consecutive kills
               # and after the last one), plus at least one replacement
               # whenever the leg's ACTIVE window (post-setup — the
               # server subprocess import can eat a short run's budget)
               # was long enough to schedule one
               and e_count.get("final", 0) > 0
               and (e_state.get("active_s", 0) < kill_s
                    or e_state["replacements"] >= 1)
               and all(
                   b > a for a, b in zip(
                       e_state["progress"],
                       e_state["progress"][1:]
                       + [e_count.get("final", 0)],
                   )
               )),
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
