#!/usr/bin/env python
"""Endurance soak: three concurrent pipelines under sustained load.

Runs (for SOAK_MINUTES, default 20):
  * an in-process jax-xla inference pipeline (micro-batched, dispatch
    window active) fed continuously;
  * an MQTT QoS-1 leg through the in-repo broker with a broker
    kill+rebind every ~2 minutes;
  * a raw-TCP query offload leg (echo server subprocess) with wire
    batching.

Asserts across the whole run: no frame loss on the lossless legs
(at-least-once on MQTT, exactly-once in-proc/tcp), thread population
returns to baseline, native pool balanced.  Writes one JSON artifact
(default SOAK.json) with per-leg frame counts and rates.

≙ the reference's soak/longevity practice (SSAT repeated pipelines,
gst leak checks) — condensed into one self-checking harness.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from nnstreamer_tpu.backends.jax_xla import register_jax_model
    from nnstreamer_tpu.distributed.mqtt import MiniBroker
    from nnstreamer_tpu.pipeline import parse_pipeline

    minutes = float(os.environ.get("SOAK_MINUTES", "20"))
    kill_s = float(os.environ.get("SOAK_KILL_S", "120"))
    out_path = sys.argv[1] if len(sys.argv) > 1 else "SOAK.json"
    deadline = time.monotonic() + minutes * 60
    baseline_threads = {t.ident for t in threading.enumerate()}
    errors: list = []

    # -- leg 1: in-process inference ---------------------------------------
    register_jax_model("soak_m", lambda p, xs: [xs[0] * 2.0 + 1.0], None)
    infer = parse_pipeline(
        "appsrc name=src max-buffers=256 ! "
        "tensor_filter framework=jax-xla model=soak_m max-batch=16 "
        "batch-timeout=5 dispatch-depth=4 ! tensor_sink name=out "
        "max-stored=1")
    infer_count = {"n": 0}
    infer.start()
    infer["out"].connect_new_data(
        lambda f: infer_count.__setitem__("n", infer_count["n"] + 1))

    def infer_feeder():
        i = 0
        while time.monotonic() < deadline:
            try:
                infer["src"].push(np.full((64,), float(i % 97), np.float32))
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(("infer", repr(e)))
                return
            time.sleep(0.002)
        infer_count["pushed"] = i

    # -- leg 1b: block-ingest inference (BatchFrame path endurance) ---------
    blk = parse_pipeline(
        "appsrc name=src max-buffers=64 ! "
        "tensor_filter framework=jax-xla model=soak_m max-batch=32 "
        "dispatch-depth=4 ! tensor_sink name=out max-stored=1")
    blk_count = {"n": 0}
    blk.start()
    blk["out"].connect_new_data(
        lambda f: blk_count.__setitem__("n", blk_count["n"] + 1))

    def blk_feeder():
        i = 0
        while time.monotonic() < deadline:
            try:
                block = np.arange(
                    i, i + 32, dtype=np.float32
                )[:, None] % 251
                blk["src"].push_block(block)
                i += 32
            except Exception as e:  # noqa: BLE001
                errors.append(("block", repr(e)))
                return
            time.sleep(0.01)
        blk_count["pushed"] = i

    # -- leg 2: MQTT QoS-1 with broker chaos --------------------------------
    broker = MiniBroker(retransmit_s=0.3)
    port = broker.port
    rx = parse_pipeline(
        f"mqttsrc host=127.0.0.1 port={port} sub-topic=soak/t "
        "client-id=soak-rx clean-session=false qos=1 sub-timeout=60000 ! "
        "tensor_sink name=out max-stored=1")
    rx.start()
    mqtt_seen: set = set()
    rx["out"].connect_new_data(
        lambda f: mqtt_seen.add(int(round(f.pts)))
        if f.pts is not None else None)
    tx = parse_pipeline(
        "appsrc name=src ! "
        f"mqttsink name=snk host=127.0.0.1 port={port} pub-topic=soak/t "
        "qos=1 client-id=soak-tx")
    tx.start()
    assert broker.wait_subscriber("soak/t", 15), "mqtt sub never landed"

    mqtt_state = {"pushed": 0, "broker": broker}

    def mqtt_feeder():
        i = 0
        last_chaos = time.monotonic()
        while time.monotonic() < deadline:
            try:
                tx["src"].push(np.full((8,), float(i % 251), np.float32),
                               pts=float(i))
                i += 1
                if time.monotonic() - last_chaos > kill_s:
                    # chaos: kill + rebind the broker under load
                    mqtt_state["broker"].close()
                    t0 = time.monotonic()
                    while time.monotonic() - t0 < 20:
                        try:
                            mqtt_state["broker"] = MiniBroker(
                                port=port, retransmit_s=0.3)
                            break
                        except OSError:
                            time.sleep(0.2)
                    last_chaos = time.monotonic()
            except Exception as e:  # noqa: BLE001
                errors.append(("mqtt", repr(e)))
                return
            time.sleep(0.02)
        mqtt_state["pushed"] = i

    # -- leg 3: raw-TCP query offload ---------------------------------------
    server_script = f"""
import sys; sys.path.insert(0, {ROOT!r})
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np, time
from nnstreamer_tpu.backends.custom_easy import register_custom_easy
from nnstreamer_tpu.pipeline import parse_pipeline
register_custom_easy("soak_echo", lambda xs: [np.asarray(xs[0])])
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 connect-type=tcp ! "
    "tensor_filter framework=custom-easy model=soak_echo ! "
    "tensor_query_serversink")
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep({minutes * 60 + 120})
"""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    srv = subprocess.Popen([sys.executable, "-c", server_script],
                           stdout=subprocess.PIPE, text=True, env=env)
    line = srv.stdout.readline()
    assert line.startswith("PORT "), line
    qport = int(line.split()[1])
    qcli = parse_pipeline(
        f"appsrc name=src max-buffers=128 ! "
        f"tensor_query_client port={qport} connect-type=tcp timeout=30 "
        "wire-batch=8 max-in-flight=8 ! tensor_sink name=out max-stored=1")
    q_count = {"n": 0}
    qcli.start()
    qcli["out"].connect_new_data(
        lambda f: q_count.__setitem__("n", q_count["n"] + 1))

    def query_feeder():
        i = 0
        payload = np.zeros((4096,), np.float32)  # 16 KB
        while time.monotonic() < deadline:
            try:
                qcli["src"].push(payload)
                i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(("query", repr(e)))
                return
            time.sleep(0.005)
        q_count["pushed"] = i

    feeders = [threading.Thread(target=f, daemon=True)
               for f in (infer_feeder, blk_feeder, mqtt_feeder, query_feeder)]
    t0 = time.monotonic()
    for t in feeders:
        t.start()
    while any(t.is_alive() for t in feeders):
        time.sleep(5)
        el = time.monotonic() - t0
        print(f"[soak] {el/60:5.1f}m  infer={infer_count['n']} "
              f"block={blk_count['n']} "
              f"mqtt={len(mqtt_seen)} query={q_count['n']} "
              f"errors={len(errors)}", flush=True)

    # drain: EOS every leg, bounded waits
    infer["src"].end_of_stream()
    infer.wait(timeout=60)
    blk["src"].end_of_stream()
    blk.wait(timeout=60)
    tx["src"].end_of_stream()
    tx.wait(timeout=60)
    unacked = (tx["snk"]._client.drain(30.0)
               if tx["snk"]._client is not None else 0)
    qcli["src"].end_of_stream()
    qcli.wait(timeout=120)
    dt = time.monotonic() - t0

    infer_done = infer_count["n"]
    blk_done = blk_count["n"]
    q_done = q_count["n"]
    deadline2 = time.time() + 60
    while len(mqtt_seen) < mqtt_state.get("pushed", 0) and \
            time.time() < deadline2:
        time.sleep(0.2)

    infer.stop()
    blk.stop()
    tx.stop()
    rx.stop()
    qcli.stop()
    mqtt_state["broker"].close()
    srv.kill()
    srv.wait(timeout=10)

    # leak check
    leak_deadline = time.time() + 30
    leaked = []
    while time.time() < leak_deadline:
        leaked = [t for t in threading.enumerate()
                  if t.is_alive() and t.ident not in baseline_threads]
        if not leaked:
            break
        time.sleep(0.5)

    mqtt_pushed = mqtt_state.get("pushed", 0)
    mqtt_missing = (
        [i for i in range(mqtt_pushed) if i not in mqtt_seen]
        if mqtt_pushed else [])
    result = {
        "metric": "soak_endurance",
        "minutes": round(dt / 60, 2),
        "legs": {
            "infer": {"pushed": infer_count.get("pushed"),
                      "delivered": infer_done,
                      "fps": round(infer_done / dt, 1)},
            "block_infer": {"pushed": blk_count.get("pushed"),
                            "delivered": blk_done,
                            "fps": round(blk_done / dt, 1)},
            "mqtt_qos1": {"pushed": mqtt_pushed,
                          "delivered_distinct": len(mqtt_seen),
                          "missing": len(mqtt_missing),
                          "unacked_at_eos": unacked,
                          "broker_kills": max(0, int(dt // kill_s))},
            "tcp_query": {"pushed": q_count.get("pushed"),
                          "delivered": q_done,
                          "fps": round(q_done / dt, 1)},
        },
        "errors": errors,
        "leaked_threads": [t.name for t in leaked],
        "ok": (not errors and not leaked and not mqtt_missing
               and unacked == 0
               and infer_done == infer_count.get("pushed")
               and blk_done == blk_count.get("pushed")
               and q_done == q_count.get("pushed")),
    }
    with open(out_path, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    raise SystemExit(main())
