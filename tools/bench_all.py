#!/usr/bin/env python
"""Run every BASELINE.md bench row (plus the host-sourced headline variant)
and collect the JSON lines into one artifact.

Usage: python tools/bench_all.py [out.json]
Honors the same env knobs as bench.py (BENCH_DEADLINE etc.).
"""

import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (BENCH_MODEL, extra env) — mobilenet runs device- AND host-sourced so the
# headline number is published alongside its transfer-inclusive variant
ROWS = [
    ("mobilenet", {"BENCH_RAW": "1"}),  # headline + same-window raw ref
    # block ingest (frames-per-tensor batching): per-frame Python ingest
    # amortized across the micro-batch — the pipeline_vs_raw >= 0.9
    # configuration on a host whose per-frame dispatch can't keep up
    ("mobilenet", {"BENCH_RAW": "1", "BENCH_INGEST": "block"}),
    # + whole-block delivery (sink/decoder keep blocks intact): removes
    # the per-frame fan-out on the output side too — the peak streaming
    # configuration for hosts far slower than the chip
    ("mobilenet", {"BENCH_RAW": "1", "BENCH_INGEST": "block",
                   "BENCH_SINK_SPLIT": "0"}),
    # depth ablation: same window, synchronous dispatch — quantifies what
    # the depth-4 in-flight window buys on the chip (VERDICT r3 #2)
    ("mobilenet", {"BENCH_RAW": "1", "BENCH_DEPTH": "1"}),
    # int8 rows are MXU-targeted: XLA-CPU has no vectorized int8 conv
    # (scalar codegen, ~1000x slower), so these time out under
    # BENCH_PLATFORM=cpu dry-runs — expected, not a defect; correctness
    # is proven small-scale by tests/test_quantize.py
    ("mobilenet", {"BENCH_QUANT": "1"}),  # int8 MXU path
    ("mobilenet", {"BENCH_BATCH": "256"}),  # amortizes per-batch link RTTs
    # cheapest per-frame device time + fewest per-batch round trips: the
    # most likely >=1000 fps configuration on a compute-rate-throttled link
    ("mobilenet", {"BENCH_QUANT": "1", "BENCH_BATCH": "256"}),
    # every lever at once: block ingest + whole-block delivery + int8 MXU
    # + batch 256 — the "don't stop at parity" configuration
    ("mobilenet", {"BENCH_RAW": "1", "BENCH_INGEST": "block",
                   "BENCH_SINK_SPLIT": "0", "BENCH_QUANT": "1",
                   "BENCH_BATCH": "256"}),
    ("ssd", {}),
    ("ssd", {"BENCH_QUANT": "1"}),  # int8 backbone
    ("yolov5", {}),
    ("yolov5", {"BENCH_QUANT": "1"}),  # int8 backbone/neck
    ("posenet", {}),
    ("vit", {}),
    # latency-optimized serving config (BASELINE.md tracks p50 per-frame
    # latency): small batch, synchronous dispatch — the fps column is NOT
    # the headline, the e2e_latency fields are
    ("mobilenet", {"BENCH_BATCH": "8", "BENCH_DEPTH": "1",
                   "BENCH_FRAMES": "1024", "BENCH_BATCH_TIMEOUT": "2"}),
    ("mnist_trainer", {}),
    # LAST on purpose, and sized to finish inside its deadline: over the
    # dev tunnel (~30 MB/s) a full 4096-frame host-sourced run cannot
    # complete, the parent kills the child mid-transfer, and a mid-transfer
    # kill is exactly the hazard that wedges the device claim (observed
    # r2 ~04:50Z and again r4 ~04:10Z).  512 frames ≈ 77 MB ≈ well inside
    # the 420 s window; on-host TPU deployments can override BENCH_FRAMES.
    ("mobilenet", {"BENCH_HOST": "1", "BENCH_FRAMES": "512"}),
]


def _row_sig(model, extra):
    return {"model": model, **{k: str(v) for k, v in sorted(extra.items())}}


def _write_rows(out_path, results):
    """Atomic checkpoint: a kill mid-dump must never truncate the artifact
    the resume feature exists to preserve."""
    tmp = out_path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(results, f, indent=2)
    os.replace(tmp, out_path)


def main() -> int:
    out_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_ROWS.json"
    results = []
    done_sigs = []
    if os.environ.get("BENCH_ALL_RESUME", "") in ("1", "true"):
        # the tunnel comes and goes in windows: re-runs keep every
        # successful row already captured and only re-measure the rest
        try:
            with open(out_path) as f:
                prior = json.load(f)
        except (OSError, json.JSONDecodeError):
            prior = []
        valid_sigs = [_row_sig(m, e) for m, e in ROWS]
        dropped = 0
        for row in prior:
            sig = row.get("_sig")
            if (row.get("value") is not None and not row.get("stale")
                    and sig in valid_sigs and sig not in done_sigs):
                # stale rows (bench.py evidence-cache fallback) are
                # banked evidence, not this sweep's measurement — always
                # re-measure them when the tunnel answers
                results.append(row)
                done_sigs.append(sig)
            else:
                # sig-less (pre-resume artifact) or a config since edited
                # out of ROWS: re-measure fresh rather than publish stale
                dropped += 1
        if dropped and prior:
            # never destroy data the new run won't reproduce verbatim
            _write_rows(out_path + ".bak", prior)
            print(f"[bench_all] resume: {dropped} prior row(s) unmatched "
                  f"(no/stale _sig) — re-measuring; originals saved to "
                  f"{out_path}.bak", flush=True)
        if results:
            print(f"[bench_all] resume: keeping {len(results)} prior rows",
                  flush=True)
    executed = 0
    for model, extra in ROWS:
        sig = _row_sig(model, extra)
        if sig in done_sigs:
            continue
        env = {**os.environ, "BENCH_MODEL": model, **extra}
        if executed > 0:
            # the first EXECUTED row already proved the backend answers;
            # later rows keep their probes short so a full sweep fits a
            # narrow tunnel-up window (resume runs skip completed rows,
            # so row 0 of the list may not be the prover)
            env.setdefault("BENCH_PROBE_TRIES", "1")
            env.setdefault("BENCH_PROBE_TIMEOUT", "60")
        executed += 1
        print(f"[bench_all] {model} {extra or ''}...", flush=True)
        r = subprocess.run(
            [sys.executable, os.path.join(ROOT, "bench.py")],
            capture_output=True, text=True, env=env,
        )
        row = None
        for line in reversed(r.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                try:
                    row = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
        if row is None:
            row = {
                "metric": model, "value": None, "unit": None,
                "vs_baseline": None,
                "error": f"no JSON line (rc={r.returncode})",
            }
        print(f"[bench_all]   -> {json.dumps(row)}", flush=True)
        row["_sig"] = sig  # resume key (self-describing row provenance)
        results.append(row)
        # incremental atomic write: a kill mid-sweep keeps completed rows
        _write_rows(out_path, results)
        # a stale-fallback row reports its live failure under live_error;
        # "re-probe:" marks a mid-run wedge (initial probe passed, the
        # post-failure probe did not) — same dead tunnel, same abort
        live_fail = str(row.get("error", "")) + str(row.get("live_error", ""))
        if (
            "unavailable" in live_fail or "re-probe:" in live_fail
        ) and not os.environ.get("BENCH_ALL_KEEP_GOING"):
            # tunnel down: every later row would burn its probe budget on
            # the same outage — fail the sweep fast and diagnosable
            print("[bench_all] backend unavailable; aborting remaining "
                  "rows (BENCH_ALL_KEEP_GOING=1 overrides)", flush=True)
            break
    print(f"[bench_all] wrote {out_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
