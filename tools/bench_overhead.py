#!/usr/bin/env python
"""Framework-overhead harness: full pipeline fps vs raw jitted-model fps
on the SAME model/batch (VERDICT r2 item 2: pipeline must be >= 0.9x raw).

Runs on CPU by default with a deliberately tiny model so per-frame
framework cost dominates — the dispatch-bound regime where the 772-vs-
1090 fps gap on the chip lives.  BENCH_OVERHEAD_MODEL=mobilenet measures
the compute-bound regime instead.

Prints per-stage tracer rows plus one JSON line:
  {"pipeline_fps", "raw_fps", "ratio", ...}
"""

import json
import os
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)


def main() -> int:
    import jax

    if os.environ.get("BENCH_OVERHEAD_PLATFORM", "cpu") == "cpu":
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from nnstreamer_tpu.backends.jax_xla import register_jax_model
    from nnstreamer_tpu.pipeline import parse_pipeline

    which = os.environ.get("BENCH_OVERHEAD_MODEL", "tiny")
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    n_frames = int(os.environ.get("BENCH_FRAMES", "8192"))

    if which == "tiny":
        classes = 1001

        def fn(params, xs):
            # one small matmul: enough to be a real XLA program, cheap
            # enough that dispatch/framework cost dominates
            import jax.numpy as jnp

            return [xs[0].astype(jnp.float32) @ params["w"]]

        params = {
            "w": np.random.default_rng(0)
            .normal(0, 0.02, (64, classes))
            .astype(np.float32)
        }
        register_jax_model("ovh_model", fn, params)
        frame_shape, frame_dtype = (64,), np.float32
    else:
        from nnstreamer_tpu.models import build

        fn, params, in_spec, out_spec = build(
            "mobilenet_v2", {"dtype": "float32"}
        )
        register_jax_model("ovh_model", fn, params, in_spec, out_spec)
        frame_shape, frame_dtype = (224, 224, 3), np.float32

    labels = "/tmp/ovh_labels.txt"
    with open(labels, "w") as f:
        f.write("\n".join(f"c{i}" for i in range(1001)))

    rng = np.random.default_rng(1)
    pool = [
        rng.normal(0, 1, frame_shape).astype(frame_dtype) for _ in range(16)
    ]
    pool_dev = [jax.device_put(p) for p in pool]
    jax.block_until_ready(pool_dev)

    # -- raw ceiling: same batched invoke the filter makes, no pipeline
    # (same helper bench.py BENCH_RAW uses, so the two ratios agree) --
    from bench import measure_raw_fps

    raw_fps = measure_raw_fps(fn, params, pool, batch, n_frames)

    # -- full pipeline on the same model -------------------------------
    pipe = parse_pipeline(
        "appsrc name=src max-buffers=512 ! "
        "tensor_filter name=f framework=jax-xla model=ovh_model "
        f"max-batch={batch} batch-timeout=20 dispatch-depth={os.environ.get('BENCH_DEPTH', '4')} ! "
        f"tensor_decoder mode=image_labeling option1={labels} ! "
        "tensor_sink name=out max-stored=1",
        name="overhead",
    )
    if os.environ.get("BENCH_TRACE", "1") == "1":
        pipe.enable_tracing()
    pipe.start()
    src, sink = pipe["src"], pipe["out"]
    done = {"n": 0}
    sink.connect_new_data(lambda f: done.__setitem__("n", done["n"] + 1))
    for i in range(batch * 2):  # warmup compiles
        src.push(pool_dev[i % len(pool)])
    t_wait = time.time()
    while done["n"] < batch * 2 and time.time() - t_wait < 120:
        time.sleep(0.01)
    assert done["n"] >= batch * 2, "warmup incomplete"
    time.sleep(0.3)

    done["n"] = 0
    t0 = time.perf_counter()
    for i in range(n_frames):
        src.push(pool_dev[i % len(pool)])
    while done["n"] < n_frames and time.perf_counter() - t0 < 300:
        time.sleep(0.005)
    pipe_fps = done["n"] / (time.perf_counter() - t0)

    if pipe.tracer is not None:
        for line in pipe.tracer.summary_lines():
            print(line, file=sys.stderr)
    src.end_of_stream()
    pipe.wait(timeout=30)
    pipe.stop()

    print(json.dumps({
        "metric": "pipeline_vs_raw_ratio",
        "model": which,
        "batch": batch,
        "pipeline_fps": round(pipe_fps, 1),
        "raw_fps": round(raw_fps, 1),
        "ratio": round(pipe_fps / raw_fps, 3),
        # the >=0.9 contract applies to REAL models (compute-bound); the
        # tiny model isolates absolute framework cost per batch instead
        "regime": (
            "dispatch-bound: ratio not meaningful, read "
            "framework_ms_per_batch" if which == "tiny" else "compute-bound"
        ),
        "framework_ms_per_batch": round(
            (1.0 / pipe_fps - 1.0 / raw_fps) * batch * 1e3, 2
        ),
        "platform": "cpu" if os.environ.get(
            "BENCH_OVERHEAD_PLATFORM", "cpu") == "cpu" else "accel",
    }))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
