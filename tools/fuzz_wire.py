#!/usr/bin/env python
"""Deterministic structure-aware fuzz harness for the wire decoders.

Builds a corpus of VALID artifacts (NNSQ v1/v2 frames, NNSB/NNSC
batches, tcp_query v1/v2 messages, protobuf and flatbuf codec frames),
then mutates each one three ways:

* **truncation at every field boundary** — header edges, meta end,
  tensor-count, each flex header / payload-length / payload edge (plus a
  seeded spread of arbitrary offsets);
* **seeded bit flips** — single-bit corruption anywhere in the buffer;
* **length/count-field mutation** — every size-carrying field is
  overwritten with adversarial values (0, 1, all-ones, buffer-length,
  buffer-length+1, 2^31, 2^63, ...), the classic hostile-input shape.

Every mutant is decoded under three assertions, the acceptance contract
of the data-plane integrity layer (ISSUE 4 / Documentation/
wire-protocol.md):

1. **no crash** — the decoder either returns a frame or raises a typed
   ``WireError`` subclass; any other exception is a failure;
2. **no hang** — each decode must finish inside a wall-clock budget;
3. **no over-allocation** — tracemalloc peak per decode stays far below
   ``wire.MAX_BODY`` (a hostile length field must be rejected BEFORE the
   allocation it describes).

Fully deterministic: one ``--seed`` pins the corpus, every mutation
position, and every adversarial value, so a failure reproduces exactly.
Run standalone (exit 0 clean / 1 failures) or in-process from tier-1
(``tests/test_wire_integrity.py`` runs the fixed-seed smoke alongside
the check_no_bare_except / check_blocking_timeouts gates).

Usage:
  python tools/fuzz_wire.py [--seed 7] [--iterations 12000] [-q]
"""

from __future__ import annotations

import argparse
import random
import sys
import time
import tracemalloc
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nnstreamer_tpu.core.buffer import TensorFrame  # noqa: E402
from nnstreamer_tpu.distributed import tcp_query, wire  # noqa: E402

# per-decode budgets (generous: a clean decode is microseconds)
TIME_BUDGET_S = 2.0
ALLOC_BUDGET = wire.MAX_BODY  # tracemalloc peak cap per decode

# adversarial replacement values for size/count fields, masked to width
EVIL = (0, 1, 2, 0x7F, 0xFF, 0xFFFF, 0x10000, 0x7FFFFFFF, 0xFFFFFFFF,
        2**33, 2**63 - 1, 2**64 - 1)


def _corpus_frames(rng: random.Random):
    """Valid TensorFrames spanning dtypes, ranks, meta shapes."""
    r = np.random.default_rng(rng.randrange(2**31))
    return [
        TensorFrame([np.arange(12, dtype=np.float32).reshape(3, 4)],
                    pts=1.25, meta={"k": "v", "n": 3}),
        TensorFrame([r.integers(0, 255, (2, 3, 4)).astype(np.uint8),
                     r.standard_normal((5,)).astype(np.float64)],
                    meta={"nested": {"a": [1, 2]}}),
        TensorFrame([np.int64([7])]),
        TensorFrame([np.float16(r.standard_normal((1, 1, 2)))],
                    pts=0.0, meta={}),
        TensorFrame([], meta={"empty": True}),
    ]


def _walk_frame_boundaries(buf: bytes) -> list:
    """Field-boundary offsets of a VALID NNSQ frame, derived by walking
    the known-good layout (independent of the decoder under test)."""
    import struct

    offs = [0, 4, 6, 14, 22]  # magic, ver, seq, pts ends
    ver = struct.unpack_from("<H", buf, 4)[0]
    head = 30 if ver == 2 else 26
    meta_len = struct.unpack_from("<I", buf, 22)[0]
    offs += [head, head + meta_len, head + meta_len + 2]
    off = head + meta_len
    (nt,) = struct.unpack_from("<H", buf, off)
    off += 2
    for _ in range(nt):
        fixed = struct.unpack_from("<IIBBH", buf, off)
        nlen, rank = fixed[2], fixed[3]
        off += 12 + 4 * rank + nlen
        offs.append(off)  # end of flex header
        (plen,) = struct.unpack_from("<Q", buf, off)
        off += 8
        offs.append(off)  # end of payload-length field
        off += plen
        offs.append(off)  # end of payload
    return sorted({o for o in offs if 0 <= o <= len(buf)})


def _len_field_offsets(buf: bytes) -> list:
    """(offset, width) of every size/count-carrying field in a valid
    NNSQ frame — the targets of the length-mutation pass."""
    import struct

    ver = struct.unpack_from("<H", buf, 4)[0]
    head = 30 if ver == 2 else 26
    meta_len = struct.unpack_from("<I", buf, 22)[0]
    fields = [(22, 4)]  # meta_len
    off = head + meta_len
    fields.append((off, 2))  # ntensors
    (nt,) = struct.unpack_from("<H", buf, off)
    off += 2
    for _ in range(nt):
        fields.append((off + 8, 1))   # flex nlen (u8)
        fields.append((off + 9, 1))   # flex rank (u8)
        fixed = struct.unpack_from("<IIBBH", buf, off)
        nlen, rank = fixed[2], fixed[3]
        off += 12 + 4 * rank + nlen
        fields.append((off, 8))  # payload_len
        (plen,) = struct.unpack_from("<Q", buf, off)
        off += 8 + plen
    return fields


class Runner:
    def __init__(self, quiet: bool = False):
        self.cases = 0
        self.wire_errors = 0
        self.clean = 0
        self.failures = []
        self.quiet = quiet
        self.max_elapsed = 0.0
        self.max_alloc = 0

    def run(self, label: str, decode, buf) -> None:
        self.cases += 1
        tracemalloc.reset_peak()
        t0 = time.perf_counter()
        try:
            decode(buf)
            self.clean += 1
        except wire.WireError:
            self.wire_errors += 1  # typed refusal: the contract
        except Exception as e:  # noqa: BLE001 — the harness records it
            self.failures.append(
                (label, f"{type(e).__name__}: {e}", bytes(buf)[:64].hex()))
        elapsed = time.perf_counter() - t0
        _, peak = tracemalloc.get_traced_memory()
        self.max_elapsed = max(self.max_elapsed, elapsed)
        self.max_alloc = max(self.max_alloc, peak)
        if elapsed > TIME_BUDGET_S:
            self.failures.append(
                (label, f"hang: decode took {elapsed:.2f}s", ""))
        if peak > ALLOC_BUDGET:
            self.failures.append(
                (label, f"over-allocation: {peak} B > {ALLOC_BUDGET}", ""))


def _mutants(rng: random.Random, buf: bytes, boundaries, len_fields,
             n_random: int):
    """Yield (tag, mutated_buffer) — deterministic given rng state."""
    for b in boundaries:
        yield f"trunc@{b}", buf[:b]
    for off, width in len_fields:
        for v in EVIL:
            mut = bytearray(buf)
            mut[off : off + width] = int(v & (2 ** (8 * width) - 1)).to_bytes(
                width, "little")
            yield f"len@{off}={v}", bytes(mut)
    for _ in range(n_random):
        mut = bytearray(buf)
        if rng.random() < 0.5 and len(mut) > 0:
            pos = rng.randrange(len(mut) * 8)
            mut[pos // 8] ^= 1 << (pos % 8)
            yield f"bitflip@{pos}", bytes(mut)
        else:
            yield f"rtrunc@{rng.randrange(len(mut) + 1)}", bytes(
                mut[: rng.randrange(len(mut) + 1)])


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--iterations", type=int, default=12000,
                    help="minimum total mutated cases (default 12000)")
    ap.add_argument("-q", "--quiet", action="store_true")
    args = ap.parse_args(argv)

    rng = random.Random(args.seed)
    runner = Runner(quiet=args.quiet)
    frames = _corpus_frames(rng)

    # (label, decode, valid bytes, structure-aware?) corpus
    corpus = []
    for v in (1, 2):
        for i, f in enumerate(frames):
            corpus.append((f"frame-v{v}-{i}", wire.decode_frame,
                           wire.encode_frame(f, version=v), True))
        corpus.append((f"batch-v{v}", wire.decode_frames,
                       wire.encode_frames(frames[:3], version=v), False))
        body = wire.encode_frame(frames[0], version=v)
        corpus.append((
            f"tcpmsg-v{v}",
            lambda d, v=v: tcp_query.parse_msg(d, version=v),
            tcp_query.encode_msg(ord("Q"), body, deadline_s=2.5, version=v),
            False,
        ))
    from nnstreamer_tpu.distributed import protobuf_codec

    for i, f in enumerate(frames[:3]):
        corpus.append((f"protobuf-{i}", protobuf_codec.decode_frame,
                       protobuf_codec.encode_frame(f), False))
    try:
        from nnstreamer_tpu.distributed import flatbuf_codec

        fbs_ok = [f for f in frames[:2] if f.tensors]
        for i, f in enumerate(fbs_ok):
            corpus.append((f"flatbuf-{i}", flatbuf_codec.decode_frame,
                           flatbuf_codec.encode_frame(f), False))
    except ImportError:  # flatbuffers runtime absent: skip that codec
        pass

    # deterministic structure-aware pass, then seeded random fill to
    # reach the requested case count
    structured = 0
    plans = []
    for label, decode, buf, aware in corpus:
        boundaries = _walk_frame_boundaries(buf) if aware else sorted(
            {0, 1, 4, len(buf) // 2, max(0, len(buf) - 1), len(buf)})
        len_fields = _len_field_offsets(buf) if aware else []
        plans.append((label, decode, buf, boundaries, len_fields))
        structured += len(boundaries) + len(len_fields) * len(EVIL)
    n_random = max(0, args.iterations - structured)
    per_item = n_random // len(plans) + 1

    tracemalloc.start()
    try:
        for label, decode, buf, boundaries, len_fields in plans:
            # the pristine buffer must still decode cleanly
            runner.run(f"{label}/valid", decode, buf)
            for tag, mut in _mutants(rng, buf, boundaries, len_fields,
                                     per_item):
                runner.run(f"{label}/{tag}", decode, mut)
    finally:
        tracemalloc.stop()

    if not args.quiet:
        print(
            f"fuzz_wire: {runner.cases} cases (seed {args.seed}) — "
            f"{runner.clean} clean decodes, {runner.wire_errors} typed "
            f"WireErrors, {len(runner.failures)} failures; "
            f"max decode {runner.max_elapsed * 1e3:.1f} ms, "
            f"max alloc {runner.max_alloc} B"
        )
    for label, msg, prefix in runner.failures[:20]:
        print(f"FAIL {label}: {msg}  buf[:64]={prefix}", file=sys.stderr)
    if runner.cases < args.iterations:
        print(f"FAIL: only {runner.cases} cases generated "
              f"(< {args.iterations})", file=sys.stderr)
        return 1
    return 1 if runner.failures else 0


if __name__ == "__main__":
    sys.exit(main())
