#!/usr/bin/env python
"""Chip-free perf truth: committed CPU-proxy baselines + trend ledger.

TPU bench rows go stale whenever the dev tunnel wedges (TUNNEL_OUTAGE.md
— stale since 2026-07-31 as of this writing), and the ``pytest -m perf``
floors are deliberately generous binary gates (e.g. the slot-multiplex
floor is 2x while steady state measures ~2.5-3x), so a 20% regression
can ship silently between chip windows.  This tool closes that gap with
a committed DISTRIBUTION per perf axis instead of a hand-picked floor:

* ``--update``   runs every axis harness k times, records median + MAD
  (median absolute deviation) into ``PERF_BASELINE.json`` at the repo
  root — committed, so the baseline diff shows up in review like any
  other contract change.
* ``--check``    re-runs each axis (best-of-k with early exit: ambient
  box load only ever LOWERS these numbers, so one clean run proves
  capability) and fails when an axis cannot reach its regression floor
  ``median - tol``.  ``--fast`` restricts to the sub-second axes — the
  subset the tier-1 perf smoke runs on every PR.
* ``--report``   emits a markdown (or ``--json``) trend report: the
  committed baseline table plus every banked ``BENCH_*.json`` evidence
  row, each stamped with its age and LOUDLY labeled STALE when it is
  chip evidence older than the staleness threshold.
* ``--self-test`` verifies the tolerance math against the committed
  baseline: a value exactly 25% below an axis median must classify as a
  regression, the median itself must pass.  Deterministic — no clocks.

Tolerance math (see Documentation/observability.md "Perf truth"):
``tol = clamp(MAD_MULT * mad, REL_MIN * median, REL_MAX * median)``.
The MAD term absorbs each axis's measured run-to-run noise; the REL_MIN
floor keeps near-zero-MAD axes from flaking on scheduler jitter; the
REL_MAX cap guarantees a 25% regression ALWAYS trips, however noisy the
update run was.  Check-side best-of-k (runs stop at the first pass)
turns residual flake probability p into p^k.

Every axis runs the SHARED harness bench.py / tools/bench_wire.py
already publish (``measure_fuse_overhead``, ``measure_dispatch_overlap``,
``measure_ingest_overlap``, ``measure_pipeline_vs_raw``,
``measure_slot_multiplex_speedup``, ``measure_generate_throughput``,
``measure_crc_bandwidth``) — the evidence row, the perf-smoke floor, and
this baseline can never measure different things.

Env: ``PERF_TRUTH_HANDICAP=0.75`` multiplies every measured sample (a
live regression-injection knob for exercising the gate end-to-end).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time
from typing import Callable, Dict, List, Optional

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.dirname(os.path.abspath(__file__))
BASELINE_PATH = os.path.join(ROOT, "PERF_BASELINE.json")

for p in (ROOT, TOOLS):
    if p not in sys.path:
        sys.path.insert(0, p)

# -- tolerance constants (the self-test pins their consequences) ------------
MAD_MULT = 4.0   # absorbed run-to-run noise: median - 4*MAD
REL_MIN = 0.08   # >= 8% of median, so a zero-MAD axis never flakes
REL_MAX = 0.20   # <= 20% of median, so a 25% regression ALWAYS trips
STALE_AFTER_DAYS = 2.0  # chip evidence older than this is labeled STALE


def _force_cpu() -> None:
    """The perf-truth layer is chip-free BY CONSTRUCTION: pin jax to CPU
    (env + config, like tests/conftest.py — the container sitecustomize
    force-points jax at the tunnel).  When jax has not been imported yet
    this also requests a 2-device virtual CPU PROXY MESH (XLA_FLAGS —
    the tests/_env_capabilities.py probe's mechanism) so the
    sharded_overhead axis constructs real meshes; with jax already
    loaded the single-device-equivalent dp:1 harness still measures."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if ("xla_force_host_platform_device_count" not in flags
            and "jax" not in sys.modules):
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2"
        ).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception as e:  # jax genuinely absent / misconfigured:
        # the harnesses will fail loudly themselves; note it and move on
        print(f"[perf_truth] jax cpu pin failed: {e}", file=sys.stderr)


def _bench():
    import bench

    return bench


def _bench_wire():
    import bench_wire

    return bench_wire


# ---------------------------------------------------------------------------
# Axes: name -> (harness label, unit, fast?, k_update, k_check, fn)
# ---------------------------------------------------------------------------
class Axis:
    def __init__(self, name: str, harness: str, unit: str, fast: bool,
                 k_update: int, k_check: int, fn: Callable[[], float]):
        self.name = name
        self.harness = harness
        self.unit = unit
        self.fast = fast
        self.k_update = k_update
        self.k_check = k_check
        self.fn = fn


def _axes() -> Dict[str, Axis]:
    return {a.name: a for a in (
        Axis("fuse_speedup", "bench.measure_fuse_overhead", "x",
             True, 5, 3,
             lambda: _bench().measure_fuse_overhead(
                 n_frames=6000, cap_s=30.0)["fuse_speedup"]),
        Axis("ingest_overlap", "bench.measure_ingest_overlap", "x",
             True, 5, 2,
             lambda: (lambda s, l: s / l)(
                 *_bench().measure_ingest_overlap(nb=14))),
        Axis("crc_bandwidth_mb_s", "bench_wire.measure_crc_bandwidth",
             "MB/s", True, 5, 2,
             lambda: _bench_wire().measure_crc_bandwidth()),
        Axis("dispatch_overlap", "bench.measure_dispatch_overlap", "ratio",
             False, 3, 2,
             lambda: _bench().measure_dispatch_overlap(
                 nbatches=24)["dispatch_overlap"]),
        Axis("pipeline_vs_raw", "bench.measure_pipeline_vs_raw", "ratio",
             False, 3, 2,
             lambda: (lambda raw, pipe: pipe / raw)(
                 *_bench().measure_pipeline_vs_raw(nbatches=24))),
        Axis("slot_multiplex", "bench.measure_slot_multiplex_speedup", "x",
             False, 5, 2,
             # max_new=96: long enough that join/prefill transients wash
             # out (at 48 the ratio is bimodal, 2.3-3.6; at 96 it holds
             # within ~5%) — the gate needs a tight distribution
             lambda: _bench().measure_slot_multiplex_speedup(
                 slots=4, streams=4, max_new=96, chunk=8)["sim_speedup"]),
        Axis("generate_tokens_per_s", "bench.measure_generate_throughput",
             "tokens/s", False, 2, 2,
             lambda: _bench().measure_generate_throughput(
                 slots=4, streams=4, max_new=24, chunk=8,
                 timeout_s=180.0)["tokens_per_s"]),
        # shared-prefix KV cache: cold/warm TTFT ratio at 256 shared
        # tokens on the CPU-proxy zoo transformer.  The hard product
        # floor (warm <= 0.5x cold, i.e. ratio >= 2.0) is pinned in
        # pytest -m perf over the SAME harness; this axis additionally
        # trend-gates the measured distribution.
        Axis("prefix_ttft_speedup", "bench.measure_prefix_ttft", "x",
             False, 3, 2,
             lambda: _bench().measure_prefix_ttft(
                 trials=3)["prefix_ttft_speedup"]),
        # mesh plumbing on a single-device-equivalent proxy mesh: fps
        # ratio sharded/unsharded (1.0 = free; interleaved rounds cancel
        # ambient load).  The dp:2 aggregate floor lives in pytest -m
        # perf over the same measure_sharded_overhead harness.
        Axis("sharded_overhead", "bench.measure_sharded_overhead", "ratio",
             False, 5, 2,
             lambda: _bench().measure_sharded_overhead()["sharded_ratio"]),
    )}


def _handicap() -> float:
    try:
        return float(os.environ.get("PERF_TRUTH_HANDICAP", "1.0"))
    except ValueError:
        return 1.0


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(xs: List[float], med: Optional[float] = None) -> float:
    med = _median(xs) if med is None else med
    return _median([abs(x - med) for x in xs])


# ---------------------------------------------------------------------------
# Tolerance math (pure — the self-test and the unit tests pin this)
# ---------------------------------------------------------------------------
def tolerance(median: float, mad: float) -> float:
    """Allowed downward slack before a fresh value counts as regressed."""
    return min(max(MAD_MULT * mad, REL_MIN * abs(median)),
               REL_MAX * abs(median))


def regression_floor(entry: Dict) -> float:
    """The committed floor for one baseline axis entry."""
    return entry["median"] - tolerance(entry["median"], entry["mad"])


def classify(value: float, entry: Dict) -> str:
    """'ok' | 'regression' for a fresh measurement against a baseline
    axis entry (all axes are higher-is-better)."""
    return "ok" if value >= regression_floor(entry) else "regression"


# ---------------------------------------------------------------------------
# Baseline I/O
# ---------------------------------------------------------------------------
def load_baseline(path: str = BASELINE_PATH) -> Dict:
    with open(path) as f:
        base = json.load(f)
    if not isinstance(base, dict) or not isinstance(base.get("axes"), dict):
        raise ValueError(f"{path}: not a perf-truth baseline")
    return base


def update(axes: Optional[List[str]] = None, k: Optional[int] = None,
           path: str = BASELINE_PATH, verbose: bool = True) -> Dict:
    """Re-measure every axis k times and (re)write the committed
    baseline.  Returns the baseline dict."""
    _force_cpu()
    bench = _bench()
    catalog = _axes()
    names = axes or list(catalog)
    unknown = sorted(set(names) - set(catalog))
    if unknown:
        raise SystemExit(
            f"[perf_truth] unknown axis(es) {unknown}; "
            f"known: {sorted(catalog)}")
    handicap = _handicap()
    captured_at = bench._utc_iso()
    rev = bench.git_rev()
    out_axes: Dict[str, Dict] = {}
    for name in names:
        ax = catalog[name]
        runs = k or ax.k_update
        samples: List[float] = []
        for i in range(runs):
            t0 = time.time()
            v = float(ax.fn()) * handicap
            samples.append(round(v, 4))
            if verbose:
                print(f"[perf_truth] {name} run {i + 1}/{runs}: "
                      f"{v:.3f} {ax.unit} ({time.time() - t0:.1f}s)",
                      file=sys.stderr)
        med = _median(samples)
        entry = {
            "unit": ax.unit,
            "harness": ax.harness,
            "fast": ax.fast,
            "k": runs,
            "samples": samples,
            "median": round(med, 4),
            "mad": round(_mad(samples, med), 4),
            # per-axis provenance: a partial --update --axes merge keeps
            # untouched axes' OWN capture stamps — bisecting against an
            # axis's git_rev must point at the commit that measured it,
            # not whichever run last touched the file
            "captured_at": captured_at,
            "git_rev": rev,
        }
        entry["floor"] = round(regression_floor(entry), 4)
        out_axes[name] = entry
    baseline = {
        "schema": 1,
        # top-level stamp = the LAST update run (per-axis stamps above
        # are authoritative for each axis's samples)
        "captured_at": captured_at,
        "git_rev": rev,
        "platform": "cpu",
        "tolerance": {"mad_mult": MAD_MULT, "rel_min": REL_MIN,
                      "rel_max": REL_MAX},
        "axes": out_axes,
    }
    if os.path.exists(path):  # partial --update --axes keeps other axes
        try:
            old = load_baseline(path)
            merged = dict(old.get("axes", {}))
            merged.update(out_axes)
            baseline["axes"] = merged
        except (OSError, ValueError):
            pass
    with open(path, "w") as f:
        json.dump(baseline, f, indent=1, sort_keys=True)
        f.write("\n")
    if verbose:
        print(f"[perf_truth] wrote {path}", file=sys.stderr)
    return baseline


# ---------------------------------------------------------------------------
# Check: fresh best-of-k vs the committed distribution
# ---------------------------------------------------------------------------
def check(fast: bool = False, axes: Optional[List[str]] = None,
          k: Optional[int] = None, path: str = BASELINE_PATH,
          baseline: Optional[Dict] = None, handicap: Optional[float] = None,
          verbose: bool = True) -> Dict:
    """Compare fresh runs against the committed baseline.

    Best-of-k with early exit per axis: the first run at-or-above the
    regression floor proves the capability still exists (ambient load
    only lowers these numbers); only k consecutive below-floor runs
    report a regression.  Returns the report dict (``ok`` key)."""
    _force_cpu()
    base = baseline if baseline is not None else load_baseline(path)
    catalog = _axes()
    handicap = _handicap() if handicap is None else float(handicap)
    names = axes or [
        n for n, ax in catalog.items()
        if (not fast or ax.fast) and n in base["axes"]
    ]
    bad = sorted(n for n in names
                 if n not in catalog or n not in base["axes"])
    if bad:
        raise SystemExit(
            f"[perf_truth] axis(es) {bad} not in both the harness "
            "catalog and the committed baseline (run --update after "
            f"adding an axis); checkable: "
            f"{sorted(set(catalog) & set(base['axes']))}")
    report: Dict = {
        "ok": True,
        "fast": fast,
        "baseline_captured_at": base.get("captured_at"),
        "baseline_git_rev": base.get("git_rev"),
        "baseline_age_days": _bench().age_days(
            base.get("captured_at", "")),
        "axes": {},
    }
    for name in names:
        entry = base["axes"][name]
        ax = catalog[name]
        floor = regression_floor(entry)
        runs: List[float] = []
        verdict = "regression"
        for i in range(k or ax.k_check):
            v = float(ax.fn()) * handicap
            runs.append(round(v, 4))
            if verbose:
                print(f"[perf_truth] check {name} run {i + 1}: "
                      f"{v:.3f} vs floor {floor:.3f} {ax.unit}",
                      file=sys.stderr)
            if classify(v, entry) == "ok":
                verdict = "ok"
                break  # capability proven; no need to burn more runs
        report["axes"][name] = {
            "value": max(runs),
            "runs": runs,
            "unit": entry["unit"],
            "baseline_median": entry["median"],
            "baseline_mad": entry["mad"],
            "floor": round(floor, 4),
            "verdict": verdict,
        }
        if verdict != "ok":
            report["ok"] = False
    return report


def self_test(path: str = BASELINE_PATH,
              baseline: Optional[Dict] = None) -> List[str]:
    """Deterministic tolerance-math verification against the committed
    baseline (no measurement, no clocks): for EVERY axis, a value 25%
    below the median must classify as a regression and the median itself
    must pass.  Returns problems (empty = the gate can detect a 25%
    regression on every committed axis)."""
    base = baseline if baseline is not None else load_baseline(path)
    problems: List[str] = []
    for name, entry in base["axes"].items():
        if classify(entry["median"], entry) != "ok":
            problems.append(
                f"{name}: the baseline median itself fails its floor "
                f"({entry['median']} < {regression_floor(entry):.4f})")
        if classify(entry["median"] * 0.75, entry) != "regression":
            problems.append(
                f"{name}: a 25% regression passes undetected "
                f"({entry['median'] * 0.75:.4f} >= "
                f"{regression_floor(entry):.4f})")
        if entry["median"] <= 0:
            problems.append(f"{name}: non-positive baseline median")
    return problems


# ---------------------------------------------------------------------------
# Trend report: committed baseline + banked BENCH_* history with ages
# ---------------------------------------------------------------------------
def _extract_rows(doc, source: str) -> List[Dict]:
    """Evidence rows from any of the repo's bench artifact shapes:
    driver artifacts ({"parsed": row}), row lists, the evidence cache
    ({sig: {captured_at, row}}), and {"rows": [...]} containers."""
    rows: List[Dict] = []

    def add(row, captured=None):
        if isinstance(row, dict) and row.get("metric"):
            rows.append({**row, "_source": source,
                         "_captured": captured or row.get("stale_since")
                         or row.get("captured_at")})

    if isinstance(doc, dict):
        if isinstance(doc.get("parsed"), dict):
            add(doc["parsed"])
        elif isinstance(doc.get("rows"), list):
            for r in doc["rows"]:
                add(r)
        else:
            for ent in doc.values():
                if isinstance(ent, dict) and isinstance(
                        ent.get("row"), dict):
                    add(ent["row"], ent.get("captured_at"))
    elif isinstance(doc, list):
        for r in doc:
            add(r)
    return rows


def collect_history(root: str = ROOT) -> List[Dict]:
    rows: List[Dict] = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_*.json"))):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        rows.extend(_extract_rows(doc, os.path.basename(path)))
    return rows


def _row_status(row: Dict, now: float) -> str:
    age = _bench().age_days(row.get("_captured") or "", now=now)
    plat = row.get("platform")
    chip = plat not in (None, "cpu")
    if row.get("value") is None:
        return "failed (no value)"
    tag = f"{age}d old" if age is not None else "age unknown"
    if chip and (age is None or age > STALE_AFTER_DAYS):
        return f"STALE chip evidence ({tag}) — live probe not confirming"
    if row.get("stale"):
        return f"stale-served ({tag})"
    return tag


def trend_report(root: str = ROOT, baseline_path: str = BASELINE_PATH,
                 now: Optional[float] = None) -> Dict:
    """The trend ledger as a dict; ``render_markdown`` formats it."""
    now = time.time() if now is None else now
    out: Dict = {"generated_at": _bench()._utc_iso(now), "baseline": None,
                 "history": []}
    if os.path.exists(baseline_path):
        try:
            out["baseline"] = load_baseline(baseline_path)
        except (OSError, ValueError) as e:
            out["baseline_error"] = str(e)
    for row in collect_history(root):
        item = {
            "metric": row.get("metric"),
            "value": row.get("value"),
            "unit": row.get("unit"),
            "platform": row.get("platform"),
            "captured": row.get("_captured"),
            "age_days": _bench().age_days(row.get("_captured") or "",
                                          now=now),
            "source": row.get("_source"),
            "status": _row_status(row, now),
        }
        if isinstance(row.get("cpu_proxy"), dict):
            proxy = dict(row["cpu_proxy"])
            item["cpu_proxy"] = {
                k: proxy.get(k) for k in (
                    "dispatch_overlap", "pipeline_vs_raw",
                    "ingest_overlap_speedup", "git_rev", "captured_at")
                if k in proxy
            }
        out["history"].append(item)
    return out


def render_markdown(report: Dict) -> str:
    lines = ["# Perf truth report", "",
             f"Generated {report['generated_at']} "
             "(tools/perf_truth.py --report)", ""]
    base = report.get("baseline")
    if base:
        age = _bench().age_days(base.get("captured_at", ""))
        lines += [
            "## Committed CPU-proxy baselines (PERF_BASELINE.json)", "",
            f"Captured {base.get('captured_at')} at rev "
            f"`{base.get('git_rev')}` ({age} days ago).", "",
            "| axis | median | MAD | regression floor | unit | "
            "captured (rev) | harness |",
            "|---|---|---|---|---|---|---|",
        ]
        for name, e in sorted(base["axes"].items()):
            # per-axis provenance: partial --update runs leave untouched
            # axes on their own (older) capture stamp
            cap = e.get("captured_at", base.get("captured_at"))
            rev = e.get("git_rev", base.get("git_rev"))
            lines.append(
                f"| {name} | {e['median']} | {e['mad']} | "
                f"{regression_floor(e):.4f} | {e['unit']} | "
                f"{cap} (`{rev}`) | `{e['harness']}` |")
        lines.append("")
    else:
        lines += ["## No committed baseline",
                  "Run `python tools/perf_truth.py --update`.", ""]
    stale = [h for h in report["history"] if h["status"].startswith("STALE")]
    lines += ["## Banked bench evidence", ""]
    if stale:
        lines += [
            f"**{len(stale)} STALE chip row(s)** — TPU evidence older "
            f"than {STALE_AFTER_DAYS:g} days with no live confirmation; "
            "between chip windows the CPU-proxy baselines above are the "
            "ONLY regression signal.", ""]
    lines += ["| metric | value | platform | captured | status | source |",
              "|---|---|---|---|---|---|"]
    for h in report["history"]:
        lines.append(
            f"| {h['metric']} | {h['value']} | {h['platform']} | "
            f"{h['captured']} | {h['status']} | {h['source']} |")
    lines.append("")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--update", action="store_true",
                    help="re-measure and rewrite PERF_BASELINE.json")
    ap.add_argument("--check", action="store_true",
                    help="compare fresh runs against the baseline")
    ap.add_argument("--fast", action="store_true",
                    help="restrict --check/--update to the fast axes")
    ap.add_argument("--report", action="store_true",
                    help="emit the trend report (markdown)")
    ap.add_argument("--json", action="store_true",
                    help="emit reports as JSON instead of markdown")
    ap.add_argument("--self-test", action="store_true",
                    help="verify the tolerance math on the baseline")
    ap.add_argument("--axes", default="",
                    help="comma-separated axis subset")
    ap.add_argument("--k", type=int, default=0,
                    help="override per-axis run count")
    ap.add_argument("--baseline", default=BASELINE_PATH)
    args = ap.parse_args(argv)
    axes = [a for a in args.axes.split(",") if a] or None
    k = args.k or None
    if args.self_test:
        problems = self_test(path=args.baseline)
        for p in problems:
            print(f"[perf_truth] {p}")
        print("self-test: " + ("FAIL" if problems else
                               "ok (25% regression detectable on every "
                               "axis)"))
        return 1 if problems else 0
    if args.update:
        if args.fast and axes is None:
            axes = [n for n, a in _axes().items() if a.fast]
        update(axes=axes, k=k, path=args.baseline)
        return 0
    if args.check:
        rep = check(fast=args.fast, axes=axes, k=k, path=args.baseline)
        print(json.dumps(rep, indent=1))
        if not rep["ok"]:
            bad = [n for n, a in rep["axes"].items()
                   if a["verdict"] != "ok"]
            print(f"[perf_truth] REGRESSION on: {', '.join(bad)}",
                  file=sys.stderr)
        return 0 if rep["ok"] else 1
    if args.report:
        rep = trend_report()
        print(json.dumps(rep, indent=1) if args.json
              else render_markdown(rep))
        return 0
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
