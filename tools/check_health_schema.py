#!/usr/bin/env python3
"""Lint gate: health/telemetry schema stability.

Every ``health_info()`` key and every registered metric name is part of
the observability API — dashboards, the perf driver, and fleet routing
consume them by name, so a silent rename is a breaking change nothing in
the type system catches.  This lint (run from tier-1 alongside
``check_no_bare_except`` / ``check_blocking_timeouts``) enforces three
contracts, statically (AST only — no imports, no side effects):

1. **Snapshot**: the union of health keys + metric names must equal
   ``tools/health_schema.json``.  A deliberate schema change regenerates
   it (``--write``) — the diff then shows up in review; an accidental
   rename fails loudly.
2. **Documented**: every name must appear backticked in
   ``Documentation/*.md`` (the observability reference tables).
3. **Catalogued**: every ``nns.*`` metric-name literal used by element
   ``metrics_info()`` hooks or the telemetry collector must be declared
   in ``telemetry.METRICS``, and every ``HEALTH_KEY_METRICS`` target
   must resolve into the catalog.

What is scanned: functions named ``health_info`` / ``liveness_snapshot``
/ ``metrics_info`` anywhere in the package, plus the scoped set below
(``Pipeline.health``, breaker/swap/admission ``snapshot``s, and the two
span-schema builders) — string dict-literal keys and string subscript
assignments inside them.
"""

from __future__ import annotations

import ast
import json
import os
import sys
from typing import Dict, List, Set, Tuple

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "nnstreamer_tpu")
SNAPSHOT_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "health_schema.json")
DOC_DIRS = [os.path.join(ROOT, "Documentation")]
DOC_FILES = [os.path.join(ROOT, "README.md")]

#: function names scanned wherever they appear in the package
SCAN_FUNCS = {"health_info", "liveness_snapshot", "metrics_info"}
#: (relative path -> function names) scanned only there
SCAN_SCOPED: Dict[str, Set[str]] = {
    "pipeline/pipeline.py": {"health"},
    "core/resilience.py": {"snapshot"},       # CircuitBreaker
    "core/lifecycle.py": {"snapshot"},        # HotSwapCoordinator
    "core/liveness.py": {"snapshot"},         # Watchdog + Admission
    "elements/query.py": {"_note_span"},      # client span + remote agg
    "distributed/service.py": {"_stamp_server_spans"},  # server span
}
TELEMETRY_PY = os.path.join(PKG, "core", "telemetry.py")


def _iter_sources():
    for dirpath, dirnames, filenames in os.walk(PKG):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def _str_keys(fn_node: ast.AST) -> Set[str]:
    """String dict-literal keys + string subscript-assign keys inside one
    function body."""
    keys: Set[str] = set()
    for node in ast.walk(fn_node):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)):
                    keys.add(t.slice.value)
    return keys


def _metric_literals(fn_node: ast.AST) -> Set[str]:
    """Every complete ``nns.*`` string literal inside one function
    (f-string fragments — dynamic names like the ``nns.health.<key>``
    auto-map — are excluded)."""
    in_fstring = {
        id(v) for node in ast.walk(fn_node)
        if isinstance(node, ast.JoinedStr) for v in node.values
    }
    out: Set[str] = set()
    for node in ast.walk(fn_node):
        if (isinstance(node, ast.Constant) and isinstance(node.value, str)
                and node.value.startswith("nns.")
                and id(node) not in in_fstring):
            out.add(node.value)
    return out


def collect() -> Tuple[Set[str], Set[str], Set[str], List[str]]:
    """(health_keys, metric_names_catalog, metric_literals_used,
    parse_problems)."""
    health_keys: Set[str] = set()
    used_metrics: Set[str] = set()
    problems: List[str] = []
    for path in _iter_sources():
        rel = os.path.relpath(path, PKG).replace(os.sep, "/")
        want = set(SCAN_FUNCS) | SCAN_SCOPED.get(rel, set())
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError as e:
            problems.append(f"{rel}: unparseable: {e}")
            continue
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name not in want:
                continue
            keys = _str_keys(node)
            # metric-name literals are catalogued, not health keys
            health_keys |= {k for k in keys if not k.startswith("nns.")}
            used_metrics |= _metric_literals(node)
    # telemetry catalog (METRICS) + the health-key mapping targets
    catalog: Set[str] = set()
    mapping_targets: Set[str] = set()
    with open(TELEMETRY_PY) as f:
        tree = ast.parse(f.read(), filename=TELEMETRY_PY)
    for node in tree.body:
        target = None
        value = None
        if isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            target, value = node.target.id, node.value
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            target, value = node.targets[0].id, node.value
        if target == "METRICS" and isinstance(value, ast.Dict):
            for k in value.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    catalog.add(k.value)
        elif target == "HEALTH_KEY_METRICS" and isinstance(value, ast.Dict):
            for k, v in zip(value.keys, value.values):
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    health_keys.add(k.value)
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    mapping_targets.add(v.value)
        elif target == "HEALTH_KEYS_SPECIAL" and isinstance(
                value, (ast.Tuple, ast.List)):
            for el in value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    health_keys.add(el.value)
    # the collector itself uses literal metric names too
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and \
                node.name == "collect_pipeline":
            used_metrics |= _metric_literals(node)
    for m in sorted(mapping_targets - catalog):
        problems.append(
            f"HEALTH_KEY_METRICS maps to {m!r}, which is not in "
            "telemetry.METRICS")
    return health_keys, catalog, used_metrics, problems


def _doc_text() -> str:
    chunks = []
    for d in DOC_DIRS:
        for dirpath, _dirnames, filenames in os.walk(d):
            for fn in filenames:
                if fn.endswith(".md"):
                    with open(os.path.join(dirpath, fn)) as f:
                        chunks.append(f.read())
    for p in DOC_FILES:
        if os.path.exists(p):
            with open(p) as f:
                chunks.append(f.read())
    return "\n".join(chunks)


def scan() -> List[str]:
    """All schema problems (empty = clean).  Importable from tests."""
    health_keys, catalog, used_metrics, problems = collect()
    # 3. catalog coverage for metric literals actually used
    for m in sorted(used_metrics - catalog):
        if m.startswith("nns.health."):
            continue  # the documented auto-map namespace
        problems.append(
            f"metric literal {m!r} is used but not declared in "
            "telemetry.METRICS")
    # 2. documentation coverage (backticked occurrence)
    docs = _doc_text()
    for name in sorted(health_keys | catalog):
        if f"`{name}`" not in docs:
            problems.append(
                f"{name!r} is not documented (no backticked mention in "
                "Documentation/*.md)")
    # 1. snapshot equality
    current = {
        "health_keys": sorted(health_keys),
        "metric_names": sorted(catalog),
    }
    if not os.path.exists(SNAPSHOT_PATH):
        problems.append(
            f"snapshot {SNAPSHOT_PATH} missing; run "
            "`python tools/check_health_schema.py --write`")
        return problems
    with open(SNAPSHOT_PATH) as f:
        snap = json.load(f)
    for field in ("health_keys", "metric_names"):
        have = set(current[field])
        want = set(snap.get(field, []))
        for name in sorted(want - have):
            problems.append(
                f"{field}: {name!r} disappeared from the code — a silent "
                "rename/removal breaks consumers; if deliberate, update "
                "Documentation/observability.md and regenerate the "
                "snapshot (--write)")
        for name in sorted(have - want):
            problems.append(
                f"{field}: {name!r} is new — document it in "
                "Documentation/observability.md and regenerate the "
                "snapshot (--write)")
    return problems


def write_snapshot() -> None:
    health_keys, catalog, _used, problems = collect()
    for p in problems:
        print(f"[schema] {p}", file=sys.stderr)
    with open(SNAPSHOT_PATH, "w") as f:
        json.dump({
            "health_keys": sorted(health_keys),
            "metric_names": sorted(catalog),
        }, f, indent=1, sort_keys=True)
        f.write("\n")
    print(f"wrote {SNAPSHOT_PATH}")


def main() -> int:
    if "--write" in sys.argv[1:]:
        write_snapshot()
        return 0
    problems = scan()
    for p in problems:
        print(f"[schema] {p}")
    if problems:
        print(f"{len(problems)} health/metric schema problem(s)")
        return 1
    print("health/metric schema clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
