#!/usr/bin/env python
"""Among-device fan-out scaling: one client round-robining a model over N
server pipelines (BASELINE.md row 2: "multi-stream via tensor_query
fan-out, linear 1->8 chips").

Real multi-chip hardware is not reachable from this harness, so this
measures the SCALING SHAPE on localhost: N OS processes each run a
serversrc -> tensor_filter -> serversink pipeline (≙ one chip's worth of
serving), and the client fans frames across them with pipelined in-flight
requests.  On a pod, each server process sits on its own chip and the
same client code fans over hosts=chip0:p,chip1:p,... — the transport,
round-robin, and in-flight machinery exercised here is exactly what runs
there.

Prints one JSON line per N with throughput and efficiency vs N=1.

Env knobs:
  FANOUT_NS        comma list of server counts (default "1,2,4")
  FANOUT_FRAMES    frames per measurement (default 256)
  FANOUT_WORK_MS   per-frame model cost to emulate, in ms (default 20)
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_SERVER = """
import sys, time
sys.path.insert(0, {root!r})
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu.backends.custom_easy import register_custom_easy
from nnstreamer_tpu.pipeline import parse_pipeline

# deterministic service time: on real hardware each server's chip spends
# WORK_MS of device time per frame; on this shared-core host a CPU spin
# would make every "chip" fight for the same cores and measure nothing,
# so the device time is emulated with a sleep (GIL released, cores idle)
# — what remains under test is exactly the part that exists at pod scale:
# transport, round-robin fan-out, pipelined in-flight, ordered delivery.
def serve(inputs):
    time.sleep({work_ms} / 1000.0)
    return [np.asarray(inputs[0])]

register_custom_easy("sleepy", serve)
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 ! "
    "tensor_filter framework=custom-easy model=sleepy ! "
    "tensor_query_serversink"
)
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep(600)
"""


def run_scale(n_servers: int, frames: int, work_ms: float) -> float:
    import numpy as np

    from nnstreamer_tpu.pipeline import parse_pipeline

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    procs, ports = [], []
    script = _SERVER.format(root=ROOT, work_ms=work_ms)
    try:
        for _ in range(n_servers):
            p = subprocess.Popen(
                [sys.executable, "-c", script],
                stdout=subprocess.PIPE, text=True, env=env,
            )
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            assert line.startswith("PORT "), line
            ports.append(int(line.split()[1]))

        hosts = ",".join(f"127.0.0.1:{pt}" for pt in ports)
        pipe = parse_pipeline(
            f"appsrc name=a max-buffers={frames + 8} ! "
            f"tensor_query_client hosts={hosts} timeout=60 "
            f"max-in-flight={4 * n_servers} ! tensor_sink name=out",
            name=f"fanout{n_servers}",
        )
        pipe.start()
        frame = np.zeros((8,), np.float32)
        # warmup (server-side jit compile on every server)
        for _ in range(2 * n_servers):
            pipe["a"].push(frame)
        deadline = time.time() + 120
        while len(pipe["out"].frames) < 2 * n_servers and time.time() < deadline:
            time.sleep(0.02)
        t0 = time.perf_counter()
        for _ in range(frames):
            pipe["a"].push(frame)
        pipe["a"].end_of_stream()
        pipe.wait(timeout=300)
        done = len(pipe["out"].frames) - 2 * n_servers
        dt = time.perf_counter() - t0
        pipe.stop()
        return done / dt
    finally:
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    ns = [int(x) for x in os.environ.get("FANOUT_NS", "1,2,4").split(",")]
    frames = int(os.environ.get("FANOUT_FRAMES", "256"))
    work_ms = float(os.environ.get("FANOUT_WORK_MS", "20"))
    base = None
    for ns_i in ns:
        fps = run_scale(ns_i, frames, work_ms)
        if base is None:
            base = fps
        print(json.dumps({
            "metric": "query_fanout_scaling_fps",
            "n_servers": ns_i,
            "value": round(fps, 1),
            "unit": "fps",
            "efficiency_vs_1": round(fps / (base * ns_i), 3),
            "work_ms_per_frame": work_ms,
            "platform": "cpu-proxy",
        }), flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
