#!/usr/bin/env python
"""Among-device fan-out scaling: one client round-robining over N server
pipelines (BASELINE.md row 2: "multi-stream via tensor_query fan-out,
linear 1->8 chips").

Real multi-chip hardware is not reachable from this harness, so three
measurement modes bound the story on localhost
(≙ tensor_query_client.c:657 fan-out):

  sleepy    N servers each emulating WORK_MS of device time with a sleep
            (cores stay idle) — isolates the SCALING SHAPE of the
            round-robin/in-flight machinery from host compute contention.
  real      N servers each running the actual jax-xla MobileNet-v2
            pipeline on CPU (micro-batched) — end-to-end proof that the
            query transport moves real model traffic; absolute fps is
            CPU-bound and the N servers share one machine's cores, so
            efficiency here is a lower bound.
  echo      servers return frames untouched — measures the CLIENT
            CEILING: how many frames/s one client can serialize, frame,
            and keep in flight.  This is the number that must exceed
            chip rate (>=1000 fps) for the transport to never be the pod
            bottleneck.

Prints one JSON line per row and writes them all to BENCH_FANOUT.json
(or argv[1]).

Env knobs:
  FANOUT_MODES     comma list of modes (default "sleepy,real,echo")
  FANOUT_NS        comma list of server counts (default "1,2,4")
  FANOUT_FRAMES    frames per measurement (default 256)
  FANOUT_WORK_MS   sleepy mode: per-frame device time to emulate (ms)
  FANOUT_ECHO_PAYLOAD  echo mode: "mobilenet" (224x224x3 uint8, default)
                       or "small" (8 floats)
"""

import json
import os
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

_SERVER_COMMON = """
import os, sys, time
sys.path.insert(0, {root!r})
# core pinning: with enough host cores each server owns one, so the
# real-compute scaling curve measures the transport, not CPU contention
# (on a 1-core host this is a no-op and contention is unavoidable)
if {pin_core} >= 0:
    try:
        os.sched_setaffinity(0, {{{pin_core}}})
    except (AttributeError, OSError):
        pass
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
from nnstreamer_tpu.pipeline import parse_pipeline
"""

# deterministic service time: on real hardware each server's chip spends
# WORK_MS of device time per frame; on this shared-core host a CPU spin
# would make every "chip" fight for the same cores and measure nothing.
_SERVER_SLEEPY = _SERVER_COMMON + """
from nnstreamer_tpu.backends.custom_easy import register_custom_easy
def serve(inputs):
    time.sleep({work_ms} / 1000.0)
    return [np.asarray(inputs[0])]
register_custom_easy("sleepy", serve)
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 ! "
    "tensor_filter framework=custom-easy model=sleepy ! "
    "tensor_query_serversink"
)
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep(600)
"""

_SERVER_REAL = _SERVER_COMMON + """
from nnstreamer_tpu.backends.jax_xla import register_jax_model
from nnstreamer_tpu.models import build
fn, params, in_spec, out_spec = build("mobilenet_v2", {{"dtype": "float32"}})
register_jax_model("fanout_mnv2", fn, params, in_spec, out_spec)
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 ! "
    "tensor_converter ! "
    "tensor_transform mode=arithmetic option=typecast:float32,div:255 ! "
    "tensor_filter framework=jax-xla model=fanout_mnv2 "
    "max-batch=4 batch-timeout=10 ! "
    "tensor_query_serversink"
)
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep(600)
"""

_SERVER_ECHO = _SERVER_COMMON + """
from nnstreamer_tpu.backends.custom_easy import register_custom_easy
register_custom_easy("echo", lambda inputs: [np.asarray(inputs[0])])
pipe = parse_pipeline(
    "tensor_query_serversrc name=src port=0 connect-type={ct} ! "
    "tensor_filter framework=custom-easy model=echo ! "
    "tensor_query_serversink"
)
pipe.start()
print("PORT", pipe["src"].props["port"], flush=True)
time.sleep(600)
"""

_SCRIPTS = {"sleepy": _SERVER_SLEEPY, "real": _SERVER_REAL,
            "echo": _SERVER_ECHO}


def run_scale(mode: str, n_servers: int, frames: int,
              work_ms: float, payload, wire_batch: int = 1,
              connect_type: str = "grpc",
              block_ingest: bool = False) -> "tuple[float, bool, int]":
    from nnstreamer_tpu.pipeline import parse_pipeline

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    procs, ports = [], []
    # pin each server to its own core when the host has enough: the first
    # ALLOWED cpu id stays with the client, servers take the next N (real
    # ids from the affinity mask — cpuset-restricted hosts don't start at
    # 0).  ncores <= N means contention is unavoidable; report it
    # honestly instead of pinning
    have_affinity = hasattr(os, "sched_getaffinity")
    cpu_ids = sorted(os.sched_getaffinity(0)) if have_affinity else []
    ncores = len(cpu_ids) if cpu_ids else 1
    pinned = mode == "real" and ncores > n_servers
    saved_affinity = set(cpu_ids) if pinned else None
    if pinned:
        # the client owns the first allowed core so its framing threads
        # cannot contend with the pinned servers
        os.sched_setaffinity(0, {cpu_ids[0]})
    try:
        for i in range(n_servers):
            script = _SCRIPTS[mode].format(
                root=ROOT, work_ms=work_ms, ct=connect_type,
                pin_core=cpu_ids[1 + i] if pinned else -1)
            p = subprocess.Popen(
                [sys.executable, "-c", script],
                stdout=subprocess.PIPE, text=True, env=env,
            )
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            assert line.startswith("PORT "), line
            ports.append(int(line.split()[1]))

        hosts = ",".join(f"127.0.0.1:{pt}" for pt in ports)
        # the ceiling measurement wants a deep pipelined window; the
        # scaling measurements keep the serving-shaped 4/server window
        inflight = 16 if mode == "echo" else 4 * n_servers
        pipe = parse_pipeline(
            f"appsrc name=a max-buffers={frames + 8} ! "
            f"tensor_query_client hosts={hosts} timeout=120 "
            f"connect-type={connect_type} "
            f"max-in-flight={inflight} wire-batch={wire_batch} ! "
            "tensor_sink name=out",
            name=f"fanout{n_servers}",
        )
        pipe.start()
        # warmup (server-side jit compile on every server; the real-model
        # servers take tens of seconds cold, persistent cache warm after)
        n_warm = 2 * n_servers
        for _ in range(n_warm):
            pipe["a"].push(payload)
        deadline = time.time() + 240
        while len(pipe["out"].frames) < n_warm and time.time() < deadline:
            time.sleep(0.02)
        if len(pipe["out"].frames) < n_warm:
            raise RuntimeError(f"warmup incomplete ({mode}, N={n_servers})")
        t0 = time.perf_counter()
        if block_ingest and wire_batch > 1:
            # blocks map 1:1 onto the wire-batch envelope: per-frame push/
            # scheduler costs are paid once per RPC instead of once per
            # frame — the client-ceiling configuration for block streams
            import numpy as _np

            block = _np.stack([_np.asarray(payload)] * wire_batch)
            for _ in range(frames // wire_batch):
                pipe["a"].push_block(block)
        else:
            for _ in range(frames):
                pipe["a"].push(payload)
        pipe["a"].end_of_stream()
        pipe.wait(timeout=300)
        done = len(pipe["out"].frames) - n_warm
        dt = time.perf_counter() - t0
        pipe.stop()
        return done / dt, pinned, ncores
    finally:
        if saved_affinity is not None:
            os.sched_setaffinity(0, saved_affinity)
        for p in procs:
            p.kill()
        for p in procs:
            p.wait(timeout=10)


def main() -> int:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    out_path = sys.argv[1] if len(sys.argv) > 1 else "BENCH_FANOUT.json"
    modes = [
        m.strip()
        for m in os.environ.get("FANOUT_MODES", "sleepy,real,echo").split(",")
        if m.strip()
    ]
    bad = [m for m in modes if m not in _SCRIPTS]
    if bad:  # fail BEFORE burning minutes of measurement
        raise SystemExit(f"unknown FANOUT_MODES {bad}; valid: {sorted(_SCRIPTS)}")
    ns = [int(x) for x in os.environ.get("FANOUT_NS", "1,2,4").split(",")]
    frames = int(os.environ.get("FANOUT_FRAMES", "256"))
    work_ms = float(os.environ.get("FANOUT_WORK_MS", "20"))
    mobilenet_frame = np.random.default_rng(0).integers(
        0, 255, (224, 224, 3), dtype=np.uint8
    )
    rows = []

    def emit(row):
        print(json.dumps(row), flush=True)
        rows.append(row)
        # incremental write: a timeout/crash in a later (slower) mode
        # must not discard completed measurements
        with open(out_path, "w") as f:
            json.dump(rows, f, indent=2)

    for mode in modes:
        if mode == "echo":
            # client-ceiling matrix: payload size × wire batching — the
            # two levers deciding whether ONE client can pump chip rate.
            # 2 echo servers keep the server side off the critical path.
            for payload, wb, ct, blk in (
                (mobilenet_frame, 1, "grpc", False),
                (mobilenet_frame, 8, "grpc", False),
                (mobilenet_frame, 1, "tcp", False),
                (mobilenet_frame, 8, "tcp", False),
                (mobilenet_frame, 8, "tcp", True),
                (mobilenet_frame, 32, "tcp", True),
                (np.zeros((8,), np.float32), 8, "tcp", False),
                (np.zeros((8,), np.float32), 8, "grpc", False),
            ):
                fps, _, _ = run_scale("echo", 2, frames, work_ms, payload,
                                      wire_batch=wb, connect_type=ct,
                                      block_ingest=blk)
                emit({
                    "metric": "query_client_ceiling_fps",
                    "mode": "echo", "n_servers": 2,
                    "value": round(fps, 1), "unit": "fps",
                    "platform": "cpu-loopback",
                    "connect_type": ct,
                    "payload_bytes": int(payload.nbytes),
                    "wire_batch": wb,
                    "ingest": "block" if blk else "frame",
                })
            continue
        payload = (
            mobilenet_frame if mode == "real"
            else np.zeros((8,), np.float32)  # payload not under test
        )
        base = None
        # real mode: with core pinning each server owns a core, so allow
        # up to ncores-1 servers; on small hosts cap at 2 (beyond that
        # only contention is measured) — at CPU-mobilenet rates fewer
        # frames still give steady state.
        host_cores = (len(os.sched_getaffinity(0))
                      if hasattr(os, "sched_getaffinity") else 1)
        mode_ns = ([n for n in ns if n <= max(2, host_cores - 1)]
                   if mode == "real" else ns)
        mode_frames = min(frames, 48) if mode == "real" else frames
        for n in mode_ns:
            fps, pinned, ncores = run_scale(mode, n, mode_frames, work_ms, payload)
            if base is None:
                base = fps
            row = {
                "metric": "query_fanout_scaling_fps",
                "mode": mode,
                "n_servers": n,
                "value": round(fps, 1),
                "unit": "fps",
                "efficiency_vs_1": round(fps / (base * n), 3),
                "platform": "cpu-proxy" if mode == "sleepy" else "cpu-real",
                **({"work_ms_per_frame": work_ms}
                   if mode == "sleepy" else {}),
            }
            if mode == "real":
                row["core_pinned"] = pinned
                row["cores_available"] = ncores
                if not pinned and n > 1:
                    row["caveat"] = (
                        f"{ncores}-core host: servers share cores, "
                        "efficiency is contention not transport")
            emit(row)
    print(f"[bench_fanout] wrote {out_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
