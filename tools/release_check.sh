#!/usr/bin/env bash
# Release gate, executed locally (≙ the reference's
# .github/workflows/ubuntu_clean_meson_build.yml clean-room build):
# build the wheel, install it into a FRESH venv, and prove the installed
# artifact works — import from the package boundary, console scripts,
# a real pipeline run, native-core build from packaged sources.
#
# Offline-friendly: the venv uses --system-site-packages for the baked-in
# heavy deps (jax, numpy, grpc); the wheel itself installs with --no-deps
# so what's proven is OUR artifact, not the dependency resolver.
#
# Usage: bash tools/release_check.sh [workdir]
# Writes a full transcript to RELEASE_CHECK.log next to this repo's root.

set -euo pipefail

ROOT="$(cd "$(dirname "$0")/.." && pwd)"
WORK="${1:-$(mktemp -d /tmp/nns_release.XXXXXX)}"
LOG="$ROOT/RELEASE_CHECK.log"
: > "$LOG"

say() { echo "[release_check] $*" | tee -a "$LOG"; }
run() { say "+ $*"; "$@" >> "$LOG" 2>&1; }

say "workdir: $WORK"
say "python: $(python --version 2>&1)"

# 1. build the wheel from a clean dist dir
rm -rf "$WORK/dist"
run python -m pip wheel "$ROOT" --no-deps --no-build-isolation -w "$WORK/dist"
WHEEL="$(ls "$WORK"/dist/nnstreamer_tpu-*.whl)"
say "wheel: $(basename "$WHEEL") ($(stat -c%s "$WHEEL") bytes)"

# 2. fresh venv.  The baked-in deps live in the *parent* environment's
# site-packages (which is itself a venv here, so --system-site-packages
# would skip it); expose exactly that directory via a .pth instead.
run python -m venv "$WORK/venv"
VPY="$WORK/venv/bin/python"
DEPS_DIR="$(python -c 'import numpy, os; print(os.path.dirname(os.path.dirname(numpy.__file__)))')"
say "parent deps dir: $DEPS_DIR"
VSITE="$("$VPY" -c 'import sysconfig; print(sysconfig.get_paths()["purelib"])')"
echo "$DEPS_DIR" > "$VSITE/baked_deps.pth"
run "$VPY" -m pip install --no-deps --force-reinstall "$WHEEL"

# 3. the installed package imports from OUTSIDE the repo (no cwd tricks)
say "import check (cwd=/tmp, repo not on sys.path)"
(cd /tmp && run "$VPY" -c "
import sys
assert not any(p.rstrip('/').endswith('repo') for p in sys.path if p), sys.path
import nnstreamer_tpu
from nnstreamer_tpu.core.types import StreamSpec, TensorSpec
from nnstreamer_tpu.pipeline import parse_pipeline
print('import OK from', nnstreamer_tpu.__file__)
assert 'site-packages' in nnstreamer_tpu.__file__
")

# 4. console scripts, as installed by the wheel entry points
say "console scripts"
run "$WORK/venv/bin/nns-tpu-inspect" queue
run "$WORK/venv/bin/nns-tpu-check" --help
JAX_PLATFORMS=cpu run "$WORK/venv/bin/nns-tpu-launch" \
  "videotestsrc num-buffers=4 ! tensor_converter ! tensor_transform mode=arithmetic option=typecast:float32,div:255 ! tensor_sink"
# offline model conversion (importer -> .jaxexport), when the reference
# test models are around to convert (override with NNS_REF_TFLITE)
REF_TFLITE="${NNS_REF_TFLITE:-/root/reference/tests/test_models/models/add.tflite}"
if [ ! -f "$REF_TFLITE" ]; then
  say "convert->serve gate SKIPPED (no reference model at $REF_TFLITE)"
fi
if [ -f "$REF_TFLITE" ]; then
  (cd /tmp && JAX_PLATFORMS=cpu run "$VPY" -c "
import jax; jax.config.update('jax_platforms', 'cpu')
from nnstreamer_tpu.cli.convert import main
import numpy as np
assert main(['$REF_TFLITE', '$WORK/add.jaxexport']) == 0
from nnstreamer_tpu import SingleShot
with SingleShot('jax-xla', '$WORK/add.jaxexport') as m:
    (out,) = m.invoke([np.float32([1.5])])
    assert float(np.asarray(out)[0]) == 3.5, out
print('convert->serve OK')
")
fi

# 5. a real pipeline through the installed package (filter + decoder)
say "smoke pipeline (jax filter + decoder, CPU)"
(cd /tmp && JAX_PLATFORMS=cpu run "$VPY" -c "
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
from nnstreamer_tpu.backends.jax_xla import register_jax_model
from nnstreamer_tpu.pipeline import parse_pipeline
register_jax_model('rc_scale', lambda p, xs: [xs[0] * 2.0], {})
pipe = parse_pipeline('appsrc name=src ! tensor_filter framework=jax-xla model=rc_scale ! tensor_sink name=out')
pipe.start()
for i in range(3):
    pipe['src'].push(np.full((4,), float(i), np.float32))
pipe['src'].end_of_stream()
pipe.wait(timeout=60)
frames = pipe['out'].frames
pipe.stop()
assert len(frames) == 3, frames
np.testing.assert_allclose(frames[2].tensors[0], np.full((4,), 4.0))
print('pipeline OK:', [f.tensors[0][0] for f in frames])
")

# 6. native core builds from the wheel's packaged sources
say "native core build from installed package data"
(cd /tmp && run "$VPY" -c "
from nnstreamer_tpu.native import runtime
assert runtime.available(block=True), 'native core failed to build'
pool = runtime.BufferPool(block_size=1024, prealloc=2)
ptr, mv = pool.acquire(); mv[:4] = b'test'; pool.release(ptr)
assert pool.outstanding == 0
pool.destroy()
print('native OK:', runtime._load()._name)
")

# 7. CI-parity quick test slice against the installed wheel (the full
#    suite runs in CI / the dev tree; this proves the artifact is testable)
say "test slice against the installed wheel"
(cd "$WORK" && cp -r "$ROOT/tests" . && JAX_PLATFORMS=cpu run "$VPY" -m pytest \
  tests/test_core_types.py tests/test_pipeline.py tests/test_wire_interop.py -q)

say "ALL CHECKS PASSED"
