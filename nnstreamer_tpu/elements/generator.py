"""tensor_generator: streaming autoregressive generation (net-new).

The serving shape of interactive LLM inference, which the reference has no
analog for (its closest relative is recurrence emulation through
tensor_repo loops, ``tests/nnstreamer_repo_lstm``): ONE prompt frame in,
token CHUNKS streamed out as they decode.  Downstream elements
(detokenizer → sink / query serversink) run CONCURRENTLY with the next
chunk's decode — the pipeline's per-element threads are the streaming
transport, no extra machinery.

TPU-first structure: the zoo transformer's KV cache (device-resident
pytree) is carried across jitted calls — prefill is one causal pass, each
chunk is one ``lax.scan`` segment (compile buckets: one per distinct
chunk length, i.e. the chunk size + one tail, bounded by an LRU).  Python
dispatch cost is per CHUNK, not per token.  Sampling (greedy/temperature/
top-k, per-step key folding) is bit-identical to one-shot ``generate:<N>``
serving (``models/transformer.py make_stream_generate``).

Continuous batching (``slots=N``, core/slots.py): the element multiplexes
MANY concurrent prompt streams into one fixed-width slot batch — live
requests occupy slots, new prompts join at token boundaries via chunked
prefill interleaved with decode, finished/cancelled/deadline-evicted
streams free their slot immediately, and the idle-slot mask keeps the
jitted decode step shape-stable (zero retracing as streams churn).  A
single occupant's output stays bit-identical to the seed per-request
path.  The engine decodes on its own pump thread; chunks are EMITTED on
the element's dispatch thread (``handle_frame``/``handle_idle`` drain
``pop_ready``), so supervision attribution is unchanged — the PR-6
CompletionWindow discipline.

Emission contract: ``handle_frame`` returns frames/generators; the
scheduler pushes each yielded frame downstream as it is produced (frames
stream, they do not wait for the full completion).  Each chunk frame
carries tokens (B, n) int32 plus meta ``stream_seq`` (source frame seq),
``chunk_index``, ``tokens_done`` and ``final`` (evicted streams add
``evicted``/``deadline_expired`` — the typed expiry).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Optional

import numpy as np

from ..core.buffer import BatchFrame
from ..core.continuity import (
    RESUME_REJECT_META,
    RESUME_REQ_META,
    prompt_digest,
    resume_signature,
)
from ..core.liveness import (
    DEADLINE_META,
    PRIORITY_MAX,
    PRIORITY_META,
    TENANT_META,
    clamp_priority,
    thread_census,
)
from ..core.types import ANY, FORMAT_FLEXIBLE, StreamSpec
from ..pipeline.element import Element, ElementError, Property, element

#: bound on live decode-chunk jit buckets (LRU — the discipline of the
#: filter's _stack_jit_cache, PR-3): distinct chunk lengths churn (tail
#: chunks, reconfigured clients) but live executables stay bounded
_JIT_BUCKET_MAX = 16


@element("tensor_generator")
class TensorGenerator(Element):
    # a block of prompts streams each logical prompt in order (lazy chain)
    BATCH_AWARE = True

    PROPERTIES = {
        "custom": Property(
            str, "",
            "zoo-transformer dialect: vocab:N,d_model:N,heads:N,layers:N,"
            "d_ff:N,seq:N,seed:N[,temperature:F,top_k:N,gen_seed:N]",
        ),
        "max-new": Property(int, 32, "tokens to generate per prompt"),
        "chunk": Property(int, 8, "tokens per streamed chunk frame"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # continuous batching (core/slots.py): 0 = per-request streaming
        # (seed path), N>0 = N-wide slot batch shared by concurrent
        # streams (compile-once per width; requests join/leave at token
        # boundaries)
        "slots": Property(
            int, 0,
            "continuous-batching slot width: concurrent prompt streams "
            "share one fixed decode batch (0 = serve requests one at a "
            "time, the pre-slot path)"),
        "prefill-chunk": Property(
            int, 32,
            "prompt tokens prefilled per engine iteration when joining a "
            "slot (chunked prefill interleaves with decode so a long "
            "prompt never stalls live streams)"),
        "prefill-priority": Property(
            int, 1,
            "prefill chunks interleaved per decode step (0 = joining "
            "prompts prefill only while nothing is decoding — decode "
            "throughput over join latency)"),
        "token-budget-s": Property(
            float, 0.0,
            "per-token pace budget: a slotted stream that takes longer "
            "than this between tokens is evicted with the typed expiry "
            "(0 = off; the request's own deadline-s budget is always "
            "honored)"),
        # per-stream SLO accounting (core/telemetry.py SloTracker,
        # engine side): declarative objectives; burn-rate gauges are
        # computed at scrape time from the log2 histograms and exported
        # per tenant as nns.slo.* (0 = objective not armed)
        "slo-ttft-p95": Property(
            float, 0.0,
            "TTFT objective: 95% of fresh streams must emit their first "
            "token within this many seconds (0 = off)"),
        "slo-token-p99": Property(
            float, 0.0,
            "per-token objective: 99% of token inter-arrivals must be "
            "under this many seconds (0 = off)"),
        "slo-availability": Property(
            float, 0.0,
            "goodput objective, e.g. 0.999: completed streams / "
            "classified streams (shed+evicted+expired+errors are the "
            "error budget; 0 = off)"),
        # mesh-sharded decode (parallel/mesh.py grammar, tp only): the
        # slot batch's transformer runs tensor-parallel across a device
        # mesh — params tp-sharded, per-slot KV pages sharded on heads
        # along tp.  Token sequences are unchanged (the resume signature
        # deliberately excludes the mesh), so sharded and unsharded
        # servers can serve the same durable streams.
        "mesh": Property(
            str, "",
            "decode the slot batch tensor-parallel across a device mesh: "
            "'tp:N' (slots >= 1 required; empty = unsharded)"),
        # shared-prefix KV cache (core/slots.py PrefixCache): prompts
        # sharing a long common prefix (system prompt / few-shot header)
        # attach refcounted published pages instead of re-prefilling
        # them — the TTFT collapse for the dominant traffic shape.
        # OFF by default: zero behavior change until armed.
        "prefix-cache": Property(
            str, "off",
            "shared-prefix KV page pool: 'on' publishes each prompt's "
            "prefix pages at grain boundaries and attaches them to later "
            "prompts sharing the prefix, skipping their prefill entirely "
            "(slots >= 1; warm streams stay bit-identical to cold "
            "prefill; 'off' = the pre-cache path, byte-identical "
            "behavior)"),
        "prefix-grain": Property(
            int, 0,
            "prefix chunk grain in tokens (0 = the wire default, 64); "
            "rounded UP to a prefill-chunk multiple so warm and cold "
            "runs share the exact prefill chunk grid (bit-exactness)"),
        "prefix-cap": Property(
            int, 256,
            "max cached prefix entries (LRU among unreferenced entries "
            "past the cap; pinned entries are never reclaimed)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._prefill = None
        self._decode = None
        self._params = None
        self._max_seq = 0
        self._jit_chunks: "OrderedDict[int, Any]" = OrderedDict()
        self._engine = None
        self._mesh = None         # tp decode mesh (mesh= prop, slotted)
        self._mesh_axes = {}
        self._resume_sig = None   # token-sequence signature (slotted)
        self._resume_rejects = 0  # RESUME requests refused (mismatch)
        # device-loss resilience: lifetime degraded state + the device
        # ordinals excluded from any future mesh claim (the dead stay
        # dead across restarts of this element)
        self._degraded = False
        self._mesh_exclude = ()
        self._mesh_override = None  # survivor spec a re-shard leaves behind
        self._zoo_props = {}      # parsed custom dialect (rebuild hook)
        self._slots = 0
        self._sim = False
        self._prefix_pool = None  # PrefixCache (prefix-cache=on, slotted)
        self._slo = None          # SloTracker (slo-* props; slotted only)
        # autoscale resize actuation (core/autoscale.py): the requested
        # slot width, applied on the DISPATCH thread at the next idle
        # boundary after every live stream handed off resumably
        self._resize_target = 0
        self._resizes = 0
        # fenced actuation (core/autoscale.py LeaderLease): a resize
        # carrying a stale lease epoch is REFUSED — a deposed
        # controller's in-flight commands must not race the new leader
        from ..core.autoscale import FencingToken
        self._fence = FencingToken()

    def start(self):
        import jax

        from ..models.transformer import build_stream

        props = {}
        for part in self.props["custom"].split(","):
            if ":" in part:
                k, _, v = part.partition(":")
                props[k.strip()] = v.strip()
        props.pop("arch", None)  # tolerated for zoo-dialect symmetry
        self._resize_target = 0
        slots = int(self.props["slots"])
        if slots < 0:
            raise ElementError(f"{self.name}: slots must be >= 0")
        mesh = None
        self._mesh_axes = {}
        mesh_spec = self.props["mesh"]
        if self._mesh_override is not None:
            # a degraded re-shard left a survivor config behind: any
            # later restart keeps serving the shrunk mesh ("" =
            # unsharded) — the original spec no longer fits once the
            # dead ordinals are excluded from the claim
            mesh_spec = self._mesh_override
        if mesh_spec:
            from ..parallel.mesh import (
                claim_devices,
                make_mesh,
                parse_mesh_spec,
            )

            try:
                axes = parse_mesh_spec(mesh_spec)
            except ValueError as e:
                raise ElementError(f"{self.name}: {e}") from None
            if axes and set(axes) != {"tp"}:
                # the slot batch IS the data axis: scattering it over dp
                # would break the per-slot page/index layout, and sp/pp
                # have no decode-step story here — refuse loudly
                raise ElementError(
                    f"{self.name}: mesh={self.props['mesh']!r} — the "
                    "slotted decode path shards on tp only")
            if axes and slots < 1:
                raise ElementError(
                    f"{self.name}: mesh= requires slots >= 1 (the mesh "
                    "serves the slot batch)")
            if axes:
                mesh = make_mesh(
                    axes,
                    devices=claim_devices(
                        axes, exclude=self._mesh_exclude))
                self._mesh_axes = {k: mesh.shape[k] for k in axes}
        self._mesh = mesh
        # slotted mode needs its OWN mailbox + dispatch thread: the
        # scheduler's idle hook (handle_idle) and pending_frames fast-poll
        # only run for chain heads, and they are how engine-completed
        # chunks reach the wire between input frames.  Checked by the
        # fusion partition, which runs after start().
        self.THREAD_BOUNDARY = slots > 0
        if slots > 0:
            from ..core.slots import SimSlotModel, SlotEngine

            sim = props.get("sim", "") not in ("", "0", "false")
            # stream continuity: the signature covers everything that
            # determines the TOKEN sequence — two servers may serve the
            # same stream iff it matches (chunk size and sim timing
            # knobs deliberately excluded: they shape latency, not
            # tokens)
            max_new = int(self.props["max-new"])
            if sim:
                self._resume_sig = resume_signature(
                    "sim", vocab=int(props.get("vocab", "997")),
                    max_new=max_new)
            else:
                self._resume_sig = resume_signature(
                    "zoo", max_new=max_new, **{
                        k: props.get(k, "")
                        for k in ("vocab", "d_model", "heads", "layers",
                                  "d_ff", "seq", "seed", "gen_seed",
                                  "temperature", "top_k")
                    })
            if sim and mesh is not None:
                raise ElementError(
                    f"{self.name}: mesh= needs the real transformer "
                    "(custom sim: has no device placement)")
            if sim:
                # async-sim proxy (PR-6 discipline): deterministic token
                # recurrence + TPU-shaped step costs — drives the slot
                # SCHEDULER through the full pipeline without a model
                # (perf floors + chaos harness).  sim_oom_step /
                # sim_lost_step are the device-resource chaos twins:
                # decode attempt N raises the typed OOM / device-loss
                # error exactly once (core/resilience.py taxonomy).
                model = SimSlotModel(
                    slots,
                    vocab=int(props.get("vocab", "997")),
                    step_base_ms=float(props.get("sim_step_ms", "1.0")),
                    step_per_slot_ms=float(
                        props.get("sim_per_slot_ms", "0.05")),
                    prefill_ms_per_token=float(
                        props.get("sim_prefill_ms", "0.02")),
                    oom_at_step=(int(props["sim_oom_step"])
                                 if "sim_oom_step" in props else None),
                    lost_at_step=(int(props["sim_lost_step"])
                                  if "sim_lost_step" in props else None),
                )
                params = None
                self._max_seq = int(props.get("seq", str(1 << 30)))
            else:
                from ..models.transformer import build_slot_stream

                model, params, self._max_seq = build_slot_stream(
                    props, slots, mesh=mesh)
                params = self._place_on_survivor(params, mesh)
            self._params = params
            self._zoo_props = dict(props)
            self._slots = slots
            self._sim = sim
            self._slo = self._build_slo()
            self._prefix_pool = self._build_prefix_pool()
            self._engine = SlotEngine(
                model, params,
                max_seq=self._max_seq,
                chunk=max(1, int(self.props["chunk"])),
                prefill_chunk=int(self.props["prefill-chunk"]),
                prefill_priority=int(self.props["prefill-priority"]),
                token_budget_s=float(self.props["token-budget-s"]),
                name=self.name,
                resume_sig=self._resume_sig,
                on_device_lost=self._rebuild_on_device_loss,
                slo=self._slo,
                prefix_cache=self._prefix_pool,
            )
            self._engine.start()
            return
        if self.props["prefix-cache"] == "on":
            raise ElementError(
                f"{self.name}: prefix-cache=on needs slots >= 1 (the "
                "pool lives in the slot engine)")
        if props.get("sim", "") not in ("", "0", "false"):
            raise ElementError(
                f"{self.name}: custom sim: needs slots >= 1 (the sim "
                "proxy drives the slot engine; slots=1 is the "
                "request-serial baseline)")
        prefill, decode_chunk, params, self._max_seq = build_stream(props)
        self._prefill = jax.jit(prefill)
        self._decode = decode_chunk
        self._params = params
        self._jit_chunks = OrderedDict()

    def stop(self):
        if self._engine is not None:
            self._engine.stop()
            self._engine = None
        self._prefix_pool = None  # restart is deliberately cache-cold
        self._prefill = self._decode = self._params = None
        self._jit_chunks.clear()

    def _decode_n(self, n: int):
        import jax

        from ..core.slots import lru_bucket

        def build(k):
            return jax.jit(
                lambda p, cache, tok, t0: self._decode(p, cache, tok, t0, k)
            )

        return lru_bucket(self._jit_chunks, n, build, _JIT_BUCKET_MAX)

    # -- negotiation --------------------------------------------------------
    def accept_spec(self, pad, spec):
        return spec

    def derive_spec(self, pad=0):
        # chunk length varies (tail chunk): flexible stream
        return StreamSpec((), FORMAT_FLEXIBLE)

    def _build_slo(self):
        """SloTracker from the slo-* props (None when no objective is
        armed — the engine's record paths then cost nothing)."""
        from ..core.telemetry import SloTracker

        try:
            tracker = SloTracker(
                ttft_p95_s=float(self.props["slo-ttft-p95"]),
                token_p99_s=float(self.props["slo-token-p99"]),
                availability=float(self.props["slo-availability"]),
            )
        except ValueError as e:
            raise ElementError(f"{self.name}: {e}") from None
        return tracker if tracker.armed else None

    def _build_prefix_pool(self):
        """PrefixCache from the prefix-* props (None = off: the engine
        takes the byte-identical pre-cache path).  The grain rounds UP
        to a prefill-chunk multiple — warm and cold runs must share the
        exact prefill chunk grid or bit-exactness breaks.  A fresh pool
        per start(): a supervision restart is deliberately CACHE-COLD
        (streams migrated here still resume bit-exactly; they just pay
        one cold prefill)."""
        mode = self.props["prefix-cache"]
        if mode not in ("off", "on"):
            raise ElementError(
                f"{self.name}: prefix-cache={mode!r} — want off|on")
        if mode != "on":
            return None
        from ..core.continuity import PREFIX_GRAIN
        from ..core.slots import PrefixCache

        pchunk = max(1, int(self.props["prefill-chunk"]))
        grain = int(self.props["prefix-grain"]) or PREFIX_GRAIN
        grain = ((max(1, grain) + pchunk - 1) // pchunk) * pchunk
        cap = int(self.props["prefix-cap"])
        if cap < 1:
            raise ElementError(
                f"{self.name}: prefix-cap must be >= 1, got {cap}")
        return PrefixCache(grain=grain, cap_entries=cap)

    def trim_prefix_cache(self) -> int:
        """Memory-pressure trim hook (``Pipeline.enable_memory_monitor``
        runs it FIRST in the ladder): drop every unreferenced cached
        prefix — recomputable capacity is the cheapest relief on the
        chip.  Returns entries freed."""
        pool = self._prefix_pool
        return pool.trim() if pool is not None else 0

    def prefix_digest_info(self) -> Optional[Dict[str, Any]]:
        """Bounded cached-prefix advertisement for the discovery digest
        (core/fleet.py): exact hit/miss counters for the observatory's
        fleet rollup plus the hottest entry digests, so routing
        dashboards can see WHICH prefixes this server holds.  None when
        the cache is off (the digest then carries no prefix block)."""
        pool = self._prefix_pool
        if pool is None:
            return None
        snap = pool.snapshot()
        return {
            "hits": snap["prefix_hits"],
            "misses": snap["prefix_misses"],
            "entries": snap["prefix_entries"],
            "hot": pool.hot_digests(),
        }

    # -- observability ------------------------------------------------------
    def health_info(self) -> Dict[str, Any]:
        """Slot occupancy / join / evict / tokens-per-step counters —
        merged into ``Pipeline.health()`` AND exported to the PR-7
        registry as ``nns.gen.*`` via the health collector's key map
        (ONE export path; metrics_info here would double-emit the same
        series).  ``gen_jit_buckets`` counts live decode-chunk compile
        buckets on BOTH paths, so retrace churn is visible."""
        info: Dict[str, Any] = {
            "gen_jit_buckets": len(self._jit_chunks),
            # both paths refuse resumes they cannot validate (the
            # pre-slot path refuses ALL of them)
            "gen_resume_rejects": self._resume_rejects,
            # zero-loss slot-width rebuilds (autoscale resize actuation)
            "gen_resizes": self._resizes,
            # device-loss resilience: 1 while serving in a reduced
            # configuration (mirrored on the discovery plane)
            "degraded": 1 if self._degraded else 0,
            # fenced actuation: stale-epoch resize refusals + the
            # highest lease epoch this generator has obeyed
            "gen_stale_epoch_rejects": self._fence.rejects,
            "gen_fence_epoch": self._fence.epoch,
        }
        if self._engine is not None:
            info.update(self._engine.snapshot())
            info["gen_jit_buckets"] += len(self._jit_chunks)
            if self._mesh is not None:
                from ..parallel.mesh import mesh_health_info

                info.update(mesh_health_info(self._mesh, self._mesh_axes))
            # named-thread census: the pump's liveness is part of the
            # health story (a wedged pump fires an incident from
            # handle_idle; the census makes it visible between polls)
            info["threads"] = thread_census(self._engine.heartbeat)
        if self._slo is not None:
            # per-tenant SLO rows (burn rates computed at read time);
            # the collector's `slo` branch exports them as nns.slo.*
            info["slo"] = self._slo.snapshot()
        return info

    def histograms_info(self):
        """Per-tenant TTFT / inter-token log2 bucket series (scrape-time
        export; empty histograms emit nothing)."""
        return self._slo.hist_rows() if self._slo is not None else []

    # -- continuous-batching hooks ------------------------------------------
    def pending_frames(self) -> int:
        """Streams parked in the slot engine plus undelivered ready
        chunks (scheduler fast-poll + drain/stop accounting)."""
        return self._engine.pending() if self._engine is not None else 0

    def handle_idle(self):
        """Drain chunks the engine completed since the last call —
        emission happens HERE, on the dispatch thread.  Doubling as the
        pump's liveness check: a pump that holds work but stopped
        beating is WEDGED (stuck inside a device call) — surface it as
        a flight-recorder incident NOW instead of waiting for a sticky
        error that a hung thread can never raise."""
        eng = self._engine
        if eng is None:
            return []
        if eng.pending() > 0 and eng.heartbeat.check_stall(busy=True):
            self.log.warning(
                "slot pump %s wedged: no heartbeat for %.1fs with %d "
                "stream(s)/chunk(s) pending", eng.heartbeat.name,
                eng.heartbeat.age_s(), eng.pending(),
            )
            p = self._pipeline
            if p is not None:
                p.incident(
                    "thread_stall", self.name,
                    f"{eng.heartbeat.name} wedged "
                    f"({eng.heartbeat.age_s():.1f}s, "
                    f"pending={eng.pending()})")
        chunks = eng.pop_ready()
        if self._resize_target and eng.idle():
            # the idle boundary: every live stream handed off resumably
            # (begin_goaway in request_resize) and every ready chunk
            # drained — safe to rebuild at the new width, and doing it
            # HERE (dispatch thread) means no frame can race the swap
            self._apply_resize()
        return chunks

    # -- autoscale resize actuation (core/autoscale.py) ---------------------
    def request_resize(self, slots: int, epoch: Optional[int] = None) -> None:
        """Arm a ZERO-LOSS slot-width resize (any thread): live streams
        are flushed as resumable GOAWAY chunks (clients migrate or
        resume them here — remaining tokens bit-identical, the resume
        signature deliberately excludes the slot width), then the slot
        model + engine rebuild at the new width on the dispatch thread's
        next idle boundary.  Poll :attr:`resize_pending` / the
        ``gen_resizes`` health counter for completion.

        ``epoch`` is the commanding controller's lease epoch; a stale
        epoch raises :class:`~..core.autoscale.StaleEpochError` BEFORE
        any stream is touched (``None`` = unfenced operator command)."""
        self._fence.check(epoch)
        slots = int(slots)
        if slots < 1:
            raise ElementError(f"{self.name}: resize slots must be >= 1")
        if self._engine is None:
            raise ElementError(
                f"{self.name}: resize needs the slotted path (slots >= 1)")
        if slots == self._slots:
            return
        self._resize_target = slots
        self._engine.begin_goaway()

    @property
    def resize_pending(self) -> bool:
        """True while a requested resize has not been applied yet."""
        return bool(self._resize_target)

    def _build_slot_model(self, slots: int):
        """(model, params, max_seq) at the requested width from the
        stored knobs — the resize twin of the ``start()`` build.  The
        one-shot chaos triggers (``sim_oom_step`` / ``sim_lost_step``)
        are deliberately NOT re-armed: they script a single synthetic
        fault, and a resize must not replay it."""
        props = self._zoo_props
        if self._sim:
            from ..core.slots import SimSlotModel

            model = SimSlotModel(
                slots,
                vocab=int(props.get("vocab", "997")),
                step_base_ms=float(props.get("sim_step_ms", "1.0")),
                step_per_slot_ms=float(
                    props.get("sim_per_slot_ms", "0.05")),
                prefill_ms_per_token=float(
                    props.get("sim_prefill_ms", "0.02")),
            )
            return model, None, self._max_seq
        from ..models.transformer import build_slot_stream

        model, params, max_seq = build_slot_stream(
            props, slots, mesh=self._mesh)
        return model, self._place_on_survivor(params, self._mesh), max_seq

    def _apply_resize(self) -> None:
        """Runs on the DISPATCH thread with the engine idle: build the
        replacement first (a failed build rolls back to serving at the
        old width), then swap engines.  The resume signature is width-
        independent, so streams handed off around the rebuild resume
        bit-identically at either width."""
        from ..core.slots import SlotEngine

        # NOTE: _resize_target stays set until the swap lands (or the
        # rollback commits) — resize_pending is the actuation-complete
        # signal controllers poll, so clearing it before the rebuild
        # would let a poller read the OLD width as the settled result
        target = self._resize_target
        old = self._engine
        try:
            model, params, max_seq = self._build_slot_model(target)
        except Exception:  # noqa: BLE001 — roll back to the old width
            self.log.exception(
                "resize to %d slots failed building the model; keeping "
                "%d slots", target, self._slots)
            old.end_goaway()
            p = self._pipeline
            if p is not None:
                p.incident(
                    "resize_failed", self.name,
                    f"slot resize {self._slots}->{target} model build "
                    "failed; serving at the old width")
            if self._resize_target == target:
                self._resize_target = 0
            return
        old.stop()
        self._params = params
        self._max_seq = max_seq
        new = SlotEngine(
            model, params,
            max_seq=max_seq,
            chunk=max(1, int(self.props["chunk"])),
            prefill_chunk=int(self.props["prefill-chunk"]),
            prefill_priority=int(self.props["prefill-priority"]),
            token_budget_s=float(self.props["token-budget-s"]),
            name=self.name,
            resume_sig=self._resume_sig,
            on_device_lost=self._rebuild_on_device_loss,
            slo=self._slo,
            # the pool survives a width resize: published pages are
            # (1, n, ...) slot-width-independent blobs from the SAME
            # params, and its counters must stay monotonic for the
            # observatory's exact fleet totals
            prefix_cache=self._prefix_pool,
        )
        # the server's lifetime ledger survives the rebuild — digests
        # and the observatory's exact fleet totals must stay monotonic
        new.adopt_ledger(old)
        new.start()
        self._engine = new
        self.log.info("slot width resized %d -> %d (zero-loss: live "
                      "streams handed off resumably)", self._slots, target)
        self._slots = target
        # keep the prop in sync so a supervision restart rebuilds at
        # the actuated width, not the parse-time one
        self.props["slots"] = target
        self._resizes += 1
        # a request_resize racing the swap may have armed a NEWER
        # target — only clear our own
        if self._resize_target == target:
            self._resize_target = 0

    # -- device-loss resilience (degrade, don't die) -------------------------
    def _place_on_survivor(self, params, mesh):
        """Commit an UNSHARDED build's params to a surviving device when
        past losses excluded ordinals — the default placement would hand
        the dead chip back (``host_init`` pins builds to cpu:0 by
        design, so the exclusion must be applied post-build; the jitted
        steps then follow the committed params).  Identity with a mesh
        (the claim already excludes the dead) or with no exclusions."""
        if mesh is not None or not self._mesh_exclude or params is None:
            return params
        import jax

        from ..core.resilience import DeviceLostError

        dead = {int(i) for i in self._mesh_exclude}
        for d in jax.devices():
            if int(d.id) not in dead:
                params = jax.device_put(params, d)
                jax.block_until_ready(params)
                return params
        raise DeviceLostError(
            "no surviving device to place on",
            device_ids=tuple(sorted(dead)))

    def _rebuild_on_device_loss(self, err):
        """SlotEngine ``on_device_lost`` hook (runs on the PUMP thread,
        after every live stream was handed off with resume state):
        rebuild the slotted model on the surviving devices — the
        ``parallel/mesh.shrink_axes`` ladder, tp halving down to
        unsharded — and mark this server degraded on the discovery
        plane.  Token sequences are untouched (the resume signature
        deliberately excludes the mesh), so streams that resume HERE
        stay bit-exact.  The sim twin recovers in place (no devices to
        lose for real); a real UNSHARDED model has no survivor to
        rebuild on — the loss re-raises into supervision, whose element
        restart re-picks devices."""
        if not self._sim and self._mesh is None:
            self.log.error(
                "device lost (%s): unsharded model has no survivors to "
                "re-mesh onto — escalating to supervision", err)
            raise err
        was_degraded = self._degraded
        self._degraded = True
        replacement = None
        detail = "sim"
        if not self._sim:
            from ..backends.jax_xla import probe_device_ids
            from ..models.transformer import build_slot_stream
            from ..parallel.mesh import (
                claim_devices,
                make_mesh,
                remesh_after_loss,
            )

            current = [int(d.id) for d in self._mesh.devices.flat]
            dead, axes, spec = remesh_after_loss(
                current, self._mesh_axes,
                getattr(err, "device_ids", ()) or (),
                probe=probe_device_ids)
            if not dead:
                # the probe reached every mesh member — the loss did
                # not reproduce: escalate to supervision (the restart
                # re-picks devices; streams already handed off resume
                # anywhere) instead of condemning a healthy chip
                self._degraded = was_degraded
                self.log.error(
                    "device lost (%s): probe found all mesh members "
                    "alive — escalating to supervision", err)
                raise err
            self._mesh_exclude = tuple(
                set(self._mesh_exclude) | set(dead))
            # later restarts must claim the SHRUNK config: the original
            # spec no longer fits once the dead ordinals are excluded
            self._mesh_override = spec
            mesh = None
            if axes:
                mesh = make_mesh(
                    axes,
                    devices=claim_devices(axes, exclude=self._mesh_exclude))
            detail = spec or "unsharded"
            self.log.error(
                "device lost (%s): rebuilding slot model on survivors "
                "as mesh=%s", err, detail)
            model, params, self._max_seq = build_slot_stream(
                self._zoo_props, self._slots, mesh=mesh)
            params = self._place_on_survivor(params, mesh)
            self._mesh = mesh
            self._mesh_axes = axes if mesh is not None else {}
            self._params = params
            replacement = (model, params)
        p = self._pipeline
        if p is not None:
            p.incident("device_lost", self.name, {"remesh": detail})
            p.degraded_feedback(
                self.name, f"device lost; decoding on mesh={detail}")
        return replacement

    def note_stream_drain(self) -> None:
        """The query serversrc of this pipeline entered its drain
        (rolling restart): hand live generation streams off as
        resumable GOAWAY chunks so clients migrate them instead of the
        drain racing its deadline against whole generations."""
        if self._engine is not None:
            self._engine.begin_goaway()

    def note_stream_cancel(self, meta: Dict[str, Any]) -> None:
        """Downstream feedback (serversink): the consumer of this stream
        is GONE — free its slot immediately instead of decoding tokens
        nobody will read."""
        if self._engine is None:
            return
        cid = meta.get("client_id")
        if cid is not None:
            self._engine.cancel(client_id=cid)

    def handle_eos(self, pad):
        """Slotted mode: the stream only ends once every live generation
        completed — flush the engine through the dispatch thread."""
        eng = self._engine
        if eng is None:
            return []

        def flush():
            while True:
                for out in eng.pop_ready():
                    yield out
                if eng.idle():
                    return
                if self.interrupted:
                    return  # watchdog escalation: stop flushing
                eng.wait_progress(0.05)

        return flush()

    # -- processing ---------------------------------------------------------
    def handle_frame(self, pad, frame):
        if self._engine is not None:
            return self._handle_slotted(frame)
        assert self._prefill is not None, f"{self.name} not started"
        if isinstance(frame, BatchFrame):
            # lazily chain one stream per logical prompt: chunk frames of
            # prompt j still leave BEFORE prompt j+1 starts decoding
            logical = frame.split()

            def multi():
                for lf in logical:
                    rej = self._refuse_unslotted_resume(lf)
                    if rej is not None:
                        yield rej
                    else:
                        yield from self._stream_one(lf)

            return multi()
        rej = self._refuse_unslotted_resume(frame)
        if rej is not None:
            return [rej]
        return self._stream_one(frame)

    def _refuse_unslotted_resume(self, lf):
        """A RESUME request landing on a pre-slot (slots=0) generator
        must be REFUSED with the typed reject, never served: this path
        has no checkpoint validation, so silently replaying the prompt
        from token 0 under a possibly-different config would corrupt
        the client's exactly-once ledger without any error (durable
        streams require slots >= 1)."""
        if lf.meta.get(RESUME_REQ_META) is None:
            return None
        return self._resume_reject(
            lf, "resume requires a slotted generator (slots >= 1)")

    def _validated_prompt(self, frame, max_new: int) -> np.ndarray:
        prompt = np.asarray(frame.tensors[0])
        if prompt.ndim == 1:
            prompt = prompt[None]
        if prompt.ndim != 2 or prompt.dtype.kind not in "iu":
            raise ElementError(
                f"{self.name}: prompt must be int tokens (B, Tp) or (Tp,), "
                f"got {prompt.shape} {prompt.dtype}"
            )
        if prompt.shape[1] + max_new > self._max_seq:
            # the cache ring would wrap and pos_embed would index past
            # max_seq — fail loud instead of streaming corrupt tokens
            raise ElementError(
                f"{self.name}: prompt {prompt.shape[1]} + max-new "
                f"{max_new} exceeds the model's seq {self._max_seq}"
            )
        return prompt

    def _handle_slotted(self, frame):
        """Submit the prompt(s) to the slot engine and drain whatever
        chunks are already ready — new prompts JOIN live decoding at the
        next token boundary instead of queueing behind it.  A frame
        carrying :data:`RESUME_REQ_META` re-joins a checkpointed stream
        (validated below) instead of starting a fresh one."""
        max_new = int(self.props["max-new"])
        chunk = max(1, int(self.props["chunk"]))
        logical = frame.split() if isinstance(frame, BatchFrame) else [frame]
        rejects = []
        for lf in logical:
            prompt = self._validated_prompt(lf, max_new)
            if prompt.shape[0] != 1:
                # one stream per slot: split multi-row prompts upstream
                # (appsrc push_block) or serve them on the pre-slot path
                raise ElementError(
                    f"{self.name}: slots>0 serves one prompt per stream; "
                    f"got a (B={prompt.shape[0]}) prompt batch — push a "
                    "block of single prompts instead"
                )
            if max_new <= 0:
                continue
            meta = lf.meta
            resume = None
            rs = meta.get(RESUME_REQ_META)
            if rs is not None:
                resume, reason = self._check_resume(
                    lf, prompt, max_new, rs)
                if resume is None:
                    rejects.append(self._resume_reject(lf, reason))
                    continue
            self._engine.submit(
                lf, prompt.astype(np.int32), max_new, chunk,
                tenant=str(meta.get(TENANT_META, "") or ""),
                priority=clamp_priority(
                    meta.get(PRIORITY_META, PRIORITY_MAX)),
                deadline_ts=meta.get(DEADLINE_META),
                resume=resume,
            )
        return rejects + self._engine.pop_ready()

    def _check_resume(self, lf, prompt, max_new: int, rs):
        """Validate one RESUME request against THIS server's token
        signature and the prompt it arrived with.  Returns
        ``(engine_resume_dict, None)`` or ``(None, reason)`` — a
        mismatch is a per-stream typed refusal, never a pipeline
        error."""
        try:
            sig = str(rs["sig"])
            r = int(rs["tokens_done"])
        except (KeyError, TypeError, ValueError):
            return None, "malformed resume state"
        if sig != self._resume_sig:
            return None, "model/sampling signature mismatch"
        if str(rs.get("digest", "")) != prompt_digest(
                prompt.astype(np.int32)):
            return None, "prompt digest mismatch"
        if not 0 <= r < max_new:
            return None, f"tokens_done {r} outside [0, {max_new})"
        if r == 0:
            return {"tokens_done": 0}, None
        if len(lf.tensors) < 2:
            return None, "resume request lacks the prefix tensor"
        prefix = np.asarray(lf.tensors[1])
        if prefix.ndim == 1:
            prefix = prefix[None]
        if (prefix.ndim != 2 or prefix.shape != (1, r)
                or prefix.dtype.kind not in "iu"):
            return None, (
                f"prefix {prefix.shape} {prefix.dtype} != (1, {r}) int")
        return {"tokens_done": r,
                "prefix": prefix.astype(np.int32)}, None

    def _resume_reject(self, lf, reason: str):
        """Typed terminal refusal of one RESUME request: the stream gets
        a tensor-less final chunk naming the reason (the client counts
        a resume failure and tries another server); the server pipeline
        — and the other streams it is decoding — survive."""
        self._resume_rejects += 1
        self.log.warning("resume refused: %s", reason)
        out = lf.with_tensors([])
        out.meta.update(
            stream_seq=lf.seq, chunk_index=0, tokens_done=0, final=True,
        )
        out.meta[RESUME_REJECT_META] = reason
        return (0, out)

    def _stream_one(self, frame):
        prompt = self._validated_prompt(frame, int(self.props["max-new"]))
        max_new = int(self.props["max-new"])
        chunk = max(1, int(self.props["chunk"]))
        if max_new <= 0:
            return []

        def stream():
            cache, tok = self._prefill(self._params, prompt.astype(np.int32))
            done = 0
            idx = 0
            pending = [np.asarray(tok)[:, None]]  # token 1 (from prefill)
            pending_n = 1
            t = 1
            while True:
                emit_now = pending_n >= chunk or (t >= max_new)
                if emit_now and pending_n:
                    toks = (
                        pending[0] if len(pending) == 1
                        else np.concatenate(pending, axis=1)
                    )
                    done += toks.shape[1]
                    out = frame.with_tensors([toks.astype(np.int32)])
                    out.meta.update(
                        stream_seq=frame.seq, chunk_index=idx,
                        tokens_done=done, final=bool(t >= max_new),
                    )
                    idx += 1
                    pending.clear()
                    pending_n = 0
                    yield (0, out)
                if t >= max_new:
                    return
                n = min(chunk - pending_n, max_new - t)
                cache2, tok2, toks = self._decode_n(n)(
                    self._params, cache, tok, t
                )
                # materialize BEFORE yielding: emission must mean "these
                # tokens exist", not "their computation was dispatched"
                pending.append(np.asarray(toks))
                pending_n += toks.shape[1]
                cache, tok = cache2, tok2
                t += n

        return stream()
