"""tensor_generator: streaming autoregressive generation (net-new).

The serving shape of interactive LLM inference, which the reference has no
analog for (its closest relative is recurrence emulation through
tensor_repo loops, ``tests/nnstreamer_repo_lstm``): ONE prompt frame in,
token CHUNKS streamed out as they decode.  Downstream elements
(detokenizer → sink / query serversink) run CONCURRENTLY with the next
chunk's decode — the pipeline's per-element threads are the streaming
transport, no extra machinery.

TPU-first structure: the zoo transformer's KV cache (device-resident
pytree) is carried across jitted calls — prefill is one causal pass, each
chunk is one ``lax.scan`` segment (compile buckets: one per distinct
chunk length, i.e. the chunk size + one tail).  Python dispatch cost is
per CHUNK, not per token.  Sampling (greedy/temperature/top-k, per-step
key folding) is bit-identical to one-shot ``generate:<N>`` serving
(``models/transformer.py make_stream_generate``).

Emission contract: ``handle_frame`` returns a GENERATOR; the scheduler
pushes each yielded frame downstream as it is produced (frames stream,
they do not wait for the full completion).  Each chunk frame carries
tokens (B, n) int32 plus meta ``stream_seq`` (source frame seq),
``chunk_index``, ``tokens_done`` and ``final``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..core.buffer import BatchFrame
from ..core.types import ANY, FORMAT_FLEXIBLE, StreamSpec
from ..pipeline.element import Element, ElementError, Property, element


@element("tensor_generator")
class TensorGenerator(Element):
    # a block of prompts streams each logical prompt in order (lazy chain)
    BATCH_AWARE = True

    PROPERTIES = {
        "custom": Property(
            str, "",
            "zoo-transformer dialect: vocab:N,d_model:N,heads:N,layers:N,"
            "d_ff:N,seq:N,seed:N[,temperature:F,top_k:N,gen_seed:N]",
        ),
        "max-new": Property(int, 32, "tokens to generate per prompt"),
        "chunk": Property(int, 8, "tokens per streamed chunk frame"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._prefill = None
        self._decode = None
        self._params = None
        self._max_seq = 0
        self._jit_chunks: Dict[int, Any] = {}

    def start(self):
        import jax

        from ..models.transformer import build_stream

        props = {}
        for part in self.props["custom"].split(","):
            if ":" in part:
                k, _, v = part.partition(":")
                props[k.strip()] = v.strip()
        props.pop("arch", None)  # tolerated for zoo-dialect symmetry
        prefill, decode_chunk, params, self._max_seq = build_stream(props)
        self._prefill = jax.jit(prefill)
        self._decode = decode_chunk
        self._params = params
        self._jit_chunks = {}

    def stop(self):
        self._prefill = self._decode = self._params = None
        self._jit_chunks.clear()

    def _decode_n(self, n: int):
        import jax

        fn = self._jit_chunks.get(n)
        if fn is None:
            fn = jax.jit(
                lambda p, cache, tok, t0: self._decode(p, cache, tok, t0, n)
            )
            self._jit_chunks[n] = fn
        return fn

    # -- negotiation --------------------------------------------------------
    def accept_spec(self, pad, spec):
        return spec

    def derive_spec(self, pad=0):
        # chunk length varies (tail chunk): flexible stream
        return StreamSpec((), FORMAT_FLEXIBLE)

    # -- processing ---------------------------------------------------------
    def handle_frame(self, pad, frame):
        assert self._prefill is not None, f"{self.name} not started"
        if isinstance(frame, BatchFrame):
            # lazily chain one stream per logical prompt: chunk frames of
            # prompt j still leave BEFORE prompt j+1 starts decoding
            logical = frame.split()

            def multi():
                for lf in logical:
                    yield from self._stream_one(lf)

            return multi()
        return self._stream_one(frame)

    def _stream_one(self, frame):
        prompt = np.asarray(frame.tensors[0])
        if prompt.ndim == 1:
            prompt = prompt[None]
        if prompt.ndim != 2 or prompt.dtype.kind not in "iu":
            raise ElementError(
                f"{self.name}: prompt must be int tokens (B, Tp) or (Tp,), "
                f"got {prompt.shape} {prompt.dtype}"
            )
        max_new = int(self.props["max-new"])
        chunk = max(1, int(self.props["chunk"]))
        if prompt.shape[1] + max_new > self._max_seq:
            # the cache ring would wrap and pos_embed would index past
            # max_seq — fail loud instead of streaming corrupt tokens
            raise ElementError(
                f"{self.name}: prompt {prompt.shape[1]} + max-new "
                f"{max_new} exceeds the model's seq {self._max_seq}"
            )
        if max_new <= 0:
            return []

        def stream():
            cache, tok = self._prefill(self._params, prompt.astype(np.int32))
            done = 0
            idx = 0
            pending = [np.asarray(tok)[:, None]]  # token 1 (from prefill)
            pending_n = 1
            t = 1
            while True:
                emit_now = pending_n >= chunk or (t >= max_new)
                if emit_now and pending_n:
                    toks = (
                        pending[0] if len(pending) == 1
                        else np.concatenate(pending, axis=1)
                    )
                    done += toks.shape[1]
                    out = frame.with_tensors([toks.astype(np.int32)])
                    out.meta.update(
                        stream_seq=frame.seq, chunk_index=idx,
                        tokens_done=done, final=bool(t >= max_new),
                    )
                    idx += 1
                    pending.clear()
                    pending_n = 0
                    yield (0, out)
                if t >= max_new:
                    return
                n = min(chunk - pending_n, max_new - t)
                cache2, tok2, toks = self._decode_n(n)(
                    self._params, cache, tok, t
                )
                # materialize BEFORE yielding: emission must mean "these
                # tokens exist", not "their computation was dispatched"
                pending.append(np.asarray(toks))
                pending_n += toks.shape[1]
                cache, tok = cache2, tok2
                t += n

        return stream()

    def handle_eos(self, pad):
        return []
