"""tensor_filter: run a model as a stream element — the heart of the
framework.

Reference: ``gst/nnstreamer/tensor_filter/tensor_filter.c`` (transform :642,
set_caps :1314, configure :960) + ``tensor_filter_common.c`` (24+ properties,
framework auto-detect :1171-1196, shared-model table :2879-3084, accelerator
parse :2719-2878, latency/throughput statistics :363-430).

TPU-native deltas:

* **micro-batching**: with ``max-batch > 1`` the scheduler drains up to N
  queued frames and the element runs ONE backend ``invoke_batch`` call — the
  single biggest throughput lever on TPU (per-frame Python dispatch cannot
  reach 1000 fps; one XLA call on a batch can).  Timestamps/metadata of each
  frame are preserved; outputs are split back per-frame.
* accelerator wish lists resolve to a concrete device (``true:tpu.1,cpu``
  pins the second chip) — see ``backends.jax_xla.pick_device``.
* backends may return device-resident jax.Arrays; the filter passes them
  through untouched (zero-copy chaining).
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..backends.base import FilterBackend, find_backend, parse_accelerator
from ..core import config as nns_config
from ..core import registry
from ..core.buffer import FRAME_POOL, BatchFrame, CustomEvent, Flush, TensorFrame
from ..core.feed import CompletionWindow, HostStagingLane, StagedBatch
from ..core.lifecycle import HotSwapCoordinator, SwapTicket
from ..core.liveness import StallError
from ..core.model_uri import resolve_model_uri
from ..core.resilience import FAULTS, DeviceLostError, DeviceOomError
from ..core.telemetry import TL_INVOKE_META, TL_RX_META
from ..core.types import ANY, FORMAT_FLEXIBLE, StreamSpec
from ..pipeline.element import ElementError, Property, TransformElement, element

# ---------------------------------------------------------------------------
# Shared model table (reference tensor_filter_common.c:2879-3084):
# filter instances with the same shared-tensor-filter-key share one backend.
# ---------------------------------------------------------------------------
_shared_lock = threading.Lock()
_shared_table: Dict[str, Tuple[FilterBackend, int]] = {}


def _shared_acquire(key: str, factory) -> FilterBackend:
    with _shared_lock:
        if key in _shared_table:
            be, refs = _shared_table[key]
            _shared_table[key] = (be, refs + 1)
            return be
        be = factory()
        _shared_table[key] = (be, 1)
        return be


def _shared_release(key: str) -> bool:
    """Returns True if the caller should close the backend."""
    with _shared_lock:
        if key not in _shared_table:
            return True
        be, refs = _shared_table[key]
        if refs <= 1:
            del _shared_table[key]
            return True
        _shared_table[key] = (be, refs - 1)
        return False


def detect_framework(model_path: str, custom: str = "") -> str:
    """framework=auto resolution from the model extension.

    Reference: ``_detect_framework_from_config`` tensor_filter_common.c:1171.
    jax-xla wins a foreign extension (e.g. .tflite) only when the pipeline
    supplies ``custom=arch:<zoo-family>`` — without it jax-xla cannot load
    the file, so auto falls through to the native runtime for that format.
    """
    ext = os.path.splitext(model_path)[1]
    # parse the "k1:v1,k2:v2" custom dialect properly — a substring test
    # would false-positive on keys/values merely containing "arch:"
    has_arch = any(
        part.partition(":")[0].strip() == "arch"
        for part in str(custom or "").split(",")
        if ":" in part
    )
    for cand in nns_config.framework_priority(ext):
        if not registry.exists(registry.KIND_FILTER, cand):
            continue
        if (
            cand == "jax-xla"
            and ext not in ("", ".py", ".msgpack")
            and ext not in nns_config.EXPORTED_MODEL_EXTS
            and not has_arch
        ):
            continue
        return cand
    raise ElementError(
        f"cannot auto-detect a backend for model {model_path!r} (ext {ext!r})"
    )


def _parse_combination(text: str) -> Optional[List[Tuple[str, int]]]:
    """Parse "0,2" / "i0,o1" combination strings into (src, idx) pairs.

    Reference: input/output-combination props (tensor_filter.c:723-765,
    856-898); bare indices mean input for input-combination and output for
    output-combination — callers pass the default source tag.
    """
    if not text:
        return None
    out = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if part[0] in ("i", "o"):
            out.append((part[0], int(part[1:])))
        else:
            out.append(("", int(part)))
    return out or None


# bounded LRU: flexible-shape streams mint a new (bucket, shape, dtype)
# key per distinct frame shape, and each entry pins a compiled XLA
# program — unbounded growth is a slow leak on long-lived servers.  64
# entries cover every steady-state pipeline observed (buckets are powers
# of two, shapes are per-model); eviction just retraces on next use.
# The lock guards the get/move_to_end/evict compound ops — the cache is
# module-global and filter workers on different pipelines share it (its
# cost is noise next to the jitted stack call it fronts).
_STACK_JIT_MAX = 64
_stack_jit_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
_stack_jit_lock = threading.Lock()


def _never() -> bool:
    """``is_deleted`` stand-in for host arrays (numpy has no donation)."""
    return False


def _stack_tensors(arrs: List[Any]):
    """Stack per-frame tensors into a batch WITHOUT pulling device-resident
    arrays to host.

    Device arrays stack through a jitted program cached per
    (count, shape, dtype): eager ``jnp.stack`` is N expand_dims + concat =
    N+1 separate dispatches per micro-batch — measured at ~85% of the
    filter worker's time at batch 128, and each dispatch is a full round
    trip on a remote/tunneled device.  One compiled call replaces them.
    Numpy stacks on host (the single host->device transfer then happens
    inside the backend).
    """
    a0 = arrs[0]
    if type(a0).__module__.split(".")[0] == "jaxlib" or hasattr(a0, "sharding"):
        import jax
        import jax.numpy as jnp

        # bucket the count to the next power of two (padding with repeated
        # references — free) so fluctuating queue-drain sizes share a
        # handful of compiles per shape instead of one per distinct count
        n = len(arrs)
        bucket = 1
        while bucket < n:
            bucket <<= 1
        key = (bucket, tuple(a0.shape), str(a0.dtype))
        with _stack_jit_lock:
            fn = _stack_jit_cache.get(key)
            if fn is not None:
                _stack_jit_cache.move_to_end(key)
        if fn is None:
            fn = jax.jit(lambda *xs: jnp.stack(xs))
            with _stack_jit_lock:
                _stack_jit_cache[key] = fn
                while len(_stack_jit_cache) > _STACK_JIT_MAX:
                    _stack_jit_cache.popitem(last=False)  # evict LRU
        stacked = fn(*(list(arrs) + [a0] * (bucket - n)))
        # lazy device slice (one op) back to the true count
        return stacked[:n] if bucket != n else stacked
    return np.stack([np.asarray(a) for a in arrs])


def _concat_tensors(arrs: List[Any]):
    """Concatenate along the existing batch axis (block-ingest merge).

    Unlike :func:`_stack_tensors` the operand count here is the number of
    QUEUE ITEMS (a handful), not the number of logical frames, so an eager
    concat is one cheap dispatch and needs no jit cache."""
    if len(arrs) == 1:
        return arrs[0]
    if any(
        type(a).__module__.split(".")[0] == "jaxlib" or hasattr(a, "sharding")
        for a in arrs
    ):
        # ANY device-resident piece keeps the concat on device — a host
        # np.concatenate would drag every device block through a sync
        # transfer only for invoke_batch to re-upload the result
        import jax.numpy as jnp

        return jnp.concatenate(arrs, axis=0)
    return np.concatenate([np.asarray(a) for a in arrs], axis=0)


def _batched_tensors(
    frames: Sequence[TensorFrame], select: Optional[List[int]]
) -> List[Any]:
    """Expand a mixed plain/BatchFrame list into ONE batched tensor list:
    plain frames gain a length-1 batch axis, blocks pass through, pieces
    concatenate per tensor index.  ``select`` optionally narrows to the
    given tensor indices (input-combination)."""
    pieces: List[List[Any]] = []
    for f in frames:
        tens = (
            [f.tensors[i] for i in select] if select is not None
            else list(f.tensors)
        )
        if not isinstance(f, BatchFrame):
            tens = [
                t[None] if hasattr(t, "shape") else np.asarray(t)[None]
                for t in tens
            ]
        pieces.append(tens)
    if len(pieces) == 1:
        return pieces[0]
    return [
        _concat_tensors([p[t] for p in pieces])
        for t in range(len(pieces[0]))
    ]


def _logical_infos(
    frames: Sequence[TensorFrame],
) -> List[Tuple[Optional[float], Optional[float], Dict[str, Any]]]:
    """Flatten (pts, duration, meta) per LOGICAL frame across a mixed list
    of plain frames and BatchFrames, in stream order."""
    infos: List[Tuple[Optional[float], Optional[float], Dict[str, Any]]] = []
    for f in frames:
        if isinstance(f, BatchFrame):
            infos.extend(f.frames_info)
        else:
            infos.append((f.pts, f.duration, f.meta))
    return infos


@element("tensor_filter")
class TensorFilter(TransformElement):
    BATCH_AWARE = True  # consumes the batch axis (micro-batching)

    PROPERTIES = {
        "framework": Property(str, "auto", "backend name or 'auto'"),
        "model": Property(str, "", "model path / registry key"),
        "custom": Property(str, "", "backend-specific options 'k1:v1,k2:v2'"),
        "accelerator": Property(str, "", "'true:tpu.N,cpu' ordered wish list -> real device pinning"),
        # mesh-sharded serving (parallel/mesh.py grammar): one logical
        # filter across a device mesh — params sharded by the parallel
        # layer's rules, micro-batches scattered over dp, replicated on
        # tp; XLA SPMD inserts the collectives (jax-xla only)
        "mesh": Property(
            str, "",
            "serve this model sharded across a device mesh: 'tp:4' / "
            "'dp:2,tp:2' / 'dp:-1' (-1 = remaining devices; empty = "
            "unsharded).  Params shard per parallel/sharding.py rules, "
            "micro-batches scatter on dp; backend must support meshes "
            "(jax-xla)"),
        "input-combination": Property(str, "", "subset/reorder input tensors, e.g. '0,2'"),
        "output-combination": Property(str, "", "compose output from 'iN'/'oN' tensors"),
        "latency": Property(int, 0, "1 = enable per-invoke latency measurement"),
        "throughput": Property(int, 0, "1 = enable throughput measurement"),
        "latency-report": Property(int, 0, "1 = post latency bus messages"),
        "is-updatable": Property(bool, False, "allow hot model reload"),
        # zero-downtime model rollout (core/lifecycle.py): reloads stage
        # the new model on a SECOND backend instance off the hot path
        # (open + schema validation + JIT warmup), swap at a frame
        # boundary, and roll back on a post-swap error burst
        "staged-reload": Property(
            bool, True,
            "hot reloads stage+validate+warm the new model on a second "
            "backend instance and swap at a frame boundary (false = "
            "legacy inline backend.reload(), still guarded: a failed "
            "reload keeps the old model serving)"),
        "observation-window": Property(
            float, 5.0,
            "seconds after a hot swap during which invoke errors are "
            "served by the retained old model and an error burst rolls "
            "the swap back"),
        "rollback-error-burst": Property(
            int, 3,
            "invoke errors within observation-window that auto-roll-back "
            "a hot swap to the previous model"),
        "shared-tensor-filter-key": Property(str, "", "share one backend instance"),
        "invoke-dynamic": Property(bool, False, "output schema varies per buffer"),
        "max-batch": Property(int, 1, "micro-batch up to N queued frames into one invoke"),
        "batch-timeout": Property(
            int, 0, "ms to wait filling a micro-batch (0 = only drain queued)"
        ),
        "dispatch-depth": Property(
            int, 4,
            "micro-batches kept in flight in the completion-driven "
            "dispatch window (a reaper thread materializes finished "
            "batches; the dispatch thread keeps stacking/dispatching and "
            "never blocks in device_get; 1 = synchronous)",
        ),
        "ingest-lane": Property(
            str, "auto",
            "auto|on|off — double-buffered host->device staging: host "
            "frames are stacked into pooled staging buffers and placed "
            "on device from a lane thread, one batch ahead, so the "
            "transfer overlaps the previous batch's compute (auto = on "
            "when the backend supports staged placement and max-batch>1)",
        ),
        # manual model-info override (≙ tensor_filter_common.c props
        # input/inputtype/inputname/inputranks + output side): declare or
        # force I/O schemas for backends that cannot infer them (custom
        # functions, raw .so) or to reshape shape-polymorphic models
        "input": Property(str, "", "manual input dims 'd:d:d[,d:d]' (reference dialect)"),
        "input-type": Property(str, "", "manual input element types 't[,t]'"),
        "inputname": Property(str, "", "manual input tensor names"),
        "inputranks": Property(str, "", "true ranks of manual input dims"),
        "output": Property(str, "", "manual output dims (validated/declared)"),
        "output-type": Property(str, "", "manual output element types"),
        "outputname": Property(str, "", "manual output tensor names"),
        "outputranks": Property(str, "", "true ranks of manual output dims"),
        "inputlayout": Property(
            str, "", "NCHW|NHWC|ANY per input (recorded; XLA owns layout)"
        ),
        "outputlayout": Property(
            str, "", "NCHW|NHWC|ANY per output (recorded; XLA owns layout)"
        ),
        "config-file": Property(
            str, "", "key=value file applied as properties (explicit "
            "pipeline-text properties win)"
        ),
        # ≙ GstShark/NNShark tracing (SURVEY §5.1) done the XLA-native way
        "trace": Property(int, 0, "1 = capture a jax.profiler trace while running"),
        "trace-dir": Property(str, "/tmp/nns_tpu_trace", "profiler output dir"),
        "batch-through": Property(
            bool, False,
            "emit micro-batches as ONE BatchFrame (device-resident) instead "
            "of per-frame outputs; downstream must be batch-aware (set "
            "automatically by the pipeline's device-fusion pass)",
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self.backend: Optional[FilterBackend] = None
        self._owns_backend = True
        self._model_in: Optional[StreamSpec] = None
        self._model_out: Optional[StreamSpec] = None
        self._latency_ring: deque = deque(maxlen=10)  # µs, reference keeps last 10
        self._nframes = 0
        self._t_first: Optional[float] = None
        self._t_last: Optional[float] = None
        # telemetry (core/telemetry.py): always-on invoke counters (two
        # int adds per invoke) + the handler-entry stamp the trace-span
        # dispatch segment is derived from
        self._invokes = 0
        self._invoked_frames = 0
        self._t_handler = 0.0
        # combination props parsed once at start (hot path stays parse-free)
        self._in_comb: Optional[List[Tuple[str, int]]] = None
        self._out_comb: Optional[List[Tuple[str, int]]] = None
        # set by the pipeline's device-fusion pass (NOT the user prop, so a
        # restart without the pass re-fusing leaves the chain unfused)
        self._auto_batch_through = False
        # the depth-N dispatch window, completion-driven: parked batches
        # are materialized by the window's reaper thread in FIFO order;
        # the dispatch thread only pops completed entries (never sits in
        # device_get) and waits on a completion EVENT when the window is
        # full (core/feed.py)
        self._inflight = CompletionWindow(self.name)
        # host-ingest staging lane + the one-batch staged deferral that
        # double-buffers it (dispatch of batch k happens while k+1 stages)
        self._lane: Optional[HostStagingLane] = None
        self._staged: Optional[Tuple[StagedBatch, List[TensorFrame], int]] = None
        # async-output capability, latched ONCE per backend instance
        # (reset at start()/swap/rollback) — the hot path never re-probes
        self._win_async: Optional[bool] = None
        # hot-swap coordinator (core/lifecycle.py), created on the first
        # reload request; None keeps the per-call check to one attr read
        self._swapper: Optional[HotSwapCoordinator] = None
        # device-resource resilience (core/resilience.py taxonomy):
        # lifetime accounting + the degraded-mesh override a re-shard
        # leaves behind (a restart keeps serving the shrunk mesh — the
        # dead chip is still dead)
        self._oom_retries = 0     # invokes retried after a device OOM
        self._oom_shrinks = 0     # micro-batches split to a smaller bucket
        self._oom_evictions = 0   # cache/pool entries trimmed on OOM
        self._device_lost = 0     # lost-device events seen
        self._remeshes = 0        # backends rebuilt on surviving devices
        self._degraded = False    # serving in a reduced configuration
        self._mesh_override: Optional[str] = None
        self._mesh_exclude: Tuple[int, ...] = ()

    @property
    def batch_through_active(self) -> bool:
        """Effective batch-through: the user prop, or the device-fusion
        pass's per-run flag (reset on every start)."""
        return bool(self.props["batch-through"]) or self._auto_batch_through

    # -- device fusion (pipeline pass) --------------------------------------
    @property
    def can_fuse_postprocess(self) -> bool:
        """True when a downstream device half can be folded into this
        filter's compiled program (no combination/dynamic-shape features
        that would change what the postprocess sees, and a private,
        postprocess-capable backend)."""
        return (
            self.backend is not None
            and hasattr(self.backend, "append_postprocess")
            and self._owns_backend
            and not self.props["invoke-dynamic"]
            and not self._out_comb
        )

    def fuse_device_postprocess(self, fn) -> None:
        """Fold ``fn`` (jit-traceable, operates on the model's output list)
        into the backend program and invalidate cached output schemas so
        negotiation re-derives the fused shape."""
        assert self.can_fuse_postprocess
        self.backend.append_postprocess(fn)
        self._model_out = None

    # -- batching hook for the scheduler ------------------------------------
    @property
    def preferred_batch(self) -> int:
        be = self.backend
        if be is not None and be.supports_batch:
            return max(1, int(self.props["max-batch"]))
        return 1

    @property
    def batch_wait_s(self) -> float:
        return max(0, int(self.props["batch-timeout"])) / 1000.0

    # -- lifecycle ----------------------------------------------------------
    @staticmethod
    def _apply_rank(shape: tuple, rank: int) -> tuple:
        """Trim/pad OUTERMOST (numpy-leading) unit dims so the shape has
        the declared true rank (≙ inputranks/outputranks, which exist in
        the reference to disambiguate trailing-1 dims of the padded dim
        string)."""
        shape = tuple(shape)
        while len(shape) > rank:
            if shape[0] not in (1, None):
                raise ElementError(
                    f"cannot reduce shape {shape} to rank {rank}: leading "
                    f"dim {shape[0]} != 1"
                )
            shape = shape[1:]
        while len(shape) < rank:
            shape = (1,) + shape
        return shape

    def _manual_spec(self, side: str) -> Optional[StreamSpec]:
        """Build the manual model-info override for 'input'/'output' from
        the reference-dialect props, or None when not configured."""
        from ..core.types import (
            FORMAT_STATIC,
            TensorSpec,
            dtype_from_name,
            parse_dims_string,
        )

        dims_text = self.props[side]
        types_text = self.props[f"{side}-type"]
        if not dims_text and not types_text:
            return None
        if not dims_text or not types_text:
            raise ElementError(
                f"{self.name}: {side} and {side}-type must be given together"
            )
        dims = [d for d in dims_text.split(",") if d.strip()]
        types = [t.strip() for t in types_text.split(",") if t.strip()]
        if len(dims) != len(types):
            raise ElementError(
                f"{self.name}: {side} declares {len(dims)} tensors but "
                f"{side}-type declares {len(types)}"
            )
        names_key = "inputname" if side == "input" else "outputname"
        ranks_key = "inputranks" if side == "input" else "outputranks"
        names = self.props[names_key].split(",") if self.props[names_key] else []
        ranks = [
            int(r) for r in self.props[ranks_key].split(",") if r.strip()
        ] if self.props[ranks_key] else []
        specs = []
        for i, (d, t) in enumerate(zip(dims, types)):
            try:
                shape = parse_dims_string(d)
                if i < len(ranks):
                    shape = self._apply_rank(shape, ranks[i])
                spec = TensorSpec(
                    shape, dtype_from_name(t),
                    names[i].strip() if i < len(names) else "",
                )
            except (ValueError, ElementError) as e:
                raise ElementError(f"{self.name}: {side}[{i}]: {e}") from None
            specs.append(spec)
        return StreamSpec(tuple(specs), FORMAT_STATIC, None)

    @staticmethod
    def _as_stream_spec(s) -> Optional[StreamSpec]:
        """Normalize a backend model-info value — None | StreamSpec |
        sequence of TensorSpec | sequence of (shape, dtype) — into a
        StreamSpec, or None when empty/unknown."""
        if s is None:
            return None
        if isinstance(s, StreamSpec):
            return s if s.tensors else None
        from ..core.types import FORMAT_STATIC, TensorSpec

        tensors = []
        for t in s:
            if isinstance(t, TensorSpec):
                tensors.append(t)
            else:
                shape, dt = t
                tensors.append(TensorSpec(tuple(shape), np.dtype(dt)))
        return (
            StreamSpec(tuple(tensors), FORMAT_STATIC, None)
            if tensors else None
        )

    _LAYOUTS = ("", "none", "any", "nchw", "nhwc")

    def _check_layouts(self) -> None:
        for key in ("inputlayout", "outputlayout"):
            for i, lay in enumerate(
                x.strip().lower()
                for x in self.props[key].split(",") if x.strip()
            ):
                if lay not in self._LAYOUTS:
                    raise ElementError(
                        f"{self.name}: {key}[{i}]: unknown layout {lay!r} "
                        f"(want NCHW|NHWC|ANY|NONE); note XLA owns physical "
                        "layout on TPU — this prop is declarative"
                    )

    def _make_backend(self, model: Optional[str]) -> FilterBackend:
        """Open ONE backend instance for ``model`` with this element's
        props.  Used at start() and by the hot-swap staging thread (which
        builds a second instance without touching the serving one)."""
        be = self._backend_cls()
        info = be.framework_info()
        if model is None and not info.run_without_model:
            raise ElementError(
                f"{self.name}: framework {self._framework!r} requires a model")
        if model and info.verify_model_path and not os.path.exists(model):
            raise ElementError(f"{self.name}: model file not found: {model}")
        props = dict(self.props)
        enabled, wishes = parse_accelerator(self.props["accelerator"])
        props["accelerators"] = wishes if enabled else ["cpu"]
        if self._mesh_override is not None:
            # degraded re-shard: every backend built from here on (the
            # re-mesh itself, later hot swaps, restarts) claims only the
            # surviving devices at the shrunk mesh config — which
            # REPLACES any legacy mesh_* custom props outright
            props["mesh"] = self._mesh_override
            props["mesh_remesh_override"] = True
        if self._mesh_exclude:
            props["mesh_exclude_ids"] = list(self._mesh_exclude)
        be.open(model, props)
        return be

    def start(self) -> None:
        self._apply_config_file()
        self._check_layouts()
        self._tracing = False
        self._auto_batch_through = False  # re-set by the fusion pass, or not
        self._in_comb = _parse_combination(self.props["input-combination"])
        self._out_comb = _parse_combination(self.props["output-combination"])
        # constant per run: does output-combination read any INPUT tensor?
        # (an outputs-only combination must not drag input blocks to host)
        self._out_needs_inputs = self._out_comb is not None and any(
            src == "i" for src, _ in self._out_comb
        )
        if self.props["batch-through"] and self._out_comb:
            # the BatchFrame fast path bypasses _compose_outputs; refusing
            # beats emitting a layout that depends on queue depth
            raise ElementError(
                f"{self.name}: batch-through=true is incompatible with "
                "output-combination"
            )
        if self.props["invoke-dynamic"] and int(self.props["max-batch"]) > 1:
            # per-buffer-varying output shapes cannot be stacked into one
            # batched XLA call (reference invoke_dynamic is per-frame too,
            # tensor_filter.c:856-930)
            raise ElementError(
                f"{self.name}: invoke-dynamic is per-frame "
                "(incompatible with max-batch>1)"
            )
        if self.props["mesh"]:
            # parse NOW so a typo'd mesh spec fails at start, not after
            # the backend loaded a model (grammar owned by parallel/mesh)
            from ..parallel.mesh import parse_mesh_spec

            try:
                parse_mesh_spec(self.props["mesh"])
            except ValueError as e:
                raise ElementError(f"{self.name}: {e}") from None
        fw = self.props["framework"]
        model = self.props["model"] or None
        if model:
            # mlagent-URI analog: model://name[/version] + file:// schemes
            # (plain paths pass through unchanged)
            model = resolve_model_uri(model)
        if fw == "auto":
            if not model:
                raise ElementError(f"{self.name}: framework=auto requires a model")
            fw = detect_framework(model, self.props["custom"])
        try:
            backend_cls = find_backend(fw)
        except KeyError:
            raise ElementError(f"{self.name}: unknown framework {fw!r}") from None
        # latched for hot model swaps: a reload keeps the framework
        # resolved at start (≙ the reference RELOAD_MODEL contract)
        self._backend_cls, self._framework = backend_cls, fw
        if self.props["mesh"] and not getattr(
                backend_cls, "SUPPORTS_MESH", False):
            # refusing beats silently serving unsharded: the operator
            # asked for a placement this backend cannot honor
            raise ElementError(
                f"{self.name}: mesh={self.props['mesh']!r} but backend "
                f"{fw!r} does not support mesh-sharded serving")

        key = self.props["shared-tensor-filter-key"]
        if key:
            self.backend = _shared_acquire(
                key, lambda: self._make_backend(model))
            self._owns_backend = False
        else:
            self.backend = self._make_backend(model)
            self._owns_backend = True
        self._model_in, self._model_out = self.backend.get_model_info()
        in_override = self._manual_spec("input")
        out_override = self._manual_spec("output")
        if in_override is not None:
            if not self._owns_backend:
                # a shared backend's model info is visible to every filter
                # on the key: mutating it (set_input_info) mid-run would
                # desynchronize siblings' negotiated schemas
                raise ElementError(
                    f"{self.name}: manual input override is incompatible "
                    "with shared-tensor-filter-key (set it on a non-shared "
                    "filter)"
                )
            model_in = self._as_stream_spec(self._model_in)
            if model_in is None:
                # backend cannot infer (custom fn / raw .so): declare
                self._model_in = in_override
                try:
                    derived = self.backend.set_input_info(in_override)
                    if self._as_stream_spec(derived) is not None:
                        self._model_out = derived
                except NotImplementedError:
                    pass
            elif not in_override.is_compatible(model_in):
                # flexible ('?'/0) override dims are wildcards — only a
                # genuinely conflicting declaration forces a reshape
                # force-reshape a shape-polymorphic model (≙ SET_INPUT_INFO)
                try:
                    self._model_out = self.backend.set_input_info(in_override)
                except NotImplementedError:
                    raise ElementError(
                        f"{self.name}: input={self.props['input']} conflicts "
                        f"with the model's declared input and backend "
                        f"{fw!r} cannot reshape"
                    ) from None
                self._model_in = in_override
        if out_override is not None:
            model_out = self._as_stream_spec(self._model_out)
            if model_out is None:
                self._model_out = out_override
            elif not out_override.is_compatible(model_out):
                raise ElementError(
                    f"{self.name}: output={self.props['output']}/"
                    f"{self.props['output-type']} does not match the "
                    f"model's output "
                    f"{tuple((t.shape, str(t.dtype)) for t in model_out.tensors)}"
                )
        # async device feed state: capability re-latched for the fresh
        # backend; host-ingest staging lane armed when the backend really
        # copies off the staging buffers (SUPPORTS_STAGING) and the hot
        # path micro-batches (invoke-dynamic already excludes max-batch>1)
        self._win_async = None
        self._staged = None
        self._lane = None
        lane_mode = str(self.props["ingest-lane"] or "auto").lower()
        if lane_mode not in ("auto", "on", "off"):
            raise ElementError(
                f"{self.name}: ingest-lane={lane_mode!r} (want auto|on|off)")
        # the one-batch dispatch deferral means an invoke error surfaces
        # during the NEXT batch's call — fine under fail-stop (the
        # pipeline tears down), but skip/restart would dead-letter or
        # replay the WRONG frames, so those policies exclude the lane
        replay_policy = (
            self.props.get("error-policy", "fail-stop") != "fail-stop"
            or self.props.get("stall-policy", "warn") == "restart"
        )
        if lane_mode != "off" and self.preferred_batch > 1:
            if replay_policy:
                if lane_mode == "on":
                    raise ElementError(
                        f"{self.name}: ingest-lane=on is incompatible "
                        "with error-policy=skip|restart / "
                        "stall-policy=restart (deferred dispatch would "
                        "misattribute the failed frames)")
            elif getattr(self.backend, "SUPPORTS_STAGING", False):
                self._lane = HostStagingLane(
                    lambda arrs: self.backend.to_device(arrs),
                    name=self.name,
                    # placement-domain key for the staging-buffer pool: a
                    # mesh/device identity, so this lane's rings never mix
                    # with a differently-placed filter's (core/buffer.py)
                    placement=self.backend.staging_placement(),
                )
            elif lane_mode == "on":
                raise ElementError(
                    f"{self.name}: ingest-lane=on but backend "
                    f"{self._framework!r} does not support staged "
                    "host->device placement")
        elif lane_mode == "on":
            raise ElementError(
                f"{self.name}: ingest-lane=on requires max-batch>1 "
                "(staging overlaps per-micro-batch transfers)")
        # trace only after the backend opened: a start() failure must not
        # leak a profiler reference (pipeline won't call stop() on us then)
        if self.props["trace"]:
            from ..core.profiler import trace_start

            self._tracing = trace_start(self.props["trace-dir"])

    def stop(self) -> None:
        if self._staged is not None:
            self._staged[0].discard()
            self._staged = None
        self._inflight.clear()  # drop parked batches (refs released now)
        if self._lane is not None:
            self._lane.close()
            self._lane = None
        if self._swapper is not None:
            # staged / retired / rolled-back backends; the coordinator
            # (and its lifetime swap counters) survives restarts
            self._swapper.close()
        if getattr(self, "_tracing", False):
            from ..core.profiler import trace_stop

            trace_stop()
            self._tracing = False
        if self.backend is not None:
            key = self.props["shared-tensor-filter-key"]
            should_close = _shared_release(key) if key else True
            if should_close and (self._owns_backend or key):
                self.backend.close()
            self.backend = None
        # stop the reaper LAST: a reaper mid-materialization may only be
        # unblocked by the backend teardown above (close() joins it)
        self._inflight.close()

    # -- zero-downtime model rollout (core/lifecycle.py) ---------------------
    def _ensure_swapper(self) -> HotSwapCoordinator:
        if self._swapper is None:
            self._swapper = HotSwapCoordinator(
                self.name,
                # "" = modelless backend (custom fns): open(None, ...)
                build=lambda m: self._make_backend(m or None),
                validate=self._validate_staged,
                warmup=self._warmup_staged,
            )
        return self._swapper

    def request_reload(self, model: str = "") -> SwapTicket:
        """Validated hot model swap (``Pipeline.reload_model`` and the
        RELOAD_MODEL event land here): stage the new model on a second
        backend instance in a background thread — open, schema check
        against the negotiated specs, JIT warmup on a zero probe frame —
        then swap atomically at the next frame boundary.  Any staging
        failure keeps the old model serving and counts ``swap_failures``
        (never the supervisor's restart budget)."""
        if not self.props["is-updatable"]:
            raise ElementError(
                f"{self.name}: model reload requires is-updatable=true")
        if self.backend is None:
            raise ElementError(f"{self.name}: not started")
        model = model or self.props["model"]
        model = resolve_model_uri(model) if model else ""
        sw = self._ensure_swapper()
        if not self._owns_backend or not self.props["staged-reload"]:
            # a shared backend is visible to every filter on the key, so a
            # per-element pointer swap cannot replace it — guarded legacy
            # inline reload (double-buffered inside backends that support
            # it, e.g. jax-xla)
            return self._inline_reload(model)
        return sw.request(
            model,
            observation_window=float(self.props["observation-window"]),
            error_burst=int(self.props["rollback-error-burst"]),
        )

    def _inline_reload(self, model: str) -> SwapTicket:
        """Legacy in-place ``backend.reload()`` with the keep-serving
        guarantee: a failed reload logs, counts ``swap_failures``, and
        leaves the old model serving — it must never escape into the
        supervision machinery and kill/restart the element."""
        sw = self._ensure_swapper()
        try:
            FAULTS.check("filter.reload.load")
            self.backend.reload(model)
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — reload boundary
            self.log.error(
                "model reload from %r failed (old model keeps serving): %s",
                model, e,
            )
            return sw.note_inline_failure(e)
        self.props["model"] = model
        self.log.info("model reloaded from %s", model)
        return sw.note_inline_swap(model)

    def _validate_staged(self, be: FilterBackend):
        """Staging-thread schema validation: the new model must accept
        the pipeline's negotiated input stream and keep producing the
        negotiated output schema (downstream never renegotiates during a
        hot swap).  Returns the raw model info the element adopts at
        swap time."""
        raw_in, raw_out = be.get_model_info()
        new_in = self._as_stream_spec(raw_in)
        new_out = self._as_stream_spec(raw_out)
        negotiated = self.sink_specs.get(0)
        if (negotiated is not None and negotiated.tensors
                and new_in is not None):
            got = self._input_for_backend(negotiated)
            if not new_in.is_compatible(got):
                raise ElementError(
                    f"{self.name}: staged model input "
                    f"{new_in.to_string()} does not accept the negotiated "
                    f"stream {got.to_string()}"
                )
        if (new_out is None and negotiated is not None
                and negotiated.tensors):
            try:
                new_out = self._as_stream_spec(
                    be.set_input_info(self._input_for_backend(negotiated)))
            except NotImplementedError:
                new_out = None
        cur_out = self.srcpads[0].spec if self.srcpads else None
        if (new_out is not None and cur_out is not None
                and getattr(cur_out, "tensors", None)
                and not self.props["invoke-dynamic"]
                and not self._out_comb
                and not cur_out.is_compatible(new_out)):
            raise ElementError(
                f"{self.name}: staged model output {new_out.to_string()} "
                f"does not match the negotiated downstream schema "
                f"{cur_out.to_string()}"
            )
        return raw_in, raw_out

    def _probe_inputs(self, model_in=None) -> Optional[List[Any]]:
        """A zero frame matching the model's (or negotiated) input
        schema, flexible dims resolved to 1; None when no static schema
        exists to probe.  ``model_in`` overrides the serving model's raw
        input info (the staging path probes the NEW model's schema)."""
        spec = self._as_stream_spec(
            self._model_in if model_in is None else model_in)
        if spec is None and model_in is None:
            negotiated = self.sink_specs.get(0)
            if negotiated is not None and negotiated.tensors:
                spec = self._input_for_backend(negotiated)
        if spec is None or not spec.tensors:
            return None
        probes = []
        for t in spec.tensors:
            shape = tuple(1 if d in (None, 0) else int(d) for d in t.shape)
            probes.append(np.zeros(shape, dtype=t.dtype))
        return probes

    def _warmup_staged(self, be: FilterBackend) -> None:
        """Staging-thread JIT warmup: one probe invoke (and a batched one
        when the hot path micro-batches) so a swap never forces a fresh
        XLA trace on the serving thread — on TPU that compile is
        multi-second, which would stall the stream."""
        probes = self._probe_inputs()
        if probes is None:
            probes = self._probe_inputs(model_in=be.get_model_info()[0])
            if probes is None:
                return  # nothing static to probe (dynamic/custom schema)
        be.invoke(list(probes))
        if be.supports_batch and self.preferred_batch > 1:
            be.invoke_batch([p[None] for p in probes])

    def _swap_tick(self) -> List[Tuple[int, TensorFrame]]:
        """Frame-boundary lifecycle work: apply a staged swap, commit an
        expired observation window, and reap retired backends — all
        strictly AFTER draining the in-flight dispatch window, so a
        retiring backend outlives its last in-flight frame.  Returns the
        drained results (the caller emits them ahead of new output)."""
        sw = self._swapper
        if sw is None or not sw.has_boundary_work:
            return []
        drained = self._flush_staged()
        drained.extend(self._drain_inflight())
        staged = sw.take_staged()
        if staged is not None:
            be, model, raw_in, raw_out, ticket = staged
            old_blob = (
                self.backend, self._model_in, self._model_out,
                self.props["model"],
            )
            self.backend = be
            self._win_async = None  # re-latch for the fresh backend
            if raw_in is not None:
                self._model_in = raw_in
            if raw_out is not None:
                self._model_out = raw_out
            self.props["model"] = model
            sw.activated(old_blob, ticket)
            self.log.info(
                "hot-swapped to model %r (version %d); observing for "
                "%.1fs", model, sw.model_version,
                float(self.props["observation-window"]),
            )
        if sw.observing:
            sw.note_ok()  # commits once the observation window elapsed
        sw.reap()
        return drained

    def _backend_invoke(self, inputs: List[Any]) -> List[Any]:
        sw = self._swapper
        if sw is None or not sw.observing:
            return self.backend.timed_invoke(inputs)
        return self._observed_invoke(False, inputs)

    def _backend_invoke_batch(
        self, inputs: List[Any], private: bool = False
    ) -> List[Any]:
        """``private=True`` marks inputs the filter freshly stacked or
        staged itself — the backend may DONATE them (XLA reuses their
        device memory for outputs: zero per-batch allocations).  Never
        donated inside a post-swap observation window: a failed invoke is
        replayed on the retained old backend with the SAME inputs, which
        donation would have destroyed."""
        sw = self._swapper
        if sw is None or not sw.observing:
            if private:
                return self.backend.timed_invoke_batch_donated(inputs)
            return self.backend.timed_invoke_batch(inputs)
        return self._observed_invoke(True, inputs)

    # -- device-resource resilience (degrade, don't die) ---------------------
    def _resilient_invoke(self, inputs: List[Any]) -> List[Any]:
        """Per-frame invoke with the OOM/device-loss recovery ladder."""
        try:
            return self._backend_invoke(inputs)
        except DeviceOomError:
            # a single frame has no batch to split: trim recreatable
            # memory and retry the frame once
            self._oom_retries += 1
            self._trim_for_oom()
            return self._backend_invoke(inputs)
        except DeviceLostError as e:
            self._remesh_after_loss(e)
            return self._backend_invoke(inputs)

    def _resilient_invoke_batch(
        self, inputs: List[Any], private: bool = False
    ) -> List[Any]:
        """Micro-batch invoke with the recovery ladder: on device OOM,
        trim recreatable memory and retry ONCE at the next-smaller
        batch bucket (the halves re-bucket through the backend's own
        ``_pad_rows`` machinery — a strictly smaller compile bucket,
        hence a strictly smaller peak working set); on device loss,
        re-mesh onto the survivors and retry.  Retries never donate:
        both halves slice the same underlying arrays."""
        try:
            return self._backend_invoke_batch(inputs, private=private)
        except DeviceOomError:
            if any(getattr(t, "is_deleted", _never)() for t in inputs):
                # the donated first attempt consumed its inputs before
                # the OOM landed (donation invalidates at dispatch, not
                # at success): nothing left to slice — surface the
                # typed transient error to supervision instead of
                # crashing on a deleted array
                raise
            self._oom_retries += 1
            self._trim_for_oom()
            n = int(inputs[0].shape[0])
            if n <= 1:
                return self._backend_invoke_batch(inputs)
            self._oom_shrinks += 1
            self.log.warning(
                "device OOM on a %d-row micro-batch: trimmed caches, "
                "retrying as two half-bucket invokes", n)
            h = (n + 1) // 2
            out1 = self._backend_invoke_batch([t[:h] for t in inputs])
            out2 = self._backend_invoke_batch([t[h:] for t in inputs])
            return [
                _concat_tensors([a, b]) for a, b in zip(out1, out2)
            ]
        except DeviceLostError as e:
            self._remesh_after_loss(e)
            if any(getattr(t, "is_deleted", _never)() for t in inputs):
                # donated inputs died with the device: the re-mesh cures
                # the NEXT frames; this one surfaces typed to supervision
                raise
            return self._backend_invoke_batch(inputs)

    def _trim_for_oom(self) -> None:
        """Release every recreatable byte before the retry: the
        backend's compiled-program cache and the process staging-buffer
        pool (exact ``oom_evictions`` accounting)."""
        from ..core.buffer import DEVICE_POOL

        freed = 0
        be = self.backend
        if be is not None:
            freed += int(be.trim_caches() or 0)
        freed += DEVICE_POOL.trim()
        self._oom_evictions += freed

    def _remesh_after_loss(self, err: DeviceLostError) -> None:
        """Degraded-mesh re-shard: build a replacement backend on the
        surviving devices (``parallel/mesh.shrink_axes`` ladder via the
        backend's ``remesh_spec_after_loss``), swap the serving pointer
        atomically once the replacement is FULLY staged, retire the
        wounded backend through the hot-swap graveyard (closed only
        after the in-flight window drains), and mark this element —
        and, via the pipeline, the serving plane — degraded.  Backends
        with no re-mesh story (or shared backends, whose pointer this
        element does not own) re-raise into supervision: an element
        restart re-picks devices."""
        self._device_lost += 1
        be = self.backend
        if be is None or not self._owns_backend:
            # shared backends (pointer not ours) re-raise untouched —
            # checked BEFORE remesh_spec_after_loss, whose per-device
            # liveness probe may block against a wedged runtime only to
            # have its result discarded here
            raise err
        reported = getattr(err, "device_ids", ()) or ()
        res = be.remesh_spec_after_loss(reported)
        if res is None:
            # no re-mesh story (unsharded, or the probe found every
            # mesh member alive): record any ordinals PROVABLY dead so
            # the supervision restart cannot re-pick the dead chip —
            # open()'s survivor placement honors the exclusion even
            # unsharded — then escalate
            dead = be.dead_ordinals_after_loss(reported)
            if dead:
                self._mesh_exclude = tuple(
                    set(self._mesh_exclude) | set(dead))
            raise err
        spec, lost = res
        self._mesh_override = spec
        # always exclude the identified dead members (reported, probed,
        # or conservatively guessed): ordinal-first claiming would
        # otherwise hand the rebuilt backend the dead chip back
        self._mesh_exclude = tuple(set(self._mesh_exclude) | set(lost))
        model = self.props["model"] or None
        if model:
            model = resolve_model_uri(model)
        self.log.error(
            "device lost (%s): re-sharding onto survivors as mesh=%r",
            err, spec or "unsharded")
        new_be = self._make_backend(model)  # fully staged before return
        new_be.degraded = True
        old_be, self.backend = self.backend, new_be
        self._win_async = None  # re-latch for the fresh backend
        self._ensure_swapper().discard(old_be)  # reaped at a drained boundary
        self._remeshes += 1
        self._degraded = True
        p = self._pipeline
        if p is not None:
            p.incident("device_lost", self.name, {
                "lost_devices": list(lost), "remesh": spec or "unsharded",
            })
            p.degraded_feedback(
                self.name, f"device lost; serving on mesh={spec or 'none'}")

    def _observed_invoke(self, batched: bool, inputs: List[Any]) -> List[Any]:
        """Invoke inside the post-swap observation window: an error is
        served by the RETAINED old model (zero frame loss) and counted;
        a burst rolls the swap back entirely.  Neither path ever reaches
        the supervisor's error-policy/restart machinery."""
        sw = self._swapper
        try:
            if FAULTS.is_armed():
                FAULTS.check("filter.reload.post",
                             interrupt=lambda: self.interrupted)
            out = (
                self.backend.timed_invoke_batch(inputs) if batched
                else self.backend.timed_invoke(inputs)
            )
        except (KeyboardInterrupt, SystemExit):
            raise
        except BaseException as e:  # noqa: BLE001 — observation boundary
            verdict = sw.note_error(e)
            if verdict is None:
                raise
            (old_be, old_in, old_out, old_model), rolled_back = verdict
            if rolled_back:
                failed = self.backend
                self.backend = old_be
                self._win_async = None  # re-latch for the restored backend
                self._model_in, self._model_out = old_in, old_out
                self.props["model"] = old_model
                sw.discard(failed)
                p = self._pipeline
                if p is not None:
                    # incident: a rollout that rolled back is exactly
                    # when "where did the time go" gets asked
                    p.incident("swap_rollback", self.name, e)
            # the frame is retried on the old backend either way — a
            # bad rollout must not cost a single frame
            return (
                old_be.timed_invoke_batch(inputs) if batched
                else old_be.timed_invoke(inputs)
            )
        sw.note_ok()
        return out

    def pending_frames(self) -> int:
        """Logical frames parked in the in-flight dispatch window plus
        the staged (not yet dispatched) ingest batch (drain/stop
        accounting, Pipeline.drain)."""
        n = sum(
            sum(getattr(f, "batch_size", 1) for f in frames)
            for frames in self._inflight.payloads()
        )
        staged = self._staged
        if staged is not None:
            n += staged[2]
        return n

    def health_info(self) -> Dict[str, Any]:
        """Model-rollout counters merged into ``Pipeline.health()``."""
        info: Dict[str, Any] = {
            "model": self.props["model"],
            "model_version": 0,
            "swaps": 0,
            "swap_failures": 0,
            "rollbacks": 0,
            # jax-profiler session held by this element (trace=1) —
            # exported as nns.profiler.active via the health collector
            "profiler_active": 1 if getattr(self, "_tracing", False) else 0,
            # device-resource resilience (nns.device.*): exact OOM
            # shrink-retry / trim / re-mesh accounting, plus the
            # degraded flag the discovery plane mirrors
            "oom_retries": self._oom_retries,
            "oom_shrinks": self._oom_shrinks,
            "oom_evictions": self._oom_evictions,
            "device_lost": self._device_lost,
            "remeshes": self._remeshes,
            "degraded": 1 if (
                self._degraded
                or (self.backend is not None and self.backend.degraded)
            ) else 0,
        }
        if self._swapper is not None:
            info.update(self._swapper.snapshot())
        # mesh-sharded serving facts (jax-xla mesh= prop): devices/axis
        # sizes + host-batch scatters — exported as nns.mesh.* via the
        # ONE health-collector path (metrics_info here would double-emit)
        be = self.backend
        if be is not None and hasattr(be, "mesh_info"):
            info.update(be.mesh_info())
        # named-thread census (core/liveness.py ThreadBeat): the async
        # feed's reaper + staging-lane workers are part of the health
        # story — a wedged one shows alive=True with a growing age
        from ..core.liveness import thread_census

        win = self._inflight
        lane = self._lane
        info["threads"] = thread_census(
            win.heartbeat if win is not None else None,
            lane.heartbeat if lane is not None else None,
        )
        return info

    def metrics_info(self):
        """Registry samples (core/telemetry.py, scrape time only): invoke
        counters plus the async-feed gauges — the CompletionWindow
        occupancy/reap counts and the HostStagingLane stats."""
        win = self._inflight
        lane = self._lane
        return [
            ("nns.filter.invokes", self._invokes),
            ("nns.filter.invoked_frames", self._invoked_frames),
            ("nns.filter.invoke_latency", self.latency_us * 1e-6),
            ("nns.feed.window_occupancy", len(win)),
            ("nns.feed.window_reaped", win.reaped),
            ("nns.feed.dispatch_waits", win.dispatch_waits),
            ("nns.feed.lane_pending",
             lane.pending() if lane is not None else 0),
            ("nns.feed.lane_staged",
             lane.staged if lane is not None else 0),
        ]

    def histograms_info(self):
        """Always-on log2 latency histograms exported by the telemetry
        collector (buckets + derived p50/p99 gauges at scrape time):
        completion-window dwell, park -> pop."""
        return [("nns.feed.window_dwell_seconds", self._inflight.dwell)]

    @staticmethod
    def _stamp_invoke_spans(frames: Sequence[TensorFrame],
                            dispatch_s: float, compute_s: float) -> None:
        """Trace spans over the query wire: frames that carry the server
        receive stamp (``TL_RX_META``, set by ``QueryServerCore.process``)
        get this invoke's (dispatch, compute) durations attached, so the
        answer's server-side decomposition can split device time out of
        queue time.  One dict-containment probe per invoke when the
        stream never crossed the wire."""
        probe = frames[0]
        m0 = (
            probe.frames_info[0][2]
            if isinstance(probe, BatchFrame) and probe.frames_info
            else probe.meta
        )
        if TL_RX_META not in m0:
            return
        span = (max(0.0, dispatch_s), max(0.0, compute_s))
        for f in frames:
            if isinstance(f, BatchFrame):
                for _, _, m in f.frames_info:
                    if TL_RX_META in m:
                        m[TL_INVOKE_META] = span
                if TL_RX_META in f.meta:
                    f.meta[TL_INVOKE_META] = span
            elif TL_RX_META in f.meta:
                f.meta[TL_INVOKE_META] = span

    # -- negotiation --------------------------------------------------------
    def _input_for_backend(self, spec: StreamSpec) -> StreamSpec:
        comb = self._in_comb if self.backend is not None else _parse_combination(
            self.props["input-combination"]
        )
        if comb:
            return spec.pick([i for _, i in comb])
        return spec

    def accept_spec(self, pad, spec):
        if self._model_in is not None and spec.tensors:
            want = self._model_in
            got = self._input_for_backend(spec)
            if not want.is_compatible(got):
                raise ElementError(
                    f"{self.name}: stream schema {got.to_string()} does not match "
                    f"model input {want.to_string()}"
                )
        return spec

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if self.props["invoke-dynamic"]:
            # per-buffer output schemas: advertise format=flexible so
            # downstream negotiates late, per frame (reference wraps
            # invoke_dynamic outputs as flexible, tensor_filter.c:856-930)
            return StreamSpec((), FORMAT_FLEXIBLE, in_spec.framerate)
        if self._model_out is not None:
            out = self._model_out
        elif self.backend is not None and in_spec.tensors:
            out = self.backend.set_input_info(self._input_for_backend(in_spec))
        else:
            return ANY
        comb = self._out_comb
        if comb:
            # 'iN' indexes the element's ORIGINAL input tensors (pre
            # input-combination), matching reference tensor_filter.c:856-898
            tensors = []
            for src, i in comb:
                tensors.append(in_spec.tensors[i] if src == "i" else out.tensors[i])
            out = StreamSpec(tuple(tensors), out.fmt, in_spec.framerate or out.framerate)
        return out

    # -- processing ---------------------------------------------------------
    def _compose_outputs(self, orig_inputs: List[Any], outputs: List[Any]) -> List[Any]:
        comb = self._out_comb
        if not comb:
            return outputs
        return [orig_inputs[i] if src == "i" else outputs[i] for src, i in comb]

    def _record_stats(self, dt_s: float, nframes: int) -> None:
        import time

        self._invokes += 1
        self._invoked_frames += nframes
        if self.props["latency"]:
            self._latency_ring.append(dt_s * 1e6 / max(nframes, 1))
            if self.props["latency-report"] and self._pipeline is not None:
                from ..pipeline.pipeline import BusMessage

                self._pipeline.post(
                    BusMessage(
                        "element",
                        self.name,
                        {"latency-us": self.latency_us, "batch": nframes},
                    )
                )
        if self.props["throughput"]:
            t = time.monotonic()
            if self._t_first is None:
                self._t_first = t
            self._t_last = t
            self._nframes += nframes

    @property
    def latency_us(self) -> float:
        """Average per-frame invoke latency of the last 10 invokes, µs
        (reference: prop `latency`, nnstreamer_plugin_api_filter.h:162)."""
        return float(np.mean(self._latency_ring)) if self._latency_ring else 0.0

    @property
    def throughput_fps(self) -> float:
        """Outputs/sec since start (reference: prop `throughput`)."""
        if not self._nframes or self._t_first is None or self._t_last == self._t_first:
            return 0.0
        return self._nframes / (self._t_last - self._t_first)

    def transform(self, frame: TensorFrame) -> TensorFrame:
        assert self.backend is not None, f"{self.name} not started"
        sw = self._swapper
        if sw is not None and sw.has_boundary_work and not self._inflight:
            # per-frame path never parks batches, so the tick's drained
            # results are always empty here
            self._swap_tick()
        comb = self._in_comb
        inputs = [frame.tensors[i] for _, i in comb] if comb else list(frame.tensors)
        import time

        FAULTS.check("filter.invoke", interrupt=lambda: self.interrupted)
        t0 = time.perf_counter()
        if isinstance(frame, BatchFrame):
            # a pre-batched block on a single-invoke path (max-batch=1,
            # invoke-dynamic, backend without native batching): the batch
            # axis must still mean "batch" — invoke() would treat it as
            # part of one frame's shape (and a mesh backend would
            # REPLICATE instead of shard).  invoke_batch's per-frame
            # fallback covers batchless backends.
            outputs = self._resilient_invoke_batch(inputs)
            dt = time.perf_counter() - t0
            self._record_stats(dt, frame.batch_size)
        else:
            outputs = self._resilient_invoke(inputs)
            dt = time.perf_counter() - t0
            self._record_stats(dt, 1)
        self._stamp_invoke_spans((frame,), 0.0, dt)
        return frame.with_tensors(self._compose_outputs(frame.tensors, outputs))

    def handle_frame_batch(
        self, pad: int, frames: List[TensorFrame]
    ) -> List[Tuple[int, TensorFrame]]:
        """Micro-batched path: scheduler hands N frames; one invoke_batch.
        A pending hot swap applies here first — a frame boundary with the
        in-flight window drained (the drained results are emitted ahead
        of this batch's, preserving stream order)."""
        sw = self._swapper
        if sw is not None and sw.has_boundary_work:
            pre = self._swap_tick()
            if pre:
                return pre + list(self._handle_batch(pad, frames) or [])
        return self._handle_batch(pad, frames)

    def _handle_batch(
        self, pad: int, frames: List[TensorFrame]
    ) -> List[Tuple[int, TensorFrame]]:
        assert self.backend is not None
        import time

        # handler-entry stamp: the trace-span "device-dispatch" segment
        # (stack/stage time before the backend call) is measured from here
        self._t_handler = time.perf_counter()
        if any(isinstance(f, BatchFrame) for f in frames):
            # block ingest (≙ converter frames-per-tensor batching,
            # gsttensor_converter.c frames-per-tensor): the batch axis
            # already exists — skip per-frame stacking entirely.  A
            # staged lane batch is older: dispatch it first (FIFO).
            return self._flush_staged() + self._handle_prebatched(frames)
        if len(frames) == 1:
            # queue-starved moment: release the staged batch and drain
            # the in-flight window first so this frame cannot overtake
            # older parked batches
            results = self._flush_staged()
            results.extend(self._drain_inflight())
            results.append((0, self.transform(frames[0])))
            return results
        comb = self._in_comb
        per_frame = [
            [f.tensors[i] for _, i in comb] if comb else list(f.tensors) for f in frames
        ]
        if self._lane is not None and type(per_frame[0][0]) is np.ndarray:
            # host ingest: stack + host->device placement move to the lane
            # thread, and dispatch is DEFERRED BY ONE BATCH — by the time
            # batch k's device arrays are needed, its transfer has been
            # overlapping batch k-1's compute (double-buffered staging)
            job = self._lane.submit(per_frame)
            prev, self._staged = self._staged, (job, frames, len(frames))
            if prev is None:
                return []
            pjob, pframes, pn = prev
            batched = self._staged_result(pjob)
            return self._run_batch(batched, pframes, pn, private=True)
        results = self._flush_staged()  # mixed stream: keep FIFO
        ntensors = len(per_frame[0])
        batched = [
            _stack_tensors([pf[t] for pf in per_frame]) for t in range(ntensors)
        ]
        results.extend(self._run_batch(batched, frames, len(frames),
                                       private=True))
        return results

    def _run_batch(
        self, batched: List[Any], frames: List[TensorFrame], nlogical: int,
        private: bool = False,
    ) -> List[Tuple[int, TensorFrame]]:
        """Shared micro-batch tail: one invoke_batch + stats, then either
        batch-through (device residency: the whole micro-batch leaves as
        ONE frame, outputs still on device — no host sync here, so the
        next batch's stack/dispatch overlaps this one's compute; downstream
        fused decoder / chained filter / sink splits or materializes at the
        real host boundary) or the depth-N dispatch window.  ``private``
        marks caller-created batches the backend may donate."""
        import time

        FAULTS.check("filter.invoke", interrupt=lambda: self.interrupted)
        t0 = time.perf_counter()
        out_b = self._resilient_invoke_batch(batched, private=private)
        dt = time.perf_counter() - t0
        self._record_stats(dt, nlogical)
        self._stamp_invoke_spans(
            frames, t0 - self._t_handler if self._t_handler else 0.0, dt)
        if self.batch_through_active:
            infos = _logical_infos(frames)
            p, d, m = infos[0]
            return [(0, FRAME_POOL.acquire_batch(
                list(out_b), pts=p, duration=d, meta=dict(m),
                frames_info=infos,
            ))]
        return self._dispatch_or_park(out_b, frames)

    def _dispatch_or_park(
        self, out_b: List[Any], frames: List[TensorFrame]
    ) -> List[Tuple[int, TensorFrame]]:
        """Completion-driven depth-N dispatch: park this batch's (async)
        device outputs in the window — its reaper thread materializes
        parked batches FIFO off the dispatch thread — then emit whatever
        has COMPLETED at the front.  The dispatch thread never sits in
        ``device_get``: when the window is full it waits on the oldest
        batch's completion event (bounded, cooperatively interruptible)
        as pure backpressure, and by the time an entry is popped its
        device->host sync has already happened on the reaper.  The raw
        benchmark sustains its rate at exactly this structure (bench.py
        BENCH_RAW); the reference's steady state is synchronous
        map->invoke->append (tensor_filter.c:642-930)."""
        depth = max(1, int(self.props["dispatch-depth"]))
        if self._win_async is None:
            # capability latched once per backend instance (reset at
            # start()/swap/rollback): the hot path never re-probes
            self._win_async = any(
                hasattr(o, "copy_to_host_async") for o in out_b
            )
            if not self._win_async and depth > 1:
                self.log.info(
                    "dispatch-depth=%d requested but %r outputs are "
                    "host-resident: the dispatch window degrades to the "
                    "synchronous path", depth, self._framework,
                )
        if depth > 1 and self._win_async:
            from ..core.buffer import start_host_copies

            start_host_copies(out_b)
            self._inflight.park(out_b, frames)
            results = self._pop_ready()
            while len(self._inflight) > depth - 1:
                self._wait_window_oldest()
                results.extend(self._pop_ready())
            return results
        # synchronous path: drain any batches parked while the window was
        # active (depth lowered mid-stream / backend change) first, so the
        # current batch cannot overtake them
        return self._drain_inflight() + self._emit_batch(out_b, frames)

    def _handle_prebatched(
        self, frames: List[TensorFrame]
    ) -> List[Tuple[int, TensorFrame]]:
        """Frames that already carry a batch axis (BatchFrame block ingest,
        possibly mixed with plain frames): concatenate on axis 0 — usually a
        no-op because the scheduler hands exactly one full block — and run
        invoke_batch.  input-combination selects tensor INDICES, which
        applies to batched tensors unchanged; output-combination's
        per-logical input rows are sliced in _emit_batch.  A block larger
        than max-batch is chunked here (lazy device slices) so max-batch
        keeps bounding the invoke's batch axis — the compiled-bucket /
        HBM-budget contract — even though the scheduler never splits a
        queue item."""
        comb = self._in_comb
        batched = _batched_tensors(
            frames, [i for _, i in comb] if comb else None
        )
        nlogical = sum(getattr(f, "batch_size", 1) for f in frames)
        mb = max(1, int(self.props["max-batch"]))
        if nlogical <= mb:
            return self._run_batch(batched, frames, nlogical)
        # out-combination 'iN' entries index ORIGINAL input tensors; when
        # in-combination narrowed `batched`, the chunks' synthetic frames
        # must carry the originals for _emit_batch to slice
        if self._out_needs_inputs and comb:
            carry = _batched_tensors(frames, None)
        else:
            carry = batched
        infos = _logical_infos(frames)
        results: List[Tuple[int, TensorFrame]] = []
        for k in range(0, nlogical, mb):
            chunk = [t[k:k + mb] for t in batched]
            cinfos = infos[k:k + mb]
            syn = BatchFrame(
                tensors=[t[k:k + mb] for t in carry],
                pts=cinfos[0][0], duration=cinfos[0][1],
                meta=dict(cinfos[0][2]), frames_info=list(cinfos),
            )
            results.extend(self._run_batch(chunk, [syn], len(cinfos)))
        return results

    def _emit_batch(
        self, out_b: Optional[List[Any]], frames: List[TensorFrame],
        out_np: Optional[List[Any]] = None,
    ) -> List[Tuple[int, TensorFrame]]:
        """Materialize one micro-batch's outputs (one overlapped
        device->host pass for all tensors, then zero-copy views per
        frame).  ``frames`` may mix plain frames (one output row each)
        and BatchFrames (``batch_size`` consecutive rows).  ``out_np``
        carries outputs the window's reaper already materialized."""
        from ..core.buffer import materialize

        if out_np is None:
            out_np = materialize(out_b)
        # only the tensor indices an 'iN' entry actually reads get pulled
        # to host; "o0"-style output subsetting (and unreferenced input
        # tensors) must not drag input blocks over the link
        need_idx = sorted({
            i for src, i in (self._out_comb or []) if src == "i"
        }) if self._out_needs_inputs else []
        results = []
        b = 0
        for f in frames:
            if isinstance(f, BatchFrame):
                ins_np: List[Any] = [None] * len(f.tensors)
                if need_idx:
                    mats = materialize([f.tensors[i] for i in need_idx])
                    for k, i in enumerate(need_idx):
                        ins_np[i] = mats[k]
                for j, (p, d, m) in enumerate(f.frames_info):
                    outs = [o[b + j] for o in out_np]
                    if self._out_comb:
                        ins = [
                            (t[j] if t is not None else None) for t in ins_np
                        ]
                        outs = self._compose_outputs(ins, outs)
                    results.append((0, FRAME_POOL.acquire(
                        outs, pts=p, duration=d, meta=dict(m),
                    )))
                b += f.batch_size
            else:
                outs = [o[b] for o in out_np]
                results.append(
                    (0, f.with_tensors(self._compose_outputs(f.tensors, outs)))
                )
                b += 1
        return results

    def _pop_ready(self) -> List[Tuple[int, TensorFrame]]:
        """Emit every batch the reaper has COMPLETED at the front of the
        window (FIFO), without blocking."""
        results: List[Tuple[int, TensorFrame]] = []
        for mats, frames in self._inflight.pop_ready():
            results.extend(self._emit_batch(None, frames, out_np=mats))
        return results

    def _wait_window_oldest(self) -> None:
        """Bounded, cooperatively interruptible wait for the oldest
        parked batch's completion (full-window backpressure)."""
        while not self._inflight.wait_oldest(timeout=0.05):
            if self.interrupted:
                raise StallError(
                    f"{self.name}: interrupted waiting on the dispatch "
                    "window")

    def _drain_inflight(self) -> List[Tuple[int, TensorFrame]]:
        results = self._pop_ready()
        while len(self._inflight):
            self._wait_window_oldest()
            results.extend(self._pop_ready())
        return results

    def _staged_result(self, job: StagedBatch) -> List[Any]:
        """Collect a staging job's device arrays (bounded waits so a
        wedged transfer stays interruptible)."""
        while not job.wait(timeout=0.05):
            if self.interrupted:
                raise StallError(
                    f"{self.name}: interrupted waiting on the ingest lane")
        # allow-blocking: the wait() loop above already saw _done set —
        # result() returns (or raises the staging error) immediately
        return job.result()

    def _flush_staged(self) -> List[Tuple[int, TensorFrame]]:
        """Dispatch the deferred (staged) ingest batch, if any.  Always
        called BEFORE draining the window at a boundary: the dispatch
        parks into the window, so a subsequent drain emits everything in
        FIFO order."""
        if self._staged is None:
            return []
        job, frames, nlogical = self._staged
        self._staged = None
        batched = self._staged_result(job)
        return self._run_batch(batched, frames, nlogical, private=True)

    def handle_eos(self, pad: int) -> List[Tuple[int, TensorFrame]]:
        """Release the staged batch and drain the in-flight window before
        EOS propagates."""
        outs = self._flush_staged()
        outs.extend(self._drain_inflight())
        outs.extend(self._swap_tick())
        return outs

    def handle_idle(self) -> List[Tuple[int, TensorFrame]]:
        """Scheduler idle hook: the input went quiet, so overlap has
        nothing left to win — release the staged batch and the parked
        window instead of withholding a live stream's tail until the next
        frame/EOS.  Also a natural frame boundary: a staged swap on an
        idle stream lands here instead of waiting for the next frame."""
        outs = self._flush_staged()
        outs.extend(self._drain_inflight())
        outs.extend(self._swap_tick())
        return outs

    # -- events -------------------------------------------------------------
    def handle_event(self, pad, ev):
        if isinstance(ev, Flush):
            # a flush drops queued frames; the staged batch and in-flight
            # results are frames too
            if self._staged is not None:
                self._staged[0].discard()
                self._staged = None
            self._inflight.clear()
            return super().handle_event(pad, ev)
        # any other in-band event must not overtake parked frames (events
        # and frames share one ordered queue, core/buffer.py) — emit the
        # staged batch and the window first, then the event
        drained = self._flush_staged()
        drained.extend(self._drain_inflight())
        if isinstance(ev, CustomEvent) and ev.name == "reload-model":
            # ≙ RELOAD_MODEL framework event (tested by
            # tests/nnstreamer_filter_reload in the reference), routed
            # through the staged swap path (core/lifecycle.py).  A failed
            # reload must NEVER escape into the supervision machinery —
            # it logs, counts swap_failures, and the old model keeps
            # serving.
            if not self.props["is-updatable"]:
                self.log.warning("reload requested but is-updatable=false")
            elif self.backend is not None:
                try:
                    ticket = self.request_reload(ev.data.get("model") or "")
                    if ticket.state == "refused":
                        # not a swap_failure (nothing was tried), but the
                        # operator's update was NOT applied — say so
                        self.log.warning(
                            "reload-model event refused (old model keeps "
                            "serving): %s", ticket.error,
                        )
                except (KeyboardInterrupt, SystemExit):
                    raise
                except BaseException as e:  # noqa: BLE001 — reload boundary
                    self._ensure_swapper().note_inline_failure(e)
                    self.log.error(
                        "reload-model event failed (old model keeps "
                        "serving): %s", e,
                    )
            return drained  # event swallowed; parked frames still flow
        return drained + list(super().handle_event(pad, ev) or [])


class SingleShot:
    """Pipeline-less single-invoke API.

    Reference: ``GTensorFilterSingle``
    (``tensor_filter_single.c:30-35``, "basis of single shot api") — wraps
    the same backends without any pipeline.
    """

    def __init__(self, framework: str = "auto", model: str = "", **props):
        if model:
            model = resolve_model_uri(model)
        merged = {"custom": "", **props}
        fw = (
            detect_framework(model, merged["custom"])
            if framework == "auto" else framework
        )
        self.backend: FilterBackend = find_backend(fw)()
        self.backend.open(model or None, merged)
        self.in_spec, self.out_spec = self.backend.get_model_info()

    def invoke(self, arrays: Sequence[Any]) -> List[Any]:
        return self.backend.invoke(list(arrays))

    def invoke_batch(self, arrays: Sequence[Any]) -> List[Any]:
        return self.backend.invoke_batch(list(arrays))

    def set_input_info(self, spec: StreamSpec) -> StreamSpec:
        return self.backend.set_input_info(spec)

    def close(self) -> None:
        self.backend.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
