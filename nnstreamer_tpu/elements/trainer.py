"""tensor_trainer: in-pipeline training element.

Reference: ``gst/nnstreamer/elements/gsttensor_trainer.c`` (SURVEY §3.4) —
a data pump + lifecycle/event manager around a trainer subplugin: first
buffer triggers create+start, every buffer becomes push_data, epoch
completion pushes a model-stats frame downstream, training completion saves
the model and lets the pipeline EOS.

Robustness (net-new vs the reference — the preemptible-TPU contract):

* **No silent death** — the training thread runs off the frame path, so a
  crash on a quiet stream used to be invisible until the next buffer (or
  forever).  The element registers a watchdog sweep that detects a dead
  backend thread within ~250ms, records a flight-recorder incident, and
  routes the typed error through the supervision taxonomy:
  ``error-policy=restart`` revives the backend (restart budget/backoff via
  the pipeline supervisor) with ``resume=1`` forced when a checkpoint-path
  exists — mid-run, on a live stream, realigning at the next epoch
  boundary; the fail-stop default surfaces the error immediately (the
  liveness-fail pattern: ``wait()`` raises without waiting for EOS).
* **Starvation-free co-hosting** — when the pipeline's memory watermark
  monitor reports sustained pressure, the sweep pauses training at the
  next step boundary (resumable — the bounded trainer queue backpressures,
  zero samples lost) and unpauses when pressure clears, so co-hosted
  serving never competes with train steps for headroom.  ``pause=true``
  is the manual override (runtime-settable).
* **Exact accounting** — ``health_info()`` exports the ``nns.train.*``
  surface (steps/samples/loss/checkpoints/resumes/pauses/...) through the
  one health-collector path; counters survive backend revives.
"""

from __future__ import annotations

import threading
from typing import Any, Dict

import numpy as np

from ..core.buffer import TensorFrame
from ..core.resilience import FatalError, TransientError, is_transient
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ..pipeline.element import Element, ElementError, Property, element
from ..pipeline.pipeline import BusMessage
from ..trainer.base import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerStatus,
    find_trainer,
)


@element("tensor_trainer")
class TensorTrainer(Element):
    PROPERTIES = {
        "framework": Property(str, "jax", "trainer backend name"),
        "model-config": Property(str, "", "config file path or inline JSON"),
        "model-save-path": Property(str, "", "where to save the trained model"),
        "model-load-path": Property(str, "", "warm-start weights"),
        "num-inputs": Property(int, 1, "input tensors per frame"),
        "num-labels": Property(int, 1, "label tensors per frame"),
        "num-training-samples": Property(int, 0, "train samples per epoch"),
        "num-validation-samples": Property(int, 0, "validation samples per epoch"),
        "epochs": Property(int, 1, "number of epochs"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # periodic full-state checkpointing (net-new vs reference, SURVEY §5.3:
        # preemptible-TPU recovery needs more than final model-save-path)
        "checkpoint-path": Property(str, "", "dir for periodic checkpoints"),
        "checkpoint-interval": Property(int, 1, "epochs between checkpoints"),
        "checkpoint-steps": Property(
            int, 0, "optimizer steps between checkpoints (0 = epoch-grain)"
        ),
        "checkpoint-keep": Property(int, 3, "checkpoints retained (0 = all)"),
        "resume": Property(bool, False, "resume from newest checkpoint"),
        # mesh-sharded train steps (the serving ``mesh=`` grammar, PR-13)
        "mesh": Property(str, "", "mesh spec (dp:2,tp:2) to shard train steps"),
        # resumable pause (starvation-free co-hosting; auto-driven by the
        # memory watermark monitor, manually via this runtime-settable prop)
        "pause": Property(bool, False, "true = pause training (runtime-settable)"),
        # ≙ gsttensor_trainer.c PROP_READY_TO_COMPLETE_TRAINING: setting
        # true on a RUNNING trainer finishes training gracefully (current
        # data drained, model saved, completion event fired)
        "ready-to-complete": Property(
            bool, False, "true = finish training now (runtime-settable)"
        ),
    }

    def set_property(self, key, value):
        super().set_property(key, value)
        k = key.replace("_", "-")
        if k == "ready-to-complete" and self.props["ready-to-complete"]:
            if self.backend is not None and self._created:
                # mirror the reference contract: graceful early finish
                # while training is live
                if hasattr(self.backend, "end_of_data"):
                    self._finish_requested = True
                    self.backend.end_of_data()
            else:
                # ≙ the reference's PLAYING-state-only warning; the flag
                # is honored when training goes live (handle_frame)
                self.log.warning(
                    "ready-to-complete set before training started; will "
                    "finish after the first pushed batch"
                )
        elif k == "pause":
            self._set_manual_pause(bool(self.props["pause"]))

    def __init__(self, name=None):
        super().__init__(name)
        self.backend = None
        self._created = False
        self._finish_requested = False
        self.training_complete = threading.Event()
        self._stats_lock = threading.Lock()
        self._stats_pending = []  # epoch stats awaiting downstream emission
        self._backend_lock = threading.Lock()  # create/revive vs sweep races
        self._sweep_cb = None       # the registered sweep hook (per-run dedup)
        self._death_handled = False  # one supervision verdict per backend
        self._revive_next = False   # next backend create resumes mid-stream
        self._manual_pause = False  # pause= prop (owns the paused state)
        self._auto_paused = False   # memory-watermark pause (yields to manual)
        # element-lifetime accounting: a revive replaces the backend, so
        # counters the chaos harness pins fold in here across restarts
        self.pauses = 0
        self.train_restarts = 0
        self._carry: Dict[str, int] = {
            "samples": 0, "checkpoints": 0, "resumes": 0,
            "replay_skipped": 0, "gap_samples": 0,
        }
        self._last_steps = 0
        self._last_status = TrainerStatus()

    def start(self):
        try:
            cls = find_trainer(self.props["framework"])
        except KeyError:
            raise ElementError(
                f"{self.name}: unknown trainer framework {self.props['framework']!r}"
            ) from None
        self.backend = cls()
        self.backend.add_listener(self._on_event)
        # reset run state so a restarted pipeline waits for the new run
        self.training_complete.clear()
        self._finish_requested = False
        self._death_handled = False
        with self._stats_lock:
            self._stats_pending = []
        p = self._pipeline
        if p is not None:
            if not p._started:
                # fresh pipeline run (vs a mid-run supervisor restart,
                # where _started is True): the stream will replay from
                # sample 0, so the mid-stream realign must not arm
                self._revive_next = False
            # dead-thread detection + memory-pressure coupling live on
            # the watchdog sweeper (~4Hz) — never on the frame path; a
            # mid-run supervisor restart must not stack a second hook
            cb = self._sweep_cb
            if cb is None or all(f is not cb for f, _ in p._sweep_hooks):
                self._sweep_cb = self._sweep
                p.register_sweep(self._sweep_cb, 0.25)

    def stop(self):
        if self.backend is not None:
            self._fold_counters(self.backend)
            self.backend.stop()
            self.backend = None
        self._created = False
        self._auto_paused = False

    def _fold_counters(self, be) -> None:
        """Preserve a dying/stopping backend's exact accounting: the
        next backend starts its session counters at zero, so the element
        carries the totals (``steps`` is global — restored from the
        checkpoint cursor — and must NOT be summed)."""
        c = self._carry
        c["samples"] += be.samples_trained
        c["checkpoints"] += be.checkpoints
        c["resumes"] += be.resumes
        c["replay_skipped"] += be.replay_skipped
        c["gap_samples"] += be.gap_samples
        self._last_steps = max(self._last_steps, be.steps)
        self._last_status = be.status

    def _on_event(self, event: str, status: TrainerStatus) -> None:
        # fires on the trainer's own thread: queue stats for in-band emission
        # (≙ reference pushing model-stats buffers) and post out-of-band
        if self._pipeline is not None:
            self._pipeline.post(BusMessage("element", self.name, {event: status.as_dict()}))
        if event == EVENT_EPOCH_COMPLETION:
            s = status
            with self._stats_lock:
                self._stats_pending.append(
                    np.asarray(
                        [s.epoch_count, s.training_loss, s.training_accuracy,
                         s.validation_loss, s.validation_accuracy],
                        np.float64,
                    )
                )
        if event == EVENT_TRAINING_COMPLETION:
            self.training_complete.set()

    def _drain_stats(self):
        with self._stats_lock:
            pending, self._stats_pending = self._stats_pending, []
        if not self.srcpads or not self.srcpads[0].is_linked:
            return []  # terminal trainer: drop (don't accumulate) stats
        return [(0, TensorFrame([stats])) for stats in pending]

    def derive_spec(self, pad=0):
        # downstream sees epoch-stats vectors
        return StreamSpec(
            (TensorSpec((5,), np.float64, "model-stats"),), FORMAT_STATIC
        )

    def _create_backend(self) -> None:
        """Create + start the backend (first buffer, or a supervision
        revive).  After a backend death with a checkpoint-path, the new
        backend resumes from the newest durable checkpoint and realigns
        on the live (non-replaying) stream."""
        props = dict(self.props)
        if self._revive_next:
            self._revive_next = False
            if props.get("checkpoint-path"):
                props["resume"] = True
                props["_midstream-restart"] = True
            self.train_restarts += 1
        self.backend.create(props)
        self.backend.start()
        if self._manual_pause or self._auto_paused:
            self.backend.pause()  # a pause spans backend revives
        self._created = True
        self._death_handled = False

    def handle_frame(self, pad, frame):
        assert self.backend is not None
        with self._backend_lock:
            if not self._created:
                # first buffer: create + start (reference :141-144)
                self._create_backend()
            be = self.backend
        be.push_data(frame)
        if (
            self.props["ready-to-complete"] and not self._finish_requested
            and hasattr(be, "end_of_data")
        ):
            # flag was set before training went live: honor it now
            self._finish_requested = True
            be.end_of_data()
        self._check_backend_error()
        return self._drain_stats()

    def _check_backend_error(self):
        err = getattr(self.backend, "error", None)
        if err is not None:
            if self.props.get("checkpoint-path"):
                self._revive_next = True  # a supervisor retry resumes
            if isinstance(err, (TransientError, FatalError)):
                # typed: the supervisor's restart policy classifies it
                # (transient -> restart budget, fatal -> fail/dead-letter)
                raise err
            raise ElementError(f"{self.name}: trainer failed: {err}") from err

    # -- watchdog sweep (dead-thread detection + pressure coupling) ----------
    def _sweep(self) -> None:
        """Runs on the pipeline's watchdog sweeper thread (~4Hz): detect
        a dead training thread even on a quiet stream, and couple the
        resumable pause to the memory watermark monitor."""
        pipe, be = self._pipeline, self.backend
        if pipe is None or be is None or not self._created:
            return
        self._pressure_sweep(pipe, be)
        if self._death_handled:
            return
        err = getattr(be, "error", None)
        if err is None and (self.training_complete.is_set()
                            or be.thread_alive()):
            # running, or finished clean (the backend fires
            # TRAINING_COMPLETION even on error — the error, not the
            # completion flag, decides whether this was a death)
            return
        if err is None:
            # the thread is gone with no recorded error: nothing a
            # restart can't also hit — treat as transient (a preemption
            # kill looks exactly like this)
            err = TransientError(f"{self.name}: training thread died silently")
        self._death_handled = True
        h = pipe.health_map.get(self.name)
        if h is not None:
            h.last_error = repr(err)
        pipe.incident("trainer_death", self.name, repr(err))
        pipe.post(BusMessage("warning", self.name, {
            "trainer": "died", "error": err,
        }))
        if self.props.get("error-policy") == "restart" and is_transient(err):
            if self.props.get("checkpoint-path"):
                self._revive_next = True
            verdict = pipe._restart_element(self, err)
            if verdict == "retry":
                with self._backend_lock:
                    try:
                        self._create_backend()
                    except Exception as e:  # revive failed: fail-stop
                        err = e
                    else:
                        return
            elif verdict == "stopping":
                return
            # degraded (budget exhausted / start failed): fall through
        # fail-stop: surface NOW (the liveness-fail pattern) — wait()
        # must raise instead of hoping a dead trainer ever reports
        if not isinstance(err, ElementError):
            err = ElementError(f"{self.name}: trainer failed: {err}")
        if h is not None:
            h.state = "failed"
        self.training_complete.set()  # never hang handle_eos on a corpse
        pipe.errors.append(err)
        pipe.post(BusMessage("error", self.name, err))
        pipe._stop_flag.set()
        pipe._sinks_done.set()

    def _pressure_sweep(self, pipe, be) -> None:
        """Memory-watermark coupling: sustained pressure pauses train
        steps (resumable, counted, incident) before serving degrades;
        training unpauses when pressure clears.  Manual ``pause=true``
        owns the state — auto never overrides it."""
        mon = pipe.memory_monitor
        if mon is None or self._manual_pause:
            return
        pressured = bool(getattr(mon, "pressured", False))
        if pressured and not self._auto_paused:
            self._auto_paused = True
            self.pauses += 1
            be.pause()
            self.log.warning(
                "%s: training paused (memory pressure; pause #%d)",
                self.name, self.pauses,
            )
            pipe.post(BusMessage("warning", self.name, {
                "train": "paused", "reason": "memory-pressure",
                "pauses": self.pauses,
            }))
            pipe.incident("train_paused", self.name,
                          {"reason": "memory-pressure"})
        elif not pressured and self._auto_paused:
            self._auto_paused = False
            be.unpause()
            self.log.info("%s: training resumed (pressure cleared)", self.name)
            pipe.post(BusMessage("element", self.name, {"train": "resumed"}))

    def _set_manual_pause(self, want: bool) -> None:
        if want == self._manual_pause:
            return
        self._manual_pause = want
        be = self.backend
        if be is None or not self._created:
            return  # honored when the backend comes up (_create_backend)
        if want:
            if not be.paused:
                self.pauses += 1
            be.pause()
        elif not self._auto_paused:
            # pressure-driven pause survives a manual unpause: the
            # watermark still governs until it clears
            be.unpause()

    # -- health export (the one collector path) ------------------------------
    def health_info(self) -> Dict[str, Any]:
        """The ``nns.train.*`` surface: exact step/sample accounting the
        kill/resume truth table and the chaos harness pin."""
        be = self.backend
        c = self._carry
        status = be.status if be is not None else self._last_status
        info = {
            "train_steps": max(self._last_steps, be.steps if be else 0),
            "train_samples": c["samples"] + (be.samples_trained if be else 0),
            "train_epochs": int(status.epoch_count),
            "train_loss": float(status.training_loss),
            "train_checkpoints": c["checkpoints"] + (be.checkpoints if be else 0),
            "train_resumes": c["resumes"] + (be.resumes if be else 0),
            "train_replay_skipped": (
                c["replay_skipped"] + (be.replay_skipped if be else 0)
            ),
            "train_gap_samples": c["gap_samples"] + (be.gap_samples if be else 0),
            "train_pauses": self.pauses,
            "train_paused": int(bool(be is not None and be.paused)),
            "train_restarts": self.train_restarts,
            "train_alive": int(bool(be is not None and be.thread_alive())),
        }
        mesh = getattr(be, "_mesh", None)
        if mesh is not None:
            from ..parallel.mesh import mesh_health_info

            info.update(mesh_health_info(mesh, be._mesh_axes))
        return info

    def handle_eos(self, pad):
        if self.backend is not None and self._created:
            if hasattr(self.backend, "end_of_data"):
                self.backend.end_of_data()
            # wait for the training thread to finish + save (reference waits
            # on TRAINING_COMPLETION before EOS)
            if not self.training_complete.wait(timeout=600):
                raise ElementError(
                    f"{self.name}: training did not complete within 600s"
                )
            self._check_backend_error()
        return self._drain_stats()
