"""tensor_trainer: in-pipeline training element.

Reference: ``gst/nnstreamer/elements/gsttensor_trainer.c`` (SURVEY §3.4) —
a data pump + lifecycle/event manager around a trainer subplugin: first
buffer triggers create+start, every buffer becomes push_data, epoch
completion pushes a model-stats frame downstream, training completion saves
the model and lets the pipeline EOS.
"""

from __future__ import annotations

import threading
from typing import Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec
from ..pipeline.element import Element, ElementError, Property, element
from ..pipeline.pipeline import BusMessage
from ..trainer.base import (
    EVENT_EPOCH_COMPLETION,
    EVENT_TRAINING_COMPLETION,
    TrainerStatus,
    find_trainer,
)


@element("tensor_trainer")
class TensorTrainer(Element):
    PROPERTIES = {
        "framework": Property(str, "jax", "trainer backend name"),
        "model-config": Property(str, "", "config file path or inline JSON"),
        "model-save-path": Property(str, "", "where to save the trained model"),
        "model-load-path": Property(str, "", "warm-start weights"),
        "num-inputs": Property(int, 1, "input tensors per frame"),
        "num-labels": Property(int, 1, "label tensors per frame"),
        "num-training-samples": Property(int, 0, "train samples per epoch"),
        "num-validation-samples": Property(int, 0, "validation samples per epoch"),
        "epochs": Property(int, 1, "number of epochs"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # periodic full-state checkpointing (net-new vs reference, SURVEY §5.3:
        # preemptible-TPU recovery needs more than final model-save-path)
        "checkpoint-path": Property(str, "", "dir for periodic checkpoints"),
        "checkpoint-interval": Property(int, 1, "epochs between checkpoints"),
        "checkpoint-keep": Property(int, 3, "checkpoints retained (0 = all)"),
        "resume": Property(bool, False, "resume from newest checkpoint"),
        # ≙ gsttensor_trainer.c PROP_READY_TO_COMPLETE_TRAINING: setting
        # true on a RUNNING trainer finishes training gracefully (current
        # data drained, model saved, completion event fired)
        "ready-to-complete": Property(
            bool, False, "true = finish training now (runtime-settable)"
        ),
    }

    def set_property(self, key, value):
        super().set_property(key, value)
        if (
            key.replace("_", "-") == "ready-to-complete"
            and self.props["ready-to-complete"]
        ):
            if self.backend is not None and self._created:
                # mirror the reference contract: graceful early finish
                # while training is live
                if hasattr(self.backend, "end_of_data"):
                    self._finish_requested = True
                    self.backend.end_of_data()
            else:
                # ≙ the reference's PLAYING-state-only warning; the flag
                # is honored when training goes live (handle_frame)
                self.log.warning(
                    "ready-to-complete set before training started; will "
                    "finish after the first pushed batch"
                )

    def __init__(self, name=None):
        super().__init__(name)
        self.backend = None
        self._created = False
        self._finish_requested = False
        self.training_complete = threading.Event()
        self._stats_lock = threading.Lock()
        self._stats_pending = []  # epoch stats awaiting downstream emission

    def start(self):
        try:
            cls = find_trainer(self.props["framework"])
        except KeyError:
            raise ElementError(
                f"{self.name}: unknown trainer framework {self.props['framework']!r}"
            ) from None
        self.backend = cls()
        self.backend.add_listener(self._on_event)
        # reset run state so a restarted pipeline waits for the new run
        self.training_complete.clear()
        self._finish_requested = False
        with self._stats_lock:
            self._stats_pending = []

    def stop(self):
        if self.backend is not None:
            self.backend.stop()
            self.backend = None
        self._created = False

    def _on_event(self, event: str, status: TrainerStatus) -> None:
        # fires on the trainer's own thread: queue stats for in-band emission
        # (≙ reference pushing model-stats buffers) and post out-of-band
        if self._pipeline is not None:
            self._pipeline.post(BusMessage("element", self.name, {event: status.as_dict()}))
        if event == EVENT_EPOCH_COMPLETION:
            s = status
            with self._stats_lock:
                self._stats_pending.append(
                    np.asarray(
                        [s.epoch_count, s.training_loss, s.training_accuracy,
                         s.validation_loss, s.validation_accuracy],
                        np.float64,
                    )
                )
        if event == EVENT_TRAINING_COMPLETION:
            self.training_complete.set()

    def _drain_stats(self):
        with self._stats_lock:
            pending, self._stats_pending = self._stats_pending, []
        if not self.srcpads or not self.srcpads[0].is_linked:
            return []  # terminal trainer: drop (don't accumulate) stats
        return [(0, TensorFrame([stats])) for stats in pending]

    def derive_spec(self, pad=0):
        # downstream sees epoch-stats vectors
        return StreamSpec(
            (TensorSpec((5,), np.float64, "model-stats"),), FORMAT_STATIC
        )

    def handle_frame(self, pad, frame):
        assert self.backend is not None
        if not self._created:
            # first buffer: create + start (reference :141-144)
            self.backend.create(dict(self.props))
            self.backend.start()
            self._created = True
        self.backend.push_data(frame)
        if (
            self.props["ready-to-complete"] and not self._finish_requested
            and hasattr(self.backend, "end_of_data")
        ):
            # flag was set before training went live: honor it now
            self._finish_requested = True
            self.backend.end_of_data()
        self._check_backend_error()
        return self._drain_stats()

    def _check_backend_error(self):
        err = getattr(self.backend, "error", None)
        if err is not None:
            raise ElementError(f"{self.name}: trainer failed: {err}") from err

    def handle_eos(self, pad):
        if self.backend is not None and self._created:
            if hasattr(self.backend, "end_of_data"):
                self.backend.end_of_data()
            # wait for the training thread to finish + save (reference waits
            # on TRAINING_COMPLETION before EOS)
            if not self.training_complete.wait(timeout=600):
                raise ElementError(
                    f"{self.name}: training did not complete within 600s"
                )
            self._check_backend_error()
        return self._drain_stats()
