"""tensor_sparse_enc / tensor_sparse_dec: static <-> sparse stream format.

Reference: ``gsttensor_sparseenc.c`` / ``gsttensor_sparsedec.c`` with the
payload layout of ``gsttensor_sparseutil.c:27-153`` (values + linear
indices + original spec).  Payloads here carry (values, indices) tensor
pairs per original tensor, with the dense spec in the flexible-stream meta.
"""

from __future__ import annotations

from typing import List

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import (
    ANY,
    FORMAT_FLEXIBLE,
    FORMAT_STATIC,
    StreamSpec,
    TensorSpec,
    sparse_decode,
    sparse_encode,
)
from ..pipeline.element import ElementError, Property, TransformElement, element


@element("tensor_sparse_enc")
class TensorSparseEnc(TransformElement):
    PROPERTIES = {"max-buffers": Property(int, 0, "mailbox depth override")}

    def derive_spec(self, pad=0):
        return StreamSpec((), FORMAT_FLEXIBLE, self.sink_specs.get(0, ANY).framerate)

    def transform(self, frame):
        tensors = []
        specs = []
        for t in frame.tensors:
            values, indices, spec = sparse_encode(np.asarray(t))
            tensors.extend([values, indices])
            specs.append(spec.to_string())
        out = frame.with_tensors(tensors)
        out.meta["sparse_specs"] = specs
        return out


@element("tensor_sparse_dec")
class TensorSparseDec(TransformElement):
    PROPERTIES = {"max-buffers": Property(int, 0, "mailbox depth override")}

    def derive_spec(self, pad=0):
        return ANY  # concrete shape restored per-buffer from meta

    def transform(self, frame):
        specs = frame.meta.get("sparse_specs")
        if specs is None:
            raise ElementError(f"{self.name}: frame lacks sparse_specs meta")
        if len(frame.tensors) != 2 * len(specs):
            raise ElementError(
                f"{self.name}: expected {2 * len(specs)} payload tensors, "
                f"got {len(frame.tensors)}"
            )
        tensors = []
        for i, spec_s in enumerate(specs):
            spec = TensorSpec.from_string(spec_s)
            values, indices = frame.tensors[2 * i], frame.tensors[2 * i + 1]
            tensors.append(sparse_decode(np.asarray(values), np.asarray(indices), spec))
        out = frame.with_tensors(tensors)
        out.meta.pop("sparse_specs", None)
        return out
