"""tensor_converter: media stream -> typed tensor stream.

Reference: ``gst/nnstreamer/elements/gsttensor_converter.c`` (chain :1015,
per-media-type framing :750-1005, external converter subplugins
``findExternalConverter`` :171).  Media types handled by the reference:
video/x-raw (RGB/BGRx/GRAY8, stride removal, frames-per-tensor batching),
audio/x-raw (frames-per-buffer), text (fixed bytes/frame), octet-stream
(reshape per input-dim/input-type), flexible tensors (parse per-memory
header), anything else via converter subplugins.

Raw media payloads arrive from the media sources (``elements/media_src.py``)
as byte buffers with a ``meta["media"]`` :class:`MediaInfo`; this element
does the reference's actual framing work: video stride removal (rows padded
to 4 bytes -> packed (H, W, C)), audio sample framing ((N, channels) per
the sample format), text fixed-size framing (pad/truncate to ``input-dim``
bytes), octet reshaping per ``input-dim``/``input-type``.  Array payloads
(appsrc/videotestsrc) pass through with ``frames-per-tensor`` batching
(reference: 3:W:H:1 -> 3:W:H:N, numpy (N,H,W,C)); flexible-header bytes are
decoded; unknown media goes to converter subplugins (registry kind
"converter").
"""

from __future__ import annotations

import time
from typing import List, Optional

import numpy as np

from ..core import registry
from ..core.buffer import BatchFrame, TensorFrame
from ..core.types import (
    ANY,
    FORMAT_STATIC,
    StreamSpec,
    TensorSpec,
    dtype_from_name,
    parse_dims_string,
    unpack_flex_header,
)
from ..pipeline.element import Element, ElementError, Property, element
from .. import converters as _converters  # noqa: F401 — registers subplugins


@element("tensor_converter")
class TensorConverter(Element):
    PROPERTIES = {
        "frames-per-tensor": Property(int, 1, "batch N media frames into one tensor"),
        "emit-blocks": Property(
            bool, False,
            "with frames-per-tensor > 1: emit a transparent BatchFrame of N "
            "logical frames (per-frame schema/pts preserved; batch-capable "
            "elements consume the batch axis, sinks/decoders split; at EOS "
            "a partial trailing block may be SMALLER than N — batch-"
            "bucketed consumers compile one tail bucket) instead "
            "of one shape-changed stacked tensor",
        ),
        "input-dim": Property(str, "", "octet mode: target dims (reference dialect)"),
        "input-type": Property(str, "", "octet mode: target element type"),
        "mode": Property(str, "", "external converter: 'custom:<subplugin-name>'"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        "set-timestamp": Property(
            bool, True,
            "stamp arrival-relative pts on frames that carry none "
            "(≙ gsttensor_converter set-timestamp)",
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._pending: List[TensorFrame] = []
        self._sub = None  # external converter subplugin instance
        self._ts_base = None  # set-timestamp: arrival-time origin

    # -- negotiation --------------------------------------------------------
    def start(self):
        self._ts_base = None  # pts restarts with the stream (restartable)
        mode = self.props["mode"]
        if mode:
            kind, _, sub = mode.partition(":")
            if kind not in ("custom", "custom-code", "custom-script"):
                raise ElementError(f"{self.name}: unknown converter mode {mode!r}")
            if sub.endswith(".py"):
                # reference dialect: mode=custom-script:<script.py>
                from ..converters.python3 import Python3Converter
                self._sub = Python3Converter(script=sub)
            else:
                # registry name (e.g. custom-script:python3 + env script)
                try:
                    cls = registry.get(registry.KIND_CONVERTER, sub)
                except KeyError:
                    raise ElementError(
                        f"{self.name}: unknown converter subplugin {sub!r}"
                    ) from None
                self._sub = cls() if isinstance(cls, type) else cls
            if hasattr(self._sub, "open"):
                self._sub.open()

    def stop(self):
        if self._sub is not None and hasattr(self._sub, "close"):
            self._sub.close()
        self._sub = None
        self._pending.clear()

    def _octet_spec(self) -> Optional[TensorSpec]:
        if not self.props["input-dim"]:
            return None
        dtype = dtype_from_name(self.props["input-type"] or "uint8")
        return TensorSpec(parse_dims_string(self.props["input-dim"]), dtype)

    def _media_tensor_spec(self, media) -> Optional[TensorSpec]:
        """Static tensor schema for a negotiated media payload (≙ the
        reference deriving other/tensors caps from video/audio/text caps,
        gsttensor_converter.c parse_caps :168)."""
        if media.mtype == "video":
            return TensorSpec(
                (media.height, media.width, media.pixel_channels),
                np.uint8, "video",
            )
        if media.mtype == "audio":
            if media.samples_per_buffer:
                return TensorSpec(
                    (media.samples_per_buffer, media.channels),
                    media.sample_dtype, "audio",
                )
            return None  # per-buffer framing resolved at runtime
        if media.mtype == "text":
            octet = self._octet_spec()
            if octet is None:
                raise ElementError(
                    f"{self.name}: text/x-raw needs input-dim= (fixed "
                    "bytes per frame, reference converter contract)"
                )
            if octet.dtype != np.uint8:
                # the reference pins text frames to uint8 bytes
                raise ElementError(
                    f"{self.name}: text/x-raw is uint8 only "
                    f"(got input-type={self.props['input-type']!r})"
                )
            return octet
        return self._octet_spec()  # octet: None until input-dim is set

    def derive_spec(self, pad=0):
        from ..media.caps import MediaSpec

        in_spec = self.sink_specs.get(0, ANY)
        if self._sub is not None and hasattr(self._sub, "get_out_spec"):
            return self._sub.get_out_spec(in_spec)
        if isinstance(in_spec, MediaSpec) and in_spec.media is not None:
            t = self._media_tensor_spec(in_spec.media)
            if t is None:
                return ANY
            fpt = self.props["frames-per-tensor"]
            fr = in_spec.media.framerate
            if fpt > 1 and not self.props["emit-blocks"]:
                # reference semantics: one shape-changed frame per group
                # (3:W:H:1 -> 3:W:H:N); emit-blocks keeps the per-frame
                # schema — a BatchFrame is a transport batch, not a shape
                t = t.with_batch(fpt)
                if fr is not None:
                    fr = fr / fpt
            return StreamSpec((t,), FORMAT_STATIC, fr)
        octet = self._octet_spec()
        if octet is not None:
            return StreamSpec((octet,), FORMAT_STATIC, in_spec.framerate)
        fpt = self.props["frames-per-tensor"]
        if self.props["emit-blocks"]:
            fpt = 1  # schema/framerate unchanged: blocks are transparent
        if in_spec.tensors:
            tensors = tuple(
                t.with_batch(fpt) if fpt > 1 else t for t in in_spec.tensors
            )
            fr = in_spec.framerate
            if fr is not None and fpt > 1:
                fr = fr / fpt
            return StreamSpec(tensors, FORMAT_STATIC, fr)
        return ANY

    # -- processing ---------------------------------------------------------
    def _convert_media(self, frame: TensorFrame, media) -> TensorFrame:
        """Frame a raw media payload into its tensor (reference per-type
        chains, gsttensor_converter.c:750-1005)."""
        buf = np.asarray(frame.tensors[0]).reshape(-1).view(np.uint8)
        if media.mtype == "video":
            h, stride, rb = media.height, media.stride, media.row_bytes
            if len(buf) != h * stride:
                raise ElementError(
                    f"{self.name}: video payload {len(buf)}B != "
                    f"height {h} x stride {stride}"
                )
            # stride removal (≙ the converter's per-row memcpy when
            # width%4 != 0) then pack to (H, W, C)
            img = buf.reshape(h, stride)[:, :rb].reshape(
                h, media.width, media.pixel_channels
            )
            return frame.with_tensors([img])
        if media.mtype == "audio":
            bpf = media.bytes_per_frame
            if len(buf) % bpf:
                raise ElementError(
                    f"{self.name}: audio payload {len(buf)}B not a "
                    f"multiple of frame size {bpf}B"
                )
            arr = buf.view(media.sample_dtype).reshape(-1, media.channels)
            return frame.with_tensors([arr])
        if media.mtype == "text":
            octet = self._octet_spec()
            if octet is None or octet.dtype != np.uint8:
                raise ElementError(
                    f"{self.name}: text/x-raw needs input-dim= "
                    "(uint8 only)"
                )
            size = octet.nbytes
            out = np.zeros(size, np.uint8)  # pad with NUL / truncate
            n = min(size, len(buf))
            out[:n] = buf[:n]
            return frame.with_tensors([out.reshape(octet.shape)])
        # octet: reshape per input-dim/input-type (reference :940-1005)
        octet = self._octet_spec()
        if octet is None:
            raise ElementError(
                f"{self.name}: octet payload needs input-dim=/input-type="
            )
        if len(buf) != octet.nbytes:
            raise ElementError(
                f"{self.name}: octet payload {len(buf)}B != schema "
                f"{octet.nbytes}B (set filesrc blocksize accordingly)"
            )
        return frame.with_tensors(
            [buf.view(octet.dtype).reshape(octet.shape)]
        )

    def _convert_one(self, frame: TensorFrame) -> TensorFrame:
        if self._sub is not None:
            out = self._sub.convert(frame)
            return out if isinstance(out, TensorFrame) else frame.with_tensors(out)
        media = frame.meta.get("media")
        if media is not None:
            out = self._convert_media(frame, media)
            out.meta = dict(out.meta)
            out.meta.pop("media", None)  # tensors now, not raw media
            return out
        octet = self._octet_spec()
        if octet is not None:
            raw = np.asarray(frame.tensors[0]).reshape(-1).view(np.uint8)
            arr = raw.view(octet.dtype).reshape(octet.shape)
            return frame.with_tensors([arr])
        tensors = []
        for t in frame.tensors:
            if isinstance(t, (bytes, bytearray, memoryview)):
                # flexible wire payload: self-describing header + data
                spec, off = unpack_flex_header(bytes(t))
                arr = np.frombuffer(t, dtype=spec.dtype, offset=off).reshape(spec.shape)
                tensors.append(arr)
            else:
                tensors.append(np.asarray(t))
        return frame.with_tensors(tensors)

    def handle_frame(self, pad, frame):
        orig = frame
        frame = self._convert_one(frame)
        if self.props["set-timestamp"] and frame.pts is None:
            # ≙ gsttensor_converter set-timestamp: stamp arrival-relative
            # running time on sources that don't timestamp (octet/appsrc).
            # Never mutate an aliased input in place (a custom subplugin
            # may return its input unchanged; tee siblings share it)
            if frame is orig:
                frame = frame.with_tensors(list(frame.tensors))
            if self._ts_base is None:
                self._ts_base = time.monotonic()
            frame.pts = time.monotonic() - self._ts_base
        fpt = self.props["frames-per-tensor"]
        if fpt <= 1:
            return [(0, frame)]
        self._pending.append(frame)
        if len(self._pending) < fpt:
            return []
        return self._emit_group()

    def _emit_group(self):
        group, self._pending = self._pending, []
        ntensors = len(group[0].tensors)
        stacked = [
            np.stack([np.asarray(f.tensors[i]) for f in group])
            for i in range(ntensors)
        ]
        first = group[0]
        if self.props["emit-blocks"]:
            # transparent batch: per-logical pts/meta survive; downstream
            # batch-capable elements consume, sinks/decoders split
            return [(0, BatchFrame.from_frames(stacked, group))]
        out = first.with_tensors(stacked)
        out.duration = sum(f.duration or 0.0 for f in group) or None
        return [(0, out)]

    def handle_eos(self, pad):
        if self.props["emit-blocks"] and self._pending:
            # a partial block changes no schema — emit it instead of
            # dropping (divergence from the reference's shape-changing
            # stacking, which must drop incomplete groups)
            return self._emit_group()
        # drop a partial trailing batch (reference drops incomplete frames)
        self._pending.clear()
        return []
