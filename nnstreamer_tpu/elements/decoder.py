"""tensor_decoder: tensor stream -> media/labels/boxes via decoder subplugins.

Reference: ``gst/nnstreamer/elements/gsttensor_decoder.c`` (mode prop + 9
option strings passed to the subplugin, ``nnstreamer_decoder_find`` :177) and
the decoder ABI ``GstTensorDecoderDef`` {init, exit, setOption, getOutCaps,
decode} (``nnstreamer_plugin_api_decoder.h:38-61``).

Decoder subplugins register under registry kind "decoder" with the contract:

    class MyDecoder:
        NAME = "my_mode"
        def set_options(self, options: list[str]) -> None: ...
        def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec: ...
        def decode(self, frame: TensorFrame, in_spec) -> TensorFrame: ...
"""

from __future__ import annotations


from ..core import registry
from ..core.buffer import BatchFrame
from ..core.types import ANY
from ..pipeline.element import ElementError, Property, TransformElement, element
from .. import decoders as _decoders  # noqa: F401 — registers decoder modes

_N_OPTIONS = 9  # reference carries option1..option9


@element("tensor_decoder")
class TensorDecoder(TransformElement):
    BATCH_AWARE = True  # splits blocks itself (or keeps them whole, fused)

    PROPERTIES = {
        "mode": Property(str, "", "decoder subplugin name"),
        **{
            f"option{i}": Property(str, "", f"mode-specific option {i}")
            for i in range(1, _N_OPTIONS + 1)
        },
        "max-buffers": Property(int, 0, "mailbox depth override"),
        "config-file": Property(
            str, "", "key=value file applied as properties (explicit "
            "pipeline-text properties win; ≙ gsttensor_decoder config-file)"
        ),
        "device-fused": Property(
            str, "auto",
            "auto = let the pipeline fold this decoder's device half "
            "(subplugin device_fn) into the upstream jax-xla filter's XLA "
            "program; never = always decode on host",
        ),
        "split-batches": Property(
            bool, True,
            "fan incoming BatchFrames out to per-frame decodes (false = "
            "decode the block vectorized and pass it downstream whole, "
            "when the subplugin implements decode_fused_batch)",
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._dec = None
        self._fused = False  # set by the pipeline's device-fusion pass

    # -- device fusion (pipeline pass) --------------------------------------
    @property
    def can_fuse_device(self) -> bool:
        if (
            self._dec is None
            or not hasattr(self._dec, "device_fn")
            or not hasattr(self._dec, "decode_fused")
            or self.props["device-fused"] == "never"
        ):
            return False
        # subplugins with per-configuration device support (e.g.
        # bounding_boxes: only some box modes are traceable) gate here
        supports = getattr(self._dec, "supports_device_fn", None)
        return supports() if callable(supports) else True

    def enable_fused(self) -> None:
        self._fused = True

    def start(self):
        self._apply_config_file()
        self._fused = False  # re-fused (or not) by the pass on every start
        mode = self.props["mode"]
        if not mode:
            raise ElementError(f"{self.name}: decoder requires mode=")
        try:
            cls = registry.get(registry.KIND_DECODER, mode)
        except KeyError:
            raise ElementError(f"{self.name}: unknown decoder mode {mode!r}") from None
        self._dec = cls() if isinstance(cls, type) else cls
        options = [self.props[f"option{i}"] for i in range(1, _N_OPTIONS + 1)]
        if hasattr(self._dec, "set_options"):
            self._dec.set_options(options)

    def stop(self):
        if self._dec is not None and hasattr(self._dec, "exit"):
            self._dec.exit()
        self._dec = None

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if self._dec is not None and hasattr(self._dec, "get_out_spec"):
            return self._dec.get_out_spec(in_spec)
        return ANY

    def transform(self, frame):
        assert self._dec is not None, f"{self.name} not started"
        if self._fused:
            return self._dec.decode_fused(frame, self.sink_specs.get(0, ANY))
        return self._dec.decode(frame, self.sink_specs.get(0, ANY))

    def handle_frame(self, pad, frame):
        # batch-through fast path: the upstream filter hands the whole
        # micro-batch as ONE device-resident BatchFrame; split() does the
        # single (tiny, post-device_fn) device->host transfer, then the
        # host finisher runs per logical frame.
        if isinstance(frame, BatchFrame):
            spec = self.sink_specs.get(0, ANY)
            if (
                self._fused
                and not self.props["split-batches"]
                and hasattr(self._dec, "decode_fused_batch")
            ):
                # vectorized host finish: the block stays whole (chip-rate
                # streams: the per-frame fan-out is itself a bottleneck)
                return [(0, self._dec.decode_fused_batch(frame, spec))]
            dec = self._dec.decode_fused if self._fused else self._dec.decode
            return [(0, dec(f, spec)) for f in frame.split()]
        return super().handle_frame(pad, frame)
