"""tensor_decoder: tensor stream -> media/labels/boxes via decoder subplugins.

Reference: ``gst/nnstreamer/elements/gsttensor_decoder.c`` (mode prop + 9
option strings passed to the subplugin, ``nnstreamer_decoder_find`` :177) and
the decoder ABI ``GstTensorDecoderDef`` {init, exit, setOption, getOutCaps,
decode} (``nnstreamer_plugin_api_decoder.h:38-61``).

Decoder subplugins register under registry kind "decoder" with the contract:

    class MyDecoder:
        NAME = "my_mode"
        def set_options(self, options: list[str]) -> None: ...
        def get_out_spec(self, in_spec: StreamSpec) -> StreamSpec: ...
        def decode(self, frame: TensorFrame, in_spec) -> TensorFrame: ...
"""

from __future__ import annotations

from typing import List, Optional

from ..core import registry
from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec
from ..pipeline.element import Element, ElementError, Property, TransformElement, element
from .. import decoders as _decoders  # noqa: F401 — registers decoder modes

_N_OPTIONS = 9  # reference carries option1..option9


@element("tensor_decoder")
class TensorDecoder(TransformElement):
    PROPERTIES = {
        "mode": Property(str, "", "decoder subplugin name"),
        **{
            f"option{i}": Property(str, "", f"mode-specific option {i}")
            for i in range(1, _N_OPTIONS + 1)
        },
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._dec = None

    def start(self):
        mode = self.props["mode"]
        if not mode:
            raise ElementError(f"{self.name}: decoder requires mode=")
        try:
            cls = registry.get(registry.KIND_DECODER, mode)
        except KeyError:
            raise ElementError(f"{self.name}: unknown decoder mode {mode!r}") from None
        self._dec = cls() if isinstance(cls, type) else cls
        options = [self.props[f"option{i}"] for i in range(1, _N_OPTIONS + 1)]
        if hasattr(self._dec, "set_options"):
            self._dec.set_options(options)

    def stop(self):
        if self._dec is not None and hasattr(self._dec, "exit"):
            self._dec.exit()
        self._dec = None

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if self._dec is not None and hasattr(self._dec, "get_out_spec"):
            return self._dec.get_out_spec(in_spec)
        return ANY

    def transform(self, frame):
        assert self._dec is not None, f"{self.name} not started"
        return self._dec.decode(frame, self.sink_specs.get(0, ANY))
