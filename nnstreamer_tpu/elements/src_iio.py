"""tensor_src_iio — Linux Industrial-I/O sensors as a tensor stream.

Reference: ``gst/nnstreamer/elements/gsttensor_srciio.c`` (2603 LoC):
enumerates ``/sys/bus/iio/devices`` for the named device (or device
number), parses ``scan_elements`` channel specs
(``[be|le]:[su]<bits>/<storage>[>><shift>]``), optionally sets
``sampling_frequency`` and the capture trigger, enables the buffer, reads
raw frames from the character device, applies per-channel scale/offset,
and pushes float32 tensors — merged into one ``(channels, samples)``
tensor (``merge-channels-data``, the reference default) or one
``(samples,)`` tensor per channel.

The sysfs/dev roots are properties so tests (and containers) can point at
a fake tree — the reference test suite does exactly this with a dummy
sysfs (``tests/nnstreamer_source/``).
"""

from __future__ import annotations

import os
import select
import time
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ..pipeline.element import (
    ElementError,
    Property,
    SourceElement,
    element,
)


class IIOChannel:
    """One scan_elements channel: name, index, and its packed-data spec."""

    def __init__(self, name: str, index: int, type_str: str,
                 scale: float = 1.0, offset: float = 0.0):
        self.name = name
        self.index = index
        self.scale = scale
        self.offset = offset
        # "le:s12/16>>4" — endian : signed bits / storage >> shift
        try:
            endian, rest = type_str.strip().split(":", 1)
            sign = rest[0]
            bits_s, _, shift_s = rest[1:].partition(">>")
            used_s, _, storage_s = bits_s.partition("/")
            self.endian = "<" if endian == "le" else ">"
            self.signed = sign == "s"
            self.bits = int(used_s)
            self.storage_bits = int(storage_s)
            self.shift = int(shift_s) if shift_s else 0
        except (ValueError, IndexError):
            raise ElementError(f"bad IIO channel type {type_str!r}") from None
        if self.storage_bits % 8 or self.storage_bits not in (8, 16, 32, 64):
            raise ElementError(f"unsupported storage bits {self.storage_bits}")

    @property
    def storage_bytes(self) -> int:
        return self.storage_bits // 8

    def decode(self, raw: np.ndarray) -> np.ndarray:
        """raw: uint array of storage width -> float32 applying
        shift/mask/sign/scale/offset (reference conversion order)."""
        v = raw.astype(np.uint64) >> np.uint64(self.shift)
        # align the used bits to the top, then shift back down: logical for
        # unsigned, arithmetic (via int64 view) for signed — masks AND
        # sign-extends any width up to 64 without Python-int overflow
        up = np.uint64(64 - self.bits)
        u = v << up
        if self.signed:
            val = u.view(np.int64) >> np.int64(up)
        else:
            val = u >> up
        return ((val.astype(np.float64) + self.offset) * self.scale).astype(
            np.float32
        )


def _read(path: str, default: Optional[str] = None) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return default


def _write(path: str, value: str) -> bool:
    try:
        with open(path, "w") as f:
            f.write(value)
        return True
    except OSError:
        return False


@element("tensor_src_iio")
class TensorSrcIIO(SourceElement):
    PROPERTIES = {
        "mode": Property(str, "continuous", "continuous | one-shot"),
        "device": Property(str, "", "IIO device name"),
        "device-number": Property(int, -1, "IIO device number (alternative)"),
        "trigger": Property(str, "", "trigger name to attach (optional)"),
        "trigger-number": Property(
            int, -1, "trigger by index: attaches 'trigger<N>' (≙ reference "
            "trigger-number; -1 = unset)"
        ),
        "silent": Property(bool, True, "suppress per-buffer logs"),
        "channels": Property(str, "auto", "auto | all | comma list of names"),
        "buffer-capacity": Property(int, 1, "samples per output frame"),
        "frequency": Property(int, 0, "sampling frequency to set (0 = keep)"),
        "merge-channels-data": Property(
            bool, True, "one (channels, samples) tensor vs per-channel tensors"
        ),
        "poll-timeout": Property(int, 10000, "read timeout, ms"),
        "num-buffers": Property(int, -1, "stop after N frames (-1 = forever)"),
        "iio-base-dir": Property(str, "/sys/bus/iio/devices", "sysfs root"),
        "dev-dir": Property(str, "/dev", "character-device root"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._device_dir: Optional[str] = None
        self._dev_path: Optional[str] = None
        self._chans: List[IIOChannel] = []
        self._frame_bytes = 0

    # -- bring-up -----------------------------------------------------------
    def _find_device(self) -> Tuple[str, str]:
        base = self.props["iio-base-dir"]
        want_name = self.props["device"]
        want_num = self.props["device-number"]
        if not os.path.isdir(base):
            raise ElementError(f"{self.name}: no IIO sysfs at {base}")
        for entry in sorted(os.listdir(base)):
            if not entry.startswith("iio:device"):
                continue
            num = int(entry[len("iio:device"):])
            d = os.path.join(base, entry)
            devname = _read(os.path.join(d, "name"), "")
            if (want_name and devname == want_name) or (
                not want_name and want_num >= 0 and num == want_num
            ):
                return d, entry
        raise ElementError(
            f"{self.name}: IIO device not found "
            f"(device={want_name!r} number={want_num})"
        )

    def _scan_channels(self, device_dir: str) -> List[IIOChannel]:
        scan = os.path.join(device_dir, "scan_elements")
        if not os.path.isdir(scan):
            raise ElementError(f"{self.name}: {scan} missing (unbuffered device)")
        sel = self.props["channels"]
        explicit = (
            {c.strip() for c in sel.split(",") if c.strip()}
            if sel not in ("auto", "all")
            else None
        )
        chans: List[IIOChannel] = []
        for fn in sorted(os.listdir(scan)):
            if not fn.endswith("_en"):
                continue
            cname = fn[:-3]
            enabled = _read(os.path.join(scan, fn), "0") == "1"
            if explicit is not None:
                want = cname in explicit
            elif sel == "all":
                want = True
            else:  # auto: keep the driver's current enables
                want = enabled
            if not want:
                # a stale enabled channel would corrupt the scan layout the
                # kernel emits vs the one we compute — failing to disable it
                # is fatal, same as failing to enable a wanted one
                if enabled and not _write(os.path.join(scan, fn), "0"):
                    raise ElementError(
                        f"{self.name}: cannot disable channel {cname}"
                    )
                continue
            if not enabled and not _write(os.path.join(scan, fn), "1"):
                raise ElementError(f"{self.name}: cannot enable channel {cname}")
            idx = int(_read(os.path.join(scan, f"{cname}_index"), "0") or 0)
            tstr = _read(os.path.join(scan, f"{cname}_type"))
            if tstr is None:
                raise ElementError(f"{self.name}: {cname}_type missing")
            scale = self._chan_attr(device_dir, cname, "scale", 1.0)
            offset = self._chan_attr(device_dir, cname, "offset", 0.0)
            chans.append(IIOChannel(cname, idx, tstr, scale, offset))
        if not chans:
            raise ElementError(f"{self.name}: no enabled IIO channels")
        chans.sort(key=lambda c: c.index)
        return chans

    @staticmethod
    def _chan_attr(device_dir: str, cname: str, attr: str,
                   default: float) -> float:
        """Per-channel attr with the IIO shared-attr fallback: many drivers
        expose one ``in_<type>_scale`` for all components instead of
        ``in_<type>_<comp>_scale`` (the reference falls back the same way)."""
        v = _read(os.path.join(device_dir, f"{cname}_{attr}"))
        if v is None and "_" in cname:
            shared = cname.rsplit("_", 1)[0]
            v = _read(os.path.join(device_dir, f"{shared}_{attr}"))
        try:
            return float(v) if v is not None else default
        except ValueError:
            return default

    def _resolve_trigger(self) -> str:
        """Trigger NAME to write into current_trigger: the `trigger` prop
        verbatim, or — with `trigger-number` — trigger<N>'s sysfs `name`
        file (current_trigger wants the name, not the directory; the dir
        name is the fallback for nameless triggers)."""
        trig = self.props["trigger"]
        if trig or self.props["trigger-number"] < 0:
            return trig
        n = self.props["trigger-number"]
        return _read(
            os.path.join(self.props["iio-base-dir"], f"trigger{n}", "name")
        ) or f"trigger{n}"

    def start(self) -> None:
        self._device_dir, entry = self._find_device()
        self._chans = self._scan_channels(self._device_dir)
        freq = self.props["frequency"]
        if freq > 0:
            _write(os.path.join(self._device_dir, "sampling_frequency"),
                   str(freq))
        trig = self._resolve_trigger()
        if trig:
            if not _write(
                os.path.join(self._device_dir, "trigger", "current_trigger"),
                trig,
            ):
                raise ElementError(f"{self.name}: cannot set trigger {trig!r}")
        # buffered capture on
        _write(os.path.join(self._device_dir, "buffer", "length"),
               str(max(2 * self.props["buffer-capacity"], 2)))
        if not _write(os.path.join(self._device_dir, "buffer", "enable"), "1"):
            raise ElementError(
                f"{self.name}: cannot enable IIO buffer (missing trigger?)"
            )
        self._dev_path = os.path.join(self.props["dev-dir"], entry)
        # kernel scan-record layout (iio_compute_scan_bytes): each element
        # naturally aligned to its own storage size, no trailing pad
        offs: List[int] = []
        pos = 0
        for c in self._chans:
            sb = c.storage_bytes
            pos = (pos + sb - 1) // sb * sb
            offs.append(pos)
            pos += sb
        self._frame_bytes = pos
        self._scan_dtype = np.dtype({
            "names": [c.name for c in self._chans],
            "formats": [
                f"{c.endian}u{c.storage_bytes}" for c in self._chans
            ],
            "offsets": offs,
            "itemsize": self._frame_bytes,
        })

    def stop(self) -> None:
        if self._device_dir:
            _write(os.path.join(self._device_dir, "buffer", "enable"), "0")
        self._device_dir = None

    # -- schema -------------------------------------------------------------
    def output_spec(self) -> StreamSpec:
        cap = self.props["buffer-capacity"]
        if self.props["merge-channels-data"]:
            specs = (
                TensorSpec((len(self._chans), cap), np.float32, "iio"),
            )
        else:
            specs = tuple(
                TensorSpec((cap,), np.float32, c.name) for c in self._chans
            )
        return StreamSpec(specs, FORMAT_STATIC)

    # -- capture ------------------------------------------------------------
    def _read_exact(self, fd: int, nbytes: int) -> Optional[bytes]:
        """Non-blocking read with a real poll-timeout (a blocking chardev
        read would never honor the deadline); None on timeout."""
        deadline = time.monotonic() + self.props["poll-timeout"] / 1000.0
        buf = b""
        while len(buf) < nbytes:
            remain = deadline - time.monotonic()
            if remain <= 0:
                return None
            r, _, _ = select.select([fd], [], [], min(remain, 0.5))
            if not r:
                continue
            try:
                chunk = os.read(fd, nbytes - len(buf))
            except BlockingIOError:
                continue
            if chunk:
                buf += chunk
            else:
                # EOF on a regular file (fake sysfs): no more data will come
                time.sleep(0.01)
        return buf

    def frames(self) -> Iterator[TensorFrame]:
        cap = self.props["buffer-capacity"]
        merge = self.props["merge-channels-data"]
        limit = self.props["num-buffers"]
        count = 0
        t0 = time.monotonic()
        fd = os.open(self._dev_path, os.O_RDONLY | os.O_NONBLOCK)
        try:
            while limit < 0 or count < limit:
                raw = self._read_exact(fd, self._frame_bytes * cap)
                if raw is None:
                    if not self.props["silent"]:
                        self.log.info("IIO read timeout/EOF; ending stream")
                    return
                rec = np.frombuffer(raw, dtype=self._scan_dtype)
                cols = [
                    c.decode(rec[c.name].astype(np.uint64))
                    for c in self._chans
                ]
                pts = time.monotonic() - t0
                tensors = [np.stack(cols)] if merge else cols
                count += 1
                yield TensorFrame(tensors, pts=pts)
                if self.props["mode"] == "one-shot":
                    return
        finally:
            os.close(fd)
