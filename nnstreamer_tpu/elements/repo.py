"""tensor_repo: out-of-band circular streams (loops without graph cycles).

Reference: ``gsttensor_repo.c`` (process-global slot table) +
``gsttensor_reposink.c`` / ``gsttensor_reposrc.c`` — a reposink publishes
frames into a numbered slot; a reposrc replays them as a source.  This is
how the reference builds recurrent pipelines (tests/nnstreamer_repo_rnn /
_lstm carry hidden state through a repo loop).
"""

from __future__ import annotations

import queue as _queue
import threading
from typing import Dict, Iterator, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec
from ..pipeline.element import Element, Property, SinkElement, SourceElement, element

_lock = threading.Lock()
_slots: Dict[int, "_Slot"] = {}


class _Slot:
    def __init__(self):
        self.q: "_queue.Queue[Optional[TensorFrame]]" = _queue.Queue()
        self.eos = threading.Event()


def _get_slot(index: int) -> _Slot:
    with _lock:
        if index not in _slots:
            _slots[index] = _Slot()
        return _slots[index]


def reset_repo() -> None:
    """Clear all slots (test isolation)."""
    with _lock:
        _slots.clear()


@element("tensor_reposink")
class TensorRepoSink(SinkElement):
    PROPERTIES = {
        "slot-index": Property(int, 0, "repo slot number"),
        "signal-rate": Property(int, 0, "reference parity (unused)"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def render(self, frame):
        _get_slot(self.props["slot-index"]).q.put(frame)

    def handle_eos(self, pad):
        slot = _get_slot(self.props["slot-index"])
        slot.eos.set()
        slot.q.put(None)
        return []


@element("tensor_reposrc")
class TensorRepoSrc(SourceElement):
    PROPERTIES = {
        "slot-index": Property(int, 0, "repo slot number"),
        "caps": Property(str, "", "announced schema (loops can't negotiate)"),
    }

    def output_spec(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def frames(self) -> Iterator[TensorFrame]:
        slot = _get_slot(self.props["slot-index"])
        while True:
            try:
                item = slot.q.get(timeout=0.1)
            except _queue.Empty:
                from ..core.lifecycle import pipeline_quiescing

                if pipeline_quiescing(self):
                    return
                if slot.eos.is_set():
                    return
                continue
            if item is None:
                return
            yield item
