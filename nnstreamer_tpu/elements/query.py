"""tensor_query_* elements: among-device inference offload.

Reference (SURVEY §3.5): ``tensor_query_client`` sends frames to a remote
server pipeline and awaits answers (async queue + timeout,
``tensor_query_client.c:657-699``); ``tensor_query_serversrc`` is the server
pipeline's entry (``tensor_query_serversrc.c:67-365``);
``tensor_query_serversink`` returns answers to the right client via
``client_id`` meta (``tensor_query_serversink.c:237-274``); a global
registry pairs src/sink by id (``tensor_query_server.c``).

TPU deltas: transport is gRPC (see distributed/service.py); the client adds
**pipelined in-flight requests with ordered delivery** (``max-in-flight``)
and **multi-host round-robin fan-out** (``hosts=h1:p1,h2:p2``) — the
mechanism that addresses a TPU pod slice as one logical filter (BASELINE
north star: linear 1->8 chip scaling).
"""

from __future__ import annotations

import queue as _queue
from collections import deque
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Deque, Iterator, List, Optional, Tuple

from ..core.buffer import BatchFrame, CustomEvent, TensorFrame
from ..core.types import ANY, StreamSpec
from ..distributed.service import (
    QueryConnection,
    get_query_server,
    release_query_server,
)
from ..pipeline.element import (
    Element,
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    element,
)


@element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    PROPERTIES = {
        "port": Property(int, 0, "listen port (0 = ephemeral)"),
        "host": Property(str, "[::]", "bind address"),
        "id": Property(int, 0, "pairs this src with the serversink of same id"),
        "connect-type": Property(
            str, "grpc",
            "transport: grpc (interop default) | tcp (zero-copy raw TCP, "
            "≙ reference nns-edge TCP)"),
        "caps": Property(str, "", "announced input schema for the handshake"),
        # hybrid discovery (≙ reference connect-type=HYBRID: MQTT control
        # plane + direct data plane): announce this server's endpoint as a
        # RETAINED message on nns/query/<topic>/<instance> so clients
        # resolve servers from the broker instead of static host:port
        "topic": Property(str, "", "announce endpoint under this topic"),
        "dest-host": Property(str, "localhost", "MQTT broker host (discovery)"),
        "dest-port": Property(
            int, 0, "MQTT broker port (0 = announcing disabled)"
        ),
        "block-ingress": Property(
            bool, False,
            "inject each wire micro-batch as ONE BatchFrame so the server "
            "pipeline pays per-frame costs once per batch (the answers "
            "split back per client in the serversink)",
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._core = None
        self._announcement = None

    def start(self):
        self._core = get_query_server(self.props["id"], self.props["port"])
        if self.props["caps"]:
            self._core.caps = self.props["caps"]
        self._core.block_ingress = bool(self.props["block-ingress"])
        ct = self.props["connect-type"]
        if ct == "tcp":
            self._core.start_tcp()
        elif ct == "grpc":
            self._core.start()
        else:
            raise ElementError(
                f"{self.name}: connect-type={ct!r} (want grpc|tcp)")
        # expose the actually-bound port (ephemeral binds)
        self.props["port"] = self._core.port
        if self.props["topic"] and self.props["dest-port"] > 0:
            try:
                self._announce()
            except Exception:
                # pipeline rollback only stops elements whose start()
                # SUCCEEDED: release the acquired core ourselves or the
                # listener/refcount leaks for the process lifetime
                self.stop()
                raise

    def _announce(self) -> None:
        """Retained per-instance endpoint announce on the MQTT control
        plane (shared machinery: distributed/hybrid.py)."""
        import os as _os
        import uuid as _uuid

        from ..distributed.hybrid import Announcement

        host = self.props["host"]
        if host in ("[::]", "0.0.0.0", ""):
            # a bind-all address is not dialable; announce loopback and
            # let multi-host deployments set host= to a reachable address
            host = "127.0.0.1"
        # instance id must be unique across the POD, not just this
        # process: element names repeat (every pipeline calls its entry
        # "src"), so pid+uuid disambiguates both in- and cross-process
        self._announcement = Announcement(
            self.props["dest-host"], self.props["dest-port"],
            f"nns/query/{self.props['topic']}/"
            f"{self.name}-{_os.getpid()}-{_uuid.uuid4().hex[:8]}",
            {
                "host": host, "port": self._core.port,
                "connect_type": self.props["connect-type"],
            },
            logger=self.log,
        )

    def stop(self):
        if self._announcement is not None:
            self._announcement.clear()
            self._announcement = None
        if self._core is not None:
            release_query_server(self.props["id"])
            self._core = None

    def output_spec(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def frames(self) -> Iterator[TensorFrame]:
        while True:
            try:
                client_id, frame = self._core.ingress.get(timeout=0.1)
            except _queue.Empty:
                if self._pipeline is not None and self._pipeline._stop_flag.is_set():
                    return
                continue
            # client_id meta was attached by the Invoke handler; just emit
            yield frame


@element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    BATCH_AWARE = True  # splits block answers per client RPC

    PROPERTIES = {
        "id": Property(int, 0, "pairs with the serversrc of the same id"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # ≙ tensor_query_serversink.c `limit`: bound per-client queued
        # answers; excess answers are dropped with a warning
        "limit": Property(int, 0, "max queued answers per client (0 = unbounded)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._core = None

    def start(self):
        self._core = get_query_server(self.props["id"])

    def stop(self):
        if self._core is not None:
            release_query_server(self.props["id"])
            self._core = None

    def render(self, frame):
        if isinstance(frame, BatchFrame):
            # block-ingress answers: resolve each logical frame (client_id
            # rides in the per-frame meta captured at injection)
            for f in frame.split():
                self.render(f)
            return
        client_id = frame.meta.get("client_id")
        if client_id is None:
            raise ElementError(
                f"{self.name}: frame lacks client_id meta (did it pass through "
                "an element that drops meta?)"
            )
        self._core.resolve(
            int(client_id), frame, limit=self.props["limit"]
        )


@element("tensor_query_client")
class TensorQueryClient(Element):
    """Looks like a local filter; actually round-trips frames through remote
    server pipeline(s) with pipelined, order-preserving dispatch."""

    BATCH_AWARE = True  # maps blocks onto the wire micro-batch envelope

    PROPERTIES = {
        "host": Property(str, "localhost", "server host"),
        "port": Property(int, 0, "server port"),
        "hosts": Property(str, "", "multi-server fan-out 'h1:p1,h2:p2' (round-robin)"),
        # hybrid discovery (≙ reference connect-type=HYBRID): resolve the
        # server set from retained announces on nns/query/<topic>/# at the
        # MQTT broker, instead of static host/hosts — pod membership then
        # changes on the broker, not in every client's pipeline text
        "topic": Property(str, "", "discover servers under this topic"),
        "dest-host": Property(str, "localhost", "MQTT broker host (discovery)"),
        "dest-port": Property(
            int, 0, "MQTT broker port (0 = discovery disabled)"
        ),
        "discovery-timeout": Property(
            float, 5.0, "s to wait for at least one announced server"
        ),
        "timeout": Property(float, 10.0, "per-request timeout, seconds"),
        "max-in-flight": Property(int, 8, "pipelined outstanding requests"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # elastic recovery (SURVEY §5.3: preemptible workers need client-side
        # retry/requeue — net-new vs the reference's single timeout)
        # default 0: retries>0 makes delivery at-least-once (a request that
        # timed out client-side but succeeded server-side is re-sent,
        # possibly to another server) — opt in only for idempotent server
        # pipelines; 0 matches the reference's single-timeout semantics
        "retries": Property(int, 0, "re-send attempts per request (0 = none; >0 = at-least-once delivery)"),
        # wire micro-batching (TPU-first, no reference analog): drain
        # whatever frames are ALREADY queued (no added latency) and ship
        # up to N of them in ONE RPC — amortizes the per-RPC transport
        # cost exactly like the filter's batched XLA invoke amortizes
        # dispatch.  1 = per-frame RPCs (reference parity).
        "wire-batch": Property(int, 1, "max frames per RPC (1 = no batching)"),
        "stream": Property(
            bool, False,
            "server-streaming invoke (gRPC): answer frames are emitted as "
            "the remote pipeline produces them until a final-flagged one "
            "arrives — remote streaming generation; incompatible with "
            "wire-batch > 1 and connect-type=tcp",
        ),
        "connect-type": Property(
            str, "grpc",
            "transport: grpc (interop default) | tcp (zero-copy raw TCP "
            "with sendmsg gather-writes and a per-client socket pool)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._conns: List[QueryConnection] = []
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight: Deque[Future] = deque()
        self._rr = 0
        # health tracking: conn index -> monotonic time until which it is
        # considered down (skipped by round-robin; retried after cooldown)
        self._down_until: dict = {}

    def _discover_targets(self) -> List[Tuple[str, int]]:
        """Resolve the server set from retained announces under
        nns/query/<topic>/# (shared machinery: distributed/hybrid.py),
        transport-filtered, deduplicated, and liveness-probed — a crashed
        server never tombstones its announce, so stale endpoints are
        dropped here instead of failing the whole client at handshake."""
        from ..distributed.hybrid import discover_endpoints, probe_endpoint

        want_ct = self.props["connect-type"]

        def validate(topic: str, info: dict) -> bool:
            got_ct = info.get("connect_type", want_ct)
            if got_ct != want_ct:
                self.log.warning(
                    "announce %s speaks %s, client wants %s (skipped)",
                    topic, got_ct, want_ct,
                )
                return False
            return True

        found = discover_endpoints(
            self.props["dest-host"], self.props["dest-port"],
            f"nns/query/{self.props['topic']}/#",
            timeout_s=self.props["discovery-timeout"],
            validate=validate, logger=self.log,
        )
        # probe CONCURRENTLY: N stale announces must cost one probe
        # timeout total, not N serial timeouts on the client's start path
        candidates = sorted(set(found.values()))
        with ThreadPoolExecutor(max_workers=max(1, len(candidates))) as ex:
            alive = list(ex.map(
                lambda hp: probe_endpoint(*hp), candidates
            ))
        targets = []
        for (host, port), ok in zip(candidates, alive):
            if ok:
                targets.append((host, port))
            else:
                self.log.warning(
                    "announced endpoint %s:%d not accepting (stale "
                    "announce from a crashed server?) — skipped",
                    host, port,
                )
        if not targets:
            raise ElementError(
                f"{self.name}: no live server announced on topic "
                f"{self.props['topic']!r} within "
                f"{self.props['discovery-timeout']}s"
            )
        return targets

    def start(self):
        ct = self.props["connect-type"]
        if ct not in ("grpc", "tcp"):
            # validate BEFORE discovery: a typo'd connect-type must fail
            # with this message, not filter every announce and surface as
            # a misleading discovery timeout
            raise ElementError(
                f"{self.name}: connect-type={ct!r} (want grpc|tcp)")
        targets: List[Tuple[str, int]] = []
        if self.props["topic"] and self.props["dest-port"] > 0:
            targets = self._discover_targets()
        elif self.props["hosts"]:
            for part in self.props["hosts"].split(","):
                part = part.strip()
                if not part:
                    continue
                h, sep, p = part.rpartition(":")
                if not sep or not h or not p.isdigit():
                    raise ElementError(
                        f"{self.name}: bad hosts entry {part!r} (want host:port)"
                    )
                targets.append((h, int(p)))
        else:
            targets.append((self.props["host"], self.props["port"]))
        if not targets or any(p == 0 for _, p in targets):
            raise ElementError(f"{self.name}: query client needs host/port")
        ct = self.props["connect-type"]
        if self.props["stream"]:
            if ct != "grpc":
                raise ElementError(
                    f"{self.name}: stream=true needs connect-type=grpc "
                    "(server-streaming RPC)"
                )
            if int(self.props["wire-batch"]) > 1:
                raise ElementError(
                    f"{self.name}: stream=true is per-request; "
                    "wire-batch must be 1"
                )
        if ct == "tcp":
            from ..distributed.tcp_query import TcpQueryConnection

            self._conns = [
                TcpQueryConnection(
                    h, p, self.props["timeout"],
                    nconns=max(1, int(self.props["max-in-flight"])),
                ) for h, p in targets
            ]
        elif ct == "grpc":
            self._conns = [
                QueryConnection(h, p, self.props["timeout"])
                for h, p in targets
            ]
        else:
            raise ElementError(
                f"{self.name}: connect-type={ct!r} (want grpc|tcp)")
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, self.props["max-in-flight"])
        )

    def stop(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        for c in self._conns:
            c.close()
        self._conns = []
        self._inflight.clear()

    # caps handshake at negotiation time (≙ edge CAPS event exchange)
    def accept_spec(self, pad, spec):
        if spec.tensors and self._conns:
            failures = []
            for conn in self._conns:
                try:
                    conn.handshake(spec.to_string())
                except Exception as e:  # noqa: BLE001 — transport boundary
                    failures.append((conn.addr, e))
            can_failover = self.props["retries"] > 0 and len(self._conns) > 1
            if failures and (len(failures) == len(self._conns) or not can_failover):
                addr, e = failures[0]
                raise ElementError(
                    f"{self.name}: caps handshake with {addr} failed: {e}"
                ) from None
            for addr, e in failures:
                # a down server is tolerable when others answered AND requests
                # can fail over (elastic recovery); it may also come back later
                self.log.warning("caps handshake with %s failed: %s", addr, e)
        return spec

    def derive_spec(self, pad=0):
        return ANY  # the server decides the answer schema

    def _drain_ready(self, block_all: bool):
        out = []
        while self._inflight:
            fut = self._inflight[0]
            if not block_all and not fut.done():
                break
            self._inflight.popleft()
            got = fut.result()  # raises on RPC error -> bus
            if isinstance(got, list):  # wire-batched request
                out.extend((0, f) for f in got)
            else:
                out.append((0, got))
        return out

    def _healthy_order(self, first: int) -> List[int]:
        """Conn indices starting at `first`, known-down ones (cooldown not
        expired) pushed to the back so a hung server doesn't eat a full
        timeout per frame."""
        import time

        now = time.monotonic()
        order = [(first + k) % len(self._conns) for k in range(len(self._conns))]
        healthy = [i for i in order if self._down_until.get(i, 0) <= now]
        return healthy + [i for i in order if i not in healthy]

    def _invoke_failover(self, frame, first: int):
        """One request: try the assigned (healthy-first) server, fail over
        round-robin to the others, `retries` extra attempts total.
        ``frame`` may be a list (wire micro-batch) -> list comes back."""
        import time

        attempts = 1 + max(0, self.props["retries"])
        timeout = self.props["timeout"]
        order = self._healthy_order(first)
        err: Optional[BaseException] = None
        for k in range(attempts):
            i = order[k % len(order)]
            conn = self._conns[i]
            try:
                if isinstance(frame, list):
                    result = conn.invoke_batch(frame, timeout)
                else:
                    result = conn.invoke(frame, timeout)
                self._down_until.pop(i, None)
                return result
            except Exception as e:  # noqa: BLE001 — transport boundary
                err = e
                self._down_until[i] = time.monotonic() + timeout
                self.log.warning(
                    "query to %s failed (attempt %d/%d): %s",
                    conn.addr, k + 1, attempts, e,
                )
        raise err  # all attempts failed -> surfaced on the bus

    _DRAIN_EVENT = "_nns_query_drain"

    def _notify_done(self, _fut) -> None:
        """Future-completion callback (pool thread): wake the worker so a
        LIVE stream emits answers as they land — without this, responses
        to the last frames of a burst sit in the in-flight window until
        the next frame or EOS arrives (latency bug for sparse streams).
        Best-effort: a full mailbox means the worker is busy and will
        drain on its next frame anyway."""
        box = self._mailbox
        if box is None:
            return  # stopping
        try:
            box.put_nowait((0, CustomEvent(self._DRAIN_EVENT, {})))
        except _queue.Full:
            pass

    def handle_event(self, pad, ev):
        if isinstance(ev, CustomEvent) and ev.name == self._DRAIN_EVENT:
            return self._drain_ready(block_all=False)  # swallow the tick
        return super().handle_event(pad, ev)

    def handle_frame(self, pad, frame):
        # one shared path: blocks flatten onto the wire micro-batch envelope
        return self.handle_frame_batch(pad, [frame])

    # scheduler micro-batch hooks: with wire-batch > 1 the pipeline drains
    # already-queued frames into handle_frame_batch (batch_wait_s = 0 so
    # batching never ADDS latency — a lone frame still ships immediately)
    @property
    def preferred_batch(self) -> int:
        return max(1, int(self.props["wire-batch"]))

    batch_wait_s = 0.0

    def handle_frame_batch(self, pad, frames):
        if any(isinstance(f, BatchFrame) for f in frames):
            logical: List[TensorFrame] = []
            for f in frames:
                logical.extend(f.split() if isinstance(f, BatchFrame) else [f])
            frames = logical
        if self.props["stream"]:
            # sequential per-request streams: chunk frames of request j
            # leave BEFORE request j+1 is sent (the scheduler pushes each
            # yielded frame immediately)
            def streams():
                for f in frames:
                    yield from self._stream_invoke(f)

            return streams()
        if len(frames) == 1:
            return self._dispatch(frames[0])
        return self._dispatch(list(frames))

    def _stream_invoke(self, frame):
        """One server-streaming request: healthy-first server order, whole
        streams fail over only BEFORE the first answer arrives (a stream
        broken mid-way surfaces as an error — replaying half a generation
        could duplicate tokens at the consumer)."""
        import time as _time

        order = self._healthy_order(self._rr % len(self._conns))
        self._rr += 1
        # retries=0 means SINGLE attempt: a request the server may already
        # have ingested must not be silently re-executed elsewhere unless
        # the user opted into at-least-once via retries>0 (same contract
        # as _invoke_failover)
        attempts = min(len(order), 1 + max(0, self.props["retries"]))
        timeout = self.props["timeout"]
        err: Optional[BaseException] = None
        for i in order[:attempts]:
            conn = self._conns[i]
            started = False
            try:
                for ans in conn.invoke_stream(frame, timeout):
                    started = True
                    self._down_until.pop(i, None)
                    yield (0, ans)
                return
            except Exception as e:  # noqa: BLE001 — transport boundary
                if started:
                    raise  # mid-stream break: no safe replay
                err = e
                # short cooldown: the stream timeout is minutes-scale (a
                # whole generation), not a health signal
                self._down_until[i] = _time.monotonic() + min(
                    float(timeout), 10.0
                )
                self.log.warning(
                    "stream to %s failed before first answer: %s",
                    conn.addr, e,
                )
        raise err if err is not None else RuntimeError("no servers")

    def _dispatch(self, frame_or_batch):
        first = self._rr % len(self._conns)
        self._rr += 1
        fut = self._pool.submit(self._invoke_failover, frame_or_batch, first)
        fut.add_done_callback(self._notify_done)
        self._inflight.append(fut)
        # backpressure: block on the oldest request once the in-flight window
        # is full, then release whatever is complete (in order)
        if len(self._inflight) >= max(1, self.props["max-in-flight"]):
            self._inflight[0].result()
        return self._drain_ready(block_all=False)

    def handle_eos(self, pad):
        return self._drain_ready(block_all=True)
