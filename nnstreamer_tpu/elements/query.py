"""tensor_query_* elements: among-device inference offload.

Reference (SURVEY §3.5): ``tensor_query_client`` sends frames to a remote
server pipeline and awaits answers (async queue + timeout,
``tensor_query_client.c:657-699``); ``tensor_query_serversrc`` is the server
pipeline's entry (``tensor_query_serversrc.c:67-365``);
``tensor_query_serversink`` returns answers to the right client via
``client_id`` meta (``tensor_query_serversink.c:237-274``); a global
registry pairs src/sink by id (``tensor_query_server.c``).

TPU deltas: transport is gRPC (see distributed/service.py); the client adds
**pipelined in-flight requests with ordered delivery** (``max-in-flight``)
and **multi-host round-robin fan-out** (``hosts=h1:p1,h2:p2``) — the
mechanism that addresses a TPU pod slice as one logical filter (BASELINE
north star: linear 1->8 chip scaling).
"""

from __future__ import annotations

import queue as _queue
import threading
from collections import OrderedDict, deque
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Deque, Iterator, List, Optional, Tuple

from ..core.buffer import BatchFrame, CustomEvent, TensorFrame
from ..core.lifecycle import ServerGoawayError
from ..core.liveness import (
    DEADLINE_META,
    PRIORITY_MAX,
    PRIORITY_META,
    TENANT_META,
    ServerBusyError,
    TenantAdmissionController,
    deadline_remaining,
    parse_tenant_quotas,
)
from ..core.continuity import prefix_route_key
from ..core.routing import (
    TIER_DEGRADED,
    TIER_DOWN,
    TIER_DRAINING,
    TIER_OK,
    ewma_scores,
    order_remotes,
    rendezvous_owner,
)
from ..core.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    RemoteApplicationError,
    RetryPolicy,
    is_remote_application_error,
)
from ..core.telemetry import (
    SPAN_META,
    SRV_SPAN_META,
    TL_ENQ_META,
    TRACE_ID_META,
    new_trace_id,
)
from ..distributed.wire import WireError
from ..core.types import ANY, StreamSpec
from ..distributed.service import (
    QueryConnection,
    get_query_server,
    release_query_server,
)
from ..pipeline.element import (
    Element,
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    element,
    enum_prop_check,
)


@element("tensor_query_serversrc")
class TensorQueryServerSrc(SourceElement):
    #: keep a thread boundary below this source: admission control's
    #: in-flight window only fills when request pull and server-pipeline
    #: processing overlap — fusing them would serialize the two and make
    #: max-inflight unreachable
    FUSE_DOWNSTREAM = False
    #: pipeline drain (core/lifecycle.py): this source runs its own
    #: serving -> draining -> stopped state machine inside frames() —
    #: the scheduler must NOT cut the pull loop at the drain flag, or
    #: requests admitted before the drain would never reach the pipeline
    OWNS_DRAIN = True

    PROPERTIES = {
        "port": Property(int, 0, "listen port (0 = ephemeral)"),
        "host": Property(str, "[::]", "bind address"),
        "id": Property(int, 0, "pairs this src with the serversink of same id"),
        "connect-type": Property(
            str, "grpc",
            "transport: grpc (interop default) | tcp (zero-copy raw TCP, "
            "≙ reference nns-edge TCP)"),
        "caps": Property(str, "", "announced input schema for the handshake"),
        # hybrid discovery (≙ reference connect-type=HYBRID: MQTT control
        # plane + direct data plane): announce this server's endpoint as a
        # RETAINED message on nns/query/<topic>/<instance> so clients
        # resolve servers from the broker instead of static host:port
        "topic": Property(str, "", "announce endpoint under this topic"),
        "dest-host": Property(str, "localhost", "MQTT broker host (discovery)"),
        "dest-port": Property(
            int, 0, "MQTT broker port (0 = announcing disabled)"
        ),
        # control-plane resilience: ordered standby brokers the announce
        # client fails over to when the primary dies; on every
        # (re)connect the retained announce + current digest re-publish,
        # so a restarted or failed-over broker reconverges within one
        # digest interval
        "dest-brokers": Property(
            str, "", "failover broker list 'host:port,host:port' tried "
            "in order after dest-host:dest-port (empty = primary only)"),
        "block-ingress": Property(
            bool, False,
            "inject each wire micro-batch as ONE BatchFrame so the server "
            "pipeline pays per-frame costs once per batch (the answers "
            "split back per client in the serversink)",
        ),
        # overload admission control (core/liveness.py): refuse work at
        # the door with a BUSY reply instead of timing out deep in the
        # stack once the pipeline is saturated
        "max-inflight": Property(
            int, 0, "admission high watermark: concurrent requests "
            "admitted before the server sheds with BUSY (0 = unlimited)"),
        "low-watermark": Property(
            int, 0, "admission hysteresis: once shedding, keep refusing "
            "until in-flight drains to this (0 = max-inflight/2)"),
        "retry-after": Property(
            float, 0.05, "seconds suggested to BUSY-shed clients before "
            "they retry (per-tenant sheds scale this with the tenant's "
            "shed streak)"),
        # per-tenant admission (core/liveness.py TenantAdmissionController):
        # tenant identity + priority class ride the request meta over both
        # transports, so one hot tenant sheds before starving the fleet
        "tenant-quota": Property(
            int, 0, "default per-tenant in-flight quota (0 = unlimited): "
            "a tenant over its quota is shed with BUSY carrying a "
            "per-tenant retry-after while other tenants keep being "
            "served — quota sheds never trip client breakers"),
        "tenant-quotas": Property(
            str, "", "per-tenant quota overrides 'tenantA:8,tenantB:4' "
            "(tenants absent here use tenant-quota)"),
        "shed-window": Property(
            float, 5.0, "seconds of uninterrupted tenant-quota shedding "
            "before a rate-limited flight-recorder incident names the "
            "tenant"),
        # memory watermarks (core/liveness.py MemoryPressureMonitor):
        # shed BUSY at admission while the chip is near HBM exhaustion,
        # BEFORE an invoke can OOM — the degrade-don't-die coupling
        "mem-high-watermark": Property(
            float, 0.0, "arm the pipeline's memory-pressure monitor at "
            "this device-HBM/host-RSS fraction: crossing it sheds every "
            "request with BUSY (reason=memory) and trims recreatable "
            "pools/caches until pressure clears (0 = off; equivalent to "
            "Pipeline.enable_memory_monitor)"),
        "mem-low-watermark": Property(
            float, 0.0, "pressure clears once the watermark fraction "
            "falls back to this (hysteresis; 0 = 0.8 * high)"),
        "mem-sustain": Property(
            float, 2.0, "seconds of sustained pressure before a "
            "rate-limited memory_pressure flight-recorder incident "
            "(thread profiler attached)"),
        # data-plane integrity (Documentation/wire-protocol.md): corrupt
        # requests are refused at the door ('C' / DATA_LOSS) without the
        # server dying; off = serve whatever decodes (debug only)
        "verify-checksum": Property(
            bool, True, "verify wire integrity checksums on received "
            "requests (v2 envelopes); corrupt requests are refused with "
            "a resend-safe reply and counted in health()"),
        "wire-version": Property(
            int, 2, "max wire version this server speaks: 2 = "
            "checksummed envelopes + per-connection negotiation with v1 "
            "clients; 1 = pin legacy checksum-free framing"),
        # rolling restart (core/lifecycle.py): serving -> draining ->
        # stopped.  Draining refuses NEW requests with GOAWAY ('G' raw
        # TCP / UNAVAILABLE+goaway gRPC — immediate resend-safe client
        # failover, never a breaker trip), finishes in-flight work, then
        # closes the listeners and ends the server pipeline's stream.
        "drain-deadline": Property(
            float, 10.0, "max seconds a drain waits for in-flight "
            "requests to finish before closing the listeners anyway"),
        # fleet observatory (core/fleet.py): periodic telemetry digest
        # published on the retained announce — seq + monotonic age,
        # tokens/s EWMA, slot occupancy, memory headroom, per-tenant
        # admitted/shed, draining/degraded/swap state.  Driven on the
        # watchdog-sweeper cadence (zero per-frame cost); requires
        # announcing (topic= + dest-port=)
        "digest-interval": Property(
            float, 2.0, "seconds between telemetry-digest publishes on "
            "the discovery plane (0 = digests off; state changes and "
            "stop still force a final publish)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._core = None
        self._announcement = None
        self._drain_requested = threading.Event()
        self._lc_state = "serving"  # serving | draining | stopped
        # device-loss resilience: a serving element of this pipeline
        # lost a device and re-sharded — the announce carries it so
        # fleet routing deprioritizes this server (TIER_DEGRADED)
        self._degraded = False
        # fleet observatory: the telemetry-digest publisher (armed in
        # start() when announcing; polled from the watchdog sweeper)
        self._digest = None
        # lease fencing (core/autoscale.py): highest controller epoch
        # this server has accepted; stale-epoch drains are refused with
        # a typed reject before touching any stream or ledger
        from ..core.autoscale import FencingToken

        self._fence = FencingToken()

    def request_drain(self, epoch=None) -> None:
        """Begin the rolling-restart drain of THIS server: GOAWAY to new
        requests, finish in-flight ones (bounded by ``drain-deadline``),
        close listeners, end the stream.  ``Pipeline.drain()`` triggers
        the same path for the whole server pipeline.

        ``epoch`` is the issuing controller's lease epoch: an epoch
        older than one already accepted raises
        :class:`~nnstreamer_tpu.core.autoscale.StaleEpochError` and the
        server keeps serving untouched (``None`` = local/operator
        command, never fenced)."""
        self._fence.check(epoch)
        self._drain_requested.set()

    @property
    def drain_complete(self) -> bool:
        """Actuation probe (``core/autoscale.py`` scale-down tickets):
        True once a requested drain has fully completed — every live
        stream handed off or finished, listeners closed, stream
        ended."""
        return self._lc_state == "stopped"

    def start(self):
        self._drain_requested.clear()
        self._lc_state = "serving"
        self._core = get_query_server(self.props["id"], self.props["port"])
        # a restart after a drain must serve again (re-opens listeners
        # below; the registry core survives while the sink holds a ref)
        self._core.draining = False
        if self.props["caps"]:
            self._core.caps = self.props["caps"]
        self._core.block_ingress = bool(self.props["block-ingress"])
        try:
            self._core.admission = TenantAdmissionController(
                int(self.props["max-inflight"]),
                int(self.props["low-watermark"]) or None,
                default_quota=int(self.props["tenant-quota"]),
                quotas=parse_tenant_quotas(
                    self.props["tenant-quotas"],
                    f"{self.name}: tenant-quotas"),
                shed_window_s=float(self.props["shed-window"]),
                on_sustained_shed=self._on_sustained_shed,
            )
        except ValueError as e:
            raise ElementError(f"{self.name}: {e}") from None
        # memory-watermark coupling (core/liveness.py): when the owning
        # pipeline armed a MemoryPressureMonitor, admission sheds BUSY
        # (reason="memory") while the watermark is crossed — the server
        # refuses work BEFORE the chip OOMs.  One attr read when unarmed.
        self._core.admission.pressure = self._memory_pressured
        high = float(self.props["mem-high-watermark"])
        if high > 0:
            p = self._pipeline
            if p is not None and p.memory_monitor is None:
                low = float(self.props["mem-low-watermark"]) or high * 0.8
                try:
                    # runs before the pipeline's _arm_watchdog pass, so
                    # the sweeper thread picks the monitor up
                    p.enable_memory_monitor(
                        high=high, low=low,
                        sustain_s=float(self.props["mem-sustain"]))
                except ValueError as e:
                    raise ElementError(f"{self.name}: {e}") from None
        self._core.busy_retry_after = float(self.props["retry-after"])
        self._core.verify_checksum = bool(self.props["verify-checksum"])
        # clamp to a version the codecs speak: the gRPC reply path hands
        # this straight to encode_frame, which refuses unknown versions
        self._core.wire_version = 2 if int(self.props["wire-version"]) >= 2 else 1
        ct = self.props["connect-type"]
        if ct == "tcp":
            self._core.start_tcp()
        elif ct == "grpc":
            self._core.start()
        else:
            raise ElementError(
                f"{self.name}: connect-type={ct!r} (want grpc|tcp)")
        # expose the actually-bound port (ephemeral binds)
        self.props["port"] = self._core.port
        if self.props["topic"] and self.props["dest-port"] > 0:
            try:
                self._announce()
            except Exception:
                # pipeline rollback only stops elements whose start()
                # SUCCEEDED: release the acquired core ourselves or the
                # listener/refcount leaks for the process lifetime
                self.stop()
                raise
            interval = float(self.props["digest-interval"])
            if interval > 0:
                from ..core.fleet import DigestPublisher

                self._digest = DigestPublisher(
                    self._digest_stats, self._publish_digest,
                    interval_s=interval, name=self.name)
                p = self._pipeline
                if p is not None:
                    # runs before the pipeline's _arm_watchdog pass, so
                    # the sweeper thread picks the publisher up (the
                    # memory-monitor precedent: slow cadence, zero
                    # per-frame cost)
                    p.register_sweep(
                        self._digest.poll, min(interval, 1.0))

    def _digest_stats(self) -> dict:
        """Raw stats for one telemetry digest: this server's admission
        ledger merged with the pipeline-wide scan (slot engines, swap
        state, SLO burn, memory headroom) — see
        :func:`~nnstreamer_tpu.core.fleet.pipeline_digest_stats`."""
        from ..core.fleet import pipeline_digest_stats

        stats: dict = {
            # any non-serving state reads as draining: a drained server
            # whose pipeline has not been stopped yet keeps its sweeper
            # running, and a periodic digest must never flip the
            # retained announce back to draining=false while the
            # listeners are closed (clients would dial a dead port)
            "draining": self._lc_state != "serving",
            "degraded": self._degraded,
        }
        core = self._core
        if core is not None:
            snap = core.admission.snapshot()
            stats.update(
                inflight=snap["inflight"], admitted=snap["admitted"],
                shed=snap["shed"], tenants=snap.get("tenants", {}),
            )
        p = self._pipeline
        if p is not None:
            stats.update(pipeline_digest_stats(p))
        return stats

    def _publish_digest(self, digest: dict) -> None:
        """Ship one digest via the retained announce (never waits for
        the broker ack — the sweeper thread must not stall).  The legacy
        top-level draining/degraded keys ride along so pre-digest
        clients keep reading the same facts (mixed-fleet contract)."""
        ann = self._announcement
        if ann is None:
            return
        # require_connected: during a broker outage the update merges
        # into the announce (the reconnect re-announce will carry it)
        # but raises — the DigestPublisher counts EXACTLY one
        # publish failure per missed interval instead of queueing
        # blindly into the reconnect backlog
        ann.update({
            "digest": digest,
            "draining": bool(digest.get("draining", False)),
            "degraded": bool(digest.get("degraded", False)),
        }, wait_ack=False, require_connected=True)

    def publish_digest(self, force: bool = True):
        """Publish a digest NOW (chaos harness / operator hook; the
        periodic path is the sweeper-driven poll)."""
        if self._digest is None:
            return None
        return self._digest.poll(force=force)

    def _announce(self) -> None:
        """Retained per-instance endpoint announce on the MQTT control
        plane (shared machinery: distributed/hybrid.py)."""
        import os as _os
        import uuid as _uuid

        from ..distributed.hybrid import Announcement

        host = self.props["host"]
        if host in ("[::]", "0.0.0.0", ""):
            # a bind-all address is not dialable; announce loopback and
            # let multi-host deployments set host= to a reachable address
            host = "127.0.0.1"
        brokers = []
        for spec in str(self.props["dest-brokers"]).split(","):
            spec = spec.strip()
            if not spec:
                continue
            bh, _, bp = spec.rpartition(":")
            try:
                brokers.append((bh, int(bp)))
            except ValueError:
                raise ElementError(
                    f"{self.name}: dest-brokers entry {spec!r} "
                    "(want host:port)") from None
        # instance id must be unique across the POD, not just this
        # process: element names repeat (every pipeline calls its entry
        # "src"), so pid+uuid disambiguates both in- and cross-process
        self._announcement = Announcement(
            self.props["dest-host"], self.props["dest-port"],
            f"nns/query/{self.props['topic']}/"
            f"{self.name}-{_os.getpid()}-{_uuid.uuid4().hex[:8]}",
            {
                "host": host, "port": self._core.port,
                "connect_type": self.props["connect-type"],
                # discovery-plane health: clients deprioritize a
                # draining or degraded host from the broker state
                # alone, before the first GOAWAY/failure round trip
                "draining": False,
                "degraded": self._degraded,
                "inflight": 0,
            },
            logger=self.log,
            brokers=brokers or None,
        )

    def _announce_state(self, draining: bool) -> None:
        """Re-publish the retained announce with this server's live
        state (draining flag + the point-in-time load summary) — the
        discovery plane carries health, not just topology.  Fired from
        the request pump, so it never waits for the broker ack: a slow
        broker must not stall the very in-flight requests the drain is
        protecting."""
        if self._announcement is None:
            return
        if self._digest is not None:
            # one publish carries BOTH the state flags and a fresh
            # digest — the digest's own draining/degraded fields must
            # never lag a state change the legacy keys already announced
            self._digest.poll(force=True)
            return
        try:
            self._announcement.update({
                "draining": bool(draining),
                "degraded": bool(self._degraded),
                "inflight": (self._core.admission.inflight
                             if self._core is not None else 0),
            }, wait_ack=False)
        except Exception as e:  # noqa: BLE001 — broker I/O is best-effort
            self.log.warning("draining announce update failed: %s", e)

    def note_degraded(self, detail: str = "") -> None:
        """Pipeline feedback (``Pipeline.degraded_feedback``): a serving
        element of this pipeline lost a device and re-sharded onto
        survivors.  Re-publish the retained announce with
        ``degraded:true`` so fleet routing deprioritizes this server
        (TIER_DEGRADED) before its next failure — the server keeps
        serving correctly, it just stops winning placement races."""
        if self._degraded:
            return
        self._degraded = True
        self.log.warning(
            "server degraded (%s); announcing degraded:true", detail)
        self._announce_state(draining=self._lc_state == "draining")

    def _memory_pressured(self) -> bool:
        """Admission's memory-watermark probe: True while the owning
        pipeline's MemoryPressureMonitor is above the high watermark
        (two attribute reads when no monitor is armed)."""
        p = self._pipeline
        mon = p.memory_monitor if p is not None else None
        return mon is not None and mon.pressured

    def _on_sustained_shed(self, tenant: str) -> None:
        """A tenant's quota sheds persisted past shed-window: dump the
        flight recorder naming the tenant (rate-limited by both the
        admission controller and the recorder)."""
        self.log.warning(
            "tenant %r quota-shed sustained for > %.1fs; recording "
            "incident", tenant, float(self.props["shed-window"]),
        )
        p = self._pipeline
        if p is not None:
            p.incident("tenant_shed", self.name, f"tenant={tenant}")

    def stop(self):
        if self._announcement is not None:
            if self._digest is not None:
                # final flush BEFORE the tombstone: sources stop first,
                # so the rest of the pipeline (slot engines, admission
                # ledgers) is still live — the observatory's retired
                # accumulator keeps this server's EXACT final counters
                self._digest.poll(force=True)
            self._announcement.clear()
            self._announcement = None
        self._digest = None
        if self._core is not None:
            release_query_server(self.props["id"])
            self._core = None

    def output_spec(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def health_info(self) -> dict:
        """Admission/load-shed counters merged into Pipeline.health()."""
        info = {"lifecycle": self._lc_state,
                "degraded": 1 if self._degraded else 0,
                "stale_epoch_rejects": self._fence.rejects,
                "fence_epoch": self._fence.epoch}
        ann = self._announcement
        if ann is not None:
            info["reannounces"] = ann.reannounces
            info["plane_reconnects"] = ann.reconnects
        if self._digest is not None:
            info["digests_published"] = self._digest.published
            info["digest_publish_failures"] = self._digest.publish_failures
        if self._core is not None:
            info.update(self._core.liveness_snapshot())
        p = self._pipeline
        mon = p.memory_monitor if p is not None else None
        if mon is not None:
            # nns.mem.* watermark gauges ride the server's health row
            info.update(mon.snapshot())
        return info

    def frames(self) -> Iterator[TensorFrame]:
        """Request pump with the rolling-restart state machine:

        ``serving``: pull admitted requests off the ingress queue.
        ``draining`` (entered via :meth:`request_drain` or a pipeline
        ``drain()``): the core refuses NEW requests with GOAWAY while
        frames already admitted keep flowing through the server
        pipeline; once nothing is in flight (or ``drain-deadline``
        expires) the listeners close and the stream ends — EOS then
        flushes the server pipeline through the serversink.
        ``stopped``: listeners closed; the generator has returned."""
        import time as _time

        core = self._core
        drain_deadline = None
        while True:
            p = self._pipeline
            if self._lc_state == "serving" and (
                    self._drain_requested.is_set()
                    or (p is not None and p.draining)):
                self._lc_state = "draining"
                core.begin_drain()
                # stream handoff (Documentation/resilience.md "Stream
                # continuity"): live generation streams are flushed as
                # resumable GOAWAY chunks so clients MIGRATE them —
                # the drain below then waits for the handoffs to
                # deliver (they hold their admission slot until the
                # final chunk is out), bounded by drain-deadline
                if p is not None:
                    p.stream_drain_feedback()
                # tell the discovery plane FIRST: clients that re-rank
                # remotes off the broker stop picking this host without
                # paying a GOAWAY round trip each
                self._announce_state(draining=True)
                drain_deadline = _time.monotonic() + max(
                    0.0, float(self.props["drain-deadline"]))
            try:
                client_id, frame = core.ingress.get(timeout=0.05)
            except _queue.Empty:
                if p is not None and p._stop_flag.is_set():
                    return
                if self._lc_state == "draining":
                    done = core.drain_complete
                    if done or _time.monotonic() >= drain_deadline:
                        if not done:
                            self.log.warning(
                                "drain-deadline expired with %d request(s) "
                                "still in flight; closing listeners",
                                core.admission.inflight,
                            )
                        core.close_listeners()
                        self._lc_state = "stopped"
                        self.log.info(
                            "query server drained and stopped accepting "
                            "(goaway_sent=%d)", core.goaway_sent,
                        )
                        return
                continue
            # client_id meta was attached by the Invoke handler; just emit
            yield frame


@element("tensor_query_serversink")
class TensorQueryServerSink(SinkElement):
    BATCH_AWARE = True  # splits block answers per client RPC

    PROPERTIES = {
        "id": Property(int, 0, "pairs with the serversrc of the same id"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # ≙ tensor_query_serversink.c `limit`: bound per-client queued
        # answers; excess answers are dropped with a warning
        "limit": Property(int, 0, "max queued answers per client (0 = unbounded)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._core = None

    def start(self):
        self._core = get_query_server(self.props["id"])

    def stop(self):
        if self._core is not None:
            release_query_server(self.props["id"])
            self._core = None

    def render(self, frame):
        if isinstance(frame, BatchFrame):
            # block-ingress answers: resolve each logical frame (client_id
            # rides in the per-frame meta captured at injection)
            for f in frame.split():
                self.render(f)
            return
        client_id = frame.meta.get("client_id")
        if client_id is None:
            raise ElementError(
                f"{self.name}: frame lacks client_id meta (did it pass through "
                "an element that drops meta?)"
            )
        delivered = self._core.resolve(
            int(client_id), frame, limit=self.props["limit"]
        )
        if (not delivered and frame.meta.get("final") is False
                and not self._core.client_live(int(client_id))):
            # mid-stream chunk for a VANISHED client (RPC cancelled,
            # socket died): tell upstream stream producers so a slot
            # engine frees the dead stream's slot immediately instead of
            # decoding tokens nobody will read
            p = self._pipeline
            if p is not None:
                p.stream_cancel_feedback(self, frame.meta)


class _PoolState:
    """One generation of the client's connection pool.

    ``conns``/``targets`` are index-aligned tuples; ``down_until`` is the
    health map for THIS generation only (a worker that captured an older
    state writes health marks into that retired state, never into a
    successor where the index means a different server).  ``epoch``
    identifies the start()-run the pool belongs to: a leftover worker
    from a previous run can neither trigger a swap of, nor resend a dead
    run's frame into, the new run's pool."""

    __slots__ = ("conns", "targets", "addrs", "gen", "epoch", "down_until")

    def __init__(self, conns, targets, gen, epoch=-1):
        self.conns = tuple(conns)
        self.targets = tuple(targets)
        # "host:port" strings precomputed once per pool generation: the
        # routing decision runs per request and must not re-format six
        # addresses per call
        self.addrs = tuple(f"{h}:{p}" for h, p in targets)
        self.gen = gen
        self.epoch = epoch
        self.down_until: dict = {}


class _StreamInterrupt(Exception):
    """Internal control flow of the stream-continuity layer: one
    transport attempt of a RESUMABLE stream ended without the stream
    completing.  ``kind`` distinguishes a crash (``"break"``: breaker/
    cooldown already recorded), a draining server's planned handoff
    (``"handoff"``: breaker-immune), and a server refusing the resume
    (``"reject"``); ``cause`` is what surfaces if the budget runs out."""

    def __init__(self, cause: BaseException, kind: str):
        super().__init__(str(cause))
        self.cause = cause
        self.kind = kind


@element("tensor_query_client")
class TensorQueryClient(Element):
    """Looks like a local filter; actually round-trips frames through remote
    server pipeline(s) with pipelined, order-preserving dispatch."""

    BATCH_AWARE = True  # maps blocks onto the wire micro-batch envelope
    #: answers are pipelined: an error raised while handling frame B may
    #: belong to in-flight frame A, so the scheduler's skip/restart
    #: policies cannot attribute it — this element degrades via its own
    #: `degrade=` property instead (the worker always runs it fail-stop)
    SUPERVISES_OWN_ERRORS = True
    #: never fuse: the completion callback wakes the worker by injecting a
    #: drain tick into this element's OWN mailbox (_notify_done) — without
    #: a private mailbox, live streams would sit on ready answers
    THREAD_BOUNDARY = True

    PROPERTIES = {
        "host": Property(str, "localhost", "server host"),
        "port": Property(int, 0, "server port"),
        "hosts": Property(str, "", "multi-server fan-out 'h1:p1,h2:p2' (round-robin)"),
        # hybrid discovery (≙ reference connect-type=HYBRID): resolve the
        # server set from retained announces on nns/query/<topic>/# at the
        # MQTT broker, instead of static host/hosts — pod membership then
        # changes on the broker, not in every client's pipeline text
        "topic": Property(str, "", "discover servers under this topic"),
        "dest-host": Property(str, "localhost", "MQTT broker host (discovery)"),
        "dest-port": Property(
            int, 0, "MQTT broker port (0 = discovery disabled)"
        ),
        "discovery-timeout": Property(
            float, 5.0, "s to wait for at least one announced server"
        ),
        "timeout": Property(float, 10.0, "per-request timeout, seconds"),
        "max-in-flight": Property(int, 8, "pipelined outstanding requests"),
        # fleet routing (core/routing.py): close the loop on the load
        # signals the servers already emit — least-inflight / span-EWMA
        # selection instead of blind rotation, with breaker-open and
        # draining remotes ALWAYS deprioritized below healthy ones
        "routing": Property(
            str, "rotate",
            "remote selection policy: rotate (round-robin) | "
            "least-inflight (fewest live in-flight requests to the "
            "remote) | ewma (lowest end-to-end latency EWMA from the "
            "trace spans, in-flight tie-break).  All policies rank "
            "breaker-open, cooled-down, and announced-draining remotes "
            "below every healthy alternative",
            convert=enum_prop_check(
                "routing", "rotate", "least-inflight", "ewma")),
        "affinity-key": Property(
            str, "",
            "consistent-hash session affinity: frames whose meta carries "
            "this key stick to the key's rendezvous-hash owner among the "
            "current servers (stateful generation streams stay on one "
            "host; fleet resize remaps the provable minimum of keys).  "
            "Failover still applies when the owner is unhealthy.  "
            "The special value 'prefix' routes by the prompt's "
            "grain-aligned prefix digest (core/continuity.py "
            "prefix_route_key) when the meta carries no literal "
            "'prefix' key, so clients sharing a prompt prefix land on "
            "the server whose shared-prefix KV cache is already warm "
            "(generator prefix-cache=on); a frame meta 'prefix_tokens' "
            "int declares how many leading tokens are shared.  "
            "Empty = no affinity"),
        # per-tenant admission (server side pairs these with
        # tenant-quota/tenant-quotas on the serversrc)
        "tenant": Property(
            str, "", "tenant identity stamped into request meta "
            "(drives server-side per-tenant quotas and accounting); "
            "frames already carrying a tenant keep theirs"),
        "priority": Property(
            int, 3, "priority class 0..3 stamped into request meta "
            "(3 = highest; lower classes shed first under server "
            "overload); frames already carrying a priority keep theirs"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # elastic recovery (SURVEY §5.3: preemptible workers need client-side
        # retry/requeue — net-new vs the reference's single timeout)
        # default 0: retries>0 makes delivery at-least-once (a request that
        # timed out client-side but succeeded server-side is re-sent,
        # possibly to another server) — opt in only for idempotent server
        # pipelines; 0 matches the reference's single-timeout semantics
        "retries": Property(int, 0, "re-send attempts per request (0 = none; >0 = at-least-once delivery)"),
        # BUSY backpressure (server admission control): a shed request
        # provably never executed, so re-sends are safe even under the
        # at-most-once default — they get their own RetryPolicy-paced
        # budget, and never count against the remote's circuit breaker
        "busy-retries": Property(
            int, 3, "extra paced re-sends when the server sheds with "
            "BUSY (separate budget from retries; 0 = treat BUSY like "
            "any other failure)"),
        # data-plane integrity (Documentation/wire-protocol.md): a
        # detected-corrupt exchange is resend-safe — a corrupt REQUEST
        # was refused before execution ('C'/DATA_LOSS), and a corrupt
        # REPLY means the answer was lost in transit, so re-asking
        # cannot double-apply it any harder than the server already did
        "corrupt-retries": Property(
            int, 2, "extra paced re-sends when an exchange fails "
            "integrity verification (own budget like busy-retries; "
            "corruption DOES count against the remote's breaker — "
            "sustained corruption trips it, one blip never does)"),
        "verify-checksum": Property(
            bool, True, "verify wire integrity checksums on replies (v2 "
            "envelopes); detected corruption is retried per "
            "corrupt-retries and counted in health()"),
        "wire-version": Property(
            int, 2, "max wire version to negotiate (tcp transport): 2 = "
            "checksummed envelopes with automatic per-connection "
            "fallback to v1 peers; 1 = force legacy framing"),
        # resilience knobs (core/resilience.py; Documentation/resilience.md)
        "retry-backoff": Property(
            float, 0.05,
            "base seconds between failover attempts (doubles per attempt, "
            "capped at 1s; 0 = immediate)"),
        "breaker-threshold": Property(
            int, 5,
            "per-remote circuit breaker: consecutive-window failures that "
            "trip it open (0 = breaker disabled)"),
        "breaker-reset": Property(
            float, 5.0, "seconds a tripped breaker stays open before "
            "half-open probing"),
        # what the STREAM sees when every remote/attempt is exhausted:
        # error (default, surfaces per the element's error-policy) |
        # passthrough (emit the request frame unanswered — degrade to a
        # camera-only stream) | skip (drop the frame with a warning)
        "degrade": Property(
            str, "error",
            "on total remote failure: error | passthrough | skip",
            convert=enum_prop_check("degrade", "error", "passthrough", "skip")),
        # wire micro-batching (TPU-first, no reference analog): drain
        # whatever frames are ALREADY queued (no added latency) and ship
        # up to N of them in ONE RPC — amortizes the per-RPC transport
        # cost exactly like the filter's batched XLA invoke amortizes
        # dispatch.  1 = per-frame RPCs (reference parity).
        "wire-batch": Property(int, 1, "max frames per RPC (1 = no batching)"),
        "stream": Property(
            bool, False,
            "server-streaming invoke (gRPC InvokeStream / raw-TCP 'S' "
            "message): answer frames are emitted as the remote pipeline "
            "produces them until a final-flagged one arrives — remote "
            "streaming generation; incompatible with wire-batch > 1",
        ),
        # stream continuity (Documentation/resilience.md): a generation
        # stream outlives the server it started on — chunks from slotted
        # tensor_generator servers carry resume state, so a mid-stream
        # break re-routes a RESUME request (prompt + delivered prefix)
        # to a healthy server, with per-chunk sequence numbers deduping
        # the overlap (delivered tokens exactly-once, bit-identical to
        # an uninterrupted run)
        "stream-resume": Property(
            bool, True,
            "resume a broken generation stream on another server from "
            "its delivered-token checkpoint, and migrate streams a "
            "draining server hands off with resumable GOAWAY chunks; "
            "false = legacy no-replay semantics (a mid-stream break "
            "surfaces as an error).  Only streams whose chunks carry "
            "resume state participate"),
        "resume-retries": Property(
            int, 3,
            "consecutive resume attempts without progress before a "
            "stream gives up (each delivered chunk refills the budget, "
            "so long streams survive repeated rolling restarts); "
            "exhaustion fires a flight-recorder incident and surfaces "
            "the original break"),
        # per-stream SLO accounting (core/telemetry.py SloTracker,
        # client side — what the USER experienced, across failovers and
        # resumes): TTFT / per-token inter-arrival histograms + goodput
        # classification per tenant, burn-rate gauges at scrape time
        "slo-ttft-p95": Property(
            float, 0.0,
            "client-observed TTFT objective: 95% of streams must see "
            "their first chunk within this many seconds (0 = off)"),
        "slo-token-p99": Property(
            float, 0.0,
            "client-observed per-token objective: 99% of token "
            "inter-arrivals under this many seconds (0 = off)"),
        "slo-availability": Property(
            float, 0.0,
            "goodput objective, e.g. 0.999: streams completed / "
            "streams classified (shed/evicted/expired/errors are the "
            "error budget; 0 = off)"),
        "connect-type": Property(
            str, "grpc",
            "transport: grpc (interop default) | tcp (zero-copy raw TCP "
            "with sendmsg gather-writes and a per-client socket pool)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        # connection-pool state is one immutable-per-generation snapshot
        # (_PoolState): workers capture it ONCE per request, so an elastic
        # pool swap can never shrink a list under a concurrent indexer or
        # cross-wire health marks between generations
        self._pstate = _PoolState((), (), 0)
        self._pool: Optional[ThreadPoolExecutor] = None
        self._inflight: Deque[Future] = deque()
        self._rr = 0
        # topic-mode elastic recovery: swap serialization + stop guard
        self._rediscover_lock = threading.Lock()
        # leader election for the discovery I/O itself: one broker
        # round-trip per failure wave, losers piggy-back on the swap
        self._discover_leader = threading.Lock()
        self._last_discovery_ts = float("-inf")
        self._stopped = True
        self._run_epoch = 0  # bumped per start(); scopes pool generations
        # per-remote circuit breakers, keyed by "host:port" — they OUTLIVE
        # pool swaps (trip counts are part of the health story) and are
        # shared by every worker thread (CircuitBreaker is thread-safe)
        self._breakers: dict = {}
        self._breakers_lock = threading.Lock()
        self._degraded = 0  # frames answered by degrade= instead of a server
        self._evicted_breaker_trips = 0  # trips of breakers evicted on swaps
        self._busy_replies = 0  # BUSY sheds seen (admission backpressure)
        self._goaway_replies = 0  # GOAWAY refusals (rolling restarts)
        self._deadline_expired = 0  # requests abandoned: budget ran out
        # data-plane integrity accounting (all under _breakers_lock —
        # pool workers race them): exact delivered/retried/corruption
        # numbers are the acceptance contract of the corruption chaos e2e
        self._corruption_detected = 0  # corrupt exchanges (request or reply)
        self._delivered = 0  # logical frames answered by a server
        self._retried = 0  # extra attempts dispatched (all causes)
        # stream continuity (core/continuity.py), exact by the chaos
        # acceptance contract: crash resumes vs planned migrations are
        # distinct counters, dedupe is visible, failures are loud
        self._stream_resumes = 0    # crash-initiated resumes issued
        self._stream_migrations = 0  # drain handoffs migrated
        self._duplicate_tokens_dropped = 0  # post-resume overlap deduped
        self._resume_failures = 0   # resume attempts that failed
        self._retry_policy = RetryPolicy()  # rebuilt from props in start()
        # trace spans (core/telemetry.py): per-remote EWMA segment
        # aggregation — the live load signal the ewma routing policy
        # consumes (under _breakers_lock like the other worker-raced
        # counters)
        self._remote_spans: dict = {}
        self._rtt_hist = None  # registry histogram, bound at start()
        # fleet routing state (core/routing.py), all under _breakers_lock:
        # live per-remote attempt counts (self-cleaning: entries vanish
        # when they drain to 0, so endpoint churn never grows the dict),
        # consistent-hash affinity assignments (bounded LRU; a remap is
        # an OWNER change, failover of a sticky request is not), and the
        # per-endpoint health hints the discovery plane announced
        self._remote_inflight: dict = {}
        self._affinity_map: "OrderedDict[str, str]" = OrderedDict()
        self._affinity_remaps = 0
        # discovery hints age out (_HINT_TTL_S past _hints_ts): a
        # drained server restarts and re-announces draining=false, but
        # a client with no failing requests never rediscovers — without
        # the TTL the restarted host would stay deprioritized forever
        self._endpoint_hints: dict = {}
        self._hints_ts = float("-inf")
        # ewma-score cache: (spans revision, addrs) -> {idx: score};
        # _note_span/_rediscover bump the revision
        self._spans_rev = 0
        self._scores_cache = None
        # per-stream SLO accounting (slo-* props; streams only) — the
        # client-side half: what the user experienced end-to-end
        self._slo = None

    @property
    def _conns(self) -> tuple:
        """Current pool's connections (tests and negotiation read this)."""
        return self._pstate.conns

    def _discover_targets(self) -> List[Tuple[str, int]]:
        """Resolve the server set from retained announces under
        nns/query/<topic>/# (shared machinery: distributed/hybrid.py),
        transport-filtered, deduplicated, and liveness-probed — a crashed
        server never tombstones its announce, so stale endpoints are
        dropped here instead of failing the whole client at handshake."""
        from ..distributed.hybrid import discover_endpoints, probe_endpoint

        want_ct = self.props["connect-type"]
        hints: dict = {}

        def validate(topic: str, info: dict) -> bool:
            got_ct = info.get("connect_type", want_ct)
            if got_ct != want_ct:
                self.log.warning(
                    "announce %s speaks %s, client wants %s (skipped)",
                    topic, got_ct, want_ct,
                )
                return False
            # discovery-plane health propagation: the announce carries
            # the server's live state — a host that says it is draining
            # is deprioritized by routing BEFORE the first GOAWAY.
            # ALWAYS overwrite per endpoint: a restarted server
            # announces healthy on a new instance topic but the same
            # host:port, and its fresh announce must override the dead
            # instance's retained draining=true.  ONE capture path
            # (core/fleet.hint_from_announce): the telemetry digest's
            # draining/degraded fields when present, the legacy
            # top-level keys for pre-digest servers — routing and the
            # fleet observatory read the same facts.  Only the FLAGS
            # are kept client-side: point-in-time load numbers must
            # never be exported as if live (routing has genuinely-live
            # signals of its own)
            from ..core.fleet import hint_from_announce

            try:
                hints[(str(info["host"]), int(info["port"]))] = (
                    hint_from_announce(info))
            except (KeyError, TypeError, ValueError):
                pass
            return True

        found = discover_endpoints(
            self.props["dest-host"], self.props["dest-port"],
            f"nns/query/{self.props['topic']}/#",
            timeout_s=self.props["discovery-timeout"],
            validate=validate, logger=self.log,
        )
        # probe CONCURRENTLY: N stale announces must cost one probe
        # timeout total, not N serial timeouts on the client's start path
        candidates = sorted(set(found.values()))
        with ThreadPoolExecutor(max_workers=max(1, len(candidates))) as ex:
            alive = list(ex.map(
                lambda hp: probe_endpoint(*hp), candidates
            ))
        targets = []
        for (host, port), ok in zip(candidates, alive):
            if ok:
                targets.append((host, port))
            else:
                self.log.warning(
                    "announced endpoint %s:%d not accepting (stale "
                    "announce from a crashed server?) — skipped",
                    host, port,
                )
        if not targets:
            raise ElementError(
                f"{self.name}: no live server announced on topic "
                f"{self.props['topic']!r} within "
                f"{self.props['discovery-timeout']}s"
            )
        # hints are replaced wholesale per discovery: a vanished
        # endpoint's row disappears with the membership that carried
        # it, and only DRAINING/DEGRADED rows are kept (absent row =
        # healthy)
        with self._breakers_lock:
            self._endpoint_hints = {
                f"{h}:{p}": hints[(h, p)] for h, p in targets
                if hints.get((h, p), {}).get("draining")
                or hints.get((h, p), {}).get("degraded")
            }
            import time as _time

            self._hints_ts = _time.monotonic()
        return targets

    def start(self):
        if self.props.get("error-policy", "fail-stop") != "fail-stop":
            self.log.warning(
                "error-policy=%s is ignored on the query client "
                "(pipelined in-flight answers make frame attribution "
                "ambiguous) — use degrade=passthrough|skip instead",
                self.props["error-policy"],
            )
        ct = self.props["connect-type"]
        if ct not in ("grpc", "tcp"):
            # validate BEFORE discovery: a typo'd connect-type must fail
            # with this message, not filter every announce and surface as
            # a misleading discovery timeout
            raise ElementError(
                f"{self.name}: connect-type={ct!r} (want grpc|tcp)")
        if not 0 <= int(self.props["priority"]) <= PRIORITY_MAX:
            raise ElementError(
                f"{self.name}: priority={self.props['priority']} "
                f"(want 0..{PRIORITY_MAX})")
        targets: List[Tuple[str, int]] = []
        if self.props["topic"] and self.props["dest-port"] > 0:
            targets = self._discover_targets()
        elif self.props["hosts"]:
            from ..pipeline.element import parse_host_list

            targets = parse_host_list(self.props["hosts"], self.name, "hosts")
        else:
            targets.append((self.props["host"], self.props["port"]))
        if not targets or any(p == 0 for _, p in targets):
            raise ElementError(f"{self.name}: query client needs host/port")
        ct = self.props["connect-type"]
        if self.props["stream"]:
            if int(self.props["wire-batch"]) > 1:
                raise ElementError(
                    f"{self.name}: stream=true is per-request; "
                    "wire-batch must be 1"
                )
        self._run_epoch += 1
        self._pstate = _PoolState(
            self._make_conns(targets), targets, 0, epoch=self._run_epoch
        )
        self._stopped = False
        # failover pacing: delay_for(k) gives the capped-exponential,
        # seeded-jitter backoff between attempt k and k+1
        self._retry_policy = RetryPolicy(
            max_attempts=1 + max(0, int(self.props["retries"])),
            base_delay_s=max(0.0, float(self.props["retry-backoff"])),
            max_delay_s=1.0,
            jitter=0.1,
            # unseeded: jitter exists to DE-synchronize clients — a fixed
            # seed would give every client the same backoff sequence and
            # recreate the thundering herd it is meant to prevent
            seed=None,
        )
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, self.props["max-in-flight"])
        )
        from ..core.telemetry import REGISTRY

        pname = (
            self._pipeline.telemetry_label
            if self._pipeline is not None else ""
        )
        self._rtt_hist = REGISTRY.histogram(
            "nns.query.rtt_seconds",
            labels={"pipeline": pname, "element": self.name},
        )
        from ..core.telemetry import SloTracker

        try:
            slo = SloTracker(
                ttft_p95_s=float(self.props["slo-ttft-p95"]),
                token_p99_s=float(self.props["slo-token-p99"]),
                availability=float(self.props["slo-availability"]),
            )
        except ValueError as e:
            raise ElementError(f"{self.name}: {e}") from None
        self._slo = slo if slo.armed else None

    def _make_conns(self, targets: List[Tuple[str, int]]) -> list:
        ct = self.props["connect-type"]
        verify = bool(self.props["verify-checksum"])
        if ct == "tcp":
            from ..distributed.tcp_query import TcpQueryConnection

            return [
                TcpQueryConnection(
                    h, p, self.props["timeout"],
                    nconns=max(1, int(self.props["max-in-flight"])),
                    wire_version=int(self.props["wire-version"]),
                    verify_checksum=verify,
                ) for h, p in targets
            ]
        return [
            QueryConnection(h, p, self.props["timeout"],
                            verify_checksum=verify)
            for h, p in targets
        ]

    def stop(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False, cancel_futures=True)
            self._pool = None
        # flag FIRST (without the lock): an in-flight rediscovery holds
        # the lock across discovery I/O — it re-checks _stopped before
        # swapping, so stop() never waits out a discovery timeout, and no
        # pool can be created after stop and leak
        self._stopped = True
        with self._rediscover_lock:
            ps, self._pstate = self._pstate, _PoolState((), (), 0)
        for c in ps.conns:
            c.close()
        self._inflight.clear()

    # caps handshake at negotiation time (≙ edge CAPS event exchange)
    def accept_spec(self, pad, spec):
        if spec.tensors and self._conns:
            failures = []
            for conn in self._conns:
                try:
                    conn.handshake(spec.to_string())
                except Exception as e:  # noqa: BLE001 — transport boundary
                    failures.append((conn.addr, e))
            can_failover = self.props["retries"] > 0 and len(self._conns) > 1
            if failures and (len(failures) == len(self._conns) or not can_failover):
                addr, e = failures[0]
                raise ElementError(
                    f"{self.name}: caps handshake with {addr} failed: {e}"
                ) from None
            for addr, e in failures:
                # a down server is tolerable when others answered AND requests
                # can fail over (elastic recovery); it may also come back later
                self.log.warning("caps handshake with %s failed: %s", addr, e)
        return spec

    def derive_spec(self, pad=0):
        return ANY  # the server decides the answer schema

    def _result_budget(self) -> float:
        """Worst-case seconds one in-flight request may legitimately take
        (failover attempts x (timeout + backoff) + busy pacing + one
        rediscovery), doubled, plus slack.  Blocking waits on the
        in-flight window use this bound so a wedged worker can never
        hang the element thread forever (audit contract,
        tools/check_blocking_timeouts.py)."""
        t = float(self.props["timeout"])
        attempts = 1 + max(0, int(self.props["retries"]))
        busy = max(0, int(self.props["busy-retries"]))
        disc = float(self.props["discovery-timeout"])
        return 2.0 * ((attempts + busy) * (t + 1.0) + disc) + 30.0

    def _await(self, fut: Future):
        try:
            return fut.result(timeout=self._result_budget())
        except FuturesTimeout:
            raise TimeoutError(
                f"{self.name}: in-flight request exceeded the "
                f"{self._result_budget():.0f}s worst-case budget "
                "(wedged worker?)"
            ) from None

    def _drain_ready(self, block_all: bool):
        out = []
        while self._inflight:
            fut = self._inflight[0]
            if not block_all and not fut.done():
                break
            self._inflight.popleft()
            got = self._await(fut)  # raises on RPC error -> error-policy/bus
            if got is None:
                continue  # degrade=skip swallowed the frame (warned)
            if isinstance(got, list):  # wire-batched request
                out.extend((0, f) for f in got)
            else:
                out.append((0, got))
        return out

    def _breaker_for(self, target: Tuple[str, int]) -> Optional[CircuitBreaker]:
        """The (lazily created) circuit breaker for one remote; None when
        disabled via breaker-threshold=0.  Keyed by endpoint so state —
        including lifetime trip counts — survives elastic pool swaps."""
        threshold = int(self.props["breaker-threshold"])
        if threshold <= 0:
            return None
        key = f"{target[0]}:{target[1]}"
        with self._breakers_lock:
            b = self._breakers.get(key)
            if b is None:
                b = CircuitBreaker(
                    failure_threshold=threshold,
                    window_s=max(1.0, float(self.props["timeout"]) * 4),
                    reset_timeout_s=float(self.props["breaker-reset"]),
                    name=f"{self.name}->{key}",
                    on_trip=self._on_breaker_trip,
                )
                self._breakers[key] = b
            return b

    def _on_breaker_trip(self, breaker: CircuitBreaker) -> None:
        """A remote's breaker tripped open: dump the flight recorder
        (rate-limited no-op without one) — the frames that burned the
        failure window are exactly what the ring still holds."""
        p = self._pipeline
        if p is not None:
            p.incident("breaker_trip", self.name, breaker.name)

    def health_info(self) -> dict:
        """Element-specific health merged into ``Pipeline.health()``:
        per-remote breaker snapshots, degrade counters, and the
        per-remote latency-segment aggregation (``remotes``) routing
        will consume."""
        with self._breakers_lock:
            breakers = {k: b.snapshot() for k, b in self._breakers.items()}
            remotes = {
                k: {
                    kk: (round(vv, 3) if isinstance(vv, float) else vv)
                    for kk, vv in agg.items()
                }
                for k, agg in self._remote_spans.items()
            }
            remote_inflight = dict(self._remote_inflight)
            hints = {k: dict(v) for k, v in self._endpoint_hints.items()
                     if v}
        return {
            "breakers": breakers,
            "remotes": remotes,
            "remote_inflight": remote_inflight,
            "endpoint_hints": hints,
            "routing": self.props["routing"],
            "affinity_remaps": self._affinity_remaps,
            "breaker_trips_evicted": self._evicted_breaker_trips,
            "degraded_frames": self._degraded,
            "busy_replies": self._busy_replies,
            "goaway_replies": self._goaway_replies,
            "deadline_expired": self._deadline_expired,
            "corruption_detected": self._corruption_detected,
            "delivered": self._delivered,
            "retried": self._retried,
            "stream_resumes": self._stream_resumes,
            "stream_migrations": self._stream_migrations,
            "duplicate_tokens_dropped": self._duplicate_tokens_dropped,
            "resume_failures": self._resume_failures,
            "servers": [f"{h}:{p}" for h, p in self._pstate.targets],
            **({"slo": self._slo.snapshot()}
               if self._slo is not None else {}),
        }

    def histograms_info(self):
        """Client-side per-tenant TTFT / inter-token log2 bucket series
        (scrape-time export; empty histograms emit nothing)."""
        return self._slo.hist_rows() if self._slo is not None else []

    def metrics_info(self):
        """Registry samples (core/telemetry.py, scrape time only).
        ``affinity_remaps`` / ``remote_inflight`` are NOT repeated here:
        they already export through the ``health_info()`` collector path
        (HEALTH_KEY_METRICS / the ``remote_inflight`` branch) — emitting
        them twice would duplicate the series in one scrape."""
        return [("nns.query.client_inflight", len(self._inflight))]

    _SPAN_EWMA = 0.2  # smoothing for the per-remote load signal

    def _note_span(self, target: Tuple[str, int], req, ans,
                   t_send: float, t_recv: float) -> None:
        """Trace-span bookkeeping for one successful exchange: attach the
        end-to-end decomposition to each answer's meta (``SPAN_META``)
        and fold it into the per-remote EWMA aggregation.

        Segments are additive BY CONSTRUCTION: the server ships a
        duration dict whose queue+dispatch+compute equals its total, and
        wire is defined as rtt minus that total — so client_queue + wire
        + server_queue + device_dispatch + device_compute == total
        exactly (clock jitter lands in the wire segment, where it
        belongs).  Peers that never stamped server spans (v1/legacy)
        degrade to wire == rtt."""
        rtt = max(0.0, t_recv - t_send)
        reqs = req if isinstance(req, list) else [req]
        answers = ans if isinstance(ans, list) else [ans]
        last = None
        for i, a in enumerate(answers):
            if a is None:
                continue
            src = reqs[i] if i < len(reqs) else reqs[-1]
            srv = a.meta.get(SRV_SPAN_META) or {}
            srv_total = min(float(srv.get("total", 0.0)), rtt)
            dispatch = float(srv.get("dispatch", 0.0))
            compute = float(srv.get("compute", 0.0))
            queue = max(0.0, srv_total - dispatch - compute)
            enq = src.meta.get(TL_ENQ_META)
            cq = max(0.0, t_send - enq) if enq is not None else 0.0
            span = {
                "trace_id": src.meta.get(TRACE_ID_META),
                "remote": f"{target[0]}:{target[1]}",
                "client_queue": cq,
                "wire": rtt - srv_total,
                "server_queue": queue,
                "device_dispatch": dispatch,
                "device_compute": compute,
                "total": cq + rtt,
            }
            a.meta[SPAN_META] = span
            last = span
        if last is None:
            return
        if self._rtt_hist is not None:
            self._rtt_hist.observe(rtt)
        addr = last["remote"]
        alpha = self._SPAN_EWMA

        def roll(old, new):
            return new if old is None else old + alpha * (new - old)

        with self._breakers_lock:
            agg = self._remote_spans.setdefault(addr, {
                "requests": 0, "e2e_ms": None, "rtt_ms": None,
                "wire_ms": None, "server_ms": None,
                "client_queue_ms": None,
            })
            agg["requests"] += 1
            agg["e2e_ms"] = roll(agg["e2e_ms"], last["total"] * 1e3)
            agg["rtt_ms"] = roll(agg["rtt_ms"], rtt * 1e3)
            agg["wire_ms"] = roll(agg["wire_ms"], last["wire"] * 1e3)
            agg["server_ms"] = roll(
                agg["server_ms"],
                (last["server_queue"] + last["device_dispatch"]
                 + last["device_compute"]) * 1e3)
            agg["client_queue_ms"] = roll(
                agg["client_queue_ms"], last["client_queue"] * 1e3)
            self._spans_rev += 1  # invalidate the routing score cache

    _AFFINITY_MAP_MAX = 4096  # LRU bound on tracked affinity keys
    #: seconds a discovery hints generation stays authoritative —
    #: comfortably past a drain (drain-deadline default 10 s) but short
    #: enough that a restarted host regains traffic without waiting for
    #: a failure-triggered rediscovery
    _HINT_TTL_S = 30.0

    def _tiers_and_signals(self, ps: "_PoolState", n: int, policy: str,
                           now: float):
        """One pass, ONE lock acquisition: the availability tier of
        every remote plus the load signals the policy needs.

        Tiers: cooled-down or breaker-OPEN remotes are TIER_DOWN (the
        selection-side guard — no policy may rank them above a healthy
        remote), hosts the discovery plane announced as draining are
        TIER_DRAINING (deprioritized before the first GOAWAY round
        trip), everything else TIER_OK.  Breaker state is a peek only —
        allow() reserves half-open probe slots and must be called
        exactly once, at attempt time; a breaker that was never created
        is closed by definition (creation stays lazy, at attempt
        time)."""
        down = ps.down_until
        addrs = ps.addrs
        peek_breakers = int(self.props["breaker-threshold"]) > 0
        tiers = {}
        inflight = scores = None
        # a whole hints generation expires at once (all rows come from
        # one discovery pass): a stale "draining" must decay, or a host
        # that drained, restarted, and re-announced healthy would stay
        # deprioritized until the next failure-triggered rediscovery
        hints_fresh = now - self._hints_ts < self._HINT_TTL_S
        # lock-free reads, by design (same contract as the watchdog's
        # heartbeat pings): every signal is a GIL-atomic dict get whose
        # worst-case staleness costs one suboptimal ranking, never a
        # crash — taking _breakers_lock here would put a lock acquisition
        # on every request of every pool worker
        breakers = self._breakers
        hints = self._endpoint_hints
        for i in range(n):
            if down.get(i, 0) > now:
                tiers[i] = TIER_DOWN
                continue
            b = breakers.get(addrs[i]) if peek_breakers else None
            if b is not None and b.state == CircuitBreaker.OPEN:
                tiers[i] = TIER_DOWN
                continue
            h = hints.get(addrs[i]) if hints_fresh else None
            if h and h.get("draining"):
                tiers[i] = TIER_DRAINING
            elif h and h.get("degraded"):
                # lost a device, serving reduced: correct but wounded —
                # deprioritized below whole servers, above draining
                tiers[i] = TIER_DEGRADED
            else:
                tiers[i] = TIER_OK
        if policy != "rotate":
            ri = self._remote_inflight
            inflight = {i: ri.get(addrs[i], 0) for i in range(n)}
            if policy == "ewma":
                # consulted per-CURRENT-address only: EWMA rows for
                # endpoints _rediscover evicted are unreachable by
                # construction.  Scores are cached per spans revision —
                # recomputed only when a completed exchange actually
                # moved an EWMA (or the pool changed), so bursts between
                # completions pay one dict lookup
                rev = (self._spans_rev, addrs)
                cached = self._scores_cache
                if cached is not None and cached[0] == rev:
                    scores = cached[1]
                else:
                    scores = ewma_scores(
                        range(n), addrs, self._remote_spans)
                    self._scores_cache = (rev, scores)
        return tiers, inflight, scores

    def _note_affinity(self, key: str, target: Tuple[str, int]) -> None:
        """Track the consistent-hash owner per affinity key; an OWNER
        change (fleet resize moved the key) counts as one remap — a
        failover of a sticky request is not a remap, the owner
        assignment is a pure function of the endpoint set."""
        addr = f"{target[0]}:{target[1]}"
        with self._breakers_lock:
            prev = self._affinity_map.pop(key, None)
            if prev is not None and prev != addr:
                self._affinity_remaps += 1
            self._affinity_map[key] = addr
            while len(self._affinity_map) > self._AFFINITY_MAP_MAX:
                self._affinity_map.popitem(last=False)

    def _route_order(self, ps: "_PoolState", frame_or_batch,
                     first: int) -> List[int]:
        """The routing decision for one request: every conn index of
        ``ps``, best first (``routing`` policy within availability
        tiers, consistent-hash affinity owner promoted within its
        tier).  Known-down remotes always rank last so a hung server
        doesn't eat a full timeout per frame."""
        import time

        n = len(ps.conns)
        akey = self.props["affinity-key"]
        if n == 1 and not akey:
            return [0]  # single remote, no affinity ledger to keep
        now = time.monotonic()
        policy = self.props["routing"]
        tiers, inflight, scores = self._tiers_and_signals(
            ps, n, policy, now)
        owner = None
        if akey:
            f0 = (frame_or_batch[0] if isinstance(frame_or_batch, list)
                  else frame_or_batch)
            meta = getattr(f0, "meta", None)
            val = meta.get(akey) if meta is not None else None
            if val is None and akey == "prefix":
                # prefix affinity: derive the route key from the prompt
                # tensor itself (wire-default grain — client and server
                # must agree with no negotiation channel), so every
                # client sharing a prompt prefix lands on the one
                # rendezvous owner whose prefix KV pages are warm
                val = self._prefix_affinity_key(f0, meta)
            if val is not None:
                owner = rendezvous_owner(str(val), ps.targets)
                self._note_affinity(str(val), ps.targets[owner])
        return order_remotes(
            policy, tiers, first, n, inflight, scores, owner)

    @staticmethod
    def _prefix_affinity_key(frame, meta) -> Optional[str]:
        """Route key for ``affinity-key=prefix``: the chain digest of
        the prompt's declared (meta ``prefix_tokens``, rounded down to
        the wire grain) or first-grain prefix.  None — fall back to the
        plain policy order — when the frame carries no usable prompt
        tensor; an unroutable frame must never fail the send path."""
        tensors = getattr(frame, "tensors", None)
        if not tensors:
            return None
        try:
            declared = int((meta or {}).get("prefix_tokens", 0) or 0)
            return prefix_route_key(tensors[0], declared=declared)
        except Exception:
            return None

    def _inflight_begin(self, addr: str) -> None:
        with self._breakers_lock:
            self._remote_inflight[addr] = (
                self._remote_inflight.get(addr, 0) + 1)

    def _inflight_end(self, addr: str) -> None:
        with self._breakers_lock:
            v = self._remote_inflight.get(addr, 0) - 1
            if v <= 0:
                self._remote_inflight.pop(addr, None)
            else:
                self._remote_inflight[addr] = v

    def _rediscover(self, failed_ps: "_PoolState") -> bool:
        """Topic mode elastic recovery: refresh the server set from the
        broker and swap the connection pool.

        ``failed_ps`` is the pool the CALLER's failures happened on: one
        discovery per failure wave — workers whose failures predate an
        already-completed swap piggy-back on it; a worker whose failure
        was CAUSED by a swap (its pool is retired) or that belongs to a
        PREVIOUS run (epoch mismatch after stop/start) never triggers a
        cascade or a ghost resend into the new run.

        All network I/O (broker discovery, conn building, caps
        handshakes) happens OUTSIDE the swap lock so stop() and
        concurrent workers never wait out a discovery timeout; the lock
        only guards the pointer swap.  Endpoints unchanged across the
        swap REUSE their live connection (a healthy server must not have
        its channel closed under other workers' in-flight requests);
        vanished endpoints' conns are closed (those servers are gone —
        their requests are doomed anyway)."""
        import time as _time

        if not (self.props["topic"] and self.props["dest-port"] > 0):
            return False
        if self._stopped:
            return False
        cur = self._pstate
        if cur.epoch != failed_ps.epoch:
            return False  # stale worker from a previous run
        if cur.gen != failed_ps.gen:
            return True  # another worker already swapped this wave
        # leader election: a whole failure wave (up to max-in-flight
        # workers failing together) costs ONE broker discovery — losers
        # queue here and piggy-back on the leader's swap
        with self._discover_leader:
            if self._stopped:
                return False
            cur = self._pstate
            if cur.epoch != failed_ps.epoch:
                return False
            if cur.gen != failed_ps.gen:
                return True  # the leader swapped while we waited
            now = _time.monotonic()
            cooldown = max(1.0, float(self.props["discovery-timeout"]))
            if now - self._last_discovery_ts < cooldown:
                # persistently bad pool (e.g. a hung-but-accepting
                # server): don't convert EVERY frame's error path into a
                # discovery stall + broker round-trip
                return False
            self._last_discovery_ts = now
            try:
                targets = self._discover_targets()
            except (ElementError, OSError) as e:
                # incl. an unreachable broker (correlated failure):
                # refresh failure is non-fatal, the ORIGINAL error
                # surfaces
                self.log.warning("re-discovery failed: %s", e)
                return False
            by_ep = dict(zip(cur.targets, cur.conns))
            spec = self.sink_specs.get(0)
            conns, kept_targets, created = [], [], []
            for ep in targets:
                conn = by_ep.get(ep)
                if conn is None:
                    try:
                        conn = self._make_conns([ep])[0]
                        if spec is not None and spec.tensors:
                            conn.handshake(spec.to_string())
                    except Exception as e:  # noqa: BLE001 — transport
                        self.log.warning(
                            "replacement endpoint %s:%d unusable: %s "
                            "(skipped)", ep[0], ep[1], e,
                        )
                        continue
                    created.append(conn)
                conns.append(conn)
                kept_targets.append(ep)
            if not conns:
                self.log.warning(
                    "re-discovery found no usable server (all handshakes "
                    "failed)"
                )
                return False
            # only the tiny pointer swap shares a lock with stop()
            with self._rediscover_lock:
                if self._stopped:
                    retired, swapped = list(created), False
                else:
                    retired = [c for c in cur.conns if c not in conns]
                    self._pstate = _PoolState(
                        conns, kept_targets, cur.gen + 1, epoch=cur.epoch
                    )
                    swapped = True
        if swapped:
            self.log.info(
                "re-discovered %d server(s): %s", len(kept_targets),
                ",".join(f"{h}:{p}" for h, p in kept_targets),
            )
            # evict breakers for endpoints the swap dropped — ephemeral
            # pod IPs would otherwise grow the dict for the element's
            # lifetime; their trip history folds into one counter
            keep = {f"{h}:{p}" for h, p in kept_targets}
            with self._breakers_lock:
                for key in [k for k in self._breakers if k not in keep]:
                    self._evicted_breaker_trips += (
                        self._breakers.pop(key).trip_count)
                # span EWMAs for vanished endpoints go with them: frozen
                # rows would keep exporting as "live" load signals (and
                # grow the dict forever under pod-IP churn)
                for key in [k for k in self._remote_spans
                            if k not in keep]:
                    del self._remote_spans[key]
                self._spans_rev += 1  # evictions invalidate scores too
        for c in retired:
            try:
                c.close()
            except Exception:  # allow-silent: teardown of dead conns
                pass
        return swapped

    @staticmethod
    def _provably_unsent(err: BaseException) -> bool:
        """True when the failure class proves the request never reached a
        server, making a resend safe even under the at-most-once default:
        a refused dial (tcp), or a gRPC UNAVAILABLE whose detail is a
        connect failure (grpc wraps refused dials in RpcError)."""
        if isinstance(err, ConnectionRefusedError):
            return True
        try:
            import grpc

            if isinstance(err, grpc.RpcError):
                code = getattr(err, "code", lambda: None)()
                detail = str(getattr(err, "details", lambda: "")()).lower()
                return code == grpc.StatusCode.UNAVAILABLE and (
                    "connection refused" in detail
                    or "failed to connect" in detail
                )
        except ImportError:  # pragma: no cover
            pass
        return False

    def _request_timeout(self, frame, base: float):
        """Per-attempt timeout honoring the frames' deadline QoS budget:
        ``(timeout, expired)`` where timeout = min(configured, tightest
        remaining budget).  The remaining budget rides the wire
        (tcp_query header deadline_s / gRPC deadline) so the server can
        expire the work before invoke — end-to-end budget propagation."""
        frames = frame if isinstance(frame, list) else [frame]
        rem: Optional[float] = None
        for f in frames:
            r = deadline_remaining(f)
            if r is not None:
                rem = r if rem is None else min(rem, r)
        if rem is None:
            return base, False
        return min(base, rem), rem <= 0

    def _note_busy(self) -> None:
        with self._breakers_lock:  # pool workers race this counter
            self._busy_replies += 1

    def _note_goaway(self) -> None:
        with self._breakers_lock:
            self._goaway_replies += 1

    def _note_corruption(self) -> None:
        with self._breakers_lock:
            self._corruption_detected += 1

    def _note_delivered(self, n: int) -> None:
        with self._breakers_lock:
            self._delivered += n

    def _note_retried(self) -> None:
        with self._breakers_lock:
            self._retried += 1

    def _note_stream_resume(self, migration: bool) -> None:
        with self._breakers_lock:
            if migration:
                self._stream_migrations += 1
            else:
                self._stream_resumes += 1

    def _note_resume_failure(self) -> None:
        with self._breakers_lock:
            self._resume_failures += 1

    def _note_dup_tokens(self, n: int) -> None:
        with self._breakers_lock:
            self._duplicate_tokens_dropped += n

    def _resume_armed(self, cont) -> bool:
        """True when the continuity layer owns this stream's failures:
        resume enabled AND the chunks seen so far carried resume
        state."""
        return bool(self.props["stream-resume"]) and cont.capable

    def _note_expired(self) -> TimeoutError:
        with self._breakers_lock:
            self._deadline_expired += 1
        return TimeoutError(f"{self.name}: deadline expired mid-request")

    def _record_remote_failure(self, breaker, ps: "_PoolState", i: int,
                               err: BaseException, cooldown_s: float) -> None:
        """Shared breaker/cooldown classification for a failed attempt
        (unary + stream paths — one place so they cannot diverge): an
        application-level reply from a live server is HEALTH, anything
        else counts against the remote."""
        import time

        if is_remote_application_error(err):
            if breaker is not None:
                breaker.record_success()
            return
        if breaker is not None:
            breaker.record_failure()
        ps.down_until[i] = time.monotonic() + cooldown_s

    def _invoke_failover(self, frame, first: int, rediscovered: bool = False):
        """One request: try the assigned (healthy-first) server, fail over
        round-robin to the others, `retries` extra attempts total.
        ``frame`` may be a list (wire micro-batch) -> list comes back.

        BUSY replies (server admission shed) are transient backpressure,
        not failures: they never touch the breaker, and get their own
        ``busy-retries`` budget of RetryPolicy-paced re-sends (safe even
        at-most-once — an admission-refused request provably never
        executed).  Frames carrying a deadline stop retrying the moment
        their budget runs out.

        Topic mode: when every attempt fails, the server set is refreshed
        from the broker (pod membership may have changed) and the request
        retried ONCE against the new pool — but only when the failure
        class proves the request never reached a server or the user opted
        into at-least-once via retries>0; a timed-out request may have
        been ingested and must not silently re-execute."""
        import time

        ps = self._pstate  # ONE snapshot: swaps never shrink our indices
        if not ps.conns:
            raise RuntimeError(f"{self.name}: no connections (stopped?)")
        attempts = 1 + max(0, self.props["retries"])
        busy_budget = max(0, int(self.props["busy-retries"]))
        corrupt_budget = max(0, int(self.props["corrupt-retries"]))
        timeout = self.props["timeout"]
        retry_policy = self._retry_policy
        order = self._route_order(ps, frame, first)
        err: Optional[BaseException] = None
        open_err: Optional[BaseException] = None
        cursor = 0
        k = 0
        busy_used = 0
        corrupt_used = 0
        goaway_used = 0
        expired_terminal = False
        while k < attempts:
            if self._stopped:
                break
            req_timeout, expired = self._request_timeout(frame, timeout)
            if expired:
                # the frame's latency budget died during earlier attempts:
                # an answer can no longer matter — stop burning remotes
                err = self._note_expired()
                expired_terminal = True
                break
            # next remote whose breaker admits a call — open breakers are
            # skipped WITHOUT consuming a retry attempt (failing fast on a
            # known-dead remote must not shrink the budget for live ones)
            i = breaker = None
            for _ in range(len(order)):
                cand = order[cursor % len(order)]
                cursor += 1
                b = self._breaker_for(ps.targets[cand])
                if b is None or b.allow():
                    i, breaker = cand, b
                    break
                open_err = CircuitOpenError(
                    f"{ps.conns[cand].addr} circuit {b.state}")
            if i is None:
                # every remote's breaker is open: burn this attempt on the
                # backoff instead of failing the whole budget instantly —
                # the reset window may grant a half-open probe before the
                # attempts run out (a 1s blip must not drop 5s of frames)
                k += 1
                if k < attempts and not self._stopped:
                    delay = retry_policy.delay_for(k)
                    if delay > 0:
                        time.sleep(delay)
                    continue
                break
            conn = ps.conns[i]
            try:
                t_send = time.perf_counter()
                # live per-remote in-flight count: the least-inflight
                # routing signal (self-cleaning dict — see __init__)
                addr_i = ps.addrs[i]
                self._inflight_begin(addr_i)
                try:
                    if isinstance(frame, list):
                        result = conn.invoke_batch(frame, req_timeout)
                    else:
                        result = conn.invoke(frame, req_timeout)
                finally:
                    self._inflight_end(addr_i)
                t_recv = time.perf_counter()
                ps.down_until.pop(i, None)
                if breaker is not None:
                    breaker.record_success()
                self._note_delivered(
                    len(frame) if isinstance(frame, list) else 1)
                self._note_span(ps.targets[i], frame, result, t_send, t_recv)
                return result
            except ServerGoawayError as e:
                # rolling restart: the host is draining.  The request
                # provably never executed (refused before ingest), the
                # reply is health (record_success — a planned restart
                # must never trip a breaker), and the failover is
                # IMMEDIATE: no pacing is owed to a host that asked us
                # to leave.  One free rotation per remote, then GOAWAYs
                # consume attempts (every host draining at once must not
                # spin).
                err = e
                self._note_goaway()
                if breaker is not None:
                    breaker.record_success()
                # deprioritize the draining host for subsequent frames
                # (healthy-first ordering; it still gets re-tried once
                # the cooldown lapses — i.e. after its restart)
                ps.down_until[i] = time.monotonic() + min(
                    float(timeout), 5.0)
                self.log.debug(
                    "server %s is draining (goaway); failing over",
                    conn.addr,
                )
                if goaway_used < len(order) and not self._stopped:
                    goaway_used += 1
                    self._note_retried()
                    continue  # immediate, unpaced failover
                k += 1
                if k < attempts and not self._stopped:
                    self._note_retried()
                    delay = retry_policy.delay_for(k)
                    if delay > 0:
                        time.sleep(delay)
            except ServerBusyError as e:
                err = e
                self._note_busy()
                if breaker is not None:
                    # the server ANSWERED (instantly, at admission): this
                    # is the healthiest a refusal gets — never a trip
                    breaker.record_success()
                if busy_used < busy_budget and not self._stopped:
                    busy_used += 1  # own budget: attempts stay intact
                    self._note_retried()
                    delay = max(e.retry_after,
                                retry_policy.delay_for(busy_used))
                    self.log.debug(
                        "server %s busy (shed %d/%d); retrying in %.3fs",
                        conn.addr, busy_used, busy_budget, delay,
                    )
                    if delay > 0:
                        time.sleep(delay)
                    continue  # rotate to the next remote, paced
                # busy budget exhausted: consumes attempts now — but
                # still paced (honoring retry_after): hammering an
                # already-shedding server with back-to-back attempts
                # would amplify the very overload BUSY exists to relieve
                k += 1
                if k < attempts and not self._stopped:
                    self._note_retried()
                    delay = max(e.retry_after, retry_policy.delay_for(k))
                    if delay > 0:
                        time.sleep(delay)
            except WireError as e:
                # detected corruption — request refused ('C'/DATA_LOSS)
                # or reply failed verification.  Resend-safe either way
                # (see corrupt-retries prop doc), so it gets its own
                # paced budget; unlike BUSY it IS a health signal: each
                # event counts toward the breaker, so one flipped bit
                # never trips it but a rotten link does.
                err = e
                self._note_corruption()
                if breaker is not None:
                    breaker.record_failure()
                self.log.warning(
                    "corrupt exchange with %s (attempt %d/%d): %s",
                    conn.addr, k + 1, attempts, e,
                )
                if corrupt_used < corrupt_budget and not self._stopped:
                    corrupt_used += 1  # own budget: attempts stay intact
                    self._note_retried()
                    delay = retry_policy.delay_for(corrupt_used)
                    if delay > 0:
                        time.sleep(delay)
                    continue
                k += 1
                if k < attempts and not self._stopped:
                    self._note_retried()
                    delay = retry_policy.delay_for(k)
                    if delay > 0:
                        time.sleep(delay)
            except Exception as e:  # noqa: BLE001 — transport boundary
                err = e
                # app-error replies (poison frames, full ingress) are
                # HEALTH — retries may still help elsewhere; transport
                # faults trip the breaker and cool the remote down
                self._record_remote_failure(breaker, ps, i, e, timeout)
                self.log.warning(
                    "query to %s failed (attempt %d/%d): %s",
                    conn.addr, k + 1, attempts, e,
                )
                k += 1
                if k < attempts:
                    # RetryPolicy backoff between failover attempts so a
                    # flapping link isn't hammered (capped exponential +
                    # seeded jitter)
                    self._note_retried()
                    delay = retry_policy.delay_for(k)
                    if delay > 0:
                        time.sleep(delay)
        if err is None:
            if open_err is not None:
                err = open_err  # every remote breaker-open, nothing tried
            else:  # stopped before any attempt
                raise RuntimeError(f"{self.name}: stopped mid-request")
        if expired_terminal:
            # no answer can matter anymore: skip rediscovery/resend
            raise err
        safe_to_resend = (
            self.props["retries"] > 0
            or self._provably_unsent(err)
            # breaker-open / admission-shed / goaway never reached the
            # pipeline; detected corruption is resend-safe by the
            # integrity contract (corrupt-retries prop doc)
            or isinstance(err, (CircuitOpenError, ServerBusyError,
                                ServerGoawayError, WireError))
        )
        if not rediscovered and self._rediscover(ps) and safe_to_resend:
            return self._invoke_failover(frame, first, rediscovered=True)
        raise err  # all attempts failed -> degrade= / bus decides from here

    _DRAIN_EVENT = "_nns_query_drain"

    def _notify_done(self, _fut) -> None:
        """Future-completion callback (pool thread): wake the worker so a
        LIVE stream emits answers as they land — without this, responses
        to the last frames of a burst sit in the in-flight window until
        the next frame or EOS arrives (latency bug for sparse streams).
        Best-effort: a full mailbox means the worker is busy and will
        drain on its next frame anyway."""
        box = self._mailbox
        if box is None:
            return  # stopping
        try:
            box.put_nowait((0, CustomEvent(self._DRAIN_EVENT, {})))
        except _queue.Full:
            pass

    def handle_event(self, pad, ev):
        if isinstance(ev, CustomEvent) and ev.name == self._DRAIN_EVENT:
            return self._drain_ready(block_all=False)  # swallow the tick
        return super().handle_event(pad, ev)

    def handle_frame(self, pad, frame):
        # one shared path: blocks flatten onto the wire micro-batch envelope
        return self.handle_frame_batch(pad, [frame])

    # scheduler micro-batch hooks: with wire-batch > 1 the pipeline drains
    # already-queued frames into handle_frame_batch (batch_wait_s = 0 so
    # batching never ADDS latency — a lone frame still ships immediately)
    @property
    def preferred_batch(self) -> int:
        return max(1, int(self.props["wire-batch"]))

    batch_wait_s = 0.0

    def handle_frame_batch(self, pad, frames):
        if any(isinstance(f, BatchFrame) for f in frames):
            logical: List[TensorFrame] = []
            for f in frames:
                logical.extend(f.split() if isinstance(f, BatchFrame) else [f])
            frames = logical
        # trace context (core/telemetry.py): trace_id crosses the wire
        # (and is minted here when no upstream recorder stamped one); the
        # enqueue instant stays host-local (TL_ prefix, stripped at
        # encode) and anchors the client-queue span segment
        import time as _time

        now = _time.perf_counter()
        tenant = self.props["tenant"]
        prio = int(self.props["priority"])
        for f in frames:
            m = f.meta
            if TRACE_ID_META not in m:
                m[TRACE_ID_META] = new_trace_id()
            m[TL_ENQ_META] = now
            # tenant identity / priority class ride ordinary meta (JSON
            # blob on the wire, both transports); frames stamped by an
            # upstream multi-tenant ingest keep their own identity
            if tenant and TENANT_META not in m:
                m[TENANT_META] = tenant
            if prio != PRIORITY_MAX and PRIORITY_META not in m:
                m[PRIORITY_META] = prio
        if self.props["stream"]:
            # sequential per-request streams: chunk frames of request j
            # leave BEFORE request j+1 is sent (the scheduler pushes each
            # yielded frame immediately).  degrade= applies here too, but
            # only to requests that produced NO answer yet — a stream
            # broken mid-way surfaces as an error regardless (its partial
            # output already left; neither skip nor passthrough can
            # retract it).
            def streams():
                for f in frames:
                    emitted = 0
                    try:
                        for out in self._stream_invoke(f):
                            emitted += 1
                            yield out
                    except Exception as e:  # noqa: BLE001 — transport
                        mode = self.props["degrade"]
                        if emitted or mode not in ("passthrough", "skip"):
                            raise
                        self._note_degraded(1, mode, e)
                        if mode == "passthrough":
                            yield (0, f)

            return streams()
        if len(frames) == 1:
            return self._dispatch(frames[0])
        return self._dispatch(list(frames))

    def _stream_invoke(self, frame):
        """One LOGICAL server-streaming request, SLO-accounted: the
        resume/migration loop below does the work; with ``slo-*``
        objectives armed the wrapper stamps client-observed TTFT on the
        first chunk, per-token inter-arrival per chunk, and classifies
        the terminal outcome (good / shed / expired / error) — per
        tenant, across every failover and resume, because what the USER
        experienced is the stream end-to-end, not one transport
        attempt."""
        gen = self._stream_resume_loop(frame)
        if self._slo is None:
            return gen
        return self._slo_wrap_stream(frame, gen)

    def _slo_wrap_stream(self, frame, gen):
        import time as _time

        slo = self._slo
        tenant = str(frame.meta.get(TENANT_META, "") or "")
        t_prev = _time.perf_counter()
        first = True
        expired = False
        try:
            for item in gen:
                out = item[1]
                n = 0
                if out.tensors:
                    t0 = out.tensors[0]
                    n = (int(t0.shape[1])
                         if getattr(t0, "ndim", 0) == 2 else 1)
                if n > 0:
                    now = _time.perf_counter()
                    if first:
                        slo.note_ttft(tenant, now - t_prev)
                        first = False
                    else:
                        slo.note_tokens(tenant, now - t_prev, n)
                    t_prev = now
                if out.meta.get("deadline_expired"):
                    # server-side typed expiry: the stream was answered
                    # with partial tokens, but the budget was blown
                    expired = True
                yield item
        except GeneratorExit:
            raise  # consumer abandoned the generator: not an outcome
        except ServerBusyError:
            slo.note_stream(tenant, "shed")
            raise
        except TimeoutError:
            slo.note_stream(tenant, "expired")
            raise
        except BaseException:
            slo.note_stream(tenant, "error")
            raise
        slo.note_stream(tenant, "expired" if expired else "good")

    def _stream_resume_loop(self, frame):
        """One LOGICAL server-streaming request across any number of
        servers (Documentation/resilience.md "Stream continuity").

        Transport attempts run in :meth:`_stream_attempt`.  When an
        attempt of a RESUMABLE stream (chunks carry resume state) is
        interrupted — mid-stream crash, a draining server's GOAWAY
        handoff, or a resume rejection — the continuity ledger builds a
        RESUME request from the original prompt plus the delivered
        prefix and re-routes it through the normal healthy-first
        ordering; the ledger dedupes the re-decoded overlap, so
        delivered tokens stay exactly-once and bit-identical to an
        uninterrupted run.  Progress refills the resume budget (a long
        stream survives arbitrarily many rolling restarts); exhaustion
        fires a flight-recorder incident and surfaces the break."""
        import time as _time

        from ..core.continuity import StreamContinuity

        cont = StreamContinuity(frame)
        budget = max(0, int(self.props["resume-retries"]))
        left = budget
        resuming = False
        last_delivered = 0
        req = frame
        while True:
            try:
                yield from self._stream_attempt(req, cont)
                return
            except _StreamInterrupt as si:
                if not self._resume_armed(cont):
                    # stream-resume=false: legacy semantics — the
                    # handoff/reject surfaces instead of resuming
                    raise si.cause
                progressed = cont.delivered > last_delivered
                last_delivered = cont.delivered
                counted = False
                if si.kind == "reject":
                    self._note_resume_failure()
                    counted = True
                elif (resuming and not progressed
                      and si.kind == "break"):
                    # the previous resume restored nothing before
                    # breaking again — that attempt failed (a handoff
                    # without progress is a planned migration, not a
                    # failed resume)
                    self._note_resume_failure()
                    counted = True
                if progressed:
                    left = budget
                if left <= 0 or self._stopped:
                    if not counted:
                        self._note_resume_failure()
                    self.log.warning(
                        "stream resume budget exhausted after %d "
                        "delivered token(s); surfacing: %s",
                        cont.delivered, si.cause)
                    p = self._pipeline
                    if p is not None:
                        p.incident(
                            "resume_exhausted", self.name,
                            f"{cont.delivered} token(s) delivered; "
                            f"cause: {si.cause}")
                    raise si.cause
                left -= 1
                if si.kind == "break":
                    # crash resumes are paced like failover attempts (a
                    # fleet-wide outage must not spin); a planned
                    # handoff migrates immediately
                    delay = self._retry_policy.delay_for(budget - left)
                    if delay > 0:
                        _time.sleep(delay)
                fresh_break = not resuming or progressed
                resuming = True
                try:
                    req = cont.build_resume_frame()
                except RuntimeError as e:
                    self._note_resume_failure()
                    self.log.warning("cannot build resume request: %s", e)
                    raise si.cause from e
                # exactly ONE count per logical recovery, so the fleet
                # cross-check 'client resumes + migrations == engine
                # gen_resumes' holds whenever resume_failures == 0: a
                # reject retry and a break-retry of a no-progress
                # resume continue the SAME recovery (already counted as
                # a failure; a rejecting/unreached server never
                # submits), while every handoff is its own migration
                if si.kind == "handoff":
                    self._note_stream_resume(migration=True)
                elif si.kind == "break" and fresh_break:
                    self._note_stream_resume(migration=False)

    def _stream_attempt(self, frame, cont, rediscovered: bool = False):
        """One transport attempt of a server-streaming request:
        healthy-first server order, whole streams fail over only BEFORE
        the first answer arrives.  Topic mode recovers elastically like
        the unary path: pre-first-answer failure of all attempts
        refreshes the pool and retries once under the same
        resend-safety contract.

        Mid-stream events route through ``cont`` (the stream-continuity
        ledger): a crash is classified as remote ill-health (breaker +
        cooldown) and then — for resumable streams — handed to
        :meth:`_stream_invoke` as a :class:`_StreamInterrupt`; a
        draining server's resumable GOAWAY handoff chunk is a planned
        migration (breaker-immune, brief deprioritization only, never
        the crash cooldown); non-resumable streams keep the legacy
        semantics (a mid-stream break surfaces as an error — replaying
        half a generation blind could duplicate tokens)."""
        import time as _time

        ps = self._pstate  # snapshot (same contract as _invoke_failover)
        if not ps.conns:
            raise RuntimeError(f"{self.name}: no connections (stopped?)")
        order = self._route_order(ps, frame, self._rr % len(ps.conns))
        self._rr += 1
        # retries=0 means SINGLE attempt: a request the server may already
        # have ingested must not be silently re-executed elsewhere unless
        # the user opted into at-least-once via retries>0 (same contract
        # as _invoke_failover)
        attempts = min(len(order), 1 + max(0, self.props["retries"]))
        timeout = self.props["timeout"]
        err: Optional[BaseException] = None
        open_err: Optional[BaseException] = None
        tried = 0
        busy_budget = max(0, int(self.props["busy-retries"]))
        busy_used = 0
        goaway_used = 0
        expired_terminal = False
        deadline_ts = frame.meta.get(DEADLINE_META)
        cursor = 0
        refused = 0  # consecutive breaker refusals (bounds the rotation)
        while tried < attempts and refused < len(order):
            i = order[cursor % len(order)]
            cursor += 1
            conn = ps.conns[i]
            breaker = self._breaker_for(ps.targets[i])
            if breaker is not None and not breaker.allow():
                # refused by the breaker: note it separately (it must
                # never mask a real transport error) and don't consume an
                # attempt slot — the next healthy remote must still get
                # its dial (same contract as the unary path)
                open_err = CircuitOpenError(
                    f"{conn.addr} circuit {breaker.state}")
                refused += 1
                continue
            refused = 0
            tried += 1
            started = False
            try:
                req_timeout, expired = self._request_timeout(frame, timeout)
                if expired:
                    err = self._note_expired()
                    expired_terminal = True
                    break
                addr_i = ps.addrs[i]
                reject = None
                self._inflight_begin(addr_i)
                try:
                    for ans in conn.invoke_stream(frame, req_timeout):
                        started = True
                        ps.down_until.pop(i, None)
                        if deadline_ts is not None:
                            ans.meta[DEADLINE_META] = deadline_ts
                        if ans.meta.get("deadline_expired"):
                            # server-side slot eviction (typed expiry):
                            # the stream was ANSWERED with its partial
                            # tokens — count the blown budget without
                            # discarding what already decoded
                            self._note_expired()
                        # stream continuity: the ledger dedupes
                        # post-resume overlap, keeps the downstream
                        # chunk numbering contiguous across servers,
                        # and spots handoff/reject markers; chunks
                        # without resume state pass through untouched
                        v = cont.accept(ans)
                        if v.dup:
                            self._note_dup_tokens(v.dup)
                        if v.reject is not None:
                            reject = v.reject
                            break
                        if v.emit is not None:
                            yield (0, v.emit)
                finally:
                    self._inflight_end(addr_i)
                if reject is not None:
                    # this server REFUSED the resume with a typed
                    # terminal chunk (signature/digest mismatch): the
                    # framing stayed aligned and the server is healthy
                    # — another server may still match
                    if breaker is not None:
                        breaker.record_success()
                    # handoff/reject markers only exist on resumable
                    # chunks: ALWAYS route through the continuity
                    # wrapper (it surfaces the cause when stream-resume
                    # is off) — raising the bare error here would be
                    # caught by the pre-first-answer handlers below and
                    # silently replay a half-delivered stream
                    raise _StreamInterrupt(RemoteApplicationError(
                        f"resume refused by {conn.addr}: {reject}"),
                        "reject")
                if cont.take_handoff():
                    # live migration: the draining server flushed this
                    # stream as a resumable final chunk.  A PLANNED
                    # restart, not a failure — breaker records health
                    # and the host is only briefly deprioritized (the
                    # unary-GOAWAY treatment), never the crash path's
                    # 10s cooldown or breaker failure
                    if breaker is not None:
                        breaker.record_success()
                    ps.down_until[i] = _time.monotonic() + min(
                        float(timeout), 5.0)
                    raise _StreamInterrupt(ServerGoawayError(
                        f"{conn.addr} handed the stream off mid-"
                        "generation (draining)"), "handoff")
                if breaker is not None:
                    # success is recorded on clean COMPLETION (empty
                    # streams included — a half-open probe slot must not
                    # leak), never on the first answer: a server that
                    # reliably crashes mid-stream would otherwise clear
                    # its failure window every request and never trip
                    breaker.record_success()
                self._note_delivered(1)
                return
            except _StreamInterrupt:
                raise  # continuity control flow, classified above
            except ServerGoawayError as e:
                # rolling restart: only ever raised BEFORE the first
                # answer (refused pre-ingest) — immediate unpaced
                # failover, breaker-immune, one refunded attempt per
                # remote (all-hosts-draining must not spin)
                err = e
                self._note_goaway()
                if breaker is not None:
                    breaker.record_success()
                ps.down_until[i] = _time.monotonic() + min(
                    float(timeout), 5.0)
                if goaway_used < len(order) and not self._stopped:
                    goaway_used += 1
                    tried -= 1
                elif tried < attempts and not self._stopped:
                    # free-rotation budget exhausted (every host draining
                    # at once): consumed attempts stay PACED like the
                    # unary path — never burn the whole budget in a
                    # microsecond spin
                    delay = self._retry_policy.delay_for(tried)
                    if delay > 0:
                        _time.sleep(delay)
                continue
            except ServerBusyError as e:
                # admission shed: only ever raised BEFORE the first
                # answer; backpressure, never a breaker/health event
                err = e
                self._note_busy()
                if breaker is not None:
                    breaker.record_success()
                if busy_used < busy_budget and not self._stopped:
                    busy_used += 1
                    tried -= 1  # own budget: the attempt slot survives
                    delay = max(e.retry_after,
                                self._retry_policy.delay_for(busy_used))
                    if delay > 0:
                        _time.sleep(delay)
                elif tried < attempts and not self._stopped:
                    # budget exhausted: attempts are consumed, but still
                    # paced — never hammer a shedding server
                    delay = max(e.retry_after,
                                self._retry_policy.delay_for(tried))
                    if delay > 0:
                        _time.sleep(delay)
                continue
            except Exception as e:  # noqa: BLE001 — transport boundary
                if isinstance(e, WireError):
                    # corrupt exchange (request refused / answer chunk
                    # failed verification): counted like the unary path
                    self._note_corruption()
                if started:
                    # mid-stream break: a health signal either way (a
                    # server that repeatedly dies mid-stream must stop
                    # winning the healthy-first ordering), so breaker +
                    # crash cooldown are recorded FIRST.  With resume
                    # state armed there now IS a safe replay: the
                    # continuity ledger re-prefills the delivered
                    # prefix elsewhere and dedupes the overlap — only
                    # streams without resume state keep the legacy
                    # no-replay error
                    if not is_remote_application_error(e):
                        if breaker is not None:
                            breaker.record_failure()
                        ps.down_until[i] = _time.monotonic() + min(
                            float(timeout), 10.0)
                    if self._resume_armed(cont):
                        raise _StreamInterrupt(e, "break") from e
                    raise
                err = e
                # short cooldown (10s cap): the stream timeout is
                # minutes-scale (a whole generation), not a health signal
                self._record_remote_failure(
                    breaker, ps, i, e, min(float(timeout), 10.0))
                self.log.warning(
                    "stream to %s failed before first answer: %s",
                    conn.addr, e,
                )
        if err is None:
            err = open_err  # only breaker refusals happened (or nothing)
        if expired_terminal:
            raise err  # no answer can matter anymore: no rediscover/resume
        if err is not None and not rediscovered:
            safe = (
                self.props["retries"] > 0
                or self._provably_unsent(err)
                # breaker-open / admission-shed / goaway: never reached
                # the pipeline; detected corruption is resend-safe
                or isinstance(err, (CircuitOpenError, ServerBusyError,
                                    ServerGoawayError, WireError))
            )
            if self._rediscover(ps) and safe:
                yield from self._stream_attempt(frame, cont,
                                                rediscovered=True)
                return
        if err is None:
            raise RuntimeError("no servers")
        if self._resume_armed(cont) and cont.delivered > 0:
            # a RESUME attempt died before its first answer: the stream
            # still holds delivered tokens — hand control back to the
            # budget-paced continuity loop instead of killing it
            raise _StreamInterrupt(err, "break")
        raise err

    def _note_degraded(self, n: int, mode: str, err: BaseException) -> None:
        """Shared degrade bookkeeping (unary + stream paths): counter,
        log, bus warning — one place so the two paths cannot diverge."""
        with self._breakers_lock:  # pool workers race this counter
            self._degraded += n
        self.log.warning(
            "all remotes failed for %d frame(s); degrade=%s: %s",
            n, mode, err,
        )
        if self._pipeline is not None:
            from ..pipeline.pipeline import BusMessage

            self._pipeline.post(BusMessage("warning", self.name, {
                "degrade": mode, "frames": n, "error": err,
            }))

    @staticmethod
    def _carry_deadline(req, ans):
        """Answers inherit their request's deadline (instants never cross
        the wire — liveness.DEADLINE_META is host-local), so an answer
        that arrives after the budget died is expired downstream with
        exact accounting instead of delivered late."""
        reqs = req if isinstance(req, list) else [req]
        answers = ans if isinstance(ans, list) else [ans]
        for i, a in enumerate(answers):
            if a is None:
                continue
            src = reqs[i] if i < len(reqs) else reqs[-1]
            ts = src.meta.get(DEADLINE_META)
            if ts is not None:
                a.meta[DEADLINE_META] = ts
        return ans

    def _invoke_or_degrade(self, frame_or_batch, first: int):
        """`_invoke_failover` + the degrade= contract: when every remote
        and retry is exhausted, either surface the error (default), pass
        the unanswered request frame(s) through, or drop them — so one
        dead pod degrades the stream instead of killing the pipeline."""
        try:
            return self._carry_deadline(
                frame_or_batch,
                self._invoke_failover(frame_or_batch, first))
        except Exception as e:  # noqa: BLE001 — transport boundary
            mode = self.props["degrade"]
            if mode not in ("passthrough", "skip"):
                raise
            n = len(frame_or_batch) if isinstance(frame_or_batch, list) else 1
            self._note_degraded(n, mode, e)
            if mode == "passthrough":
                return frame_or_batch
            return [] if isinstance(frame_or_batch, list) else None

    def pending_frames(self) -> int:
        """Logical frames whose answers have not been emitted yet
        (drain/stop accounting, ``Pipeline._count_abandoned``)."""
        return sum(
            getattr(f, "_nns_logical", 1) for f in list(self._inflight)
        )

    def _dispatch(self, frame_or_batch):
        first = self._rr % max(1, len(self._pstate.conns))
        self._rr += 1
        fut = self._pool.submit(self._invoke_or_degrade, frame_or_batch, first)
        fut._nns_logical = (
            len(frame_or_batch) if isinstance(frame_or_batch, list) else 1
        )
        fut.add_done_callback(self._notify_done)
        self._inflight.append(fut)
        # backpressure: block on the oldest request once the in-flight window
        # is full, then release whatever is complete (in order); bounded —
        # a wedged worker must surface, not hang the stream silently
        if len(self._inflight) >= max(1, self.props["max-in-flight"]):
            self._await(self._inflight[0])
        return self._drain_ready(block_all=False)

    def handle_eos(self, pad):
        return self._drain_ready(block_all=True)
