"""File media sources: raw bytes, .y4m video, .wav audio, text.

The reference gets media into pipelines through stock GStreamer sources
(``filesrc``, ``v4l2src``, ``multifilesrc``) plus parsers/converters; its
tensor_converter then ingests negotiated ``video/x-raw``/``audio/x-raw``/
``text/x-raw``/octet buffers (``gsttensor_converter.c:750-1005``).  These
elements are the framework's own front door for the same pipelines:

- ``filesrc``: raw byte chunks (``blocksize`` per buffer), octet media —
  pairs with ``tensor_converter input-dim=/input-type=``;
- ``videofilesrc`` (alias ``y4msrc``): .y4m file -> ``video/x-raw``
  payloads in RGB/BGRx/GRAY8 with rows padded to 4 bytes, exactly the
  layout the converter's stride removal expects;
- ``audiofilesrc`` (alias ``wavsrc``): .wav -> ``audio/x-raw`` payloads of
  ``samples-per-buffer`` frames;
- ``textfilesrc``: one line per buffer as ``text/x-raw``.

Payload convention: ``tensors[0]`` is a 1-D uint8 array of the raw media
bytes; ``meta["media"]`` carries the :class:`MediaInfo`; the advertised
schema is a :class:`MediaSpec` so ``tensor_converter`` derives the exact
tensor schema during static negotiation.
"""

from __future__ import annotations

from typing import Iterator, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..media.caps import MediaInfo, MediaSpec
from ..pipeline.element import ElementError, Property, SourceElement, element


def _pad_rows(img: np.ndarray, stride: int) -> np.ndarray:
    """(h, w, c) -> flat bytes with each row padded to `stride` bytes."""
    h = img.shape[0]
    flat = img.reshape(h, -1)
    if flat.shape[1] == stride:
        return flat.reshape(-1)
    out = np.zeros((h, stride), np.uint8)
    out[:, : flat.shape[1]] = flat
    return out.reshape(-1)


class _MediaSource(SourceElement):
    def _media_frame(
        self, payload: np.ndarray, media: MediaInfo,
        pts: Optional[float] = None, duration: Optional[float] = None,
    ) -> TensorFrame:
        f = TensorFrame([payload], pts=pts, duration=duration)
        f.meta["media"] = media
        return f


@element("filesrc")
class FileSrc(_MediaSource):
    """Raw file bytes in ``blocksize`` chunks (≙ GStreamer filesrc)."""

    PROPERTIES = {
        "location": Property(str, "", "file path"),
        "blocksize": Property(int, 4096, "bytes per buffer"),
        "num-buffers": Property(int, -1, "stop after N buffers (-1 = all)"),
    }

    def output_spec(self):
        return MediaSpec(media=MediaInfo("octet"))

    def frames(self) -> Iterator[TensorFrame]:
        path = self.props["location"]
        if not path:
            raise ElementError(f"{self.name}: location= is required")
        media = MediaInfo("octet")
        limit = self.props["num-buffers"]
        n = 0
        with open(path, "rb") as f:
            while limit < 0 or n < limit:
                chunk = f.read(self.props["blocksize"])
                if not chunk:
                    return
                yield self._media_frame(np.frombuffer(chunk, np.uint8), media)
                n += 1


@element("videofilesrc", "y4msrc")
class VideoFileSrc(_MediaSource):
    """.y4m file -> video/x-raw payloads (RGB/BGRx/GRAY8, 4-byte row
    stride, BT.601 conversion in ``media/y4m.py``)."""

    PROPERTIES = {
        "location": Property(str, "", ".y4m file path"),
        "format": Property(str, "RGB", "RGB|BGRx|GRAY8 output pixel format"),
        "num-buffers": Property(int, -1, "stop after N frames (-1 = all)"),
        "loop": Property(bool, False, "restart at EOF (stream soak tests)"),
    }

    def _media(self) -> MediaInfo:
        from ..media.y4m import Y4MReader

        with Y4MReader(self.props["location"]) as r:
            return MediaInfo(
                "video", self.props["format"],
                width=r.width, height=r.height, framerate=r.framerate,
            )

    def output_spec(self):
        if not self.props["location"]:
            raise ElementError(f"{self.name}: location= is required")
        return MediaSpec(media=self._media())

    def frames(self) -> Iterator[TensorFrame]:
        from ..media.y4m import Y4MReader

        media = self._media()
        fmt = self.props["format"]
        dt = (
            float(1 / media.framerate) if media.framerate else None
        )
        limit = self.props["num-buffers"]
        n = 0
        while True:
            with Y4MReader(self.props["location"]) as r:
                for rgb in r.frames_rgb():
                    if limit >= 0 and n >= limit:
                        return
                    if fmt == "RGB":
                        img = rgb
                    elif fmt == "BGRx":
                        img = np.concatenate(
                            [rgb[..., ::-1],
                             np.full(rgb.shape[:2] + (1,), 255, np.uint8)],
                            axis=-1,
                        )
                    elif fmt == "GRAY8":
                        # BT.601 luma of the already-converted RGB
                        img = np.clip(
                            0.299 * rgb[..., 0] + 0.587 * rgb[..., 1]
                            + 0.114 * rgb[..., 2], 0, 255,
                        ).astype(np.uint8)[..., None]
                    else:
                        raise ElementError(
                            f"{self.name}: unsupported format {fmt!r}"
                        )
                    payload = _pad_rows(img, media.stride)
                    yield self._media_frame(
                        payload, media,
                        pts=n * dt if dt is not None else None, duration=dt,
                    )
                    n += 1
            if not self.props["loop"]:
                return


@element("imagefilesrc", "multifilesrc")
class ImageFileSrc(_MediaSource):
    """Still images (PNG/JPEG/BMP via Pillow) -> video/x-raw payloads
    (≙ ``multifilesrc ! pngdec/jpegdec ! videoconvert``).

    ``location`` is one path, a comma list, or a glob pattern; all images
    must share one size (the stream schema is static, like the
    reference's caps).  ``framerate`` spaces pts for downstream
    rate/sync elements."""

    PROPERTIES = {
        "location": Property(
            str, "",
            "path, comma list, glob, or printf pattern (img_%04d.png)",
        ),
        "format": Property(str, "RGB", "RGB|GRAY8 output pixel format"),
        "framerate": Property(str, "30/1", "pts spacing, N/D"),
        "num-buffers": Property(int, -1, "stop after N frames (-1 = all)"),
        "loop": Property(bool, False, "cycle the file list forever"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._cached: Optional[tuple] = None  # (paths, MediaInfo)

    def _fps(self):
        from fractions import Fraction

        n, _, d = self.props["framerate"].partition("/")
        den = int(d or "1")
        if den == 0:
            raise ElementError(
                f"{self.name}: bad framerate {self.props['framerate']!r}"
            )
        # exact Fraction (24000/1001 stays canonical in the caps);
        # 0/1 = GStreamer's still-image rate -> no pts spacing
        return Fraction(int(n), den)

    def _scan(self):
        """(paths, MediaInfo) — globbed and probed ONCE per start."""
        if self._cached is not None:
            return self._cached
        import glob as _glob

        from ..media.image import read_image

        loc = self.props["location"]
        if not loc:
            raise ElementError(f"{self.name}: location= is required")
        if "," in loc:
            paths = [p.strip() for p in loc.split(",") if p.strip()]
        elif any(ch in loc for ch in "*?["):
            paths = sorted(_glob.glob(loc))
        else:
            from .datarepo import _fmt_sample_path, _is_image_pattern

            if _is_image_pattern(loc):
                # canonical multifilesrc form: img_%04d.png, indexed from
                # 0 until the first gap
                import os as _os

                paths = []
                i = 0
                while _os.path.exists(_fmt_sample_path(loc, i)):
                    paths.append(_fmt_sample_path(loc, i))
                    i += 1
            else:
                paths = [loc]
        if not paths:
            raise ElementError(f"{self.name}: no files match {loc!r}")
        first = read_image(paths[0], self.props["format"])
        media = MediaInfo(
            "video", self.props["format"],
            width=first.shape[1], height=first.shape[0],
            framerate=self._fps(),
        )
        self._cached = (paths, media)
        return self._cached

    def start(self):
        self._cached = None  # re-scan on every run (files may change)
        self._scan()

    def output_spec(self):
        return MediaSpec(media=self._scan()[1])

    def frames(self) -> Iterator[TensorFrame]:
        from ..media.image import read_image

        paths, media = self._scan()
        fmt = self.props["format"]
        fps = self._fps()
        dt = float(1 / fps) if fps else None
        limit = self.props["num-buffers"]
        n = 0
        while True:
            for p in paths:
                if limit >= 0 and n >= limit:
                    return
                img = read_image(p, fmt)
                if (img.shape[0], img.shape[1]) != (media.height, media.width):
                    raise ElementError(
                        f"{self.name}: {p} is {img.shape[1]}x{img.shape[0]}"
                        f", stream is {media.width}x{media.height} (static "
                        "schema; resize your images or split the pipeline)"
                    )
                payload = _pad_rows(img, media.stride)
                f = self._media_frame(
                    payload, media,
                    pts=n * dt if dt is not None else None, duration=dt,
                )
                f.meta["filename"] = p
                yield f
                n += 1
            if not self.props["loop"]:
                return


@element("audiofilesrc", "wavsrc")
class AudioFileSrc(_MediaSource):
    """.wav file -> audio/x-raw payloads of ``samples-per-buffer`` frames."""

    PROPERTIES = {
        "location": Property(str, "", ".wav file path"),
        "samples-per-buffer": Property(int, 1024, "audio frames per buffer"),
        "num-buffers": Property(int, -1, "stop after N buffers (-1 = all)"),
    }

    def _read(self):
        from ..media.wav import read_wav

        return read_wav(self.props["location"])

    def _media_of(self, rate: int, channels: int, fmt: str) -> MediaInfo:
        return MediaInfo(
            "audio", fmt, rate=rate, channels=channels,
            samples_per_buffer=max(1, self.props["samples-per-buffer"]),
        )

    def output_spec(self):
        if not self.props["location"]:
            raise ElementError(f"{self.name}: location= is required")
        rate, channels, fmt, _ = self._read()
        return MediaSpec(media=self._media_of(rate, channels, fmt))

    def frames(self) -> Iterator[TensorFrame]:
        rate, channels, fmt, data = self._read()
        media = self._media_of(rate, channels, fmt)
        spb = max(1, self.props["samples-per-buffer"])
        limit = self.props["num-buffers"]
        n = 0
        for off in range(0, len(data) - spb + 1, spb):
            if limit >= 0 and n >= limit:
                return
            chunk = data[off : off + spb]
            yield self._media_frame(
                np.frombuffer(chunk.tobytes(), np.uint8), media,
                pts=off / rate, duration=spb / rate,
            )
            n += 1


@element("textfilesrc")
class TextFileSrc(_MediaSource):
    """Text file -> one line per buffer as text/x-raw (utf-8 bytes)."""

    PROPERTIES = {
        "location": Property(str, "", "text file path"),
        "num-buffers": Property(int, -1, "stop after N lines (-1 = all)"),
    }

    def output_spec(self):
        return MediaSpec(media=MediaInfo("text"))

    def frames(self) -> Iterator[TensorFrame]:
        path = self.props["location"]
        if not path:
            raise ElementError(f"{self.name}: location= is required")
        media = MediaInfo("text")
        limit = self.props["num-buffers"]
        with open(path, "rb") as f:
            for n, line in enumerate(f):
                if limit >= 0 and n >= limit:
                    return
                yield self._media_frame(
                    np.frombuffer(line.rstrip(b"\r\n"), np.uint8), media
                )
