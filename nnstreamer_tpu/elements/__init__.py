"""Stream elements (L3).  Importing this package registers every element
factory (≙ plugin registration, reference
``gst/nnstreamer/registerer/nnstreamer.c:91-122``)."""

import os as _os
from importlib import import_module as _imp

from . import basic  # noqa: F401

_here = _os.path.dirname(__file__)
for _mod in (
    "media_src",
    "converter",
    "filter",
    "transform",
    "decoder",
    "mux",
    "aggregator",
    "flow",
    "repo",
    "sparse",
    "datarepo",
    "trainer",
    "validator",
    "generator",
    "query",
    "edge",
    "debug",
    "src_iio",
    "mqtt",
    "grpc_io",
):
    # only skip modules that are not built yet; real import errors propagate
    if _os.path.exists(_os.path.join(_here, _mod + ".py")):
        _imp(f"{__name__}.{_mod}")
