"""tensor_debug: in-pipeline inspection probe.

Reference: ``gsttensor_debug.c`` — console output of schema/timestamps per
frame, passthrough payload.  Output modes: console-info / console-warn /
off; capability print option.
"""

from __future__ import annotations

import numpy as np

from ..core.buffer import TensorFrame
from ..pipeline.element import Property, TransformElement, element


@element("tensor_debug")
class TensorDebug(TransformElement):
    PROPERTIES = {
        "output-method": Property(str, "console-info", "console-info|console-warn|off"),
        "capability": Property(bool, True, "print the negotiated schema once"),
        "summary": Property(bool, True, "print per-tensor min/max/mean"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._caps_printed = False
        self.seen = 0

    def _emit(self, text: str) -> None:
        method = self.props["output-method"]
        if method == "off":
            return
        (self.log.warning if method == "console-warn" else self.log.info)(text)

    def transform(self, frame: TensorFrame) -> TensorFrame:
        self.seen += 1
        if self.props["output-method"] == "off":
            return frame  # no summary cost (device arrays stay on device)
        if self.props["capability"] and not self._caps_printed:
            spec = self.sink_specs.get(0)
            self._emit(f"caps: {spec.to_string() if spec else '(unknown)'}")
            self._caps_printed = True
        parts = [f"frame seq={frame.seq} pts={frame.pts}"]
        if self.props["summary"]:
            for i, t in enumerate(frame.tensors):
                a = np.asarray(t)
                if a.size and np.issubdtype(a.dtype, np.number):
                    parts.append(
                        f"t{i} {a.dtype}{list(a.shape)} "
                        f"min={a.min():.4g} max={a.max():.4g} mean={a.mean():.4g}"
                    )
                else:
                    parts.append(f"t{i} {a.dtype}{list(a.shape)}")
        self._emit(" | ".join(parts))
        return frame
