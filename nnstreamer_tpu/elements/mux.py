"""N:1 / 1:N tensor stream composition: mux, demux, merge, split.

Reference:
  * ``tensor_mux``   — N×tensor(s) -> 1×tensors, num_tensors grows; sync
    policies (``gsttensor_mux.c``)
  * ``tensor_demux`` — split per-tensor streams, ``tensorpick`` subset
    (``gsttensor_demux.c``)
  * ``tensor_merge`` — N single tensors -> 1 tensor concatenated on an axis
    with sync policies (``gsttensor_merge.c``)
  * ``tensor_split`` — slice one tensor into N along an axis (``tensorseg``)
    (``gsttensor_split.c``)
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.sync import Collator, SyncPolicy
from ..core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec, ref_dim_to_axis
from ..pipeline.element import Element, ElementError, Property, element


class _SyncedNto1(Element):
    """Shared machinery for mux/merge: collator-driven N:1 elements."""

    NUM_SINK_PADS = None  # request pads

    PROPERTIES = {
        "sync-mode": Property(str, "nosync", "nosync|slowest|basepad|refresh"),
        "sync-option": Property(str, "", "basepad: '<pad>:<window-s>'"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._collator: Optional[Collator] = None

    def start(self):
        policy = SyncPolicy.from_string(
            self.props["sync-mode"], self.props["sync-option"]
        )
        self._collator = Collator(max(self.num_sink_pads, 1), policy)

    def combine(self, frames: List[TensorFrame]) -> TensorFrame:
        raise NotImplementedError

    def _drain(self):
        out = []
        while (group := self._collator.collect()) is not None:
            out.append((0, self.combine(group)))
        return out

    def handle_frame(self, pad, frame):
        self._collator.push(pad, frame)
        return self._drain()

    def handle_eos(self, pad):
        self._collator.mark_eos(pad)
        return self._drain()


@element("tensor_mux")
class TensorMux(_SyncedNto1):
    """Concatenate the tensor *lists* of N synchronized streams."""

    def derive_spec(self, pad=0):
        specs = [self.sink_specs.get(i) for i in range(self.num_sink_pads)]
        if any(s is None or not s.tensors for s in specs):
            return ANY
        tensors: Tuple[TensorSpec, ...] = ()
        for s in specs:
            tensors = tensors + s.tensors
        fr = next((s.framerate for s in specs if s.framerate), None)
        return StreamSpec(tensors, FORMAT_STATIC, fr)

    def combine(self, frames):
        tensors = [t for f in frames for t in f.tensors]
        base = frames[0]
        return TensorFrame(tensors, pts=base.pts, duration=base.duration,
                           meta=dict(base.meta))


@element("tensor_merge")
class TensorMerge(_SyncedNto1):
    """Concatenate N single tensors along one dimension (reference mode
    ``linear`` with option = reference dim index)."""

    PROPERTIES = {
        **_SyncedNto1.PROPERTIES,
        "mode": Property(str, "linear", "only 'linear' (reference parity)"),
        "option": Property(str, "0", "reference dim index to concat on"),
    }

    def _np_axis(self, rank: int) -> int:
        try:
            return ref_dim_to_axis(int(self.props["option"]), rank)
        except ValueError as e:
            raise ElementError(f"{self.name}: {e}") from None

    def derive_spec(self, pad=0):
        specs = [self.sink_specs.get(i) for i in range(self.num_sink_pads)]
        if any(s is None or not s.tensors for s in specs):
            return ANY
        first = specs[0].tensors[0]
        if not first.is_static:
            return specs[0]
        axis = self._np_axis(len(first.shape))
        dims = list(first.shape)
        dims[axis] = sum(s.tensors[0].shape[axis] for s in specs)
        fr = next((s.framerate for s in specs if s.framerate), None)
        return StreamSpec(
            (TensorSpec(tuple(dims), first.dtype, first.name),), FORMAT_STATIC, fr
        )

    def combine(self, frames):
        arrays = [np.asarray(f.tensors[0]) for f in frames]
        axis = self._np_axis(arrays[0].ndim)
        out = np.concatenate(arrays, axis=axis)
        base = frames[0]
        return TensorFrame([out], pts=base.pts, duration=base.duration,
                           meta=dict(base.meta))


def _parse_pick(text: str) -> Optional[List[List[int]]]:
    """'0,1,2' or '0:1,2' — comma separates output pads, ':' or '+' joins
    several input tensors onto one pad (reference tensorpick dialect)."""
    if not text:
        return None
    groups = []
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        groups.append([int(x) for x in part.replace("+", ":").split(":")])
    return groups or None


@element("tensor_demux")
class TensorDemux(Element):
    """Split a multi-tensor stream into per-tensor (or grouped) streams."""

    NUM_SRC_PADS = None  # request pads

    PROPERTIES = {
        "tensorpick": Property(str, "", "e.g. '0,1:2' — tensors per src pad"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def _groups(self, ntensors: int) -> List[List[int]]:
        return _parse_pick(self.props["tensorpick"]) or [[i] for i in range(ntensors)]

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if not in_spec.tensors:
            return ANY
        groups = self._groups(in_spec.num_tensors)
        if pad >= len(groups):
            return ANY
        return StreamSpec(
            tuple(in_spec.tensors[i] for i in groups[pad]),
            in_spec.fmt,
            in_spec.framerate,
        )

    def handle_frame(self, pad, frame):
        groups = self._groups(len(frame.tensors))
        out = []
        for p, idxs in enumerate(groups):
            if p >= len(self.srcpads) or not self.srcpads[p].is_linked:
                continue
            out.append((p, frame.pick(idxs)))
        return out


@element("tensor_split")
class TensorSplit(Element):
    """Slice one tensor into N along a dimension.

    Reference props: ``tensorseg`` (sizes) + ``tensorpick``; here
    ``tensorseg`` is a comma list of sizes along reference dim ``option``.
    """

    NUM_SRC_PADS = None

    PROPERTIES = {
        "tensorseg": Property(str, "", "comma sizes, e.g. '2,1' along the dim"),
        "tensorpick": Property(
            str, "",
            "emit only these segment indices, in order (e.g. '0,2'); "
            "empty = all segments",
        ),
        "option": Property(str, "0", "reference dim index to split on"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    _seg_cache: Optional[List[int]] = None
    _pick_cache: Optional[List[int]] = None

    def start(self):
        # parse-once convention (hot path stays parse-free, like
        # tensor_transform); direct handle_frame calls without start()
        # (unit tests) fall back to parsing per call
        self._seg_cache = self._sizes()
        self._pick_cache = self._picks(len(self._seg_cache))

    def stop(self):
        self._seg_cache = self._pick_cache = None

    def _sizes(self) -> List[int]:
        if self._seg_cache is not None:
            return self._seg_cache
        text = self.props["tensorseg"]
        if not text:
            raise ElementError(f"{self.name}: tensor_split requires tensorseg=")
        return [int(x) for x in text.split(",") if x.strip()]

    def _picks(self, nseg: int) -> List[int]:
        """Pad index -> segment index (≙ gsttensor_split.c tensorpick)."""
        if self._pick_cache is not None:
            return self._pick_cache
        text = self.props["tensorpick"]
        if not text:
            return list(range(nseg))
        picks = [int(x) for x in text.split(",") if x.strip()]
        bad = [p for p in picks if not 0 <= p < nseg]
        if bad:
            raise ElementError(
                f"{self.name}: tensorpick {bad} out of range for "
                f"{nseg} segments"
            )
        return picks

    def _np_axis(self, rank: int) -> int:
        try:
            return ref_dim_to_axis(int(self.props["option"]), rank)
        except ValueError as e:
            raise ElementError(f"{self.name}: {e}") from None

    def accept_spec(self, pad, spec):
        if spec.tensors:
            t = spec.tensors[0]
            if t.is_static:
                axis = self._np_axis(len(t.shape))
                if sum(self._sizes()) != t.shape[axis]:
                    raise ElementError(
                        f"{self.name}: tensorseg {self._sizes()} does not sum to "
                        f"dim {t.shape[axis]}"
                    )
        return spec

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if not in_spec.tensors or not in_spec.tensors[0].is_static:
            return ANY
        t = in_spec.tensors[0]
        sizes = self._sizes()
        picks = self._picks(len(sizes))
        if pad >= len(picks):
            return ANY
        axis = self._np_axis(len(t.shape))
        dims = list(t.shape)
        dims[axis] = sizes[picks[pad]]
        return StreamSpec(
            (TensorSpec(tuple(dims), t.dtype, t.name),),
            in_spec.fmt,
            in_spec.framerate,
        )

    def handle_frame(self, pad, frame):
        arr = np.asarray(frame.tensors[0])
        sizes = self._sizes()
        axis = self._np_axis(arr.ndim)
        offsets = []
        off = 0
        for size in sizes:
            offsets.append((off, size))
            off += size
        out = []
        for p, seg in enumerate(self._picks(len(sizes))):
            if p < len(self.srcpads) and self.srcpads[p].is_linked:
                o, size = offsets[seg]
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(o, o + size)
                out.append((p, frame.with_tensors([arr[tuple(sl)]])))
        return out
