"""tensor_transform: elementwise ops on tensor streams.

Reference: ``gst/nnstreamer/elements/gsttensor_transform.c`` (mode enum
``gsttensor_transform.h:57-84``): ``typecast``, ``arithmetic`` (chained
add/mul/div with optional typecast), ``transpose``, ``dimchg``, ``stand``
(standardize), ``clamp``.  The reference accelerates cast/arith with ORC
SIMD (:463-533); here the ops run as numpy on host arrays and jax.numpy on
device arrays — a jax-xla filter upstream keeps payloads on device, so the
transform fuses into the XLA graph instead of touching the host
(device-residency is the TPU answer to ORC).

Option dialects follow the reference:
  * ``mode=typecast option=float32``
  * ``mode=arithmetic option=typecast:float32,add:-127.5,div:127.5``
  * ``mode=transpose option=1:0:2:3`` (reference dims, innermost-first)
  * ``mode=dimchg option=0:2`` (move reference-dim 0 to position 2)
  * ``mode=stand option=default|dc-average[:dtype]``
  * ``mode=clamp option=min:max``
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec, TensorSpec, dtype_from_name
from ..pipeline.element import ElementError, Property, TransformElement, element


def _xp(arr):
    """numpy for host arrays, jax.numpy for device arrays (stay on device)."""
    if type(arr).__module__.startswith(("jax", "jaxlib")):
        import jax.numpy as jnp

        return jnp
    return np


def _ref_axes_to_numpy_perm(ref_perm: List[int], rank: int) -> List[int]:
    """Convert a reference-dialect transpose spec (innermost-first dims) to a
    numpy axis permutation."""
    if sorted(ref_perm) != list(range(rank)):
        raise ElementError(f"transpose option must be a permutation, got {ref_perm}")
    # numpy axis j <-> reference dim (rank-1-j)
    return [rank - 1 - ref_perm[rank - 1 - j] for j in range(rank)]


class _Op:
    """A parsed transform op: array -> array + spec -> spec."""

    def __init__(self, apply: Callable, spec: Callable[[TensorSpec], TensorSpec]):
        self.apply = apply
        self.spec = spec


@element("tensor_transform")
class TensorTransform(TransformElement):
    PROPERTIES = {
        "mode": Property(str, "", "typecast|arithmetic|transpose|dimchg|stand|clamp"),
        "option": Property(str, "", "mode-specific option string"),
        "acceleration": Property(bool, True, "kept for reference parity (no-op)"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # ≙ gsttensor_transform.c `apply`: comma list of tensor indices
        # the op applies to; others pass through untouched
        "apply": Property(
            str, "", "tensor indices to transform (empty = all)"
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._op: Optional[_Op] = None
        self._apply_idx: Optional[set] = None

    # -- option parsing (done once at start; hot path stays parse-free) -----
    def start(self):
        mode = self.props["mode"]
        option = self.props["option"]
        if not mode:
            raise ElementError(f"{self.name}: tensor_transform requires mode=")
        builder = getattr(self, f"_build_{mode.replace('-', '_')}", None)
        if builder is None:
            raise ElementError(f"{self.name}: unknown transform mode {mode!r}")
        self._op = builder(option)
        apply_opt = self.props["apply"]
        self._apply_idx = (
            {int(x) for x in apply_opt.split(",") if x.strip()}
            if apply_opt else None
        )
        if self._apply_idx is not None and any(
            i < 0 for i in self._apply_idx
        ):
            raise ElementError(
                f"{self.name}: apply indices must be >= 0 "
                f"(got {sorted(self._apply_idx)})"
            )

    def _build_typecast(self, option: str) -> _Op:
        dtype = dtype_from_name(option)

        def apply(a):
            return a.astype(dtype)

        return _Op(apply, lambda t: TensorSpec(t.shape, dtype, t.name))

    def _build_arithmetic(self, option: str) -> _Op:
        # "typecast:float32,add:-127.5,div:127.5" — ops applied in order;
        # values may be per-channel vectors "add:1|2|3" (broadcast on the
        # innermost/channel dim, reference per-channel option).
        steps: List[Tuple[str, Any]] = []
        out_dtype: Optional[np.dtype] = None
        for part in option.split(","):
            part = part.strip()
            if not part:
                continue
            op, _, val = part.partition(":")
            op = op.strip().lower()
            if op == "typecast":
                out_dtype = dtype_from_name(val)
                steps.append(("typecast", out_dtype))
            elif op in ("add", "sub", "mul", "div"):
                vals = [float(v) for v in val.split("|")]
                steps.append((op, vals[0] if len(vals) == 1 else np.asarray(vals)))
            else:
                raise ElementError(f"unknown arithmetic op {op!r}")
        if not steps:
            raise ElementError("arithmetic mode requires option=")

        def apply(a):
            xp = _xp(a)
            for op, v in steps:
                if op == "typecast":
                    a = a.astype(v)
                elif op == "add":
                    a = a + v
                elif op == "sub":
                    a = a - v
                elif op == "mul":
                    a = a * v
                elif op == "div":
                    a = a / v
            return a

        def spec(t: TensorSpec) -> TensorSpec:
            # exact dtype propagation: run the op chain on a zero scalar so
            # numpy's promotion rules (incl. int+float -> float) are the
            # single source of truth
            probe = apply(np.zeros((1,), t.dtype))
            return TensorSpec(t.shape, probe.dtype, t.name)

        return _Op(apply, spec)

    def _build_transpose(self, option: str) -> _Op:
        ref_perm = [int(x) for x in option.split(":") if x != ""]
        if len(set(ref_perm)) != len(ref_perm):
            raise ElementError(f"transpose option has duplicate axes: {option!r}")

        def apply(a):
            return a.transpose(_ref_axes_to_numpy_perm(ref_perm, a.ndim))

        def spec(t: TensorSpec) -> TensorSpec:
            if not t.is_static:
                return t
            perm = _ref_axes_to_numpy_perm(ref_perm, len(t.shape))
            return TensorSpec(tuple(t.shape[p] for p in perm), t.dtype, t.name)

        return _Op(apply, spec)

    def _build_dimchg(self, option: str) -> _Op:
        a_s, _, b_s = option.partition(":")
        ref_from, ref_to = int(a_s), int(b_s)

        def _np_axes(rank):
            from ..core.types import ref_dim_to_axis

            return ref_dim_to_axis(ref_from, rank), ref_dim_to_axis(ref_to, rank)

        def apply(a):
            src, dst = _np_axes(a.ndim)
            return _xp(a).moveaxis(a, src, dst)

        def spec(t: TensorSpec) -> TensorSpec:
            if not t.is_static:
                return t
            src, dst = _np_axes(len(t.shape))
            dims = list(t.shape)
            dims.insert(dst, dims.pop(src))
            return TensorSpec(tuple(dims), t.dtype, t.name)

        return _Op(apply, spec)

    def _build_stand(self, option: str) -> _Op:
        parts = (option or "default").split(":")
        kind = parts[0] or "default"
        dtype = dtype_from_name(parts[1]) if len(parts) > 1 else np.dtype(np.float32)
        if kind not in ("default", "dc-average"):
            raise ElementError(f"unknown stand option {kind!r}")

        def apply(a):
            xp = _xp(a)
            a = a.astype(dtype)
            if kind == "dc-average":
                return a - xp.mean(a)
            std = xp.std(a)
            return (a - xp.mean(a)) / (std + dtype.type(1e-10))

        return _Op(apply, lambda t: TensorSpec(t.shape, dtype, t.name))

    def _build_clamp(self, option: str) -> _Op:
        lo_s, _, hi_s = option.partition(":")
        lo, hi = float(lo_s), float(hi_s)
        if lo > hi:
            raise ElementError(f"clamp: min {lo} > max {hi}")

        def apply(a):
            return _xp(a).clip(a, lo, hi)

        return _Op(apply, lambda t: t)

    # -- negotiation / processing -------------------------------------------
    def _applies(self, i: int) -> bool:
        return self._apply_idx is None or i in self._apply_idx

    def accept_spec(self, pad, spec):
        # a typo'd apply index must fail loud at negotiation, not become
        # a silent no-op (mirror of tensor_split's tensorpick range check)
        if self._apply_idx is not None and spec.tensors:
            bad = [i for i in self._apply_idx if i >= len(spec.tensors)]
            if bad:
                raise ElementError(
                    f"{self.name}: apply indices {sorted(bad)} out of "
                    f"range for a {len(spec.tensors)}-tensor stream"
                )
        return spec

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if self._op is None or not in_spec.tensors:
            return in_spec
        return StreamSpec(
            tuple(
                self._op.spec(t) if self._applies(i) else t
                for i, t in enumerate(in_spec.tensors)
            ),
            in_spec.fmt,
            in_spec.framerate,
        )

    def transform(self, frame: TensorFrame) -> TensorFrame:
        assert self._op is not None, f"{self.name} not started"
        return frame.with_tensors([
            self._op.apply(t) if self._applies(i) else t
            for i, t in enumerate(frame.tensors)
        ])
