"""Data-dependent flow control: tensor_if, tensor_crop, tensor_rate.

Reference:
  * ``tensor_if``  — route/modify frames by comparing a derived value
    against supplied operands (``gsttensor_if.c``; enums
    ``include/tensor_if.h:42-91``).  Compared-value modes A_VALUE /
    TENSOR_TOTAL_VALUE / TENSOR_AVERAGE_VALUE / CUSTOM (callback
    registration ≙ ``tensor_if.h:20-45``), 10 operators, then/else
    behaviors PASSTHROUGH / SKIP / TENSORPICK.
  * ``tensor_crop`` — crop a raw tensor stream using a second *info* tensor
    stream (CollectPads pair, flexible output; ``gsttensor_crop.c:130``).
  * ``tensor_rate`` — framerate control with drop/duplicate and QoS
    throttling (``gsttensor_rate.c``).
"""

from __future__ import annotations

from fractions import Fraction
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core import registry
from ..core.buffer import TensorFrame
from ..core.sync import Collator, SyncPolicy
from ..core.types import ANY, FORMAT_FLEXIBLE, StreamSpec
from ..pipeline.element import Element, ElementError, Property, TransformElement, element

# -- tensor_if --------------------------------------------------------------

_OPERATORS: Dict[str, Callable[[float, List[float]], bool]] = {
    "eq": lambda v, s: v == s[0],
    "ne": lambda v, s: v != s[0],
    "gt": lambda v, s: v > s[0],
    "ge": lambda v, s: v >= s[0],
    "lt": lambda v, s: v < s[0],
    "le": lambda v, s: v <= s[0],
    "range_inclusive": lambda v, s: s[0] <= v <= s[1],
    "range_exclusive": lambda v, s: s[0] < v < s[1],
    "not_in_range_inclusive": lambda v, s: not (s[0] <= v <= s[1]),
    "not_in_range_exclusive": lambda v, s: not (s[0] < v < s[1]),
}


def register_if_custom(name: str, fn: Callable[[TensorFrame], bool]) -> None:
    """Register a custom tensor_if predicate (≙ nnstreamer_if_custom_register)."""
    registry.register(registry.KIND_CUSTOM, f"if:{name}", fn)


def unregister_if_custom(name: str) -> bool:
    return registry.unregister(registry.KIND_CUSTOM, f"if:{name}")


_BEHAVIORS = (
    "passthrough", "skip", "fill_zero", "fill_values", "fill_with_file",
    "fill_with_file_rpt", "repeat_previous_frame", "tensorpick",
)


@element("tensor_if")
class TensorIf(Element):
    """Two src pads: 0 = 'then' branch, 1 = 'else' branch (if linked);
    behaviors modify/route the frame per branch.

    Full reference matrix (``gsttensor_if.h:42-91``): 6 compared-value
    modes x 10 operators x 8 then/else behaviors.
    """

    NUM_SRC_PADS = None  # 1 or 2

    PROPERTIES = {
        "compared-value": Property(
            str, "a_value",
            "a_value|tensor_total_value|all_tensors_total_value|"
            "tensor_average_value|all_tensors_average_value|custom",
        ),
        "compared-value-option": Property(
            str, "", "a_value: '<refdims>,<tensor>'; total/avg: tensor "
            "idx (all_*: comma list, empty = all); custom: name"
        ),
        "supplied-value": Property(str, "", "operand(s), comma separated"),
        "operator": Property(str, "gt", "|".join(_OPERATORS)),
        "then": Property(str, "passthrough", "|".join(_BEHAVIORS)),
        "then-option": Property(
            str, "", "tensorpick indices | fill value(s) | fill file path"
        ),
        "else": Property(str, "skip", "|".join(_BEHAVIORS)),
        "else-option": Property(
            str, "", "tensorpick indices | fill value(s) | fill file path"
        ),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        # REPEAT_PREVIOUS_FRAME cache, per OUTPUT PAD: "resend the previous
        # output frame" (tensor_if.h header contract) means the last frame
        # that left that pad — whichever branch produced it — so
        # else=repeat_previous_frame re-sends the last passed-through frame
        # when both branches share a pad.  First frame on a pad: zeros.
        self._prev: Dict[int, TensorFrame] = {}
        self._file_cache: Dict[str, bytes] = {}

    def start(self):
        self._prev = {}
        self._file_cache.clear()
        for which in ("then", "else"):
            if self.props[which].lower() not in _BEHAVIORS:
                raise ElementError(
                    f"{self.name}: unknown behavior {self.props[which]!r}"
                )

    def _tensor_indices(self, opt: str, frame: TensorFrame) -> List[int]:
        if not opt:
            return list(range(len(frame.tensors)))
        return [int(s) for s in opt.split(",") if s != ""]

    def _compared_value(self, frame: TensorFrame) -> float:
        mode = self.props["compared-value"].lower()
        opt = self.props["compared-value-option"]
        if mode == "custom":
            fn = registry.get(registry.KIND_CUSTOM, f"if:{opt}")
            return fn(frame)
        if mode == "a_value":
            # "<d0>:<d1>:...,<tensor-idx>" reference dialect (innermost-first)
            coord_s, _, idx_s = opt.partition(",")
            ti = int(idx_s or "0")
            arr = np.asarray(frame.tensors[ti])
            coords = [int(c) for c in coord_s.split(":")] if coord_s else []
            # innermost-first -> numpy order; unspecified outer dims = 0
            np_index = tuple(reversed(coords))[-arr.ndim:] if arr.ndim else ()
            np_index = (0,) * (arr.ndim - len(np_index)) + np_index
            return float(arr[np_index] if np_index else arr)
        if mode in ("all_tensors_total_value", "all_tensors_average_value"):
            idxs = self._tensor_indices(opt, frame)
            vals = [
                np.asarray(frame.tensors[i], dtype=np.float64) for i in idxs
            ]
            if mode.endswith("total_value"):
                return float(sum(v.sum() for v in vals))
            total = sum(v.sum() for v in vals)
            count = sum(v.size for v in vals)
            return float(total / count) if count else 0.0
        ti = int(opt or "0")
        arr = np.asarray(frame.tensors[ti], dtype=np.float64)
        if mode == "tensor_total_value":
            return float(arr.sum())
        if mode == "tensor_average_value":
            return float(arr.mean())
        raise ElementError(f"{self.name}: unknown compared-value {mode!r}")

    def _decide(self, frame: TensorFrame) -> bool:
        op = self.props["operator"].lower()
        if op not in _OPERATORS:
            raise ElementError(f"{self.name}: unknown operator {op!r}")
        supplied = [
            float(s) for s in str(self.props["supplied-value"]).split(",") if s != ""
        ]
        if not supplied:
            raise ElementError(f"{self.name}: supplied-value required")
        return _OPERATORS[op](self._compared_value(frame), supplied)

    def _file_bytes(self, path: str) -> bytes:
        data = self._file_cache.get(path)
        if data is None:
            with open(path, "rb") as f:
                data = f.read()
            self._file_cache[path] = data
        return data

    def _fill_from_bytes(self, frame: TensorFrame, raw: bytes,
                         repeat: bool) -> TensorFrame:
        """FILL_WITH_FILE(_RPT): tensors refilled from a flat byte blob —
        short files pad with zeros (plain) or cycle (rpt)."""
        outs, off = [], 0
        for t in frame.tensors:
            arr = np.asarray(t)
            n = arr.nbytes
            if repeat and raw:
                reps = -(-(off + n) // len(raw))  # ceil
                chunk = (raw * reps)[off : off + n]
            else:
                chunk = raw[off : off + n]
            buf = np.zeros(n, np.uint8)
            buf[: len(chunk)] = np.frombuffer(chunk, np.uint8)
            outs.append(buf.view(arr.dtype)[: arr.size].reshape(arr.shape))
            off += n
        return frame.with_tensors(outs)

    def _behave(self, frame: TensorFrame, which: str, src_pad: int = 0):
        action = self.props[which].lower()
        option = self.props[f"{which}-option"]
        if action == "passthrough":
            out = frame
        elif action == "skip":
            return None
        elif action == "tensorpick":
            idxs = [int(s) for s in option.split(",") if s != ""]
            out = frame.pick(idxs)
        elif action == "fill_zero":
            out = frame.with_tensors(
                [np.zeros_like(np.asarray(t)) for t in frame.tensors]
            )
        elif action == "fill_values":
            vals = [float(s) for s in option.split(",") if s != ""]
            if not vals:
                raise ElementError(
                    f"{self.name}: fill_values needs {which}-option"
                )
            out = frame.with_tensors([
                np.full_like(
                    np.asarray(t), vals[i] if i < len(vals) else vals[-1]
                )
                for i, t in enumerate(frame.tensors)
            ])
        elif action in ("fill_with_file", "fill_with_file_rpt"):
            if not option:
                raise ElementError(
                    f"{self.name}: {action} needs {which}-option (file path)"
                )
            out = self._fill_from_bytes(
                frame, self._file_bytes(option), action.endswith("rpt")
            )
        elif action == "repeat_previous_frame":
            prev = self._prev.get(src_pad)
            if prev is None:  # first on this pad: zeros (header contract)
                out = frame.with_tensors(
                    [np.zeros_like(np.asarray(t)) for t in frame.tensors]
                )
            else:
                out = frame.with_tensors(list(prev.tensors))
        else:
            raise ElementError(f"{self.name}: unknown behavior {action!r}")
        return out

    def handle_frame(self, pad, frame):
        cond = self._decide(frame)
        which = "then" if cond else "else"
        src = 0 if cond else (1 if len(self.srcpads) > 1 and self.srcpads[1].is_linked else 0)
        out = self._behave(frame, which, src)
        if out is None:
            return []
        out.meta["tensor_if"] = which
        self._prev[src] = out
        return [(src, out)]


# -- tensor_crop ------------------------------------------------------------


@element("tensor_crop")
class TensorCrop(Element):
    """sink 0 = raw tensors, sink 1 = crop info [[x, y, w, h], ...];
    output: flexible stream, one cropped tensor per region."""

    NUM_SINK_PADS = None  # exactly 2 used

    PROPERTIES = {
        "lateness": Property(int, -1, "reference parity (unused)"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._collator: Optional[Collator] = None

    def start(self):
        self._collator = Collator(2, SyncPolicy.from_string("nosync"))

    def derive_spec(self, pad=0):
        return StreamSpec((), FORMAT_FLEXIBLE, None)  # per-buffer shapes vary

    def _crop(self, raw_f: TensorFrame, info_f: TensorFrame):
        img = np.asarray(raw_f.tensors[0])
        regions = np.asarray(info_f.tensors[0]).reshape(-1, 4).astype(np.int64)
        crops = []
        H, W = img.shape[0], img.shape[1]
        for x, y, w, h in regions:
            x0, y0 = max(0, int(x)), max(0, int(y))
            x1, y1 = min(W, x0 + int(w)), min(H, y0 + int(h))
            if x1 <= x0 or y1 <= y0:
                continue
            crops.append(img[y0:y1, x0:x1])
        out = raw_f.with_tensors(crops if crops else [img[0:0, 0:0]])
        out.meta["crop_regions"] = regions.tolist()
        return out

    def _drain(self):
        out = []
        while (group := self._collator.collect()) is not None:
            out.append((0, self._crop(group[0], group[1])))
        return out

    def handle_frame(self, pad, frame):
        self._collator.push(pad, frame)
        return self._drain()

    def handle_eos(self, pad):
        self._collator.mark_eos(pad)
        return self._drain()


# -- tensor_rate ------------------------------------------------------------


@element("tensor_rate")
class TensorRate(TransformElement):
    """Adjust frame rate by dropping/duplicating against pts.

    Reference props (``gsttensor_rate.c:81-88``): framerate "n/d",
    throttle (drop without duplicating), silent.
    """

    PROPERTIES = {
        "framerate": Property(str, "", "target 'n/d'"),
        "throttle": Property(bool, True, "drop-only (no duplication)"),
        "silent": Property(bool, True, "suppress per-frame counter logs"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        # ≙ the reference's QoS event handling (gsttensor_rate.c
        # gst_tensor_rate_src_event QOS): downstream deadline misses feed
        # back here (Pipeline._qos_feedback -> note_qos) and frames up to
        # the reported late timestamp are shed at the throttle — where
        # dropping is cheapest — instead of after the expensive work
        "qos": Property(bool, True, "honor downstream deadline-miss "
                        "feedback by dropping late-flagged frames here"),
        # read-only QoS counters ≙ gsttensor_rate.c:955-977
        "in": Property(int, 0, "input frame count (read-only)"),
        "out": Property(int, 0, "output frame count (read-only)"),
        "duplicate": Property(int, 0, "duplicated frame count (read-only)"),
        "drop": Property(int, 0, "dropped frame count (read-only)"),
        "qos-dropped": Property(
            int, 0, "frames shed by QoS feedback (read-only; also counted "
            "in drop)"),
    }

    _COUNTER_ATTRS = {
        "in": "in_frames", "out": "out_frames",
        "duplicate": "duplicated", "drop": "dropped",
        "qos-dropped": "qos_dropped",
    }

    def get_property(self, key):
        attr = self._COUNTER_ATTRS.get(key.replace("_", "-"))
        if attr is not None:
            return getattr(self, attr)
        return super().get_property(key)

    def set_property(self, key, value):
        if key.replace("_", "-") in self._COUNTER_ATTRS:
            raise ElementError(f"{self.name}: {key!r} is read-only")
        super().set_property(key, value)

    def __init__(self, name=None):
        super().__init__(name)
        self._next_ts: Optional[float] = None
        self._last: Optional[TensorFrame] = None
        # readable QoS counters ≙ reference props in/out/dup/drop
        # (gsttensor_rate.c:81-88)
        self.in_frames = 0
        self.out_frames = 0
        self.dropped = 0
        self.duplicated = 0
        self.qos_dropped = 0
        # QoS feedback state: frames with pts <= this are shed (a plain
        # float store/read under the GIL — note_qos is called from
        # downstream worker threads)
        self._qos_until = float("-inf")

    def start(self):
        self._next_ts = None
        self._last = None
        self.in_frames = self.out_frames = 0
        self.dropped = self.duplicated = 0
        self.qos_dropped = 0
        self._qos_until = float("-inf")

    def note_qos(self, pts: Optional[float], lateness: float) -> None:
        """Deadline-miss feedback from downstream (the pipeline routes
        every deadline drop to upstream throttlers): shed frames up to
        the late frame's pts plus the observed lateness — ≙ the
        reference applying a QoS event's timestamp+jitter
        (gsttensor_rate.c)."""
        if not self.props["qos"] or pts is None:
            return
        until = pts + max(0.0, lateness)
        if until > self._qos_until:
            self._qos_until = until

    def _period(self) -> Optional[float]:
        fr = self.props["framerate"]
        if not fr:
            return None
        n, _, d = fr.partition("/")
        return float(Fraction(int(d or 1), int(n)))

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        period = self._period()
        if period is None or not in_spec.tensors:
            return in_spec
        return StreamSpec(
            in_spec.tensors, in_spec.fmt, Fraction(1) / Fraction(period).limit_denominator(10**6)
        )

    def transform(self, frame):
        self.in_frames += 1
        if (frame.pts is not None and frame.pts <= self._qos_until):
            # QoS throttle: downstream missed deadlines around this
            # stream time — shed here, before any downstream cost
            self.dropped += 1
            self.qos_dropped += 1
            if not self.props["silent"]:
                self.log.info(
                    "rate: qos-shed pts=%.4f (until %.4f)",
                    frame.pts, self._qos_until,
                )
            return None
        period = self._period()
        if period is None or frame.pts is None:
            self.out_frames += 1
            return frame
        if self._next_ts is None:
            self._next_ts = frame.pts
        outs = []
        # duplicate to fill gaps (unless throttle)
        if not self.props["throttle"] and self._last is not None:
            while frame.pts - self._next_ts >= period:
                dup = self._last.with_tensors(list(self._last.tensors))
                dup.pts = self._next_ts
                outs.append(dup)
                self.duplicated += 1
                self._next_ts += period
        if frame.pts >= self._next_ts:
            f = frame.with_tensors(list(frame.tensors))
            f.pts = self._next_ts
            self._next_ts += period
            self._last = frame
            outs.append(f)
        else:
            self.dropped += 1
            if not self.props["silent"]:
                self.log.info(
                    "rate: in=%d out=%d dup=%d drop=%d",
                    self.in_frames, self.out_frames,
                    self.duplicated, self.dropped,
                )
        self.out_frames += len(outs)
        if not outs:
            return None
        return outs[0] if len(outs) == 1 else outs

    def handle_frame(self, pad, frame):
        out = self.transform(frame)
        if out is None:
            return []
        if isinstance(out, list):
            return [(0, f) for f in out]
        return [(0, out)]
