"""tensor_aggregator: temporal batching/re-framing.

Reference: ``gst/nnstreamer/elements/gsttensor_aggregator.c`` (props
:64-233): collect ``frames-in`` input frames, emit ``frames-out`` frames
per output, advance by ``frames-flush`` (0 = non-overlapping), where the
frame axis within each buffer is reference dim ``frames-dim``; with
``concat=true`` the collected frames are concatenated along that dim
(e.g. 300:300 @30fps, frames-out=2, concat on dim 2 -> 300:300:2 @15fps).
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec, ref_dim_to_axis
from ..pipeline.element import Element, ElementError, Property, element


@element("tensor_aggregator")
class TensorAggregator(Element):
    PROPERTIES = {
        "frames-in": Property(int, 1, "frames carried per incoming buffer"),
        "frames-out": Property(int, 1, "frames per outgoing buffer"),
        "frames-flush": Property(int, 0, "frames to drop per emit (0 = frames-out)"),
        "frames-dim": Property(int, 0, "reference dim index that counts frames"),
        "concat": Property(bool, True, "concatenate along frames-dim"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        # per-tensor queues of single frames along the frame axis
        self._buf: List[Deque[np.ndarray]] = []

    def start(self):
        self._buf = []

    def _np_axis(self, rank: int) -> int:
        try:
            return ref_dim_to_axis(int(self.props["frames-dim"]), rank)
        except ValueError as e:
            raise ElementError(f"{self.name}: frames-dim {e}") from None

    def _extends_rank(self, rank: int) -> bool:
        """frames-dim == rank means "a new outermost axis" — the reference
        pads every tensor to rank 4 with 1s, so e.g. frames-dim=3 on video
        3:W:H means the (implicit) N axis.  We extend the rank instead."""
        return int(self.props["frames-dim"]) == rank

    def derive_spec(self, pad=0):
        in_spec = self.sink_specs.get(0, ANY)
        if not in_spec.tensors or not in_spec.tensors[0].is_static:
            return ANY
        fin, fout = self.props["frames-in"], self.props["frames-out"]
        tensors = []
        for t in in_spec.tensors:
            dims = list(t.shape)
            if self._extends_rank(len(dims)):
                dims = [1] + dims
            axis = self._np_axis(len(dims))
            per_buf = dims[axis] // fin  # frame size along the axis
            if self.props["concat"]:
                dims[axis] = per_buf * fout
            else:
                # stacked output: new leading axis of size frames-out
                dims[axis] = per_buf
                dims = [fout] + dims
            tensors.append(TensorSpec(tuple(dims), t.dtype, t.name))
        fr = in_spec.framerate
        if fr is not None and fout:
            fr = fr * self.props.get("frames-in", 1) / fout if fout else fr
        return StreamSpec(tuple(tensors), FORMAT_STATIC, in_spec.framerate and fr)

    def handle_frame(self, pad, frame):
        fin = max(1, self.props["frames-in"])
        fout = max(1, self.props["frames-out"])
        flush = self.props["frames-flush"] or fout
        if not self._buf:
            self._buf = [deque() for _ in frame.tensors]
        # slice each incoming buffer into unit frames along the frame axis
        for i, t in enumerate(frame.tensors):
            arr = np.asarray(t)
            if self._extends_rank(arr.ndim):
                arr = arr[None]
            axis = self._np_axis(arr.ndim)
            if arr.shape[axis] % fin:
                raise ElementError(
                    f"{self.name}: dim {arr.shape[axis]} not divisible by "
                    f"frames-in {fin}"
                )
            unit = arr.shape[axis] // fin
            for j in range(fin):
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(j * unit, (j + 1) * unit)
                self._buf[i].append(arr[tuple(sl)])
        out = []
        while len(self._buf[0]) >= fout:
            tensors = []
            for q in self._buf:
                chunk = [q[j] for j in range(fout)]
                axis = self._np_axis(chunk[0].ndim)
                tensors.append(
                    np.concatenate(chunk, axis=axis)
                    if self.props["concat"]
                    else np.stack(chunk)
                )
            for q in self._buf:
                for _ in range(min(flush, len(q))):
                    q.popleft()
            out.append((0, frame.with_tensors(tensors)))
        return out

    def handle_eos(self, pad):
        self._buf = []  # drop incomplete tail (reference behavior)
        return []
