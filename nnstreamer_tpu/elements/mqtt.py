"""mqttsink / mqttsrc — tensor streams over MQTT pub/sub.

Reference: ``gst/mqtt/`` (mqttsink.c, mqttsrc.c, ~3100 LoC on paho MQTT)
and ``Documentation/synchronization-in-mqtt-elements.md``: the sink embeds
its pipeline base-time *as an epoch* in every message header; the source
rebases incoming buffer timestamps into its own clock domain
(``pts += sender_base_epoch - receiver_base_epoch``) so multi-device
pipelines stay aligned without a shared GStreamer clock (the reference
derives the epoch via NTP, ``ntputil.c``; wall clock here — same contract).

Transport is the in-repo MQTT 3.1.1 client/broker
(:mod:`nnstreamer_tpu.distributed.mqtt`) — no external broker required:
point both elements at a :class:`MiniBroker` (or any MQTT 3.1.1 broker).

Message = 24-byte header (8B magic, f64 base epoch, f64 sent epoch) + wire-encoded
frame (:mod:`nnstreamer_tpu.distributed.wire` — the flex-header format the
query/edge elements speak).
"""

from __future__ import annotations

import queue as _queue
import struct
import threading
import time
from typing import Iterator, Optional

from ..core.buffer import TensorFrame
from ..core.resilience import FAULTS
from ..core.types import ANY, StreamSpec
from ..distributed import wire
from ..distributed.mqtt import MqttClient
from ..pipeline.element import (
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    element,
)

_HDR = struct.Struct(">8sdd")  # magic, base_epoch, sent_epoch
_MAGIC = b"NNSMQTT1"


def _ref_alias(el, canonical: str, reference: str):
    """One rule for reference-spelled alias pairs (cleansession vs
    clean-session): the reference spelling wins when EXPLICITLY set,
    else the canonical prop's value applies."""
    if reference in el._explicit_props:
        return el.props[reference]
    return el.props[canonical]


def _effective_qos(el) -> int:
    """mqtt-qos (reference name) wins when set (>= 0), else qos."""
    mq = el.props.get("mqtt-qos", -1)
    return mq if mq >= 0 else el.props["qos"]


def _apply_debug(el) -> None:
    """debug=true = verbose logging for THIS run, without mutating the
    user-visible `silent` prop (an explicit silent= wins; the level is
    re-derived on every start, so clearing debug restores quiet)."""
    import logging

    if "silent" in el._explicit_props:
        return  # explicit silent= wins over debug
    el.log.setLevel(
        logging.DEBUG if el.props["debug"] else logging.NOTSET
    )


@element("mqttsink")
class MqttSink(SinkElement):
    PROPERTIES = {
        "host": Property(str, "127.0.0.1", "broker host"),
        "port": Property(int, 1883, "broker port"),
        "pub-topic": Property(str, "", "topic to publish to (required)"),
        "client-id": Property(str, "", "MQTT client id (auto if empty)"),
        "retain": Property(bool, False, "retain the last message"),
        "num-buffers": Property(int, -1, "stop after N messages (-1 = all)"),
        "idl": Property(str, "flex", "payload IDL: flex | protobuf | flatbuf (interop)"),
        # ≙ reference mqtt_qos (gst/mqtt/mqttsink.h:77); 1 = at-least-once
        # with PUBACK + DUP redelivery across broker restarts
        "qos": Property(int, 0, "MQTT QoS: 0 (fire-forget) | 1 (at-least-once)"),
        # publishers reconnect slower than subscribers by default so that
        # after a broker restart subscriptions are re-established before
        # QoS-1 redelivery lands (see distributed/mqtt.py)
        "reconnect-delay": Property(float, 1.0, "initial reconnect backoff, s"),
        # reference-name props (gst/mqtt/mqttsink.c): mqtt-qos/cleansession
        # are the reference spellings of qos/clean-session
        "mqtt-qos": Property(int, -1, "alias of qos (reference name; -1 = unset)"),
        "clean-session": Property(bool, True, "false = persistent session"),
        "cleansession": Property(
            bool, True, "alias of clean-session (reference name)"
        ),
        "keep-alive-interval": Property(int, 60, "MQTT keepalive, seconds"),
        "max-buffer-size": Property(
            int, 0, "max encoded message bytes (0 = unlimited; larger drops "
            "with a warning)"
        ),
        "ntp-sync": Property(
            bool, True,
            "stamp the base-epoch header for cross-device pts rebasing "
            "(clock assumed NTP/chrony-disciplined; ≙ mqttsink ntp-sync)"
        ),
        "ntp-srvs": Property(
            str, "", "NTP servers (recorded; time discipline is the "
            "fleet's — systemd-timesyncd/chrony — not per-element)"
        ),
        "debug": Property(bool, False, "verbose logging (≙ reference debug)"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._client: Optional[MqttClient] = None
        self._base_epoch = 0.0
        self._sent = 0
        self._encode = wire.encode_frame

    def _effective_qos(self) -> int:
        return _effective_qos(self)

    def start(self) -> None:
        if not self.props["pub-topic"]:
            raise ElementError(f"{self.name}: pub-topic is required")
        _apply_debug(self)
        self._encode, _ = wire.get_codec(self.props["idl"])
        clean = _ref_alias(self, "clean-session", "cleansession")
        self._client = MqttClient(
            self.props["host"], self.props["port"],
            client_id=self.props["client-id"],
            keepalive=self.props["keep-alive-interval"],
            clean_session=clean,
            reconnect_delay_s=self.props["reconnect-delay"],
        )
        # pipeline base-time as epoch (≙ ntputil-derived base in the sink's
        # message header) — receivers rebase against their own base.
        # ntp-sync=false: no epoch (receivers keep their own pts domain)
        self._base_epoch = time.time() if self.props["ntp-sync"] else 0.0
        self._sent = 0

    def stop(self) -> None:
        if self._client is not None:
            # at-least-once: give parked QoS-1 publishes a bounded window
            # to reach the broker before tearing the client down
            left = self._client.drain(5.0)
            if left:
                self.log.warning(
                    "stopping with %d unacknowledged QoS-1 publish(es)", left
                )
            self._client.close()
            self._client = None

    def render(self, frame: TensorFrame) -> None:
        limit = self.props["num-buffers"]
        if self._client is None or (0 <= limit <= self._sent):
            return
        payload = _HDR.pack(_MAGIC, self._base_epoch, time.time()) + (
            self._encode(frame)
        )
        if FAULTS.is_armed():
            # corrupt= faults mutate the encoded message post-checksum
            # (wire-corruption simulation: the subscriber's
            # verify-on-decode must catch and drop it)
            payload = FAULTS.mangle("mqtt.publish", payload)
        cap = self.props["max-buffer-size"]
        if cap and len(payload) > cap:
            self.log.warning(
                "message %d bytes exceeds max-buffer-size %d (dropped)",
                len(payload), cap,
            )
            return
        self._client.publish(
            self.props["pub-topic"], payload,
            retain=self.props["retain"], qos=self._effective_qos(),
        )
        self._sent += 1


@element("mqttsrc")
class MqttSrc(SourceElement):
    PROPERTIES = {
        "host": Property(str, "127.0.0.1", "broker host"),
        "port": Property(int, 1883, "broker port"),
        "sub-topic": Property(str, "", "topic filter (+/# wildcards ok)"),
        "client-id": Property(str, "", "MQTT client id (auto if empty)"),
        "num-buffers": Property(int, -1, "EOS after N messages (-1 = forever)"),
        "sub-timeout": Property(int, 10000, "ms without a message before EOS"),
        "max-msg-buf-size": Property(int, 64, "receive queue depth"),
        "idl": Property(str, "flex", "payload IDL: flex | protobuf | flatbuf (interop)"),
        "reconnect-delay": Property(float, 0.1, "initial reconnect backoff, s"),
        # subscriber-side QoS (broker grants in SUBACK, deliveries carry
        # packet ids + DUP retransmit); pair qos=1 with clean-session=false
        # and a stable client-id for no-loss across subscriber restarts
        "qos": Property(int, 0, "subscription QoS: 0 | 1 (at-least-once)"),
        "clean-session": Property(bool, True, "false = persistent session"),
        # reference-name props (gst/mqtt/mqttsrc.c)
        "mqtt-qos": Property(int, -1, "alias of qos (reference name; -1 = unset)"),
        "cleansession": Property(
            bool, True, "alias of clean-session (reference name)"
        ),
        "keep-alive-interval": Property(int, 60, "MQTT keepalive, seconds"),
        "debug": Property(bool, False, "verbose logging (≙ reference debug)"),
        "is-live": Property(
            bool, True,
            "live source semantics (a broker feed is always live; false is "
            "accepted for reference parity and ignored)"
        ),
        "verify-checksum": Property(
            bool, True, "verify wire integrity checksums on received "
            "frames (v2 envelopes); corrupt messages are dropped and "
            "counted in health()"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._decode_payload = wire.decode_frame
        self._client: Optional[MqttClient] = None
        self._q: "_queue.Queue[bytes]" = _queue.Queue(64)
        self._base_epoch = 0.0
        self._stopping = threading.Event()
        self._corrupt_dropped = 0

    def output_spec(self) -> StreamSpec:
        return ANY

    def start(self) -> None:
        if not self.props["sub-topic"]:
            raise ElementError(f"{self.name}: sub-topic is required")
        self._stopping = threading.Event()  # fresh per run (restartable)
        _apply_debug(self)
        _, self._decode_payload = wire.get_codec(self.props["idl"])
        self._q = _queue.Queue(self.props["max-msg-buf-size"])
        clean = _ref_alias(self, "clean-session", "cleansession")
        qos = _effective_qos(self)
        self._client = MqttClient(
            self.props["host"], self.props["port"],
            client_id=self.props["client-id"],
            keepalive=self.props["keep-alive-interval"],
            reconnect_delay_s=self.props["reconnect-delay"],
            clean_session=clean,
        )
        self._base_epoch = time.time()
        self._client.subscribe(
            self.props["sub-topic"], self._on_message,
            qos=min(1, max(0, qos)),
        )

    def stop(self) -> None:
        self._stopping.set()  # wakes frames() out of its queue wait
        if self._client is not None:
            self._client.close()
            self._client = None

    def _on_message(self, topic: str, payload: bytes) -> None:
        try:
            self._q.put(payload, timeout=1.0)
        except _queue.Full:
            self.log.warning("receive queue full; dropping message")

    def health_info(self) -> dict:
        """Integrity accounting merged into ``Pipeline.health()``."""
        return {"corrupt_dropped": self._corrupt_dropped}

    def frames(self) -> Iterator[TensorFrame]:
        limit = self.props["num-buffers"]
        timeout_s = self.props["sub-timeout"] / 1000.0
        n = 0
        while limit < 0 or n < limit:
            # bounded wait slices so stop() ends the stream immediately
            # instead of holding the worker for the full sub-timeout
            deadline = time.monotonic() + timeout_s
            payload = None
            while payload is None:
                from ..core.lifecycle import pipeline_quiescing

                if self._stopping.is_set() or pipeline_quiescing(self):
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.log.info("sub-timeout reached; ending stream")
                    return
                try:
                    payload = self._q.get(timeout=min(0.25, remaining))
                except _queue.Empty:
                    continue
            if len(payload) < _HDR.size:
                self._corrupt_dropped += 1
                self.log.warning("short MQTT message dropped")
                continue
            magic, base_epoch, sent_epoch = _HDR.unpack_from(payload, 0)
            if magic != _MAGIC:
                self._corrupt_dropped += 1
                self.log.warning("bad MQTT message magic; dropped")
                continue
            try:
                frame = self._decode_payload(
                    payload[_HDR.size:],
                    verify=self.props["verify-checksum"])
            except wire.WireError as e:
                self._corrupt_dropped += 1
                self.log.warning("undecodable MQTT frame dropped: %s", e)
                continue
            # cross-device timestamp rebasing (reference sync doc): shift the
            # sender's stream clock into ours via the epoch difference.
            # base_epoch 0.0 = sender published with ntp-sync=false: no
            # shared epoch, receivers keep the sender's pts domain as-is
            if frame.pts is not None and base_epoch > 0.0:
                frame.pts += base_epoch - self._base_epoch
            frame.meta["mqtt-sent-epoch"] = sent_epoch
            frame.meta["mqtt-latency-s"] = max(0.0, time.time() - sent_epoch)
            n += 1
            yield frame
