"""datareposrc / datareposink: the MLOps dataset repository.

Reference: ``gst/datarepo/gstdatareposrc.c`` (props :79-88 — location,
json meta, start/stop-sample-index, epochs, is-shuffle, tensors-sequence)
and ``gstdatareposink.c`` (render :106 writes sample files + JSON meta).

Formats:

* flat binary — one file of fixed-size samples (all tensors of one frame
  concatenated) + JSON meta::

      {"format": "static", "tensors": ["float32:1:28:28", "int64:1"],
       "total_samples": N, "sample_size": bytes}

* image — one decoded file per sample with a printf-style ``location``
  pattern (``img_%04d.png``), meta ``{"format": "image", "total_samples":
  N}`` — ≙ the reference's image media type (samples read via
  pngdec/jpegdec; here ``media/image.py``/Pillow).  The sink picks this
  mode automatically when ``location`` contains a ``%`` pattern and the
  sample is a single uint8 H×W×C tensor.

Deterministic resume comes from sample indices + epochs (reference §5.4);
``is-shuffle`` uses a seeded permutation per epoch so a restarted run
replays the same order.
"""

from __future__ import annotations

import json
import os
import re
from typing import Iterator, List, Optional

import numpy as np

from ..core.buffer import TensorFrame
from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ..pipeline.element import ElementError, Property, SinkElement, SourceElement, element

# accepts printf length modifiers (%04ld, %04lld — gstdatareposrc.c
# documents them); Python rejects ll and only ignores single l, so
# _fmt_sample_path strips them before %-formatting
_IMG_PATTERN = re.compile(r"%0?\d*(?:ll?)?d")


def _is_image_pattern(location: str) -> bool:
    """Image mode iff the location holds a printf-style integer pattern
    (``img_%04d.png``); a ``%`` with no ``%d`` pattern stays flat-binary."""
    return bool(_IMG_PATTERN.search(location))


def _fmt_sample_path(location: str, idx: int) -> str:
    """``location % idx`` with stray-% errors surfaced as ElementError
    (a second bare ``%`` in the path makes %-formatting throw)."""
    try:
        return _IMG_PATTERN.sub(
            lambda m: m.group(0).replace("l", ""), location, count=1
        ) % idx
    except (ValueError, TypeError) as e:
        raise ElementError(
            f"bad sample-path pattern {location!r}: {e} (exactly one "
            "%d-style field is supported; escape other percents as %%)"
        ) from None


def _tmp_sibling(path: str) -> str:
    """Temp name in the SAME directory (rename must not cross devices),
    dot-prefixed so printf-pattern scans and shell globs skip it, with
    the real name kept as the SUFFIX so extension-sniffing writers (PIL
    picks the container from the extension) still work."""
    d, base = os.path.split(path)
    return os.path.join(d, f".tmp-{os.getpid()}-{base}")


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _atomic_replace(tmp: str, path: str) -> None:
    """fsync + rename: after this returns, `path` is either the old file
    or the COMPLETE new one — a writer killed at any instant can never
    leave a half-written file under the final name."""
    _fsync_path(tmp)
    os.replace(tmp, path)


def _atomic_write_json(path: str, obj) -> None:
    tmp = _tmp_sibling(path)
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


@element("datareposink")
class DataRepoSink(SinkElement):
    PROPERTIES = {
        "location": Property(str, "", "data file path"),
        "json": Property(str, "", "meta file path"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._file = None
        self._tmp_location: Optional[str] = None
        self._count = 0
        self._specs: Optional[List[TensorSpec]] = None
        self._sample_size = 0
        self._image_mode = False

    def start(self):
        if not self.props["location"] or not self.props["json"]:
            raise ElementError(f"{self.name}: datareposink needs location= and json=")
        self._image_mode = _is_image_pattern(self.props["location"])
        if self._image_mode:
            self._file = None
            self._tmp_location = None
        else:
            # crash-atomic repo: stream into a temp sibling; stop()
            # fsyncs and renames data THEN meta over the final names, so
            # a killed writer leaves the previous repo untouched and
            # never a half-written sample under the real location
            self._tmp_location = _tmp_sibling(self.props["location"])
            self._file = open(self._tmp_location, "wb")
        self._count = 0
        self._specs = None  # re-derive the schema from the new run's frame 0
        self._sample_size = 0

    def _check_schema(self, arrays) -> None:
        """Every sample must match frame 0 (fixed-stride repo / one image
        schema), in BOTH modes — a mismatched write must fail at write
        time, not at read time mid-training."""
        if self._specs is None:
            self._specs = [TensorSpec(a.shape, a.dtype) for a in arrays]
            self._sample_size = sum(a.nbytes for a in arrays)
            return
        if len(arrays) != len(self._specs) or any(
            tuple(a.shape) != s.shape or a.dtype != s.dtype
            for a, s in zip(arrays, self._specs)
        ):
            got = [f"{a.dtype}{list(a.shape)}" for a in arrays]
            raise ElementError(
                f"{self.name}: sample {self._count} schema {got} differs "
                f"from first sample {[s.to_string() for s in self._specs]}"
            )

    def render(self, frame):
        arrays = [np.ascontiguousarray(np.asarray(t)) for t in frame.tensors]
        if self._image_mode:
            ok = (
                len(arrays) == 1
                and arrays[0].dtype == np.uint8
                and arrays[0].ndim == 3
                and arrays[0].shape[-1] in (1, 3)
            )
            if not ok:
                # only shapes the src can decode BACK may be written
                raise ElementError(
                    f"{self.name}: image mode writes ONE uint8 (H, W, C) "
                    f"tensor per sample with C in (1, 3), got "
                    f"{[f'{a.dtype}{list(a.shape)}' for a in arrays]}"
                )
            self._check_schema(arrays)
            from ..media.image import write_image

            # per-sample crash atomicity: temp write + fsync + rename —
            # a kill mid-encode leaves a dot-tmp orphan, never a
            # half-encoded image under a sample name the src would read
            path = _fmt_sample_path(self.props["location"], self._count)
            tmp = _tmp_sibling(path)
            write_image(tmp, arrays[0])
            _atomic_replace(tmp, path)
            self._count += 1
            return
        self._check_schema(arrays)
        for a in arrays:
            self._file.write(a.tobytes())
        self._count += 1

    def stop(self):
        if self._image_mode:
            if not self.props["json"]:
                return
            meta = {
                "format": "image",
                "tensors": [s.to_string() for s in (self._specs or [])],
                "total_samples": self._count,
            }
            _atomic_write_json(self.props["json"], meta)
            return
        if self._file is None:
            return
        # publish order matters: data first, meta last — a crash between
        # the two renames leaves old-meta + new-data, and the src's
        # size check (not a decode error deep into an epoch) reports it
        self._file.flush()
        os.fsync(self._file.fileno())
        self._file.close()
        self._file = None
        os.replace(self._tmp_location, self.props["location"])
        self._tmp_location = None
        meta = {
            "format": "static",
            "tensors": [s.to_string() for s in (self._specs or [])],
            "total_samples": self._count,
            "sample_size": self._sample_size,
        }
        _atomic_write_json(self.props["json"], meta)


@element("datareposrc")
class DataRepoSrc(SourceElement):
    PROPERTIES = {
        "location": Property(str, "", "data file path"),
        "json": Property(str, "", "meta file path"),
        "start-sample-index": Property(int, 0, "first sample (inclusive)"),
        "stop-sample-index": Property(int, -1, "last sample (inclusive; -1 = end)"),
        "epochs": Property(int, 1, "repeat the range N times"),
        "is-shuffle": Property(bool, False, "seeded shuffle per epoch"),
        "shuffle-seed": Property(int, 0, "determinism for resume"),
        "tensors-sequence": Property(str, "", "reorder tensors, e.g. '1,0'"),
        "caps": Property(str, "", "override announced schema"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._specs: List[TensorSpec] = []
        self._total = 0
        self._sample_size = 0
        self._image_mode = False
        self._truncated_samples = 0  # meta-claimed samples the file lacks

    def start(self):
        if not self.props["location"] or not self.props["json"]:
            raise ElementError(f"{self.name}: datareposrc needs location= and json=")
        if self.props["start-sample-index"] < 0:
            # a negative start would read negative indices (mid-epoch with
            # shuffle on) — fail here, not hours into a run
            raise ElementError(
                f"{self.name}: start-sample-index must be >= 0, got "
                f"{self.props['start-sample-index']}"
            )
        with open(self.props["json"]) as f:
            meta = json.load(f)
        self._specs = [TensorSpec.from_string(s) for s in meta["tensors"]]
        self._total = int(meta["total_samples"])
        self._image_mode = meta.get("format") == "image"
        if self._image_mode:
            if not _is_image_pattern(self.props["location"]):
                raise ElementError(
                    f"{self.name}: image repo needs a printf-style "
                    "location pattern (e.g. img_%04d.png)"
                )
            # completeness check at START (flat mode verifies file size
            # here): a deleted/missing sample must not surface hours into
            # a shuffled training run.  Only the configured index range is
            # checked — pruned repos read with start/stop-sample-index
            # stay valid, and the scan cost is bounded by the range.
            lo = self.props["start-sample-index"]
            hi = self.props["stop-sample-index"]
            hi = self._total - 1 if hi < 0 else min(hi, self._total - 1)
            missing = [
                i for i in range(lo, hi + 1)
                if not os.path.exists(
                    _fmt_sample_path(self.props["location"], i)
                )
            ]
            if missing:
                raise ElementError(
                    f"{self.name}: image repo is missing "
                    f"{len(missing)} of samples [{lo}, {hi}] "
                    f"(first: {_fmt_sample_path(self.props['location'], missing[0])})"
                )
            self._sample_size = 0
            return
        self._sample_size = int(meta["sample_size"])
        self._truncated_samples = 0
        size = os.path.getsize(self.props["location"])
        need = self._total * self._sample_size
        if size < need:
            # a killed writer (or interrupted copy) can leave a repo
            # whose file ends mid-sample.  Detect it HERE and serve the
            # complete prefix with a loud report — not a numpy/short-read
            # crash hours into a shuffled training run, and not a silent
            # epoch of garbage.  Zero complete samples is still fatal.
            complete = size // self._sample_size if self._sample_size else 0
            if complete <= 0:
                raise ElementError(
                    f"{self.name}: data file smaller than meta claims "
                    f"({size} < {self._total}×{self._sample_size}) and "
                    "holds no complete sample"
                )
            trailing = size - complete * self._sample_size
            self._truncated_samples = self._total - complete
            self.log.warning(
                "%s: data file truncated (killed writer?): meta claims %d "
                "samples (%d B) but the file holds %d B — serving the %d "
                "complete sample(s)%s",
                self.name, self._total, need, size, complete,
                f"; {trailing} trailing byte(s) of a partial sample "
                "ignored" if trailing else "",
            )
            self._total = complete

    def health_info(self) -> dict:
        """Repo-integrity accounting merged into ``Pipeline.health()``."""
        return {"truncated_samples": self._truncated_samples}

    def _sequence(self) -> Optional[List[int]]:
        text = self.props["tensors-sequence"]
        if not text:
            return None
        return [int(x) for x in text.split(",") if x.strip()]

    def output_spec(self) -> StreamSpec:
        if self.props["caps"]:
            return StreamSpec.from_string(self.props["caps"])
        specs = self._specs
        seq = self._sequence()
        if seq:
            specs = [specs[i] for i in seq]
        return StreamSpec(tuple(specs), FORMAT_STATIC)

    def _open_reader(self):
        """Native mmap reader when the core is built (one memcpy per
        sample, GIL released, next-sample prefetch — ≙ the reference's C
        reader in gstdatareposrc.c); Python seek/read fallback otherwise.
        Image repos decode one file per sample via media/image.py.

        Returns (read(idx) -> uint8 view, prefetch(idx), close())."""
        if self._image_mode:
            from ..media.image import read_image

            spec = self._specs[0]
            fmt = "GRAY8" if spec.shape[-1] == 1 else "RGB"

            def read_img(idx: int):
                arr = read_image(
                    _fmt_sample_path(self.props["location"], int(idx)), fmt
                )
                if tuple(arr.shape) != tuple(spec.shape):
                    raise ElementError(
                        f"{self.name}: sample {idx} is {list(arr.shape)}, "
                        f"meta says {list(spec.shape)}"
                    )
                return arr.reshape(-1).view(np.uint8)

            return read_img, lambda idx: None, lambda: None
        try:
            from ..native.runtime import SampleReader

            r = SampleReader(self.props["location"], self._sample_size)
            return r.read, r.prefetch, r.close
        except (RuntimeError, OSError):
            f = open(self.props["location"], "rb")

            def read(idx: int):
                f.seek(int(idx) * self._sample_size)
                return np.frombuffer(f.read(self._sample_size), np.uint8)

            return read, lambda idx: None, f.close

    def frames(self) -> Iterator[TensorFrame]:
        start = self.props["start-sample-index"]
        stop = self.props["stop-sample-index"]
        stop = self._total - 1 if stop < 0 else min(stop, self._total - 1)
        if start > stop:
            raise ElementError(f"{self.name}: empty sample range [{start}, {stop}]")
        indices = np.arange(start, stop + 1)
        seq = self._sequence()
        read, prefetch, close = self._open_reader()
        try:
            for epoch in range(max(1, self.props["epochs"])):
                order = indices
                if self.props["is-shuffle"]:
                    rng = np.random.default_rng(self.props["shuffle-seed"] + epoch)
                    order = rng.permutation(indices)
                for i, idx in enumerate(order):
                    if self._pipeline is not None and self._pipeline._stop_flag.is_set():
                        return
                    raw = read(int(idx))
                    if i + 1 < len(order):
                        prefetch(int(order[i + 1]))
                    tensors = []
                    off = 0
                    for spec in self._specs:
                        n = spec.nbytes
                        tensors.append(
                            raw[off : off + n].view(spec.dtype).reshape(spec.shape)
                        )
                        off += n
                    if seq:
                        tensors = [tensors[i] for i in seq]
                    frame = TensorFrame(tensors)
                    frame.meta["sample_index"] = int(idx)
                    frame.meta["epoch"] = epoch
                    yield frame
        finally:
            close()
