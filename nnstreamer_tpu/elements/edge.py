"""edgesink / edgesrc: general tensor-stream pub/sub among devices.

Reference: ``gst/edge/`` — edgesink publishes a stream (server role),
edgesrc subscribes (client role); connect types TCP / HYBRID / MQTT / AITT
(``edge_common.c:23-35``), topics for brokered modes, caps carried in the
edge handshake.  The MQTT elements (``gst/mqtt/``) add broker pub/sub with
NTP-epoch timestamp rebasing for cross-device sync
(``Documentation/synchronization-in-mqtt-elements.md``).

TPU build: one gRPC broker (distributed/service.py EdgeBroker) covers both
the direct (edgesink hosts the broker) and brokered (both ends dial a
third-party broker) layouts.  Timestamp rebasing: the publisher embeds
``wall_base`` (epoch seconds at pts=0) in frame meta; subscribers rebase
pts into their local clock domain — the NTP-sync analog.
"""

from __future__ import annotations

import queue as _queue
import time
from typing import Iterator, Optional

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec
from ..distributed.service import (
    EdgePublisher,
    EdgeSubscriber,
    get_edge_broker,
    release_edge_broker,
)
from ..pipeline.element import Property, SinkElement, SourceElement, element


@element("edgesink")
class EdgeSink(SinkElement):
    PROPERTIES = {
        "port": Property(int, 0, "broker port (hosted here unless connect-type=client)"),
        "dest-host": Property(str, "localhost", "remote broker host (client mode)"),
        "dest-port": Property(int, 0, "remote broker port (client mode)"),
        "topic": Property(str, "nns", "pub/sub topic"),
        "connect-type": Property(str, "server", "server (host broker) | client"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._broker = None
        self._pub: Optional[EdgePublisher] = None
        self._wall_base: Optional[float] = None

    def start(self):
        if self.props["connect-type"] == "client":
            self._pub = EdgePublisher(
                self.props["dest-host"], self.props["dest-port"], self.props["topic"]
            )
        else:
            self._broker = get_edge_broker(self.props["port"])
            self._broker.start()
            self.props["port"] = self._broker.port

    def stop(self):
        if self._pub is not None:
            self._pub.close()
            self._pub = None
        if self._broker is not None:
            release_edge_broker(self._broker.port)
            self._broker = None

    def render(self, frame):
        if self._wall_base is None:
            self._wall_base = time.time() - (frame.pts or 0.0)
        frame.meta["wall_base"] = self._wall_base  # cross-device sync anchor
        if self._pub is not None:
            self._pub.publish(frame)
        else:
            from ..distributed.wire import encode_frame

            self._broker.publish_local(self.props["topic"], encode_frame(frame))


@element("edgesrc")
class EdgeSrc(SourceElement):
    PROPERTIES = {
        "dest-host": Property(str, "localhost", "broker/publisher host"),
        "dest-port": Property(int, 0, "broker/publisher port"),
        "topic": Property(str, "nns", "pub/sub topic"),
        "caps": Property(str, "", "announced schema"),
        "rebase-pts": Property(bool, True, "rebase pts into the local clock"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._sub: Optional[EdgeSubscriber] = None

    def start(self):
        self._sub = EdgeSubscriber(
            self.props["dest-host"], self.props["dest-port"], self.props["topic"]
        )

    def stop(self):
        if self._sub is not None:
            self._sub.close()
            self._sub = None

    def output_spec(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def frames(self) -> Iterator[TensorFrame]:
        import threading

        out: "_queue.Queue[Optional[TensorFrame]]" = _queue.Queue(64)

        def pump():
            try:
                for frame in self._sub.frames():
                    out.put(frame)
            except Exception:  # stream cancelled / broker gone
                pass
            finally:
                out.put(None)

        t = threading.Thread(target=pump, daemon=True, name=f"{self.name}-pump")
        t.start()
        local_epoch = time.time()
        while True:
            try:
                frame = out.get(timeout=0.1)
            except _queue.Empty:
                if self._pipeline is not None and self._pipeline._stop_flag.is_set():
                    return
                continue
            if frame is None:
                return
            if self.props["rebase-pts"] and frame.pts is not None:
                wall_base = frame.meta.get("wall_base")
                if wall_base is not None:
                    # publisher wall-clock time of this frame, rebased local
                    frame.pts = (wall_base + frame.pts) - local_epoch
            yield frame
