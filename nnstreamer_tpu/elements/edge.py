"""edgesink / edgesrc: general tensor-stream pub/sub among devices.

Reference: ``gst/edge/`` — edgesink publishes a stream (server role),
edgesrc subscribes (client role); connect types TCP / HYBRID / MQTT / AITT
(``edge_common.c:23-35``), topics for brokered modes, caps carried in the
edge handshake.  The MQTT elements (``gst/mqtt/``) add broker pub/sub with
NTP-epoch timestamp rebasing for cross-device sync
(``Documentation/synchronization-in-mqtt-elements.md``).

TPU build: one gRPC broker (distributed/service.py EdgeBroker) covers both
the direct (edgesink hosts the broker) and brokered (both ends dial a
third-party broker) layouts.  ``connect-type=hybrid`` reproduces the
reference's MQTT-hybrid split (control over MQTT + data over TCP for
throughput, ``CHANGES:8-13``): the sink hosts its data broker and
announces ``{host, port}`` as a RETAINED MQTT message on
``nns/edge/<topic>``; sources discover the endpoint from the MQTT broker
and attach to the gRPC data plane directly — bulk tensors never transit
MQTT.  ``connect-type=tcp`` is the raw-socket data channel
(``distributed/tcp_edge.py`` — length-prefixed NNSQ frames, no gRPC
dependency), matching the reference's plain-TCP connect type.  AITT
(Samsung-internal transport) is out of scope.

Timestamp rebasing: the publisher embeds ``wall_base`` (epoch seconds at
pts=0) in frame meta; subscribers rebase pts into their local clock
domain — the NTP-sync analog.
"""

from __future__ import annotations

import queue as _queue
import time
from typing import Iterator, Optional

from ..core.buffer import TensorFrame
from ..core.log import get_logger
from ..core.types import ANY, StreamSpec
from ..distributed.service import (
    EdgePublisher,
    EdgeSubscriber,
    get_edge_broker,
    release_edge_broker,
)
from ..pipeline.element import Property, SinkElement, SourceElement, element


def _control_topic(topic: str) -> str:
    return f"nns/edge/{topic}"


@element("edgesink")
class EdgeSink(SinkElement):
    PROPERTIES = {
        "port": Property(int, 0, "broker port (hosted here unless connect-type=client)"),
        "dest-host": Property(str, "localhost", "remote broker host (client/hybrid)"),
        "dest-port": Property(int, 0, "remote broker port (client: data; hybrid: MQTT)"),
        "topic": Property(str, "nns", "pub/sub topic"),
        "connect-type": Property(
            str, "server", "server (host gRPC broker) | client | hybrid "
            "(announce over MQTT, data over gRPC) | tcp (host a raw-TCP "
            "data channel — no gRPC dependency, ≙ reference edge TCP)"
        ),
        "host": Property(str, "127.0.0.1", "hybrid: address announced to subscribers"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._broker = None
        self._pub: Optional[EdgePublisher] = None
        self._wall_base: Optional[float] = None
        self._announcement = None
        self._tcp = None

    def start(self):
        mode = self.props["connect-type"]
        if mode == "client":
            self._pub = EdgePublisher(
                self.props["dest-host"], self.props["dest-port"], self.props["topic"]
            )
            return
        if mode == "tcp":
            from ..distributed.tcp_edge import TcpEdgeServer

            self._tcp = TcpEdgeServer(port=self.props["port"])
            self.props["port"] = self._tcp.port
            return
        self._broker = get_edge_broker(self.props["port"])
        self._broker.start()
        self.props["port"] = self._broker.port
        if mode == "hybrid":
            # control plane: retained announce on the MQTT broker at
            # dest-host:dest-port; data stays on the local gRPC broker
            # (shared machinery: distributed/hybrid.py)
            from ..distributed.hybrid import Announcement

            try:
                self._announcement = Announcement(
                    self.props["dest-host"], self.props["dest-port"],
                    _control_topic(self.props["topic"]),
                    {"host": self.props["host"], "port": self._broker.port},
                    logger=self.log,
                )
            except Exception:
                # rollback won't stop a failed element: release the
                # started data broker ourselves
                self.stop()
                raise

    def stop(self):
        if self._pub is not None:
            self._pub.close()
            self._pub = None
        if self._announcement is not None:
            self._announcement.clear()
            self._announcement = None
        if self._tcp is not None:
            self._tcp.close()
            self._tcp = None
        if self._broker is not None:
            release_edge_broker(self._broker.port)
            self._broker = None

    def render(self, frame):
        if self._wall_base is None:
            self._wall_base = time.time() - (frame.pts or 0.0)
        frame.meta["wall_base"] = self._wall_base  # cross-device sync anchor
        if self._pub is not None:
            self._pub.publish(frame)
            return
        from ..distributed.wire import encode_frame

        if self._tcp is not None:
            self._tcp.publish(self.props["topic"], encode_frame(frame))
        else:
            self._broker.publish_local(self.props["topic"], encode_frame(frame))


class _TcpFrameSubscriber:
    """Adapts TcpEdgeSubscriber (raw payloads) to the EdgeSubscriber
    surface edgesrc consumes (frames() iterator + close())."""

    def __init__(self, sub, verify_checksum: bool = True):
        self._sub = sub
        self._verify = verify_checksum
        #: frames dropped on failed decode/integrity checks — a corrupt
        #: transmission degrades to a gap, never ends the stream
        self.corrupt_dropped = 0

    def frames(self):
        from ..distributed.wire import WireError, decode_frame

        for payload in self._sub.payloads():
            try:
                yield decode_frame(payload, verify=self._verify)
            except WireError as e:
                self.corrupt_dropped += 1
                log = get_logger("edgesrc")
                log.warning("undecodable tcp edge frame dropped: %s", e)

    def close(self):
        self._sub.close()


@element("edgesrc")
class EdgeSrc(SourceElement):
    PROPERTIES = {
        "dest-host": Property(str, "localhost", "broker host (hybrid: MQTT broker)"),
        "dest-port": Property(int, 0, "broker port (hybrid: MQTT broker)"),
        # multi-remote failover (resilience layer): candidate publishers
        # tried in order at connect AND reconnect time — a dead primary
        # degrades to the next remote instead of failing the stream
        "dest-hosts": Property(
            str, "", "failover publisher list 'h1:p1,h2:p2' (overrides "
            "dest-host/dest-port; direct/tcp only)"),
        "topic": Property(str, "nns", "pub/sub topic"),
        "caps": Property(str, "", "announced schema"),
        "connect-type": Property(
            str, "direct", "direct (dial the gRPC data broker) | hybrid "
            "(discover the data endpoint over MQTT) | tcp (dial a raw-TCP "
            "edgesink)"
        ),
        "discovery-timeout": Property(float, 10.0, "hybrid: seconds to wait for the announce"),
        "rebase-pts": Property(bool, True, "rebase pts into the local clock"),
        # elastic recovery: an unexpectedly-ended stream (publisher died,
        # link dropped) is re-dialed — cycling through dest-hosts — with
        # exponential backoff, instead of silently ending the source.
        # 0 keeps the historical end-on-hangup behavior.
        "max-reconnects": Property(
            int, 0, "re-dial attempts PER stream break (the budget "
            "refills on every successful reconnect; 0 = end the stream, "
            "historical behavior)"),
        "reconnect-backoff": Property(
            float, 0.2, "base seconds between re-dials (doubles per "
            "attempt, capped at 2s)"),
        "verify-checksum": Property(
            bool, True, "verify wire integrity checksums on received "
            "frames (v2 envelopes); corrupt frames are dropped and "
            "counted in health()"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._sub: Optional[EdgeSubscriber] = None
        self._targets: list = []
        self._next_target = 0
        # corrupt-drop counts from subscribers retired by reconnects
        self._corrupt_base = 0

    def _discover(self) -> tuple:
        """Hybrid control plane: read the retained announce from MQTT
        (shared machinery: distributed/hybrid.py; single fixed topic, so
        no settle window is needed)."""
        from ..distributed.hybrid import discover_endpoints
        from ..pipeline.element import ElementError

        found = discover_endpoints(
            self.props["dest-host"], self.props["dest-port"],
            _control_topic(self.props["topic"]),
            timeout_s=self.props["discovery-timeout"], settle_s=0.0,
            logger=self.log,
        )
        if not found:
            raise ElementError(
                f"{self.name}: no edge announce for topic "
                f"{self.props['topic']!r} within "
                f"{self.props['discovery-timeout']}s"
            )
        return next(iter(found.values()))

    def _parse_targets(self) -> list:
        from ..pipeline.element import parse_host_list

        raw = self.props["dest-hosts"]
        if not raw:
            return [(self.props["dest-host"], self.props["dest-port"])]
        return parse_host_list(raw, self.name, "dest-hosts")

    def _dial(self, host: str, port: int, probe: bool = False):
        verify = bool(self.props["verify-checksum"])
        if self.props["connect-type"] == "tcp":
            from ..distributed.tcp_edge import TcpEdgeSubscriber

            return _TcpFrameSubscriber(TcpEdgeSubscriber(
                host, port, self.props["topic"],
            ), verify_checksum=verify)
        if probe or len(self._targets) > 1:
            # gRPC channels connect lazily and never fail at dial time,
            # which would make dest-hosts failover (and the reconnect
            # budget) a silent no-op: probe the endpoint for real before
            # declaring this dial a success.  Initial single-target
            # start() stays lazy — a subscriber may legitimately start
            # before its publisher exists.
            from ..distributed.hybrid import probe_endpoint

            if not probe_endpoint(host, port):
                raise ConnectionError(
                    f"edge endpoint {host}:{port} not accepting")
        return EdgeSubscriber(host, port, self.props["topic"],
                              verify_checksum=verify)

    def _connect_any(self, probe: bool = False):
        """Dial the target list starting at the rotation cursor; first
        answering publisher wins (multi-remote failover)."""
        last: Optional[BaseException] = None
        n = len(self._targets)
        for k in range(n):
            host, port = self._targets[(self._next_target + k) % n]
            try:
                sub = self._dial(host, port, probe=probe)
                self._next_target = (self._next_target + k) % n
                return sub
            except Exception as e:  # noqa: BLE001 — transport boundary
                last = e
                self.log.warning("edge dial %s:%d failed: %s", host, port, e)
        raise last if last is not None else ConnectionError("no edge targets")

    def start(self):
        if self.props["connect-type"] == "hybrid":
            self._targets = [self._discover()]
        else:
            self._targets = self._parse_targets()
        self._sub = self._connect_any()

    def stop(self):
        if self._sub is not None:
            self._sub.close()
            self._sub = None

    def output_spec(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def health_info(self) -> dict:
        """Integrity accounting merged into ``Pipeline.health()``."""
        return {
            "corrupt_dropped": self._corrupt_base
            + getattr(self._sub, "corrupt_dropped", 0)
        }

    def _stopping(self) -> bool:
        from ..core.lifecycle import pipeline_quiescing

        return pipeline_quiescing(self)

    def _backoff_wait(self, delay: float) -> bool:
        """Sleep `delay` seconds; True if the pipeline stopped meanwhile."""
        if self._pipeline is not None:
            return self._pipeline._stop_flag.wait(delay)
        time.sleep(delay)
        return False

    def frames(self) -> Iterator[TensorFrame]:
        import threading

        local_epoch = time.time()
        reconnects_left = int(self.props["max-reconnects"])
        failed_redials = 0
        while True:
            out: "_queue.Queue[Optional[TensorFrame]]" = _queue.Queue(64)
            sub = self._sub

            def pump(sub=sub, out=out):
                try:
                    for frame in sub.frames():
                        out.put(frame)
                except Exception:  # allow-silent: stream cancelled /
                    pass  # broker gone — the None below IS the signal
                finally:
                    out.put(None)

            t = threading.Thread(
                target=pump, daemon=True, name=f"{self.name}-pump")
            t.start()
            while True:
                try:
                    frame = out.get(timeout=0.1)
                except _queue.Empty:
                    if self._stopping():
                        return
                    continue
                if frame is None:
                    break  # stream ended — fall through to reconnect logic
                if self.props["rebase-pts"] and frame.pts is not None:
                    wall_base = frame.meta.get("wall_base")
                    if wall_base is not None:
                        # publisher wall-clock of this frame, rebased local
                        frame.pts = (wall_base + frame.pts) - local_epoch
                yield frame
            if self._stopping():
                return
            # elastic recovery: the publisher hung up (or died) — re-dial
            # with RetryPolicy backoff (capped exponential + jitter: N
            # subscribers that lost the same publisher must not redial in
            # synchronized bursts), rotating through dest-hosts so a dead
            # primary fails over to the next remote
            from ..core.resilience import RetryPolicy

            base = max(0.0, float(self.props["reconnect-backoff"]))
            policy = RetryPolicy(
                base_delay_s=base, max_delay_s=2.0, jitter=0.1)
            while reconnects_left > 0:
                reconnects_left -= 1
                delay = policy.delay_for(failed_redials + 1) if base else 0.0
                if delay > 0 and self._backoff_wait(delay):
                    return
                try:
                    old, self._sub = self._sub, None
                    if old is not None:
                        # carry the retired subscriber's integrity count
                        self._corrupt_base += getattr(
                            old, "corrupt_dropped", 0)
                        old.close()
                    if self.props["connect-type"] == "hybrid":
                        # the publisher may have come back on a NEW
                        # endpoint: re-read its retained announce rather
                        # than redialing the one captured at start()
                        self._targets = [self._discover()]
                    self._next_target = (
                        (self._next_target + 1) % max(1, len(self._targets))
                    )
                    # probe=True: a re-dial must verify the peer is real
                    # (lazy gRPC channels would otherwise refill the
                    # budget forever against a permanently dead publisher)
                    self._sub = self._connect_any(probe=True)
                    failed_redials = 0
                    # per-break budget: a recovered stream starts fresh —
                    # N isolated publisher restarts over weeks must not
                    # add up to silent stream death
                    reconnects_left = int(self.props["max-reconnects"])
                    self.log.info("edge stream re-established")
                    break
                except Exception as e:  # noqa: BLE001 — transport boundary
                    failed_redials += 1
                    self.log.warning("edge reconnect failed: %s", e)
            else:
                return  # budget exhausted (or 0): end of stream
