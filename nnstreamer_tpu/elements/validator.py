"""model_validator: the promotion gate of the continuous-learning loop.

Closes the data-collection → train → validate → serve loop in ONE
pipeline graph (ROADMAP item 7): downstream of ``tensor_trainer``, each
epoch-stats frame triggers a validation pass — the newest durable
checkpoint is scored on a held-out datarepo split with the SAME loss the
trainer optimizes (``trainer.jax_trainer.make_loss_fn``) — and a
candidate that improves on the best promoted score is exported
(crash-atomic msgpack) and promoted into a co-hosted serving
``tensor_filter`` through the staged hot swap (PR-5): stage + schema
validation + warmup off the hot path, swap at a frame boundary, and an
observation-window error burst rolls back with zero frame loss.

Gate semantics (degrade, don't die):

* **Refused on regression** — a candidate that does not improve the
  held-out ``metric`` (loss or accuracy) by at least ``min-delta`` over
  the best PROMOTED score is refused (counted, bus warning) and the
  serving filter keeps its current model.
* **Promotion failure keeps serving** — an export/reload failure (the
  ``trainer.promote`` fault site) counts ``train_promote_failures`` and
  records a flight-recorder incident; it never kills the pipeline or
  touches the serving model.
* **Bad promotion rolls back** — a model that validates clean but
  error-bursts in serving is the filter's observation window's job; the
  swap rolls back to the previous model (``nns.filter.rollbacks``).

The target filter must serve the same arch (``framework=jax-xla
custom=arch:<zoo-name>,... is-updatable=true``) so the promoted msgpack
params load into its template.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..core.resilience import FAULTS
from ..pipeline.element import Element, ElementError, Property, element
from ..pipeline.pipeline import BusMessage


@element("model_validator")
class ModelValidator(Element):
    PROPERTIES = {
        "checkpoint-path": Property(str, "", "trainer checkpoint dir to score"),
        "model-config": Property(str, "", "trainer model config (file or inline JSON)"),
        "data-location": Property(str, "", "held-out datarepo data file"),
        "data-json": Property(str, "", "held-out datarepo meta file"),
        "holdout-start": Property(int, 0, "first held-out sample index"),
        "holdout-stop": Property(int, -1, "one past the last held-out sample (-1 = end)"),
        "num-inputs": Property(int, 1, "input tensors per sample"),
        "num-labels": Property(int, 1, "label tensors per sample"),
        "metric": Property(str, "loss", "gate metric: loss | accuracy"),
        "min-delta": Property(
            float, 0.0, "required improvement over the best promoted score"
        ),
        "validate-every": Property(int, 1, "validate every Nth stats frame"),
        "target": Property(str, "", "co-hosted tensor_filter to promote into"),
        "promote-path": Property(str, "", "msgpack export path for promotion"),
        "auto-promote": Property(
            bool, True, "false = score + gate only, never reload the target"
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._cfg: Dict[str, Any] = {}
        self._fn = None
        self._template = None          # zoo params template (restore shape)
        self._opt_template = None      # optimizer-state template (restore shape)
        self._loss_fn = None
        self._eval = None
        self._holdout: Optional[List[Tuple[list, list]]] = None
        self._seen = 0                 # stats frames observed
        self._last_validated: Optional[int] = None
        # element-lifetime accounting (the nns.train.* validation surface)
        self.validations = 0
        self.val_score = 0.0
        self.promotions = 0
        self.promotions_refused = 0
        self.promote_failures = 0
        self.best_score: Optional[float] = None  # best PROMOTED score
        self.last_ticket = None

    def start(self):
        cfg_text = self.props["model-config"] or "{}"
        if os.path.isfile(cfg_text):
            with open(cfg_text) as f:
                self._cfg = json.load(f)
        else:
            self._cfg = json.loads(cfg_text)
        if "arch" not in self._cfg:
            raise ElementError(
                f"{self.name}: model-config must name an 'arch'")
        if not self.props["checkpoint-path"]:
            raise ElementError(f"{self.name}: checkpoint-path is required")
        if self.props["metric"] not in ("loss", "accuracy"):
            raise ElementError(
                f"{self.name}: metric={self.props['metric']!r} (want loss|accuracy)")
        # model + scorer build lazily (first validation) — start() must
        # stay cheap and the held-out repo may still be being written

    def _build(self) -> None:
        if self._fn is not None:
            return
        import jax
        import optax

        from .. import models as zoo
        from ..trainer.jax_trainer import make_loss_fn

        arch_props = {
            k: str(v) for k, v in self._cfg.get("arch_props", {}).items()
        }
        self._fn, self._template, _, _ = zoo.build(self._cfg["arch"], arch_props)
        # the checkpoint pytree is {"params", "opt_state"}: rebuild the
        # trainer's optimizer from the SAME config so the restore
        # template matches structurally (the opt_state is discarded)
        tx = {
            "adam": optax.adam, "adamw": optax.adamw, "sgd": optax.sgd,
        }[self._cfg.get("optimizer", "adam")](
            float(self._cfg.get("learning_rate", 1e-3)))
        self._opt_template = jax.jit(tx.init)(self._template)
        self._loss_fn = make_loss_fn(
            self._fn, self._cfg.get("loss", "softmax_ce"))
        self._eval = jax.jit(self._loss_fn)

    def _load_holdout(self) -> List[Tuple[list, list]]:
        """Read the held-out slice straight from the datarepo flat-binary
        layout (meta ``tensors``/``sample_size``; one fixed-size record
        per sample) — no second pipeline needed to score a candidate."""
        if self._holdout is not None:
            return self._holdout
        from ..core.types import TensorSpec

        data, meta_path = self.props["data-location"], self.props["data-json"]
        if not data or not meta_path:
            raise ElementError(
                f"{self.name}: data-location= and data-json= are required")
        with open(meta_path) as f:
            meta = json.load(f)
        specs = [TensorSpec.from_string(s) for s in meta["tensors"]]
        sample_size = int(meta["sample_size"])
        size = os.path.getsize(data)
        total = min(int(meta["total_samples"]),
                    size // sample_size if sample_size else 0)
        start = max(0, int(self.props["holdout-start"]))
        stop = int(self.props["holdout-stop"])
        stop = total if stop < 0 else min(stop, total)
        if start >= stop:
            raise ElementError(
                f"{self.name}: empty holdout [{start}, {stop})")
        n_in = int(self.props["num-inputs"])
        samples = []
        with open(data, "rb") as f:
            f.seek(start * sample_size)
            for _ in range(start, stop):
                buf = f.read(sample_size)
                tensors, off = [], 0
                for s in specs:
                    nb = s.nbytes
                    tensors.append(
                        np.frombuffer(buf[off:off + nb], dtype=s.dtype)
                        .reshape(s.shape))
                    off += nb
                samples.append((tensors[:n_in], tensors[n_in:]))
        self._holdout = samples
        self.log.info("%s: held-out split loaded: %d sample(s) [%d, %d)",
                      self.name, len(samples), start, stop)
        return samples

    def _score(self, cid: int) -> float:
        """Held-out score of checkpoint ``cid`` under the gate metric."""
        from ..core import checkpoint as ckpt

        self._build()
        state = ckpt.restore_state(
            self.props["checkpoint-path"], cid,
            {"params": self._template, "opt_state": self._opt_template})
        params = state["params"]
        samples = self._load_holdout()
        batch = int(self._cfg.get("batch_size", 32))
        losses, accs, weights = [], [], []
        for i in range(0, len(samples), batch):
            chunk = samples[i:i + batch]
            xs = [np.stack([s[0][t] for s in chunk])
                  for t in range(len(chunk[0][0]))]
            ys = [np.stack([s[1][t] for s in chunk])
                  for t in range(len(chunk[0][1]))]
            loss, acc = self._eval(params, xs, ys)
            losses.append(float(loss))
            accs.append(float(acc))
            weights.append(len(chunk))
        w = np.asarray(weights, np.float64)
        score = float(np.average(
            losses if self.props["metric"] == "loss" else accs, weights=w))
        self._scored_params = params  # promoted as-is on a gate pass
        return score

    def _improves(self, score: float) -> bool:
        if self.best_score is None:
            return True
        delta = float(self.props["min-delta"])
        if self.props["metric"] == "loss":
            return score <= self.best_score - delta
        return score >= self.best_score + delta

    def _promote(self, cid: int, score: float) -> None:
        """Export the scored params (crash-atomic msgpack) and stage them
        into the target filter via the validated hot swap.  Any failure
        here keeps the old model serving."""
        from flax import serialization

        from ..core.checkpoint import atomic_write_bytes

        FAULTS.check("trainer.promote")
        path = self.props["promote-path"]
        atomic_write_bytes(path, serialization.to_bytes(self._scored_params))
        pipe = self._pipeline
        target = pipe[self.props["target"]]
        self.last_ticket = target.request_reload(path)
        self.promotions += 1
        self.best_score = score
        self.log.info(
            "%s: promoted checkpoint %d (%s=%.6f) into %s",
            self.name, cid, self.props["metric"], score,
            self.props["target"],
        )
        if pipe is not None:
            pipe.post(BusMessage("element", self.name, {
                "promotion": {"checkpoint": cid, "score": score,
                              "target": self.props["target"]},
            }))

    def handle_frame(self, pad, frame):
        out = [(0, frame)] if (
            self.srcpads and self.srcpads[0].is_linked) else []
        self._seen += 1
        every = max(1, int(self.props["validate-every"]))
        if self._seen % every:
            return out
        from ..core import checkpoint as ckpt

        cid = ckpt.latest_step(self.props["checkpoint-path"])
        if cid is None or cid == self._last_validated:
            return out  # nothing new and durable to judge
        score = self._score(cid)
        self._last_validated = cid
        self.validations += 1
        self.val_score = score
        pipe = self._pipeline
        if pipe is not None:
            pipe.post(BusMessage("element", self.name, {
                "validation": {"checkpoint": cid, "score": score,
                               "metric": self.props["metric"]},
            }))
        if not self._improves(score):
            # validation regression: refuse promotion, keep serving the
            # current model (counted — the gate must be auditable)
            self.promotions_refused += 1
            self.log.warning(
                "%s: promotion refused for checkpoint %d: %s=%.6f does "
                "not improve on %.6f (min-delta=%s)",
                self.name, cid, self.props["metric"], score,
                self.best_score, self.props["min-delta"],
            )
            if pipe is not None:
                pipe.post(BusMessage("warning", self.name, {
                    "promotion_refused": {
                        "checkpoint": cid, "score": score,
                        "best": self.best_score,
                    },
                }))
            return out
        if (self.props["auto-promote"] and self.props["target"]
                and self.props["promote-path"] and pipe is not None):
            try:
                self._promote(cid, score)
            except (KeyboardInterrupt, SystemExit):
                raise
            except BaseException as e:  # noqa: BLE001 — promotion boundary
                # degrade, don't die: the serving filter keeps its model
                self.promote_failures += 1
                self.log.error(
                    "%s: promotion of checkpoint %d failed (old model "
                    "keeps serving): %s", self.name, cid, e,
                )
                pipe.post(BusMessage("warning", self.name, {
                    "promotion_failed": {"checkpoint": cid, "error": e},
                }))
                pipe.incident("promotion_failed", self.name, repr(e))
        return out

    def health_info(self) -> Dict[str, Any]:
        return {
            "train_validations": self.validations,
            "train_val_score": float(self.val_score),
            "train_promotions": self.promotions,
            "train_promotions_refused": self.promotions_refused,
            "train_promote_failures": self.promote_failures,
        }
