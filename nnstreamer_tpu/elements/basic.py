"""Basic source/sink/utility elements.

Reference analogs: appsrc/videotestsrc (GStreamer core sources used by every
nnstreamer example pipeline), ``tensor_sink`` (appsink-like terminal with
``new-data`` signals — ``gst/nnstreamer/elements/gsttensor_sink.c``),
``queue`` (thread boundary; here every element already has a thread so it
only sets mailbox depth), ``tee`` (fan-out), capsfilter (schema constraint),
``join`` (N:1 first-come forwarding — ``gst/join/gstjoin.c``).
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from fractions import Fraction
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from ..core.buffer import FRAME_POOL, BatchFrame, TensorFrame
from ..core.types import ANY, FORMAT_STATIC, StreamSpec, TensorSpec
from ..pipeline.element import (
    Element,
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    TransformElement,
    element,
)



def _frame_interval(framerate: str) -> float:
    """Seconds per frame from an "n/d" framerate string ("30" == "30/1")."""
    n, _, d = framerate.partition("/")
    return float(Fraction(int(d or 1), int(n)))

@element("appsrc")
class AppSrc(SourceElement):
    """Push-model source: the application feeds frames via ``push()``.

    ≙ GStreamer appsrc, the standard way tests/apps inject data.
    """

    PROPERTIES = {
        "max-buffers": Property(int, 64, "internal queue depth"),
        "framerate": Property(str, "", "n/d framerate stamped on frames without pts"),
    }

    @staticmethod
    def _make_queue(depth: int):
        # native condvar mailbox when built (GIL-released blocking puts,
        # bulk drain in frames()); stdlib queue otherwise
        from ..native.runtime import NativeMailbox, available

        if available():
            return NativeMailbox(depth)
        return _queue.Queue(maxsize=depth)

    def __init__(self, name=None):
        super().__init__(name)
        self._q = self._make_queue(self.PROPERTIES["max-buffers"].default)
        self._spec: StreamSpec = ANY
        self._count = 0
        # logical frames pushed/popped — two single-writer counters (app
        # thread / streaming thread), no lock: pending_frames() drives
        # graceful-drain flushing and exact dropped accounting
        self._pushed_logical = 0
        self._popped_logical = 0

    def pending_frames(self) -> int:
        """Logical frames pushed but not yet pulled into the stream
        (drain flushes these; an immediate stop abandons them)."""
        return max(0, self._pushed_logical - self._popped_logical)

    def health_info(self) -> dict:
        """Ingest-buffer depth merged into ``Pipeline.health()`` (and the
        telemetry registry as ``nns.source.pending``)."""
        return {"pending_frames": self.pending_frames()}

    def start(self):
        # honor max-buffers: a full queue blocks push() — backpressure
        # reaches the producer (≙ appsrc max-buffers/block)
        depth = int(self.props["max-buffers"])
        if self._q.maxsize != depth and self._q.empty():
            self._q = self._make_queue(depth)

    def set_spec(self, spec: StreamSpec) -> None:
        self._spec = spec

    def output_spec(self) -> StreamSpec:
        return self._spec

    def push(self, frame_or_arrays: Any, pts: Optional[float] = None) -> None:
        if isinstance(frame_or_arrays, TensorFrame):
            frame = frame_or_arrays
        else:
            arrays = (
                list(frame_or_arrays)
                if isinstance(frame_or_arrays, (list, tuple))
                else [frame_or_arrays]
            )
            # keep device arrays (jax.Array) as-is — zero-copy into the stream
            frame = TensorFrame(
                [a if hasattr(a, "shape") else np.asarray(a) for a in arrays],
                pts=pts,
            )
        if frame.pts is None:
            fr = self.props["framerate"]
            if fr:
                frame.pts = self._count * _frame_interval(fr)
        self._count += 1
        # a pushed frame may itself be a BatchFrame (N logical frames):
        # count what the pop side will count or pending_frames() skews
        self._pushed_logical += getattr(frame, "batch_size", 1)
        self._q.put(frame)

    def push_block(
        self, arrays: Any, pts: Optional[Sequence[Optional[float]]] = None
    ) -> None:
        """Push N logical frames as ONE pre-batched stream item.

        ``arrays`` is a tensor (or list of tensors) whose LEADING axis is
        the frame axis — the block travels the pipeline as a single
        :class:`BatchFrame`, so per-frame mailbox/stacking costs are paid
        once per block instead of once per frame (≙ the reference
        converter's ``frames-per-tensor`` batching,
        gsttensor_converter.c frames-per-tensor).  Batch-capable elements
        (tensor_filter micro-batching, fused decoders) consume the batch
        axis directly; sinks and decoders split it back out.  Other
        per-frame elements (transform/if/...) are NOT batch-aware — feed
        blocks straight into a tensor_filter, or keep per-frame pushes
        when such an element sits upstream of it."""
        tensors = (
            list(arrays) if isinstance(arrays, (list, tuple)) else [arrays]
        )
        tensors = [t if hasattr(t, "shape") else np.asarray(t) for t in tensors]
        n = int(tensors[0].shape[0])
        for t in tensors[1:]:
            if int(t.shape[0]) != n:
                raise ValueError(
                    f"push_block: tensors disagree on the frame axis "
                    f"({n} vs {int(t.shape[0])})"
                )
        if pts is not None and len(pts) != n:
            raise ValueError(
                f"push_block: {len(pts)} pts for {n} frames — a mismatched "
                "frames_info silently misaligns rows downstream"
            )
        if n == 0:
            return  # a VALID empty block carries no frames: explicit no-op
        if pts is None:
            fr = self.props["framerate"]
            if fr:
                dt = _frame_interval(fr)
                pts = [(self._count + i) * dt for i in range(n)]
            else:
                pts = [None] * n
        frame = BatchFrame(
            tensors=tensors,
            pts=pts[0],
            frames_info=[(p, None, {}) for p in pts],
        )
        self._count += n
        self._pushed_logical += n
        self._q.put(frame)

    def push_event(self, event) -> None:
        """Queue an out-of-band event into the stream in arrival order
        (e.g. ``CustomEvent("reload-model", {...})`` ≙ RELOAD_MODEL)."""
        self._q.put(event)

    def end_of_stream(self) -> None:
        self._q.put(None)

    def frames(self) -> Iterator[TensorFrame]:
        get_many = getattr(self._q, "get_many", None)
        while True:
            try:
                if get_many is not None:
                    # bulk drain: one native call per burst, not per frame
                    items = get_many(32, timeout=0.1)
                else:
                    items = [self._q.get(timeout=0.1)]
            except _queue.Empty:
                # stay responsive to pipeline stop/drain while idle
                from ..core.lifecycle import pipeline_quiescing

                p = self._pipeline
                if p is not None and p._stop_flag.is_set():
                    return
                # graceful drain must flush frames already pushed: a
                # push can land between the Empty above and the flag
                # check, so only end the stream once pending_frames()
                # confirms nothing is held (push() bumps the counter
                # BEFORE enqueuing, making this re-check sufficient)
                if pipeline_quiescing(self) and self.pending_frames() <= 0:
                    return
                continue
            for item in items:
                if item is None:
                    return
                if isinstance(item, TensorFrame):
                    self._popped_logical += getattr(item, "batch_size", 1)
                yield item


@element("videotestsrc")
class VideoTestSrc(SourceElement):
    """Synthetic video source (≙ gst videotestsrc as used in reference SSAT
    tests): deterministic RGB pattern frames."""

    PROPERTIES = {
        "num-buffers": Property(int, 10, "number of frames to emit (-1 = unlimited)"),
        "width": Property(int, 224),
        "height": Property(int, 224),
        "framerate": Property(str, "30/1"),
        "pattern": Property(str, "gradient", "gradient|solid|random"),
        "seed": Property(int, 0),
    }

    def output_spec(self) -> StreamSpec:
        h, w = self.props["height"], self.props["width"]
        n, _, d = self.props["framerate"].partition("/")
        return StreamSpec(
            (TensorSpec((h, w, 3), np.uint8, "video"),),
            FORMAT_STATIC,
            Fraction(int(n), int(d or 1)),
        )

    def frames(self) -> Iterator[TensorFrame]:
        h, w = self.props["height"], self.props["width"]
        dt = _frame_interval(self.props["framerate"])
        rng = np.random.default_rng(self.props["seed"])
        count = self.props["num-buffers"]
        i = 0
        while count < 0 or i < count:
            if self.props["pattern"] == "random":
                img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
            elif self.props["pattern"] == "solid":
                img = np.full((h, w, 3), (i * 8) % 256, np.uint8)
            else:  # gradient, phase-shifted per frame
                row = (np.arange(w, dtype=np.uint32) * 255 // max(w - 1, 1) + i * 3) % 256
                img = np.broadcast_to(row[None, :, None], (h, w, 3)).astype(np.uint8)
            yield TensorFrame([img], pts=i * dt, duration=dt)
            i += 1


@element("tensor_sink", "appsink")
class TensorSink(SinkElement):
    """Terminal sink emitting new-data callbacks and retaining frames.

    ≙ ``tensor_sink`` (gsttensor_sink.c): signals new-data/eos, property to
    cap retained frames.
    """

    BATCH_AWARE = True  # splits blocks itself (split-batches prop)

    PROPERTIES = {
        "max-stored": Property(int, 0, "retain at most N frames (0 = all)"),
        "to-host": Property(bool, True, "materialize device arrays on render"),
        "max-buffers": Property(int, 0, "mailbox depth override"),
        "split-batches": Property(
            bool, True,
            "fan incoming BatchFrames back out to per-frame callbacks "
            "(false = deliver the block whole; callbacks check batch_size)",
        ),
        # ≙ gsttensor_sink.c props: gate/throttle the new-data signal
        # (frames are still stored either way)
        "emit-signal": Property(bool, True, "emit new-data callbacks"),
        "signal-rate": Property(
            int, 0, "max new-data callbacks per second (0 = every frame)"
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self.frames: List[TensorFrame] = []
        self._callbacks: List[Callable[[TensorFrame], None]] = []
        self.eos_received = threading.Event()
        self._last_signal_ts = 0.0
        # logical frames rendered (single-writer: the sink's streaming
        # thread) — the terminal-delivery counter telemetry exports
        self._rendered = 0

    def connect_new_data(self, cb: Callable[[TensorFrame], None]) -> None:
        self._callbacks.append(cb)

    def health_info(self) -> dict:
        """Delivery counter merged into ``Pipeline.health()`` (and the
        telemetry registry as ``nns.sink.rendered``)."""
        return {"rendered_frames": self._rendered}

    def render(self, frame: TensorFrame) -> None:
        if isinstance(frame, BatchFrame) and self.props["split-batches"]:
            # batch-through chains end here: fan the micro-batch back out
            # so callbacks/stored frames see per-frame granularity
            # (split-batches=false delivers the block whole — at chip-rate
            # streams the per-frame fan-out is itself the bottleneck)
            for f in frame.split():
                self.render(f)
            return
        if self.props["to-host"]:
            frame = frame.to_host()
        self._rendered += getattr(frame, "batch_size", 1)
        limit = self.props["max-stored"]
        self.frames.append(frame)
        if limit and len(self.frames) > limit:
            evicted = self.frames.pop(0)
            # frame-pool recycling: the sink is the end of most frames'
            # lives; the refcount guard refuses frames a callback retained
            FRAME_POOL.recycle(evicted)
        if not self.props["emit-signal"]:
            return
        rate = self.props["signal-rate"]
        if rate > 0:
            now = time.monotonic()
            if now - self._last_signal_ts < 1.0 / rate:
                return
            self._last_signal_ts = now
        for cb in self._callbacks:
            cb(frame)

    def handle_eos(self, pad):
        # the scheduler routes EOS here (not handle_event)
        self.eos_received.set()
        return []


@element("queue")
class Queue(TransformElement):
    """Thread-boundary element (≙ GstQueue): the explicit way to break a
    fused streaming thread.  A linear chain shares ONE worker thread under
    the scheduler's fusion pass; inserting `queue` ends the segment, giving
    the downstream half its own thread and a bounded mailbox — use it where
    pipeline parallelism pays (a slow stage that should overlap its
    neighbors).  Also sets the buffering depth (`max-buffers` maps to the
    mailbox size) and provides the live-pipeline ``leaky`` modes (≙
    GstQueue leaky): a full queue then DROPS frames instead of blocking the
    producer — ``leaky=upstream`` drops the incoming frame,
    ``leaky=downstream`` drops the oldest queued frame.  Events are never
    dropped."""

    BATCH_AWARE = True  # batch-transparent pass-through
    THREAD_BOUNDARY = True  # the explicit fusion boundary

    PROPERTIES = {
        "max-buffers": Property(int, 16, "bounded queue depth (backpressure)"),
        "leaky": Property(
            str, "",
            "''|no|upstream|downstream — full queue drops frames instead "
            "of blocking (upstream: incoming; downstream: oldest)",
        ),
    }

    def start(self):
        mode = (self.props["leaky"] or "no").lower()
        if mode not in ("", "no", "upstream", "downstream"):
            from ..pipeline.element import ElementError

            raise ElementError(
                f"{self.name}: leaky must be ''|no|upstream|downstream, "
                f"got {self.props['leaky']!r}"
            )

    @property
    def leaky_policy(self) -> str:
        mode = (self.props["leaky"] or "no").lower()
        return "" if mode in ("", "no") else mode

    def transform(self, frame):
        return frame


@element("identity")
class Identity(TransformElement):
    BATCH_AWARE = True  # batch-transparent; sleep scales per logical frame

    PROPERTIES = {
        "sleep": Property(float, 0.0, "artificial per-frame delay, seconds (tests)"),
    }

    def transform(self, frame):
        if self.props["sleep"]:
            time.sleep(
                self.props["sleep"] * getattr(frame, "batch_size", 1)
            )
        return frame


@element("tee")
class Tee(Element):
    """1:N fan-out; frames are pushed to every linked src pad (payloads are
    shared, not copied — downstream must not mutate in place)."""

    BATCH_AWARE = True  # batch-transparent fan-out

    NUM_SRC_PADS = None  # request pads

    def derive_spec(self, pad=0):
        return self.sink_specs.get(0, ANY)

    def handle_frame(self, pad, frame):
        return [(i, frame) for i in range(len(self.srcpads))]


@element("capsfilter")
class CapsFilter(TransformElement):
    """Constrain the stream schema (≙ capsfilter with other/tensors caps).

    The parser creates one for bare schema strings between ``!`` links.
    """

    BATCH_AWARE = True  # batch-transparent

    PROPERTIES = {"caps": Property(str, "", "tensors schema string")}

    def _target(self) -> StreamSpec:
        text = self.props["caps"]
        return StreamSpec.from_string(text) if text else ANY

    def accept_spec(self, pad, spec):
        merged = self._target().intersect(spec)
        if merged is None:
            raise ElementError(
                f"{self.name}: schema {spec.to_string()} does not satisfy {self.props['caps']}"
            )
        return merged

    def derive_spec(self, pad=0):
        return self.sink_specs.get(0, self._target())

    def transform(self, frame):
        return frame


@element("join")
class Join(Element):
    """N:1 first-come forwarding without synchronization.

    ≙ ``gst/join/gstjoin.c``: whichever sink pad receives data first pushes
    through; no collation.
    """

    BATCH_AWARE = True  # batch-transparent forwarding

    NUM_SINK_PADS = None

    def derive_spec(self, pad=0):
        for spec in self.sink_specs.values():
            return spec
        return ANY

    def handle_frame(self, pad, frame):
        return [(0, frame)]

    def handle_eos(self, pad):
        return []  # scheduler emits EOS when all pads end
