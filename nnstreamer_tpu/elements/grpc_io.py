"""tensor_src_grpc / tensor_sink_grpc — raw tensor streams over gRPC.

Reference: ``ext/nnstreamer/tensor_source/tensor_src_grpc.c`` (515 LoC) and
``tensor_sink/tensor_sink_grpc.c`` (396) over
``nnstreamer_grpc_{common,protobuf,flatbuf}.cc``: either element can run as
the gRPC *server* or *client* (``server`` prop), with protobuf/flatbuf IDL.
Unlike tensor_query there is no request/response pairing — this is a
one-way tensor pipe.

TPU build mapping: the wire IDL is the in-repo flex-header format
(:mod:`nnstreamer_tpu.distributed.wire` — the same schema the query/edge
elements speak); two RPCs cover both role combinations:

  * ``nns.Stream/Send``  (unary)            sink-as-client  -> src-as-server
  * ``nns.Stream/Pull``  (server streaming) src-as-client   <- sink-as-server
"""

from __future__ import annotations

import queue as _queue
import threading
import time
from concurrent import futures
from typing import Iterator, Optional

import grpc

from ..core.buffer import TensorFrame
from ..core.types import ANY, StreamSpec
from ..distributed import wire
from ..distributed.service import GRPC_OPTS as _OPTS, identity_codec as _ident
from ..pipeline.element import (
    ElementError,
    Property,
    SinkElement,
    SourceElement,
    element,
)


class _StreamServer:
    """One gRPC server hosting Send (inbound) and Pull (outbound) for an
    element running in server mode."""

    def __init__(self, host: str, port: int, depth: int):
        self.inbox: "_queue.Queue[bytes]" = _queue.Queue(depth)
        self.outbox: "_queue.Queue[Optional[bytes]]" = _queue.Queue(depth)
        self._stop = threading.Event()
        handlers = {
            "Send": grpc.unary_unary_rpc_method_handler(
                self._send, request_deserializer=_ident,
                response_serializer=_ident,
            ),
            "Pull": grpc.unary_stream_rpc_method_handler(
                self._pull, request_deserializer=_ident,
                response_serializer=_ident,
            ),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8), options=_OPTS
        )
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler("nns.Stream", handlers),)
        )
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        if self.port == 0:
            raise ElementError(f"cannot bind gRPC stream server on {port}")
        self._server.start()

    def _send(self, request: bytes, context) -> bytes:
        try:
            self.inbox.put(request, timeout=10.0)
        except _queue.Full:
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, "inbox full")
        return b""

    def _pull(self, request: bytes, context):
        while not self._stop.is_set():
            try:
                item = self.outbox.get(timeout=0.2)
            except _queue.Empty:
                continue
            if item is None:  # EOS
                return
            yield item

    def stop(self) -> None:
        self._stop.set()
        self._server.stop(grace=0.5)


@element("tensor_sink_grpc")
class GrpcSink(SinkElement):
    PROPERTIES = {
        "host": Property(str, "127.0.0.1", "bind/connect host"),
        "port": Property(int, 55115, "bind/connect port (0 = auto in server mode)"),
        "server": Property(bool, False, "run as gRPC server (clients Pull)"),
        "idl": Property(str, "flex", "wire IDL: flex | protobuf | flatbuf (interop)"),
        "max-buffers": Property(int, 64, "stream queue depth"),
        "retry-timeout": Property(
            float, 10.0,
            "client mode: keep retrying a failed Send for up to this many "
            "seconds (peer restart window); 0 = fail fast",
        ),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._srv: Optional[_StreamServer] = None
        self._channel = None
        self._stub = None
        self.bound_port: Optional[int] = None
        self._encode = wire.encode_frame

    def start(self) -> None:
        self._encode, _ = wire.get_codec(self.props["idl"])
        if self.props["server"]:
            self._srv = _StreamServer(
                self.props["host"], self.props["port"],
                self.props["max-buffers"],
            )
            self.bound_port = self._srv.port
        else:
            self._channel = grpc.insecure_channel(
                f"{self.props['host']}:{self.props['port']}", options=_OPTS
            )
            self._stub = self._channel.unary_unary(
                "/nns.Stream/Send",
                request_serializer=_ident, response_deserializer=_ident,
            )

    def stop(self) -> None:
        if self._srv is not None:
            try:  # signal EOS to pullers
                self._srv.outbox.put_nowait(None)
            except _queue.Full:
                pass
            self._srv.stop()
            self._srv = None
        if self._channel is not None:
            self._channel.close()
            self._channel = None
            self._stub = None

    # transient codes worth retrying through a peer restart; anything else
    # (INVALID_ARGUMENT, UNIMPLEMENTED, ...) fails fast
    _RETRYABLE = frozenset({
        grpc.StatusCode.UNAVAILABLE,
        grpc.StatusCode.DEADLINE_EXCEEDED,
        grpc.StatusCode.RESOURCE_EXHAUSTED,
    })

    def _stopping(self) -> bool:
        p = self._pipeline
        return p is not None and p._stop_flag.is_set()

    def render(self, frame: TensorFrame) -> None:
        payload = self._encode(frame)
        if self._srv is not None:
            self._srv.outbox.put(payload, timeout=10.0)
        elif self._stub is not None:
            # survive a server restart mid-stream: the channel reconnects
            # on its own, so retry the Send with backoff inside the window
            deadline = time.monotonic() + max(0.0, self.props["retry-timeout"])
            backoff = 0.1
            while True:
                try:
                    self._stub(payload, timeout=10.0)
                    return
                except grpc.RpcError as e:
                    code = e.code() if hasattr(e, "code") else None
                    if code not in self._RETRYABLE:
                        raise ElementError(
                            f"{self.name}: Send failed ({code}): {e}"
                        ) from None
                    if time.monotonic() >= deadline or self._stopping():
                        if self._stopping():
                            return  # pipeline is tearing down; drop quietly
                        raise ElementError(
                            f"{self.name}: Send failed after retries: {e}"
                        ) from None
                    self.log.info("grpc send failed; retrying in %.1fs", backoff)
                    time.sleep(backoff)
                    backoff = min(backoff * 2, 1.0)

    def handle_eos(self, pad):
        if self._srv is not None:
            try:
                self._srv.outbox.put(None, timeout=1.0)
            except _queue.Full:
                pass
        return None


@element("tensor_src_grpc")
class GrpcSrc(SourceElement):
    PROPERTIES = {
        "host": Property(str, "127.0.0.1", "bind/connect host"),
        "port": Property(int, 55115, "bind/connect port (0 = auto in server mode)"),
        "server": Property(bool, True, "run as gRPC server (peers Send)"),
        "idl": Property(str, "flex", "wire IDL: flex | protobuf | flatbuf (interop)"),
        "num-buffers": Property(int, -1, "EOS after N frames (-1 = forever)"),
        "timeout": Property(int, 10000, "ms without a frame before EOS"),
        "verify-checksum": Property(
            bool, True, "verify wire integrity checksums on received "
            "frames (flex v2 envelopes); corrupt frames are dropped and "
            "counted in health()"),
    }

    def __init__(self, name=None):
        super().__init__(name)
        self._srv: Optional[_StreamServer] = None
        self._channel = None
        self.bound_port: Optional[int] = None
        self._reader_stop = threading.Event()
        self._decode_payload = wire.decode_frame
        self._corrupt_dropped = 0

    def output_spec(self) -> StreamSpec:
        return ANY

    def start(self) -> None:
        self._reader_stop.clear()
        _, self._decode_payload = wire.get_codec(self.props["idl"])
        if self.props["server"]:
            self._srv = _StreamServer(
                self.props["host"], self.props["port"], 64
            )
            self.bound_port = self._srv.port
        else:
            self._channel = grpc.insecure_channel(
                f"{self.props['host']}:{self.props['port']}", options=_OPTS
            )

    def stop(self) -> None:
        self._reader_stop.set()
        if self._srv is not None:
            self._srv.stop()
            self._srv = None
        if self._channel is not None:
            self._channel.close()
            self._channel = None

    def frames(self) -> Iterator[TensorFrame]:
        limit = self.props["num-buffers"]
        timeout_s = self.props["timeout"] / 1000.0
        n = 0
        if self._srv is not None:
            inbox = self._srv.inbox
        else:
            # client mode: a reader thread feeds an inbox so the 'timeout'
            # prop gives a real inter-frame deadline (a bare stream iterator
            # would block forever on a stalled peer)
            inbox = _queue.Queue(64)
            pull = self._channel.unary_stream(
                "/nns.Stream/Pull",
                request_serializer=_ident, response_deserializer=_ident,
            )

            stop = self._reader_stop

            def _reader():
                # reconnect-on-server-restart: the Pull stream breaking is
                # NOT end-of-stream for the element — re-open it with
                # backoff until stop; the frames() inter-frame timeout
                # remains the only EOS authority (matching the failover
                # quality of the query elements, VERDICT item 10)
                backoff = 0.1
                while not stop.is_set():
                    try:
                        for payload in pull(b"", timeout=None):
                            backoff = 0.1  # healthy stream resets backoff
                            # bounded put with a stop check: once frames()
                            # exits nobody drains the inbox, and an
                            # unconditional put() would park this thread
                            # forever holding payload + channel
                            while not stop.is_set():
                                try:
                                    inbox.put(payload, timeout=0.25)
                                    break
                                except _queue.Full:
                                    continue
                            if stop.is_set():
                                return
                    except grpc.RpcError as e:
                        self.log.info(
                            "grpc pull broke (%s); retrying in %.1fs",
                            getattr(e, "code", lambda: e)(), backoff,
                        )
                    if stop.wait(backoff):
                        return
                    backoff = min(backoff * 2, 2.0)

            threading.Thread(
                target=_reader, name=f"{self.name}-pull", daemon=True
            ).start()
        while limit < 0 or n < limit:
            # bounded wait slices: stop/drain must end the stream without
            # holding the worker for the whole sub-timeout
            deadline = time.monotonic() + timeout_s
            payload = None
            while payload is None:
                from ..core.lifecycle import pipeline_quiescing

                if pipeline_quiescing(self):
                    return
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self.log.info("grpc src timeout; ending stream")
                    return
                try:
                    payload = inbox.get(timeout=min(0.25, remaining))
                except _queue.Empty:
                    continue
            frame = self._decode(payload)
            if frame is not None:
                n += 1
                yield frame

    def health_info(self) -> dict:
        """Integrity accounting merged into ``Pipeline.health()``."""
        return {"corrupt_dropped": self._corrupt_dropped}

    def _decode(self, payload: bytes) -> Optional[TensorFrame]:
        try:
            return self._decode_payload(
                payload, verify=self.props["verify-checksum"])
        except wire.WireError as e:
            self._corrupt_dropped += 1
            self.log.warning("undecodable grpc frame dropped: %s", e)
            return None
