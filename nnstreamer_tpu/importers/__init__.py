"""Model importers: load third-party model formats and lower them to JAX.

The reference achieves "drop a model file in and it runs" through ~20
vendor-runtime subplugins under ``ext/nnstreamer/tensor_filter/`` (each
wraps an external interpreter).  On TPU there is exactly one runtime that
matters — XLA — so the TPU-native equivalent is an *importer*: parse the
foreign format, lower the graph to jnp, and let jax-xla run it.  First
format: TFLite flatbuffers (the reference's flagship format,
``tensor_filter_tensorflow_lite.cc``).
"""

from .tflite_reader import TFLiteModel, read_tflite
from .tflite_lower import lower_tflite
from .onnx_reader import OnnxModel, read_onnx
from .onnx_lower import lower_onnx

__all__ = ["TFLiteModel", "read_tflite", "lower_tflite",
           "OnnxModel", "read_onnx", "lower_onnx"]
