"""Lower a parsed ONNX graph to a jittable JAX function.

Same design as ``tflite_lower.py``: the whole graph traces ONCE into a
single XLA program (convs/matmuls on the MXU, elementwise fused by XLA),
versus the reference's vendor-runtime subplugins that interpret per-op.

ONNX is NCHW; lowering keeps that layout (XLA lays out for TPU itself).
Shape-computation chains (Shape → Gather → Unsqueeze → Concat → Reshape,
the pattern torch exports emit) fold at trace time: ops whose inputs are
all statically known compute in numpy and stay usable as shape/axis
arguments — XLA requires static shapes, so data-dependent shapes are
rejected at load with a clear error.

Covered op set: the common CNN/MLP/attention inventory (Conv /
ConvTranspose / pools / Gemm / MatMul / BatchNorm / LayerNorm /
activations / reductions / shape ops / Resize / Pad / Slice / Concat /
Split / Where / comparisons / Erf-Gelu).  Unsupported ops raise
``OnnxLowerError`` naming the op at load time.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .onnx_reader import OnnxModel, OnnxNode


class OnnxLowerError(NotImplementedError):
    pass


def _act_pads(pads: Sequence[int], ndim: int) -> List[Tuple[int, int]]:
    """ONNX pads [x1b, x2b, ..., x1e, x2e, ...] -> per-spatial (lo, hi)."""
    half = len(pads) // 2
    return [(int(pads[i]), int(pads[i + half])) for i in range(half)]


def _auto_pad(auto_pad: bytes, in_shape, kernel, strides, dilations):
    """SAME_UPPER / SAME_LOWER / VALID handling (deprecated but emitted)."""
    mode = auto_pad.decode() if isinstance(auto_pad, bytes) else auto_pad
    if mode == "VALID":
        return [(0, 0)] * len(in_shape)
    out = []
    for i, (size, k, s, d) in enumerate(
            zip(in_shape, kernel, strides, dilations)):
        eff = (k - 1) * d + 1
        total = max(0, (-(-size // s) - 1) * s + eff - size)
        lo = total // 2
        hi = total - lo
        if mode == "SAME_LOWER":
            lo, hi = hi, lo
        out.append((lo, hi))
    return out


class _Lowering:
    def __init__(self, model: OnnxModel):
        self.m = model
        self.consts: Dict[str, np.ndarray] = dict(model.initializers)
        # Constant nodes are initializer-equivalent: fold them at load
        for node in model.nodes:
            if node.op_type == "Constant":
                val = node.attrs.get("value")
                if val is None:
                    for k in ("value_float", "value_int"):
                        if k in node.attrs:
                            val = np.asarray(node.attrs[k])
                if val is None:
                    raise OnnxLowerError(
                        "Constant node without tensor value")
                self.consts[node.outputs[0]] = np.asarray(val)
        unsupported = sorted({
            n.op_type for n in model.nodes
            if n.op_type not in _OP_IMPLS and n.op_type != "Constant"})
        if unsupported:
            raise OnnxLowerError(
                f"unsupported onnx ops: {', '.join(unsupported)} "
                f"(supported: {', '.join(sorted(_OP_IMPLS))})")
        # trace-time static values (shape chains); reset per run
        self.static: Dict[str, np.ndarray] = {}

    def params(self) -> Dict[str, np.ndarray]:
        return dict(self.consts)

    def drop_host_consts(self) -> None:
        """See tflite_lower.drop_host_consts — the params pytree owns the
        weights once the caller takes it; keep only the small arrays the
        trace needs as static shape/axis arguments."""
        self.consts = {k: v for k, v in self.consts.items() if v.size <= 256}

    # -- value access -------------------------------------------------------
    def val(self, env, name: str):
        if not name:
            return None
        if name in env:
            return env[name]
        if name in self.consts:
            return jnp.asarray(self.consts[name])
        raise OnnxLowerError(f"tensor {name!r} undefined (graph order?)")

    def static_val(self, env, name: str) -> np.ndarray:
        """Integer-domain static value (shape vectors, axes, pads)."""
        if name in self.static:
            return self.static[name]
        if name in self.consts:
            return np.asarray(self.consts[name])
        raise OnnxLowerError(
            f"tensor {name!r} must be statically known (XLA needs static "
            "shapes; data-dependent shape arguments are not supported)")

    def maybe_static(self, env, name: str) -> Optional[np.ndarray]:
        if name in self.static:
            return self.static[name]
        if name in self.consts:
            return np.asarray(self.consts[name])
        return None

    def set_out(self, env, node: OnnxNode, value, static=None) -> None:
        env[node.outputs[0]] = value
        if static is not None:
            self.static[node.outputs[0]] = np.asarray(static)

    # -- the jittable function ---------------------------------------------
    def __call__(self, *inputs):
        return self.run(self.consts, *inputs)

    def run(self, consts: Dict[str, Any], *inputs):
        m = self.m
        if len(inputs) != len(m.inputs):
            raise ValueError(
                f"model takes {len(m.inputs)} inputs, got {len(inputs)}")
        env: Dict[str, Any] = dict(consts)
        self.static = {}
        for vi, x in zip(m.inputs, inputs):
            env[vi.name] = jnp.asarray(x)
        for node in m.nodes:
            if node.op_type == "Constant":
                continue  # folded at load
            _OP_IMPLS[node.op_type](self, env, node)
        return tuple(env[vi.name] for vi in m.outputs)


# -- op implementations ------------------------------------------------------

def _ints(node: OnnxNode, key: str, default=None):
    v = node.attrs.get(key, default)
    return None if v is None else [int(x) for x in v]


def _op_conv(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])            # NCHW
    w = L.val(env, node.inputs[1])            # [O, I/g, kH, kW]
    b = L.val(env, node.inputs[2]) if len(node.inputs) > 2 else None
    spatial = x.ndim - 2
    kernel = _ints(node, "kernel_shape") or list(w.shape[2:])
    strides = _ints(node, "strides") or [1] * spatial
    dilations = _ints(node, "dilations") or [1] * spatial
    group = int(node.attrs.get("group", 1))
    auto_pad = node.attrs.get("auto_pad", b"NOTSET")
    if auto_pad and auto_pad not in (b"NOTSET", "NOTSET"):
        pads = _auto_pad(auto_pad, x.shape[2:], kernel, strides, dilations)
    else:
        pads = _act_pads(_ints(node, "pads") or [0] * (2 * spatial), x.ndim)
    dn = ("NCHW", "OIHW", "NCHW") if spatial == 2 else None
    if spatial == 1:
        # lift 1-D conv to 2-D (XLA tiles 2-D convs onto the MXU)
        x2 = x[:, :, None, :]
        w2 = w[:, :, None, :]
        y = lax.conv_general_dilated(
            x2, w2, window_strides=(1, strides[0]),
            padding=[(0, 0), pads[0]],
            rhs_dilation=(1, dilations[0]),
            dimension_numbers=("NCHW", "OIHW", "NCHW"),
            feature_group_count=group)
        y = y[:, :, 0, :]
    elif spatial == 2:
        y = lax.conv_general_dilated(
            x, w, window_strides=tuple(strides), padding=pads,
            rhs_dilation=tuple(dilations), dimension_numbers=dn,
            feature_group_count=group)
    else:
        raise OnnxLowerError(f"Conv with {spatial} spatial dims")
    if b is not None:
        y = y + b.reshape((1, -1) + (1,) * spatial)
    L.set_out(env, node, y)


def _op_conv_transpose(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])            # NCHW
    w = L.val(env, node.inputs[1])            # [I, O/g, kH, kW]
    b = L.val(env, node.inputs[2]) if len(node.inputs) > 2 else None
    if int(node.attrs.get("group", 1)) != 1:
        raise OnnxLowerError("grouped ConvTranspose")
    spatial = x.ndim - 2
    if spatial != 2:
        raise OnnxLowerError("ConvTranspose only 2-D")
    strides = _ints(node, "strides") or [1, 1]
    pads = _act_pads(_ints(node, "pads") or [0, 0, 0, 0], x.ndim)
    out_pads = _ints(node, "output_padding") or [0, 0]
    kh, kw = w.shape[2], w.shape[3]
    # gradient-style: lhs-dilate by stride, VALID conv with flipped kernel
    w_flip = jnp.flip(w, axis=(2, 3))
    w_t = jnp.transpose(w_flip, (1, 0, 2, 3))  # [O, I, kH, kW]
    y = lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1),
        padding=[(kh - 1 - pads[0][0], kh - 1 - pads[0][1] + out_pads[0]),
                 (kw - 1 - pads[1][0], kw - 1 - pads[1][1] + out_pads[1])],
        lhs_dilation=tuple(strides),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if b is not None:
        y = y + b.reshape(1, -1, 1, 1)
    L.set_out(env, node, y)


def _pool(L: _Lowering, env, node: OnnxNode, kind: str):
    x = L.val(env, node.inputs[0])
    spatial = x.ndim - 2
    kernel = _ints(node, "kernel_shape")
    strides = _ints(node, "strides") or [1] * spatial
    if _ints(node, "dilations", [1] * spatial) != [1] * spatial:
        raise OnnxLowerError(f"{node.op_type} with dilations")
    if int(node.attrs.get("ceil_mode", 0)):
        raise OnnxLowerError(f"{node.op_type} ceil_mode")
    auto_pad = node.attrs.get("auto_pad", b"NOTSET")
    if auto_pad and auto_pad not in (b"NOTSET", "NOTSET"):
        pads = _auto_pad(auto_pad, x.shape[2:], kernel, strides,
                         [1] * spatial)
    else:
        pads = _act_pads(_ints(node, "pads") or [0] * (2 * spatial), x.ndim)
    window = (1, 1) + tuple(kernel)
    wstrides = (1, 1) + tuple(strides)
    wpads = [(0, 0), (0, 0)] + pads
    if kind == "max":
        y = lax.reduce_window(x, -jnp.inf, lax.max, window, wstrides, wpads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, wstrides, wpads)
        if int(node.attrs.get("count_include_pad", 0)):
            y = summed / float(np.prod(kernel))
        else:
            ones = jnp.ones(x.shape, x.dtype)
            counts = lax.reduce_window(
                ones, 0.0, lax.add, window, wstrides, wpads)
            y = summed / counts
    L.set_out(env, node, y)


def _op_gemm(L: _Lowering, env, node: OnnxNode):
    a = L.val(env, node.inputs[0])
    b = L.val(env, node.inputs[1])
    c = L.val(env, node.inputs[2]) if len(node.inputs) > 2 else None
    alpha = float(node.attrs.get("alpha", 1.0))
    beta = float(node.attrs.get("beta", 1.0))
    if int(node.attrs.get("transA", 0)):
        a = a.T
    if int(node.attrs.get("transB", 0)):
        b = b.T
    y = alpha * (a @ b)
    if c is not None and beta:
        y = y + beta * c
    L.set_out(env, node, y)


def _op_batchnorm(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    scale = L.val(env, node.inputs[1])
    bias = L.val(env, node.inputs[2])
    mean = L.val(env, node.inputs[3])
    var = L.val(env, node.inputs[4])
    eps = float(node.attrs.get("epsilon", 1e-5))
    shape = (1, -1) + (1,) * (x.ndim - 2)
    y = (x - mean.reshape(shape)) * (
        scale.reshape(shape) / jnp.sqrt(var.reshape(shape) + eps)
    ) + bias.reshape(shape)
    L.set_out(env, node, y)


def _op_layernorm(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    scale = L.val(env, node.inputs[1])
    bias = L.val(env, node.inputs[2]) if len(node.inputs) > 2 else None
    axis = int(node.attrs.get("axis", -1))
    eps = float(node.attrs.get("epsilon", 1e-5))
    axes = tuple(range(axis % x.ndim, x.ndim))
    mu = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=axes, keepdims=True)
    y = (x - mu) / jnp.sqrt(var + eps) * scale
    if bias is not None:
        y = y + bias
    L.set_out(env, node, y)


def _op_reshape(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    shape = [int(v) for v in L.static_val(env, node.inputs[1]).ravel()]
    if not int(node.attrs.get("allowzero", 0)):
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    L.set_out(env, node, jnp.reshape(x, shape))


def _op_flatten(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    axis = int(node.attrs.get("axis", 1)) % (x.ndim + 1)
    lead = int(np.prod(x.shape[:axis])) if axis else 1
    L.set_out(env, node, jnp.reshape(x, (lead, -1)))


def _op_transpose(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    perm = _ints(node, "perm") or list(range(x.ndim))[::-1]
    L.set_out(env, node, jnp.transpose(x, perm))


def _op_concat(L: _Lowering, env, node: OnnxNode):
    parts = [L.val(env, n) for n in node.inputs]
    axis = int(node.attrs.get("axis", 0))
    statics = [L.maybe_static(env, n) for n in node.inputs]
    static = (np.concatenate(statics, axis=axis)
              if all(s is not None for s in statics) else None)
    L.set_out(env, node, jnp.concatenate(parts, axis=axis), static)


def _op_softmax(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    axis = int(node.attrs.get("axis", -1))
    L.set_out(env, node, jax.nn.softmax(x, axis=axis))


def _op_clip(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    lo = (L.val(env, node.inputs[1])
          if len(node.inputs) > 1 and node.inputs[1] else
          node.attrs.get("min"))
    hi = (L.val(env, node.inputs[2])
          if len(node.inputs) > 2 and node.inputs[2] else
          node.attrs.get("max"))
    y = x
    if lo is not None:
        y = jnp.maximum(y, lo)
    if hi is not None:
        y = jnp.minimum(y, hi)
    L.set_out(env, node, y)


def _op_shape(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    shape = np.asarray(x.shape, np.int64)
    L.set_out(env, node, jnp.asarray(shape.astype(np.int32)), shape)


def _op_gather(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    axis = int(node.attrs.get("axis", 0))
    idx_static = L.maybe_static(env, node.inputs[1])
    x_static = L.maybe_static(env, node.inputs[0])
    if idx_static is not None and x_static is not None:
        static = np.take(x_static, idx_static.astype(np.int64), axis=axis)
    else:
        static = None
    idx = (jnp.asarray(idx_static.astype(np.int32))
           if idx_static is not None
           else env[node.inputs[1]].astype(jnp.int32))
    L.set_out(env, node, jnp.take(x, idx, axis=axis), static)


def _op_unsqueeze(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    if len(node.inputs) > 1:                   # opset >= 13: axes input
        axes = [int(v) for v in L.static_val(env, node.inputs[1]).ravel()]
    else:
        axes = _ints(node, "axes")
    y = x
    for ax in sorted(a % (x.ndim + len(axes)) for a in axes):
        y = jnp.expand_dims(y, ax)
    s = L.maybe_static(env, node.inputs[0])
    static = None
    if s is not None:
        static = s
        for ax in sorted(a % (s.ndim + len(axes)) for a in axes):
            static = np.expand_dims(static, ax)
    L.set_out(env, node, y, static)


def _op_squeeze(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    if len(node.inputs) > 1 and node.inputs[1]:
        axes = tuple(int(v) % x.ndim
                     for v in L.static_val(env, node.inputs[1]).ravel())
    else:
        axes = tuple(_ints(node, "axes") or
                     [i for i, d in enumerate(x.shape) if d == 1])
    L.set_out(env, node, jnp.squeeze(x, axis=axes))


def _op_slice(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    if len(node.inputs) > 1:                   # opset >= 10: inputs
        starts = L.static_val(env, node.inputs[1]).ravel()
        ends = L.static_val(env, node.inputs[2]).ravel()
        axes = (L.static_val(env, node.inputs[3]).ravel()
                if len(node.inputs) > 3 and node.inputs[3]
                else np.arange(len(starts)))
        steps = (L.static_val(env, node.inputs[4]).ravel()
                 if len(node.inputs) > 4 and node.inputs[4]
                 else np.ones(len(starts), np.int64))
    else:                                      # opset 1 attrs
        starts = np.asarray(_ints(node, "starts"))
        ends = np.asarray(_ints(node, "ends"))
        axes = np.asarray(_ints(node, "axes") or range(len(starts)))
        steps = np.ones(len(starts), np.int64)
    idx: List[Any] = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        ax = int(ax) % x.ndim
        idx[ax] = slice(int(st), None if en >= 2**31 - 1 else int(en),
                        int(sp))
    s = L.maybe_static(env, node.inputs[0])
    static = s[tuple(idx)] if s is not None else None
    L.set_out(env, node, x[tuple(idx)], static)


def _op_split(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    axis = int(node.attrs.get("axis", 0)) % x.ndim
    if len(node.inputs) > 1 and node.inputs[1]:
        sizes = [int(v) for v in L.static_val(env, node.inputs[1]).ravel()]
    else:
        sizes = _ints(node, "split")
    if sizes:
        bounds = np.cumsum(sizes)[:-1].tolist()
        parts = jnp.split(x, bounds, axis=axis)
    else:
        parts = jnp.split(x, len(node.outputs), axis=axis)
    for name, part in zip(node.outputs, parts):
        env[name] = part


def _op_pad(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    mode = node.attrs.get("mode", b"constant")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    if len(node.inputs) > 1:
        pads = [int(v) for v in L.static_val(env, node.inputs[1]).ravel()]
        value = 0.0
        if len(node.inputs) > 2 and node.inputs[2]:
            value = float(np.asarray(
                L.static_val(env, node.inputs[2])).ravel()[0])
    else:
        pads = _ints(node, "pads")
        value = float(node.attrs.get("value", 0.0))
    half = len(pads) // 2
    widths = [(pads[i], pads[i + half]) for i in range(half)]
    if mode == "constant":
        y = jnp.pad(x, widths, constant_values=value)
    elif mode in ("reflect", "edge"):
        y = jnp.pad(x, widths, mode="reflect" if mode == "reflect"
                    else "edge")
    else:
        raise OnnxLowerError(f"Pad mode {mode!r}")
    L.set_out(env, node, y)


def _op_resize(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])             # NCHW
    if x.ndim != 4:
        raise OnnxLowerError("Resize only 4-D NCHW")
    def sizes_from_scales(scales) -> Optional[List[int]]:
        scales = np.asarray(scales, np.float64).ravel()
        if not scales.size:
            return None
        return [int(round(d * s)) for d, s in zip(x.shape, scales)]

    sizes = None
    if len(node.inputs) > 3 and node.inputs[3]:
        # Resize-11+: [X, roi, scales, sizes]
        sizes = [int(v) for v in L.static_val(env, node.inputs[3]).ravel()]
    elif len(node.inputs) > 2 and node.inputs[2]:
        sizes = sizes_from_scales(L.static_val(env, node.inputs[2]))
    elif len(node.inputs) > 1 and node.inputs[1]:
        # Resize-10 / Upsample-9: [X, scales]
        sizes = sizes_from_scales(L.static_val(env, node.inputs[1]))
    elif node.attrs.get("scales"):
        # Upsample-7: scales attribute
        sizes = sizes_from_scales(node.attrs["scales"])
    if sizes is None:
        raise OnnxLowerError("Resize/Upsample without static scales/sizes")
    mode = node.attrs.get("mode", b"nearest")
    mode = mode.decode() if isinstance(mode, bytes) else mode
    coord = node.attrs.get(
        "coordinate_transformation_mode", b"half_pixel")
    coord = coord.decode() if isinstance(coord, bytes) else coord
    out_h, out_w = sizes[2], sizes[3]
    # reuse the tflite coordinate machinery (NHWC) via a transpose
    from .tflite_lower import _resize_bilinear, _resize_nearest

    xn = jnp.transpose(x, (0, 2, 3, 1))
    align = coord == "align_corners"
    half = coord in ("half_pixel", "pytorch_half_pixel")
    if mode == "nearest":
        yn = _resize_nearest(xn, out_h, out_w, align, half)
    elif mode in ("linear", "cubic"):          # cubic approximated linear
        yn = _resize_bilinear(xn, out_h, out_w, align, half)
    else:
        raise OnnxLowerError(f"Resize mode {mode!r}")
    L.set_out(env, node, jnp.transpose(yn, (0, 3, 1, 2)))


def _op_cast(L: _Lowering, env, node: OnnxNode):
    from .onnx_reader import ONNX_DTYPES

    x = L.val(env, node.inputs[0])
    to = ONNX_DTYPES.get(int(node.attrs.get("to", 1)), "float32")
    np_dtype = np.dtype("int32" if to == "int64" else to)
    s = L.maybe_static(env, node.inputs[0])
    L.set_out(env, node, x.astype(np_dtype),
              None if s is None else s.astype(np.dtype(to)))


def _op_constant_of_shape(L: _Lowering, env, node: OnnxNode):
    shape = [int(v) for v in L.static_val(env, node.inputs[0]).ravel()]
    value = node.attrs.get("value")
    fill = float(np.asarray(value).ravel()[0]) if value is not None else 0.0
    dtype = np.asarray(value).dtype if value is not None else np.float32
    L.set_out(env, node, jnp.full(shape, fill, dtype),
              np.full(shape, fill, dtype))


def _op_expand(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    shape = [int(v) for v in L.static_val(env, node.inputs[1]).ravel()]
    # ONNX Expand broadcast: dims of 1 in shape take x's dim
    full = list(np.broadcast_shapes(tuple(x.shape), tuple(shape)))
    L.set_out(env, node, jnp.broadcast_to(x, full))


def _op_reduce(fn, default_keep=1):
    def impl(L: _Lowering, env, node: OnnxNode):
        x = L.val(env, node.inputs[0])
        if len(node.inputs) > 1 and node.inputs[1]:   # opset >= 18
            axes = tuple(int(v) % x.ndim
                         for v in L.static_val(env, node.inputs[1]).ravel())
        else:
            raw = _ints(node, "axes")
            axes = (tuple(a % x.ndim for a in raw) if raw
                    else tuple(range(x.ndim)))
        keep = bool(int(node.attrs.get("keepdims", default_keep)))
        L.set_out(env, node, fn(x, axis=axes, keepdims=keep))
    return impl


def _op_argmax(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    axis = int(node.attrs.get("axis", 0))
    keep = bool(int(node.attrs.get("keepdims", 1)))
    y = jnp.argmax(x, axis=axis).astype(jnp.int32)
    if keep:
        y = jnp.expand_dims(y, axis)
    L.set_out(env, node, y)


def _binop(fn):
    def impl(L: _Lowering, env, node: OnnxNode):
        a = L.val(env, node.inputs[0])
        b = L.val(env, node.inputs[1])
        sa = L.maybe_static(env, node.inputs[0])
        sb = L.maybe_static(env, node.inputs[1])
        static = None
        if sa is not None and sb is not None:
            try:
                static = fn(sa, sb)
            except Exception:  # noqa: BLE001 — fold is best-effort
                static = None
        L.set_out(env, node, fn(a, b), static)
    return impl


def _unop(fn):
    def impl(L: _Lowering, env, node: OnnxNode):
        L.set_out(env, node, fn(L.val(env, node.inputs[0])))
    return impl


def _op_identity(L: _Lowering, env, node: OnnxNode):
    L.set_out(env, node, L.val(env, node.inputs[0]),
              L.maybe_static(env, node.inputs[0]))


def _op_dropout(L: _Lowering, env, node: OnnxNode):
    # inference: identity; optional mask output = all true
    x = L.val(env, node.inputs[0])
    env[node.outputs[0]] = x
    if len(node.outputs) > 1:
        env[node.outputs[1]] = jnp.ones(x.shape, bool)


def _qparams(L: _Lowering, env, node: OnnxNode):
    """(scale, zero_point, axis) for Quantize/DequantizeLinear."""
    scale = np.asarray(L.static_val(env, node.inputs[1]), np.float32)
    zp = (np.asarray(L.static_val(env, node.inputs[2]))
          if len(node.inputs) > 2 and node.inputs[2]
          else np.zeros_like(scale, np.int64))
    return scale, zp, int(node.attrs.get("axis", 1))


def _per_axis_shape(arr_ndim: int, axis: int, size: int):
    shape = [1] * arr_ndim
    shape[axis % arr_ndim] = size
    return shape


def _op_quantize_linear(L: _Lowering, env, node: OnnxNode):
    """QDQ-style quantization boundary: x -> clip(round(x/s)+zp).  Kept
    in the integer dtype so a following DequantizeLinear restores the
    grid exactly (the QDQ pattern quantization-aware exporters emit)."""
    x = L.val(env, node.inputs[0])
    scale, zp, axis = _qparams(L, env, node)
    # the zero-point initializer's dtype names the target integer type
    # (spec default uint8 when absent — our zeros placeholder is int64)
    if zp.dtype == np.int64:
        np_dtype = np.dtype("uint8")
    elif zp.dtype in (np.dtype("int8"), np.dtype("uint8"),
                      np.dtype("int16"), np.dtype("uint16"),
                      np.dtype("int32")):
        np_dtype = zp.dtype
    else:
        raise OnnxLowerError(
            f"QuantizeLinear to {zp.dtype} not supported")
    lo, hi = (np.iinfo(np_dtype).min, np.iinfo(np_dtype).max)
    if scale.size > 1:
        shape = _per_axis_shape(x.ndim, axis, scale.size)
        s = scale.reshape(shape)
        z = zp.astype(np.float32).reshape(shape)
    else:
        s = float(scale.ravel()[0])
        z = float(zp.ravel()[0])
    # spec order: round(x/s) THEN add zp (an odd zp must not shift
    # round-half-even tie results)
    q = jnp.clip(jnp.round(x / s) + z, lo, hi)
    env[node.outputs[0]] = q.astype(np_dtype)


def _op_dequantize_linear(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    scale, zp, axis = _qparams(L, env, node)
    if scale.size > 1:
        shape = _per_axis_shape(x.ndim, axis, scale.size)
        s = jnp.asarray(scale.reshape(shape))
        z = jnp.asarray(zp.astype(np.float32).reshape(shape))
    else:
        s = float(scale.ravel()[0])
        z = float(zp.ravel()[0])
    env[node.outputs[0]] = (x.astype(jnp.float32) - z) * s


def _op_where(L: _Lowering, env, node: OnnxNode):
    c = L.val(env, node.inputs[0])
    a = L.val(env, node.inputs[1])
    b = L.val(env, node.inputs[2])
    L.set_out(env, node, jnp.where(c, a, b))


def _op_prelu(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    alpha = L.val(env, node.inputs[1])
    L.set_out(env, node, jnp.where(x >= 0, x, x * alpha))


def _op_lrn(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])             # NCHW
    size = int(node.attrs["size"])
    alpha = float(node.attrs.get("alpha", 1e-4))
    beta = float(node.attrs.get("beta", 0.75))
    bias = float(node.attrs.get("k", 1.0))
    half = size // 2
    sq = x * x
    pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    sq = jnp.pad(sq, pad)
    acc = sum(sq[:, i:i + x.shape[1]] for i in range(size))
    L.set_out(env, node, x / (bias + alpha / size * acc) ** beta)


_OP_IMPLS: Dict[str, Callable] = {
    "Conv": _op_conv,
    "ConvTranspose": _op_conv_transpose,
    "MaxPool": lambda L, e, n: _pool(L, e, n, "max"),
    "AveragePool": lambda L, e, n: _pool(L, e, n, "avg"),
    "GlobalAveragePool": _unop(
        lambda x: jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)),
    "GlobalMaxPool": _unop(
        lambda x: jnp.max(x, axis=tuple(range(2, x.ndim)), keepdims=True)),
    "Gemm": _op_gemm,
    "MatMul": _binop(jnp.matmul),
    "BatchNormalization": _op_batchnorm,
    "LayerNormalization": _op_layernorm,
    "InstanceNormalization": lambda L, e, n: _op_instancenorm(L, e, n),
    "LRN": _op_lrn,
    "Relu": _unop(jax.nn.relu),
    "LeakyRelu": lambda L, e, n: L.set_out(
        e, n, jnp.where(
            L.val(e, n.inputs[0]) >= 0, L.val(e, n.inputs[0]),
            L.val(e, n.inputs[0]) * float(n.attrs.get("alpha", 0.01)))),
    "PRelu": _op_prelu,
    "Sigmoid": _unop(jax.nn.sigmoid),
    "HardSigmoid": lambda L, e, n: L.set_out(
        e, n, jnp.clip(
            L.val(e, n.inputs[0]) * float(n.attrs.get("alpha", 0.2))
            + float(n.attrs.get("beta", 0.5)), 0.0, 1.0)),
    "Tanh": _unop(jnp.tanh),
    "Erf": _unop(jax.scipy.special.erf),
    "Gelu": _unop(jax.nn.gelu),
    "Softplus": _unop(jax.nn.softplus),
    "Softmax": _op_softmax,
    "LogSoftmax": lambda L, e, n: L.set_out(
        e, n, jax.nn.log_softmax(
            L.val(e, n.inputs[0]), axis=int(n.attrs.get("axis", -1)))),
    "Clip": _op_clip,
    "Add": _binop(jnp.add),
    "Sub": _binop(jnp.subtract),
    "Mul": _binop(jnp.multiply),
    "Div": _binop(jnp.divide),
    "Pow": _binop(jnp.power),
    "Min": _binop(jnp.minimum),
    "Max": _binop(jnp.maximum),
    "Equal": _binop(lambda a, b: a == b),
    "Greater": _binop(lambda a, b: a > b),
    "Less": _binop(lambda a, b: a < b),
    "Sqrt": _unop(jnp.sqrt),
    "Exp": _unop(jnp.exp),
    "Log": _unop(jnp.log),
    "Abs": _unop(jnp.abs),
    "Neg": _unop(jnp.negative),
    "Floor": _unop(jnp.floor),
    "Ceil": _unop(jnp.ceil),
    "Reciprocal": _unop(lambda x: 1.0 / x),
    "Reshape": _op_reshape,
    "Flatten": _op_flatten,
    "Transpose": _op_transpose,
    "Concat": _op_concat,
    "Shape": _op_shape,
    "Gather": _op_gather,
    "Unsqueeze": _op_unsqueeze,
    "Squeeze": _op_squeeze,
    "Slice": _op_slice,
    "Split": _op_split,
    "Pad": _op_pad,
    "Resize": _op_resize,
    "Upsample": _op_resize,
    "Cast": _op_cast,
    "ConstantOfShape": _op_constant_of_shape,
    "Expand": _op_expand,
    "ReduceMean": _op_reduce(jnp.mean),
    "ReduceSum": _op_reduce(jnp.sum),
    "ReduceMax": _op_reduce(jnp.max),
    "ReduceMin": _op_reduce(jnp.min),
    "ReduceProd": _op_reduce(jnp.prod),
    "ArgMax": _op_argmax,
    "Identity": _op_identity,
    "Dropout": _op_dropout,
    "Where": _op_where,
    "QuantizeLinear": _op_quantize_linear,
    "DequantizeLinear": _op_dequantize_linear,
}


def _op_instancenorm(L: _Lowering, env, node: OnnxNode):
    x = L.val(env, node.inputs[0])
    scale = L.val(env, node.inputs[1])
    bias = L.val(env, node.inputs[2])
    eps = float(node.attrs.get("epsilon", 1e-5))
    axes = tuple(range(2, x.ndim))
    mu = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean((x - mu) ** 2, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    L.set_out(env, node, (x - mu) / jnp.sqrt(var + eps)
              * scale.reshape(shape) + bias.reshape(shape))


def lower_onnx(model: OnnxModel, jit: bool = True) -> Callable:
    """Build ``fn(*inputs) -> tuple(outputs)`` from the ONNX graph."""
    lowering = _Lowering(model)
    return jax.jit(lowering) if jit else lowering
