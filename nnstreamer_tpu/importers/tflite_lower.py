"""Lower a parsed TFLite graph to a jittable JAX function.

Replaces the reference's CPU-interpreter execution
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:488-540``,
``TFLiteInterpreter::invoke`` — per-tensor memcpy into interpreter slots,
``interpreter->Invoke()``) with an XLA-native design: the whole graph is
traced ONCE into a single jit program, so every conv/matmul lands on the
MXU and XLA fuses the elementwise tail ops — no per-op interpreter
dispatch at runtime.

Quantized models (uint8/int8 per TFLite quantization spec) execute in
*fake-quant simulation*: constants are dequantized at load time
(per-channel where ``quantized_dimension`` says so); activations run in
float32; every tensor that carries quantization parameters is re-quantized
(round → clip to the dtype's limits → dequantize) at op boundaries, which
reproduces the integer kernels' saturation/rounding semantics to within
one quantum.  Graph inputs/outputs keep their declared integer dtypes so
the pipeline-facing contract matches the reference tflite subplugin's.

The op set covers the common CNN inventory (conv / depthwise / pool /
dense / elementwise / shape ops / resize / softmax …) — enough for the
reference's own test models (mobilenet_v2 quant, deeplabv3, add, FC nets).
Unsupported ops raise ``TFLiteLowerError`` naming the op, at *load* time.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .tflite_reader import TFLiteModel, TFLOp, TFLTensor, QuantParams


class TFLiteLowerError(NotImplementedError):
    pass


# integer limits for fake-quant clipping
_QLIMITS = {
    "uint8": (0, 255),
    "int8": (-128, 127),
    "int16": (-32768, 32767),
    "int32": (-2**31, 2**31 - 1),
    "int64": (-2**63, 2**63 - 1),
    "uint32": (0, 2**32 - 1),
}


def _dequantize_const(t: TFLTensor) -> np.ndarray:
    """Constant tensor -> compute-domain numpy: quantized weights/biases
    dequantize to float32 (honoring per-channel scales); fp16 widens;
    integer-typed non-quantized constants keep their dtype (they may feed
    genuine integer math)."""
    data = np.asarray(t.data)
    q = t.quant
    if q is None or t.dtype not in _QLIMITS:
        return data.astype(np.float32) if t.dtype == "float16" else data
    scale, zp = q.scale, q.zero_point.astype(np.float32)
    if q.per_channel:
        # broadcast scale along quantized_dimension
        shape = [1] * data.ndim
        shape[q.quantized_dimension] = scale.size
        scale = scale.reshape(shape)
        zp = zp.reshape(shape)
    else:
        scale = scale[0]
        zp = zp[0]
    return (data.astype(np.float32) - zp) * scale


def _fake_quant(x, q: QuantParams, dtype: str):
    """Round-trip x through the tensor's integer grid (simulates the
    integer kernels' output requantization)."""
    lo, hi = _QLIMITS[dtype]
    scale = float(q.scale[0])
    zp = float(q.zero_point[0])
    qx = jnp.clip(jnp.round(x / scale + zp), lo, hi)
    return (qx - zp) * scale


def _quantize_out(x, q: QuantParams, dtype: str):
    lo, hi = _QLIMITS[dtype]
    scale = float(q.scale[0])
    zp = float(q.zero_point[0])
    return jnp.clip(jnp.round(x / scale + zp), lo, hi).astype(np.dtype(dtype))


def _dequantize_in(x, q: QuantParams):
    return (x.astype(jnp.float32) - float(q.zero_point[0])) * float(q.scale[0])


def _activate(x, name: Optional[str]):
    if name is None:
        return x
    if name == "relu":
        return jax.nn.relu(x)
    if name == "relu6":
        return jnp.clip(x, 0.0, 6.0)
    if name == "relu_n1_to_1":
        return jnp.clip(x, -1.0, 1.0)
    if name == "tanh":
        return jnp.tanh(x)
    raise TFLiteLowerError(f"fused activation {name!r} not supported")


def _same_pads(in_size: int, stride: int, kernel: int, dilation: int = 1
               ) -> Tuple[int, int]:
    """TFLite/TF SAME padding: total pad for one spatial dim."""
    eff_k = (kernel - 1) * dilation + 1
    out = -(-in_size // stride)  # ceil
    total = max(0, (out - 1) * stride + eff_k - in_size)
    return total // 2, total - total // 2


def _conv_padding(opts, x_shape, k_h, k_w):
    if opts["padding"] == "VALID":
        return [(0, 0), (0, 0)]
    return [
        _same_pads(x_shape[1], opts["stride_h"], k_h, opts.get("dilation_h", 1)),
        _same_pads(x_shape[2], opts["stride_w"], k_w, opts.get("dilation_w", 1)),
    ]


def _resize_coords(out_size: int, in_size: int, align_corners: bool,
                   half_pixel: bool):
    """Source sampling coordinates for one spatial dim (all three TFLite
    coordinate conventions)."""
    i = jnp.arange(out_size, dtype=jnp.float32)
    if align_corners and out_size > 1:
        scale = (in_size - 1) / (out_size - 1)
        return i * scale
    scale = in_size / out_size
    if half_pixel:
        return jnp.maximum((i + 0.5) * scale - 0.5, 0.0)
    return i * scale


def _resize_bilinear(x, out_h: int, out_w: int, align_corners: bool,
                     half_pixel: bool):
    n, h, w, c = x.shape
    ys = _resize_coords(out_h, h, align_corners, half_pixel)
    xs = _resize_coords(out_w, w, align_corners, half_pixel)
    y0 = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
    y1 = jnp.clip(y0 + 1, 0, h - 1)
    x0 = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    x1 = jnp.clip(x0 + 1, 0, w - 1)
    wy = (ys - y0.astype(jnp.float32))[None, :, None, None]
    wx = (xs - x0.astype(jnp.float32))[None, None, :, None]
    top = x[:, y0][:, :, x0] * (1 - wx) + x[:, y0][:, :, x1] * wx
    bot = x[:, y1][:, :, x0] * (1 - wx) + x[:, y1][:, :, x1] * wx
    return top * (1 - wy) + bot * wy


def _resize_nearest(x, out_h: int, out_w: int, align_corners: bool,
                    half_pixel: bool):
    n, h, w, c = x.shape
    ys = _resize_coords(out_h, h, align_corners, half_pixel)
    xs = _resize_coords(out_w, w, align_corners, half_pixel)
    # TFLite nearest: round-half-away for half_pixel/align, floor otherwise
    if half_pixel or align_corners:
        yi = jnp.clip(jnp.round(ys).astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(jnp.round(xs).astype(jnp.int32), 0, w - 1)
    else:
        yi = jnp.clip(jnp.floor(ys).astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(jnp.floor(xs).astype(jnp.int32), 0, w - 1)
    return x[:, yi][:, :, xi]


def _pool(x, opts, kind: str):
    pads = [(0, 0)] + _conv_padding(
        opts, x.shape, opts["filter_h"], opts["filter_w"]) + [(0, 0)]
    window = (1, opts["filter_h"], opts["filter_w"], 1)
    strides = (1, opts["stride_h"], opts["stride_w"], 1)
    if kind == "max":
        out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides, pads)
    else:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
        # divide by the true (edge-clipped) window size, as TFLite does
        ones = jnp.ones(x.shape[:3] + (1,), x.dtype)
        count = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        out = summed / count
    return _activate(out, opts.get("activation"))


class _Lowering:
    """One pass over the graph building a closure env of constants and a
    list of (op, impl) steps; `__call__` replays the steps under jit."""

    def __init__(self, model: TFLiteModel, fake_quant: bool = True,
                 int8_compute: bool = False):
        self.m = model
        self.fake_quant = fake_quant
        # int8_compute: quantized conv/depthwise/dense run as TRUE integer
        # arithmetic — int8×int8→int32 on the MXU (2× the bf16 rate) with
        # the standard zero-point expansion, instead of dequantized float.
        # Elementwise stays float (XLA fuses it; the FLOPs are in the
        # convs).  See _int8_conv_core for the algebra.
        self.int8_compute = int8_compute
        # trace-time shape constants (SHAPE / BROADCAST_ARGS results):
        # XLA needs static shapes, so shape-producing ops fold to numpy
        # here and stay usable as shape arguments downstream
        self.static: Dict[int, np.ndarray] = {}
        # when on, every op output is checked against the shape the file
        # declares for that tensor — a structural proof that our
        # padding/stride/layout semantics match what the TFLite converter
        # computed.  Only valid for unbatched (declared-shape) calls.
        self.validate_shapes = False
        # float32 views of every constant, dequantized once at load
        self.consts: Dict[int, np.ndarray] = {}
        # constants that must stay integer (shape/axis/pad arguments)
        self.raw_consts: Dict[int, np.ndarray] = {}
        for t in model.tensors:
            if t.is_const:
                self.raw_consts[t.index] = np.asarray(t.data)
                self.consts[t.index] = _dequantize_const(t)
        unsupported = sorted({
            op.opcode for op in model.ops
            if op.opcode.split(":")[0] not in _OP_IMPLS})
        if unsupported:
            raise TFLiteLowerError(
                f"unsupported tflite ops: {', '.join(unsupported)} "
                f"(supported: {', '.join(sorted(_OP_IMPLS))})")

    def _int8_weight_indices(self) -> set:
        """Tensor indices whose weights the int8 path reads RAW (baked
        into the trace as int8 constants) — their dequantized float
        copies must not ride the params pytree too."""
        if not self.int8_compute:
            return set()
        out = set()
        for op in self.m.ops:
            if op.opcode in ("CONV_2D", "DEPTHWISE_CONV_2D",
                             "FULLY_CONNECTED"):
                _, _, ok = _int8_quant_triple(self, op)
                if ok:
                    out.add(op.inputs[1])
        return out

    def params(self) -> Dict[int, np.ndarray]:
        """The constants as a pytree: pass to :meth:`run` so the caller
        controls placement (device_put / bf16 cast / mesh sharding).
        Weights consumed by the int8 path are excluded (they ship as
        int8 trace constants; a float copy would waste 4× the HBM)."""
        skip = self._int8_weight_indices()
        return {k: v for k, v in self.consts.items() if k not in skip}

    def drop_host_consts(self) -> None:
        """Release the host-side dequantized-constant copies.  A caller
        that took :meth:`params` (and will always use :meth:`run` with
        that pytree) doesn't need the ``val()`` fallback — dropping the
        dict avoids keeping a second full float32 copy of every weight in
        host RAM next to the device copy.  ``raw_consts`` stays: those
        are the trace-time shape/axis/pad lookups (and they are views
        into the single mmap-like file buffer, not copies)."""
        self.consts = {}

    # -- value access during trace -----------------------------------------
    def val(self, env, idx: int):
        """Compute-domain value of tensor idx (dequantized constants)."""
        if idx < 0:
            return None
        if idx in env:
            return env[idx]
        return jnp.asarray(self.consts[idx])

    def raw(self, idx: int) -> np.ndarray:
        """Integer-domain constant (shape vectors, pad matrices, axes),
        either from the file or folded at trace time (SHAPE etc.)."""
        if idx in self.raw_consts:
            return self.raw_consts[idx]
        if idx in self.static:
            return self.static[idx]
        raise TFLiteLowerError(
            f"tensor {idx} must be a constant (dynamic shapes are not "
            "jittable; XLA requires static shapes)")

    def out_quant(self, x, idx: int):
        """Quantization boundary for an op output.

        fake_quant=True: full round-trip through the integer grid.
        fake_quant=False: keep only the RANGE CLAMP.  The clamp is load-
        bearing, not an approximation knob: TOCO-era models encode fused
        ReLU6 in the output quant range (scale*255 ~= 6, zp=0), so
        dropping it entirely would remove the activations.
        """
        t = self.m.tensors[idx]
        if (t.quant is None or t.dtype not in _QLIMITS
                or t.quant.per_channel):
            return x
        if self.fake_quant:
            return _fake_quant(x, t.quant, t.dtype)
        lo, hi = _QLIMITS[t.dtype]
        scale = float(t.quant.scale[0])
        zp = float(t.quant.zero_point[0])
        return jnp.clip(x, (lo - zp) * scale, (hi - zp) * scale)

    # -- the jittable function ---------------------------------------------
    def __call__(self, *inputs):
        return self.run(self.consts, *inputs)

    def run(self, consts: Dict[int, Any], *inputs):
        """Trace the graph with an externally-placed constants pytree."""
        m = self.m
        if len(inputs) != len(m.inputs):
            raise ValueError(
                f"model takes {len(m.inputs)} inputs, got {len(inputs)}")
        env: Dict[int, Any] = dict(consts)
        self.static = {}
        for idx, x in zip(m.inputs, inputs):
            t = m.tensors[idx]
            x = jnp.asarray(x)
            if t.quant is not None and t.dtype in _QLIMITS:
                x = _dequantize_in(x, t.quant)
            elif x.dtype in (jnp.uint8, jnp.int8) and t.dtype == "float32":
                x = x.astype(jnp.float32)
            env[idx] = x
        for op in m.ops:
            impl = _OP_IMPLS[op.opcode.split(":")[0]]
            impl(self, env, op)
            if self.validate_shapes:
                for out_idx in op.outputs:
                    decl = m.tensors[out_idx].shape
                    got = tuple(env[out_idx].shape)
                    if decl and got != decl:
                        raise TFLiteLowerError(
                            f"{op.opcode}: tensor {out_idx} "
                            f"({m.tensors[out_idx].name}) computed shape "
                            f"{got} != declared {decl}")
        outs = []
        for idx in m.outputs:
            t = m.tensors[idx]
            x = env[idx]
            if t.quant is not None and t.dtype in _QLIMITS:
                x = _quantize_out(x, t.quant, t.dtype)
            elif t.dtype in ("int32", "int64", "bool"):
                x = x.astype(np.dtype(t.dtype))
            outs.append(x)
        return tuple(outs)


# -- true-int8 compute core --------------------------------------------------

def _int8_quant_triple(L: _Lowering, op: TFLOp):
    """(in_q, w_tensor, usable) for the int8 path: per-tensor quant on
    the input activation; weights either per-tensor, or per-channel
    SYMMETRIC int8 (all zero points 0 — the TFLite int8 spec's standard
    layout, where the per-channel scale just vectorizes the epilogue)."""
    t_in = L.m.tensors[op.inputs[0]]
    t_w = L.m.tensors[op.inputs[1]]
    # per-channel scales must index the OUTPUT-channel axis (dim 3 for
    # depthwise [1,kh,kw,C*m], dim 0 otherwise) — anything else falls
    # back to fake-quant, which handles arbitrary quantized_dimension
    out_dim = 3 if op.opcode == "DEPTHWISE_CONV_2D" else 0
    w_ok = (
        t_w.quant is not None and t_w.is_const
        and t_w.dtype in ("uint8", "int8")
        and (not t_w.quant.per_channel
             or (t_w.dtype == "int8"
                 and not t_w.quant.zero_point.any()
                 and t_w.quant.quantized_dimension == out_dim))
    )
    ok = (
        L.int8_compute
        and t_in.quant is not None and not t_in.quant.per_channel
        and t_in.dtype in ("uint8", "int8")
        and w_ok
    )
    return t_in, t_w, ok


def _to_i8(q_vals: np.ndarray, dtype: str):
    """Quantized values -> int8 with the matching zero-point shift
    (uint8 shifts by 128 so the full 0..255 range fits int8)."""
    if dtype == "uint8":
        return (q_vals.astype(np.int32) - 128).astype(np.int8), 128
    return q_vals.astype(np.int8), 0


def _int8_operands(L: _Lowering, op: TFLOp, x):
    """Shared int8 prep: (x_i8, zp_in_p, s_in, w_i8_np, zp_w_p, s_w) —
    the float-domain activation quantized to shifted int8 and the raw
    weights shifted to int8, ready for the zero-point expansion.
    ``s_w`` is a scalar for per-tensor weights or a per-output-channel
    vector for the symmetric per-channel layout (zp 0, no shift)."""
    t_in, t_w, _ = _int8_quant_triple(L, op)
    s_in = float(t_in.quant.scale[0])
    zp_in = int(t_in.quant.zero_point[0])
    q_x = jnp.round(x / s_in) + zp_in
    shift_in = 128 if t_in.dtype == "uint8" else 0
    x_i8 = (q_x - shift_in).astype(jnp.int8)
    if t_w.quant.per_channel:
        # guard guarantees int8 already: no copy
        w_i8_np = np.asarray(t_w.data).astype(np.int8, copy=False)
        zp_w_p = 0
        s_w = t_w.quant.scale.astype(np.float32)
    else:
        w_i8_np, shift_w = _to_i8(np.asarray(t_w.data), t_w.dtype)
        zp_w_p = int(t_w.quant.zero_point[0]) - shift_w
        s_w = float(t_w.quant.scale[0])
    return x_i8, zp_in - shift_in, s_in, w_i8_np, zp_w_p, s_w


def _int8_epilogue(L: _Lowering, env, op: TFLOp, acc, s_in: float, s_w):
    """Accumulator -> float domain + bias + fused activation.  ``s_w``
    may be a per-output-channel vector; output channels are the last
    axis in every consumer (NHWC conv, dense), so it broadcasts."""
    if np.ndim(s_w):
        y = acc.astype(jnp.float32) * jnp.asarray(s_in * s_w)
    else:
        y = acc.astype(jnp.float32) * (s_in * s_w)
    b = (L.val(env, op.inputs[2])
         if len(op.inputs) > 2 and op.inputs[2] >= 0 else None)
    if b is not None:
        y = y + b
    return _activate(y, op.options["activation"])


def _int8_conv_core(L: _Lowering, env, op: TFLOp, x, depthwise: bool):
    """Quantized conv as integer arithmetic.

    With q_x = x/s_in + zp_in and q_w the stored weights, the real-valued
    conv expands to

      s_in*s_w * [ conv(q_x - 128, q_w - 128)
                   - zp_w' * patchsum(q_x - 128)
                   - zp_in' * sum(q_w - 128)
                   + K * zp_in' * zp_w' ]

    (primed zero points are shifted by the same 128).  The first conv is
    int8×int8→int32 — the MXU's double-rate path; the patch-sum is a
    ones-kernel conv, C_out× cheaper than the main one.  Output returns
    to the float domain for the fused elementwise tail.
    """
    o = op.options
    x_i8, zp_in_p, s_in, w_i8_np, zp_w_p, s_w = _int8_operands(L, op, x)

    kh, kw = w_i8_np.shape[1], w_i8_np.shape[2]
    strides = (o["stride_h"], o["stride_w"])
    dil = (o.get("dilation_h", 1), o.get("dilation_w", 1))
    # SAME padding must contribute REAL zero, i.e. the shifted zero
    # point — XLA's implicit conv padding injects 0 in the shifted int8
    # domain (= a nonzero real value), so pad explicitly and run VALID
    sp = _conv_padding(o, x.shape, kh, kw)
    if any(p != (0, 0) for p in sp):
        x_i8 = jnp.pad(
            x_i8, [(0, 0), sp[0], sp[1], (0, 0)],
            constant_values=np.int8(zp_in_p))
    pads = [(0, 0), (0, 0)]

    if depthwise:
        in_ch = x.shape[3]
        w_i8 = jnp.reshape(
            jnp.transpose(jnp.asarray(w_i8_np), (1, 2, 0, 3)),
            (kh, kw, 1, -1))
        dn = ("NHWC", "HWIO", "NHWC")
        groups = in_ch
        sum_w = w_i8_np.astype(np.int64).sum(axis=(0, 1, 2))  # per ch*mult
        ones = jnp.ones((kh, kw, 1, w_i8.shape[-1]), jnp.int8)
    else:
        w_i8 = jnp.asarray(w_i8_np)                   # [O, kh, kw, I]
        dn = ("NHWC", "OHWI", "NHWC")
        groups = 1
        sum_w = w_i8_np.astype(np.int64).sum(axis=(1, 2, 3))  # per O
        ones = jnp.ones((1, kh, kw, x.shape[3]), jnp.int8)

    acc = lax.conv_general_dilated(
        x_i8, w_i8, window_strides=strides, padding=pads,
        rhs_dilation=dil, dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=jnp.int32)

    if zp_w_p:
        if depthwise:
            # per-channel patch sums, broadcast across the multiplier
            psum = lax.conv_general_dilated(
                x_i8, ones, window_strides=strides, padding=pads,
                rhs_dilation=dil, dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=x.shape[3],
                preferred_element_type=jnp.int32)
        else:
            psum = lax.conv_general_dilated(
                x_i8, ones, window_strides=strides, padding=pads,
                rhs_dilation=dil, dimension_numbers=("NHWC", "OHWI", "NHWC"),
                preferred_element_type=jnp.int32)
        acc = acc - zp_w_p * psum
    k_elems = kh * kw * (1 if depthwise else x.shape[3])
    acc = acc - jnp.asarray(zp_in_p * sum_w, jnp.int32)
    acc = acc + jnp.int32(k_elems * zp_in_p * zp_w_p)
    return _int8_epilogue(L, env, op, acc, s_in, s_w)


# -- op implementations -----------------------------------------------------
# Each: (lowering, env, op) -> writes env[op.outputs[...]]

def _op_conv2d(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    _, _, int8_ok = _int8_quant_triple(L, op)
    if int8_ok:
        y = _int8_conv_core(L, env, op, x, depthwise=False)
        env[op.outputs[0]] = L.out_quant(y, op.outputs[0])
        return
    w = L.val(env, op.inputs[1])            # [O, Kh, Kw, I]
    b = L.val(env, op.inputs[2]) if len(op.inputs) > 2 else None
    o = op.options
    pads = _conv_padding(o, x.shape, w.shape[1], w.shape[2])
    y = lax.conv_general_dilated(
        x, w,
        window_strides=(o["stride_h"], o["stride_w"]),
        padding=pads,
        rhs_dilation=(o["dilation_h"], o["dilation_w"]),
        dimension_numbers=("NHWC", "OHWI", "NHWC"),
    )
    if b is not None:
        y = y + b
    env[op.outputs[0]] = L.out_quant(_activate(y, o["activation"]),
                                     op.outputs[0])


def _op_depthwise(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    _, _, int8_ok = _int8_quant_triple(L, op)
    if int8_ok:
        y = _int8_conv_core(L, env, op, x, depthwise=True)
        env[op.outputs[0]] = L.out_quant(y, op.outputs[0])
        return
    w = L.val(env, op.inputs[1])            # [1, Kh, Kw, I*mult]
    b = L.val(env, op.inputs[2]) if len(op.inputs) > 2 else None
    o = op.options
    in_ch = x.shape[3]
    kh, kw = w.shape[1], w.shape[2]
    # HWIO with I=1, feature_group_count=in_ch -> per-channel conv
    w = jnp.reshape(jnp.transpose(w, (1, 2, 0, 3)), (kh, kw, 1, -1))
    pads = _conv_padding(o, x.shape, kh, kw)
    y = lax.conv_general_dilated(
        x, w,
        window_strides=(o["stride_h"], o["stride_w"]),
        padding=pads,
        rhs_dilation=(o["dilation_h"], o["dilation_w"]),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=in_ch,
    )
    if b is not None:
        y = y + b
    env[op.outputs[0]] = L.out_quant(_activate(y, o["activation"]),
                                     op.outputs[0])


def _op_transpose_conv(L: _Lowering, env, op: TFLOp):
    # inputs: [output_shape(const), weights(O,Kh,Kw,I), x, (bias)]
    out_shape = tuple(int(v) for v in L.raw(op.inputs[0]))
    w = L.val(env, op.inputs[1])
    x = L.val(env, op.inputs[2])
    b = L.val(env, op.inputs[3]) if len(op.inputs) > 3 else None
    o = op.options
    sh, sw = o["stride_h"], o["stride_w"]
    kh, kw = w.shape[1], w.shape[2]
    # gradient-style transpose conv: lhs-dilate x by the stride, then a
    # VALID conv with the spatially-flipped kernel and full padding
    if o["padding"] == "SAME":
        pt, pb = _same_pads(out_shape[1], sh, kh)
        pl, pr = _same_pads(out_shape[2], sw, kw)
    else:
        pt = pb = pl = pr = 0
    w_flip = jnp.flip(w, axis=(1, 2))       # [O,Kh,Kw,I] flipped
    w_t = jnp.transpose(w_flip, (1, 2, 0, 3))  # HW O I -> use IOHW mapping
    y = lax.conv_general_dilated(
        x, w_t,
        window_strides=(1, 1),
        padding=[(kh - 1 - pt, kh - 1 - pb), (kw - 1 - pl, kw - 1 - pr)],
        lhs_dilation=(sh, sw),
        dimension_numbers=("NHWC", "HWOI", "NHWC"),
    )
    y = y[:, :out_shape[1], :out_shape[2], :]
    if b is not None:
        y = y + b
    env[op.outputs[0]] = L.out_quant(
        _activate(y, o.get("activation")), op.outputs[0])


def _op_fully_connected(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    o = op.options
    if o.get("weights_format", 0) != 0:
        raise TFLiteLowerError("FULLY_CONNECTED shuffled-weights format")
    _, t_w, int8_ok = _int8_quant_triple(L, op)
    if int8_ok:
        # dense int8: same zero-point expansion as the conv core, on a
        # plain MXU matmul contracted over the LAST axis (keep_num_dims
        # inputs may be rank > 2)
        in_features = np.asarray(t_w.data).shape[1]
        if not o.get("keep_num_dims", False):
            x = jnp.reshape(x, (-1, in_features))
        x_i8, zp_in_p, s_in, w_i8_np, zp_w_p, s_w = _int8_operands(
            L, op, x)
        acc = lax.dot_general(
            x_i8, jnp.asarray(w_i8_np),
            (((x_i8.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        if zp_w_p:
            acc = acc - zp_w_p * jnp.sum(
                x_i8.astype(jnp.int32), axis=-1, keepdims=True)
        sum_w = w_i8_np.astype(np.int64).sum(axis=1)
        acc = acc - jnp.asarray(zp_in_p * sum_w, jnp.int32)
        acc = acc + jnp.int32(in_features * zp_in_p * zp_w_p)
        env[op.outputs[0]] = L.out_quant(
            _int8_epilogue(L, env, op, acc, s_in, s_w), op.outputs[0])
        return
    w = L.val(env, op.inputs[1])            # [O, I]
    b = L.val(env, op.inputs[2]) if len(op.inputs) > 2 and op.inputs[2] >= 0 else None
    if not o.get("keep_num_dims", False):
        x = jnp.reshape(x, (-1, w.shape[1]))
    y = x @ w.T
    if b is not None:
        y = y + b
    env[op.outputs[0]] = L.out_quant(_activate(y, o["activation"]),
                                     op.outputs[0])


def _op_pool_avg(L: _Lowering, env, op: TFLOp):
    env[op.outputs[0]] = L.out_quant(
        _pool(L.val(env, op.inputs[0]), op.options, "avg"), op.outputs[0])


def _op_pool_max(L: _Lowering, env, op: TFLOp):
    env[op.outputs[0]] = L.out_quant(
        _pool(L.val(env, op.inputs[0]), op.options, "max"), op.outputs[0])


def _binop(fn):
    def impl(L: _Lowering, env, op: TFLOp):
        a = L.val(env, op.inputs[0])
        b = L.val(env, op.inputs[1])
        y = _activate(fn(a, b), op.options.get("activation"))
        env[op.outputs[0]] = L.out_quant(y, op.outputs[0])
    return impl


def _unop(fn, quant: bool = True):
    def impl(L: _Lowering, env, op: TFLOp):
        y = fn(L.val(env, op.inputs[0]))
        env[op.outputs[0]] = L.out_quant(y, op.outputs[0]) if quant else y
    return impl


def _op_reshape(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    if len(op.inputs) > 1 and op.inputs[1] >= 0:
        shape = [int(v) for v in L.raw(op.inputs[1]).ravel()]
    else:
        shape = list(op.options.get("new_shape") or
                     L.m.tensors[op.outputs[0]].shape)
    env[op.outputs[0]] = jnp.reshape(x, shape)


def _op_softmax(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    y = jax.nn.softmax(x * op.options.get("beta", 1.0), axis=-1)
    env[op.outputs[0]] = L.out_quant(y, op.outputs[0])


def _op_concat(L: _Lowering, env, op: TFLOp):
    parts = [L.val(env, i) for i in op.inputs]
    y = jnp.concatenate(parts, axis=op.options["axis"])
    env[op.outputs[0]] = L.out_quant(
        _activate(y, op.options.get("activation")), op.outputs[0])


def _op_pad(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    pads = L.raw(op.inputs[1]).reshape(-1, 2)
    value = 0.0
    if len(op.inputs) > 2 and op.inputs[2] >= 0:       # PADV2 constant
        value = float(np.asarray(L.raw(op.inputs[2])).ravel()[0])
    env[op.outputs[0]] = jnp.pad(
        x, [(int(a), int(b)) for a, b in pads], constant_values=value)


def _op_mirror_pad(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    pads = L.raw(op.inputs[1]).reshape(-1, 2)
    env[op.outputs[0]] = jnp.pad(
        x, [(int(a), int(b)) for a, b in pads], mode=op.options["mode"])


def _reduce(fn):
    def impl(L: _Lowering, env, op: TFLOp):
        x = L.val(env, op.inputs[0])
        axes = tuple(int(v) for v in L.raw(op.inputs[1]).ravel())
        y = fn(x, axis=axes, keepdims=op.options.get("keep_dims", False))
        env[op.outputs[0]] = L.out_quant(y, op.outputs[0])
    return impl


def _op_strided_slice(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    begin = L.raw(op.inputs[1]).ravel()
    end = L.raw(op.inputs[2]).ravel()
    strides = L.raw(op.inputs[3]).ravel()
    o = op.options
    if o.get("ellipsis_mask") or o.get("new_axis_mask"):
        raise TFLiteLowerError("STRIDED_SLICE ellipsis/new-axis masks")
    idx = []
    for d in range(x.ndim):
        if d >= begin.size:
            idx.append(slice(None))
            continue
        b = None if (o["begin_mask"] >> d) & 1 else int(begin[d])
        e = None if (o["end_mask"] >> d) & 1 else int(end[d])
        s = int(strides[d])
        if (o["shrink_axis_mask"] >> d) & 1:
            idx.append(int(begin[d]))
        else:
            idx.append(slice(b, e, s))
    env[op.outputs[0]] = x[tuple(idx)]


def _op_slice(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    begin = [int(v) for v in L.raw(op.inputs[1]).ravel()]
    size = [int(v) for v in L.raw(op.inputs[2]).ravel()]
    size = [x.shape[d] - begin[d] if s == -1 else s for d, s in enumerate(size)]
    env[op.outputs[0]] = lax.dynamic_slice(x, begin, size)


def _op_transpose(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    perm = [int(v) for v in L.raw(op.inputs[1]).ravel()]
    env[op.outputs[0]] = jnp.transpose(x, perm)


def _op_resize_bilinear(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    out_h, out_w = (int(v) for v in L.raw(op.inputs[1]).ravel())
    y = _resize_bilinear(x, out_h, out_w, op.options["align_corners"],
                         op.options["half_pixel_centers"])
    env[op.outputs[0]] = L.out_quant(y, op.outputs[0])


def _op_resize_nearest(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    out_h, out_w = (int(v) for v in L.raw(op.inputs[1]).ravel())
    y = _resize_nearest(x, out_h, out_w, op.options["align_corners"],
                        op.options["half_pixel_centers"])
    env[op.outputs[0]] = L.out_quant(y, op.outputs[0])


def _op_squeeze(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    dims = op.options.get("squeeze_dims") or None
    env[op.outputs[0]] = jnp.squeeze(
        x, axis=tuple(dims) if dims else None)


def _op_expand_dims(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    axis = int(L.raw(op.inputs[1]).ravel()[0])
    env[op.outputs[0]] = jnp.expand_dims(x, axis)


def _op_shape(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    dtype = np.dtype(op.options.get("out_dtype") or "int32")
    L.static[op.outputs[0]] = np.asarray(x.shape, dtype)
    # traced view stays int32: x64 is disabled under jit and shapes fit
    env[op.outputs[0]] = jnp.asarray(x.shape, jnp.int32)


def _op_broadcast_args(L: _Lowering, env, op: TFLOp):
    a = tuple(int(v) for v in L.raw(op.inputs[0]).ravel())
    b = tuple(int(v) for v in L.raw(op.inputs[1]).ravel())
    shape = np.asarray(np.broadcast_shapes(a, b), np.int32)
    L.static[op.outputs[0]] = shape
    env[op.outputs[0]] = jnp.asarray(shape)


def _op_broadcast_to(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    shape = tuple(int(v) for v in L.raw(op.inputs[1]).ravel())
    env[op.outputs[0]] = jnp.broadcast_to(x, shape)


def _op_batch_matmul(L: _Lowering, env, op: TFLOp):
    a = L.val(env, op.inputs[0])
    b = L.val(env, op.inputs[1])
    env[op.outputs[0]] = L.out_quant(jnp.matmul(a, b), op.outputs[0])


def _op_cast(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    out_dtype = op.options.get("out_dtype") or L.m.tensors[op.outputs[0]].dtype
    env[op.outputs[0]] = x.astype(np.dtype(out_dtype))


def _op_arg_max(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    axis = int(L.raw(op.inputs[1]).ravel()[0])
    env[op.outputs[0]] = jnp.argmax(x, axis=axis).astype(
        np.dtype(op.options.get("output_type") or "int64"))


def _op_arg_min(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    axis = int(L.raw(op.inputs[1]).ravel()[0])
    env[op.outputs[0]] = jnp.argmin(x, axis=axis).astype(
        np.dtype(op.options.get("output_type") or "int64"))


def _op_gather(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    if op.inputs[1] in L.raw_consts:
        idx = jnp.asarray(L.raw(op.inputs[1]))
    else:
        idx = env[op.inputs[1]].astype(jnp.int32)
    env[op.outputs[0]] = jnp.take(x, idx, axis=op.options.get("axis", 0))


def _op_pack(L: _Lowering, env, op: TFLOp):
    parts = [L.val(env, i) for i in op.inputs]
    env[op.outputs[0]] = jnp.stack(parts, axis=op.options.get("axis", 0))


def _op_unpack(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    axis = op.options.get("axis", 0)
    for j, out_idx in enumerate(op.outputs):
        env[out_idx] = lax.index_in_dim(x, j, axis=axis, keepdims=False)


def _op_split(L: _Lowering, env, op: TFLOp):
    # inputs: [axis(const), x]
    axis = int(L.raw(op.inputs[0]).ravel()[0])
    x = L.val(env, op.inputs[1])
    parts = jnp.split(x, len(op.outputs), axis=axis)
    for out_idx, part in zip(op.outputs, parts):
        env[out_idx] = part


def _op_split_v(L: _Lowering, env, op: TFLOp):
    # inputs: [x, size_splits(const), axis(const)]
    x = L.val(env, op.inputs[0])
    sizes = [int(v) for v in L.raw(op.inputs[1]).ravel()]
    axis = int(L.raw(op.inputs[2]).ravel()[0])
    bounds = np.cumsum(sizes)[:-1].tolist()
    for out_idx, part in zip(op.outputs, jnp.split(x, bounds, axis=axis)):
        env[out_idx] = part


def _op_tile(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    reps = [int(v) for v in L.raw(op.inputs[1]).ravel()]
    env[op.outputs[0]] = jnp.tile(x, reps)


def _op_space_to_depth(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    b = op.options["block_size"]
    n, h, w, c = x.shape
    y = x.reshape(n, h // b, b, w // b, b, c)
    y = jnp.transpose(y, (0, 1, 3, 2, 4, 5)).reshape(n, h // b, w // b, c * b * b)
    env[op.outputs[0]] = y


def _op_depth_to_space(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    b = op.options["block_size"]
    n, h, w, c = x.shape
    y = x.reshape(n, h, w, b, b, c // (b * b))
    y = jnp.transpose(y, (0, 1, 3, 2, 4, 5)).reshape(n, h * b, w * b, c // (b * b))
    env[op.outputs[0]] = y


def _op_l2_norm(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    y = x / jnp.sqrt(jnp.maximum(jnp.sum(x * x, axis=-1, keepdims=True), 1e-6))
    env[op.outputs[0]] = L.out_quant(
        _activate(y, op.options.get("activation")), op.outputs[0])


def _op_prelu(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    alpha = L.val(env, op.inputs[1])
    env[op.outputs[0]] = L.out_quant(
        jnp.where(x >= 0, x, x * alpha), op.outputs[0])


def _op_leaky_relu(L: _Lowering, env, op: TFLOp):
    x = L.val(env, op.inputs[0])
    a = op.options.get("alpha", 0.0)
    env[op.outputs[0]] = L.out_quant(jnp.where(x >= 0, x, x * a),
                                     op.outputs[0])


def _op_dequantize(L: _Lowering, env, op: TFLOp):
    # value is already float in our env; just pass through
    env[op.outputs[0]] = L.val(env, op.inputs[0])


def _op_quantize(L: _Lowering, env, op: TFLOp):
    env[op.outputs[0]] = L.out_quant(L.val(env, op.inputs[0]), op.outputs[0])


_OP_IMPLS: Dict[str, Callable] = {
    "CONV_2D": _op_conv2d,
    "DEPTHWISE_CONV_2D": _op_depthwise,
    "TRANSPOSE_CONV": _op_transpose_conv,
    "FULLY_CONNECTED": _op_fully_connected,
    "AVERAGE_POOL_2D": _op_pool_avg,
    "MAX_POOL_2D": _op_pool_max,
    "ADD": _binop(jnp.add),
    "SUB": _binop(jnp.subtract),
    "MUL": _binop(jnp.multiply),
    "DIV": _binop(jnp.divide),
    "MAXIMUM": _binop(jnp.maximum),
    "MINIMUM": _binop(jnp.minimum),
    "SQUARED_DIFFERENCE": _binop(lambda a, b: (a - b) ** 2),
    "POW": _binop(jnp.power),
    "FLOOR_DIV": _binop(lambda a, b: jnp.floor(a / b)),
    "GREATER": _binop(lambda a, b: (a > b)),
    "EQUAL": _binop(lambda a, b: (a == b)),
    "RELU": _unop(jax.nn.relu),
    "RELU6": _unop(lambda x: jnp.clip(x, 0.0, 6.0)),
    "RELU_N1_TO_1": _unop(lambda x: jnp.clip(x, -1.0, 1.0)),
    "LOGISTIC": _unop(jax.nn.sigmoid),
    "TANH": _unop(jnp.tanh),
    "HARD_SWISH": _unop(lambda x: x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0),
    "EXP": _unop(jnp.exp),
    "LOG": _unop(jnp.log),
    "SQRT": _unop(jnp.sqrt),
    "RSQRT": _unop(lambda x: 1.0 / jnp.sqrt(x)),
    "SQUARE": _unop(jnp.square),
    "ABS": _unop(jnp.abs),
    "NEG": _unop(jnp.negative),
    "SIN": _unop(jnp.sin),
    "SOFTMAX": _op_softmax,
    "RESHAPE": _op_reshape,
    "CONCATENATION": _op_concat,
    "PAD": _op_pad,
    "MIRROR_PAD": _op_mirror_pad,
    "MEAN": _reduce(jnp.mean),
    "SUM": _reduce(jnp.sum),
    "REDUCE_MAX": _reduce(jnp.max),
    "REDUCE_MIN": _reduce(jnp.min),
    "REDUCE_PROD": _reduce(jnp.prod),
    "STRIDED_SLICE": _op_strided_slice,
    "SLICE": _op_slice,
    "TRANSPOSE": _op_transpose,
    "RESIZE_BILINEAR": _op_resize_bilinear,
    "RESIZE_NEAREST_NEIGHBOR": _op_resize_nearest,
    "SQUEEZE": _op_squeeze,
    "EXPAND_DIMS": _op_expand_dims,
    "SHAPE": _op_shape,
    "BROADCAST_ARGS": _op_broadcast_args,
    "BROADCAST_TO": _op_broadcast_to,
    "BATCH_MATMUL": _op_batch_matmul,
    "CAST": _op_cast,
    "ARG_MAX": _op_arg_max,
    "ARG_MIN": _op_arg_min,
    "GATHER": _op_gather,
    "PACK": _op_pack,
    "UNPACK": _op_unpack,
    "SPLIT": _op_split,
    "SPLIT_V": _op_split_v,
    "TILE": _op_tile,
    "SPACE_TO_DEPTH": _op_space_to_depth,
    "DEPTH_TO_SPACE": _op_depth_to_space,
    "L2_NORMALIZATION": _op_l2_norm,
    "PRELU": _op_prelu,
    "LEAKY_RELU": _op_leaky_relu,
    "DEQUANTIZE": _op_dequantize,
    "QUANTIZE": _op_quantize,
}


def lower_tflite(model: TFLiteModel, jit: bool = True,
                 fake_quant: bool = True,
                 int8_compute: bool = False) -> Callable:
    """Build a callable ``fn(*inputs) -> tuple(outputs)`` from the graph.

    Inputs/outputs follow the model's declared dtypes (quantized models
    take/return uint8/int8).  With ``jit=True`` the whole graph compiles
    into one XLA program; ``int8_compute`` runs quantized conv/dense as
    true int8×int8→int32 MXU arithmetic.
    """
    lowering = _Lowering(model, fake_quant=fake_quant,
                         int8_compute=int8_compute)
    return jax.jit(lowering) if jit else lowering
