"""TFLite flatbuffer reader — no TensorFlow dependency.

Parses ``.tflite`` files directly against the public TFLite schema
(``tensorflow/lite/schema/schema.fbs``, file identifier ``TFL3``) using
the stock ``flatbuffers`` Python runtime's generic ``Table`` accessors —
the same machinery flatc-generated readers are sugar over.  The vtable
slot numbers below follow the schema's field declaration order, which is
what flatc assigns and is frozen by TFLite's compatibility guarantee.

Reference capability being replaced:
``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc:158-276``
(TFLiteInterpreter wraps the TFLite C++ interpreter).  Here the file is
parsed in-process and lowered to jnp (see ``tflite_lower.py``) so the
model runs on TPU through XLA instead of a CPU interpreter.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np
import flatbuffers.number_types as N
from flatbuffers import encode
from flatbuffers.table import Table

FILE_IDENTIFIER = b"TFL3"

# -- schema enums -----------------------------------------------------------

# TensorType (schema.fbs)
TENSOR_DTYPES = {
    0: "float32", 1: "float16", 2: "int32", 3: "uint8", 4: "int64",
    5: "string", 6: "bool", 7: "int16", 8: "complex64", 9: "int8",
    10: "float64", 11: "complex128", 12: "uint64", 13: "resource",
    14: "variant", 15: "uint32", 16: "uint16", 17: "int4",
}

# BuiltinOperator — names for the codes the lowerer handles (plus a few
# neighbours so error messages for unsupported models are readable)
BUILTIN_OPS = {
    0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
    4: "DEPTHWISE_CONV_2D", 5: "DEPTH_TO_SPACE", 6: "DEQUANTIZE",
    9: "FULLY_CONNECTED", 11: "L2_NORMALIZATION", 14: "LOGISTIC",
    17: "MAX_POOL_2D", 18: "MUL", 19: "RELU", 20: "RELU_N1_TO_1",
    21: "RELU6", 22: "RESHAPE", 23: "RESIZE_BILINEAR", 25: "SOFTMAX",
    26: "SPACE_TO_DEPTH", 28: "TANH", 32: "CUSTOM", 34: "PAD",
    36: "GATHER", 39: "TRANSPOSE", 40: "MEAN", 41: "SUB", 42: "DIV",
    43: "SQUEEZE", 45: "STRIDED_SLICE", 47: "EXP", 49: "SPLIT",
    53: "CAST", 54: "PRELU", 55: "MAXIMUM", 56: "ARG_MAX", 57: "MINIMUM",
    59: "NEG", 61: "GREATER", 65: "SLICE", 66: "SIN", 67: "TRANSPOSE_CONV",
    69: "TILE", 70: "EXPAND_DIMS", 71: "EQUAL", 73: "LOG", 74: "SUM",
    75: "SQRT", 76: "RSQRT", 77: "SHAPE", 78: "POW", 79: "ARG_MIN",
    81: "REDUCE_PROD", 82: "REDUCE_MAX", 83: "PACK", 88: "UNPACK",
    89: "REDUCE_MIN", 90: "FLOOR_DIV", 92: "SQUARE", 97: "RESIZE_NEAREST_NEIGHBOR",
    98: "LEAKY_RELU", 99: "SQUARED_DIFFERENCE", 100: "MIRROR_PAD",
    101: "ABS", 102: "SPLIT_V", 114: "QUANTIZE", 117: "HARD_SWISH",
    126: "BATCH_MATMUL", 130: "BROADCAST_TO", 145: "BROADCAST_ARGS",
}

PADDING = {0: "SAME", 1: "VALID"}
ACTIVATIONS = {0: None, 1: "relu", 2: "relu_n1_to_1", 3: "relu6",
               4: "tanh", 5: "sign_bit"}

# -- generic flatbuffer field helpers --------------------------------------

def _vt(slot: int) -> int:
    return 4 + 2 * slot


def _scalar(t: Table, slot: int, flags, default):
    o = t.Offset(_vt(slot))
    if o == 0:
        return default
    return t.Get(flags, t.Pos + o)


def _string(t: Table, slot: int) -> Optional[str]:
    o = t.Offset(_vt(slot))
    if o == 0:
        return None
    return t.String(t.Pos + o).decode("utf-8", "replace")


def _table(t: Table, slot: int) -> Optional[Table]:
    o = t.Offset(_vt(slot))
    if o == 0:
        return None
    return Table(t.Bytes, t.Indirect(t.Pos + o))


def _union_table(t: Table, slot: int) -> Optional[Table]:
    """A union *value* field: stored like a table offset."""
    return _table(t, slot)


def _vec_np(t: Table, slot: int, flags) -> np.ndarray:
    o = t.Offset(_vt(slot))
    if o == 0:
        return np.zeros(0, N.to_numpy_type(flags))
    return t.GetVectorAsNumpy(flags, o)


def _vec_tables(t: Table, slot: int) -> List[Table]:
    o = t.Offset(_vt(slot))
    if o == 0:
        return []
    n = t.VectorLen(o)
    if n * 4 > len(t.Bytes):
        # a table-offset vector cannot outnumber the file's bytes/4 —
        # corrupted counts must not drive a near-infinite loop
        raise TFLiteParseError(f"corrupt vector length {n}")
    start = t.Vector(o)
    return [Table(t.Bytes, t.Indirect(start + 4 * j)) for j in range(n)]


def _vec_bytes_zero_copy(t: Table, slot: int) -> Optional[memoryview]:
    """[ubyte] vector as a zero-copy view into the file buffer."""
    o = t.Offset(_vt(slot))
    if o == 0:
        return None
    n = t.VectorLen(o)
    start = t.Vector(o)
    return memoryview(t.Bytes)[start:start + n]


# -- parsed-model dataclasses ----------------------------------------------

@dataclass
class QuantParams:
    scale: np.ndarray          # per-tensor (len 1) or per-channel
    zero_point: np.ndarray
    quantized_dimension: int = 0

    @property
    def per_channel(self) -> bool:
        return self.scale.size > 1


@dataclass
class TFLTensor:
    index: int
    name: str
    shape: Tuple[int, ...]
    dtype: str
    buffer: int
    quant: Optional[QuantParams] = None
    data: Optional[np.ndarray] = None   # constant data (None for activations)

    @property
    def is_const(self) -> bool:
        return self.data is not None


@dataclass
class TFLOp:
    opcode: str
    inputs: List[int]           # tensor indices; -1 = optional-absent
    outputs: List[int]
    options: Dict[str, Any] = field(default_factory=dict)


@dataclass
class TFLiteModel:
    version: int
    description: str
    tensors: List[TFLTensor]
    inputs: List[int]
    outputs: List[int]
    ops: List[TFLOp]

    def op_histogram(self) -> Dict[str, int]:
        h: Dict[str, int] = {}
        for op in self.ops:
            h[op.opcode] = h.get(op.opcode, 0) + 1
        return h


# -- options decoding -------------------------------------------------------
# Each decoder maps (options Table) -> dict of the fields the lowerer uses.
# Slot numbers are the schema declaration order of each options table.

def _opt_conv2d(t: Table) -> Dict[str, Any]:
    return {
        "padding": PADDING[_scalar(t, 0, N.Int8Flags, 0)],
        "stride_w": _scalar(t, 1, N.Int32Flags, 1) or 1,
        "stride_h": _scalar(t, 2, N.Int32Flags, 1) or 1,
        "activation": ACTIVATIONS.get(_scalar(t, 3, N.Int8Flags, 0)),
        "dilation_w": _scalar(t, 4, N.Int32Flags, 1) or 1,
        "dilation_h": _scalar(t, 5, N.Int32Flags, 1) or 1,
    }


def _opt_depthwise(t: Table) -> Dict[str, Any]:
    return {
        "padding": PADDING[_scalar(t, 0, N.Int8Flags, 0)],
        "stride_w": _scalar(t, 1, N.Int32Flags, 1) or 1,
        "stride_h": _scalar(t, 2, N.Int32Flags, 1) or 1,
        "depth_multiplier": _scalar(t, 3, N.Int32Flags, 1) or 1,
        "activation": ACTIVATIONS.get(_scalar(t, 4, N.Int8Flags, 0)),
        "dilation_w": _scalar(t, 5, N.Int32Flags, 1) or 1,
        "dilation_h": _scalar(t, 6, N.Int32Flags, 1) or 1,
    }


def _opt_pool2d(t: Table) -> Dict[str, Any]:
    return {
        "padding": PADDING[_scalar(t, 0, N.Int8Flags, 0)],
        "stride_w": _scalar(t, 1, N.Int32Flags, 1) or 1,
        "stride_h": _scalar(t, 2, N.Int32Flags, 1) or 1,
        "filter_w": _scalar(t, 3, N.Int32Flags, 1) or 1,
        "filter_h": _scalar(t, 4, N.Int32Flags, 1) or 1,
        "activation": ACTIVATIONS.get(_scalar(t, 5, N.Int8Flags, 0)),
    }


def _opt_fully_connected(t: Table) -> Dict[str, Any]:
    return {
        "activation": ACTIVATIONS.get(_scalar(t, 0, N.Int8Flags, 0)),
        "weights_format": _scalar(t, 1, N.Int8Flags, 0),
        "keep_num_dims": bool(_scalar(t, 2, N.BoolFlags, 0)),
    }


def _opt_softmax(t: Table) -> Dict[str, Any]:
    return {"beta": _scalar(t, 0, N.Float32Flags, 1.0) or 1.0}


def _opt_activation_only(t: Table) -> Dict[str, Any]:
    return {"activation": ACTIVATIONS.get(_scalar(t, 0, N.Int8Flags, 0))}


def _opt_reshape(t: Table) -> Dict[str, Any]:
    return {"new_shape": _vec_np(t, 0, N.Int32Flags).tolist()}


def _opt_concat(t: Table) -> Dict[str, Any]:
    return {
        "axis": _scalar(t, 0, N.Int32Flags, 0),
        "activation": ACTIVATIONS.get(_scalar(t, 1, N.Int8Flags, 0)),
    }


def _opt_reducer(t: Table) -> Dict[str, Any]:
    return {"keep_dims": bool(_scalar(t, 0, N.BoolFlags, 0))}


def _opt_strided_slice(t: Table) -> Dict[str, Any]:
    return {
        "begin_mask": _scalar(t, 0, N.Int32Flags, 0),
        "end_mask": _scalar(t, 1, N.Int32Flags, 0),
        "ellipsis_mask": _scalar(t, 2, N.Int32Flags, 0),
        "new_axis_mask": _scalar(t, 3, N.Int32Flags, 0),
        "shrink_axis_mask": _scalar(t, 4, N.Int32Flags, 0),
    }


def _opt_resize_bilinear(t: Table) -> Dict[str, Any]:
    return {
        "align_corners": bool(_scalar(t, 2, N.BoolFlags, 0)),
        "half_pixel_centers": bool(_scalar(t, 3, N.BoolFlags, 0)),
    }


def _opt_resize_nearest(t: Table) -> Dict[str, Any]:
    return {
        "align_corners": bool(_scalar(t, 0, N.BoolFlags, 0)),
        "half_pixel_centers": bool(_scalar(t, 1, N.BoolFlags, 0)),
    }


def _opt_leaky_relu(t: Table) -> Dict[str, Any]:
    return {"alpha": _scalar(t, 0, N.Float32Flags, 0.0)}


def _opt_pack(t: Table) -> Dict[str, Any]:
    return {"values_count": _scalar(t, 0, N.Int32Flags, 0),
            "axis": _scalar(t, 1, N.Int32Flags, 0)}


def _opt_unpack(t: Table) -> Dict[str, Any]:
    return {"num": _scalar(t, 0, N.Int32Flags, 0),
            "axis": _scalar(t, 1, N.Int32Flags, 0)}


def _opt_gather(t: Table) -> Dict[str, Any]:
    return {"axis": _scalar(t, 0, N.Int32Flags, 0),
            "batch_dims": _scalar(t, 1, N.Int32Flags, 0)}


def _opt_arg_minmax(t: Table) -> Dict[str, Any]:
    return {"output_type": TENSOR_DTYPES.get(
        _scalar(t, 0, N.Int8Flags, 4), "int64")}


def _opt_split(t: Table) -> Dict[str, Any]:
    return {"num_splits": _scalar(t, 0, N.Int32Flags, 0)}


def _opt_squeeze(t: Table) -> Dict[str, Any]:
    return {"squeeze_dims": _vec_np(t, 0, N.Int32Flags).tolist()}


def _opt_cast(t: Table) -> Dict[str, Any]:
    return {
        "in_dtype": TENSOR_DTYPES.get(_scalar(t, 0, N.Int8Flags, 0)),
        "out_dtype": TENSOR_DTYPES.get(_scalar(t, 1, N.Int8Flags, 0)),
    }


def _opt_space_depth(t: Table) -> Dict[str, Any]:
    return {"block_size": _scalar(t, 0, N.Int32Flags, 0)}


def _opt_mirror_pad(t: Table) -> Dict[str, Any]:
    return {"mode": {0: "reflect", 1: "symmetric"}[_scalar(t, 0, N.Int8Flags, 0)]}


def _opt_transpose_conv(t: Table) -> Dict[str, Any]:
    return {
        "padding": PADDING[_scalar(t, 0, N.Int8Flags, 0)],
        "stride_w": _scalar(t, 1, N.Int32Flags, 1) or 1,
        "stride_h": _scalar(t, 2, N.Int32Flags, 1) or 1,
        "activation": ACTIVATIONS.get(_scalar(t, 3, N.Int8Flags, 0)),
    }


def _opt_shape(t: Table) -> Dict[str, Any]:
    return {"out_dtype": TENSOR_DTYPES.get(_scalar(t, 0, N.Int8Flags, 2), "int32")}


# opcode name -> options decoder (the BuiltinOptions union member that
# accompanies each op is fixed by the schema, so dispatching on the
# opcode is equivalent to dispatching on builtin_options_type)
_OPT_DECODERS = {
    "CONV_2D": _opt_conv2d,
    "DEPTHWISE_CONV_2D": _opt_depthwise,
    "AVERAGE_POOL_2D": _opt_pool2d,
    "MAX_POOL_2D": _opt_pool2d,
    "FULLY_CONNECTED": _opt_fully_connected,
    "SOFTMAX": _opt_softmax,
    "ADD": _opt_activation_only,
    "SUB": _opt_activation_only,
    "MUL": _opt_activation_only,
    "DIV": _opt_activation_only,
    "L2_NORMALIZATION": _opt_activation_only,
    "RESHAPE": _opt_reshape,
    "CONCATENATION": _opt_concat,
    "MEAN": _opt_reducer,
    "SUM": _opt_reducer,
    "REDUCE_MAX": _opt_reducer,
    "REDUCE_MIN": _opt_reducer,
    "REDUCE_PROD": _opt_reducer,
    "STRIDED_SLICE": _opt_strided_slice,
    "RESIZE_BILINEAR": _opt_resize_bilinear,
    "RESIZE_NEAREST_NEIGHBOR": _opt_resize_nearest,
    "LEAKY_RELU": _opt_leaky_relu,
    "PACK": _opt_pack,
    "UNPACK": _opt_unpack,
    "GATHER": _opt_gather,
    "ARG_MAX": _opt_arg_minmax,
    "ARG_MIN": _opt_arg_minmax,
    "SPLIT": _opt_split,
    "SQUEEZE": _opt_squeeze,
    "CAST": _opt_cast,
    "SPACE_TO_DEPTH": _opt_space_depth,
    "DEPTH_TO_SPACE": _opt_space_depth,
    "MIRROR_PAD": _opt_mirror_pad,
    "TRANSPOSE_CONV": _opt_transpose_conv,
    "SHAPE": _opt_shape,
}


# -- top-level parse --------------------------------------------------------

class TFLiteParseError(ValueError):
    pass


class _EmptyTable:
    """Stand-in for an omitted options table: every field reads as absent,
    so decoders produce the schema defaults."""

    Bytes = b"\x00" * 8
    Pos = 4

    def Offset(self, _vt):
        return 0


_EMPTY_TABLE = _EmptyTable()


def _parse_quant(t: Optional[Table]) -> Optional[QuantParams]:
    if t is None:
        return None
    scale = _vec_np(t, 2, N.Float32Flags)
    zp = _vec_np(t, 3, N.Int64Flags)
    if scale.size == 0:
        return None
    if zp.size == 0:
        zp = np.zeros_like(scale, dtype=np.int64)
    return QuantParams(
        scale=scale.astype(np.float32),
        zero_point=zp.astype(np.int64),
        quantized_dimension=_scalar(t, 6, N.Int32Flags, 0),
    )


def read_tflite(path_or_bytes, subgraph: int = 0) -> TFLiteModel:
    """Parse a .tflite file (or bytes) into a TFLiteModel.

    Model files cross trust boundaries; every malformed input fails with
    :class:`TFLiteParseError` — low-level decode errors (flatbuffers
    range checks, struct/numpy) never escape raw."""
    try:
        return _read_tflite(path_or_bytes, subgraph)
    except TFLiteParseError:
        raise
    except (TypeError, ValueError, IndexError, KeyError, OverflowError,
            UnicodeDecodeError, MemoryError, struct.error) as e:
        raise TFLiteParseError(f"malformed tflite flatbuffer: {e}") from e


def _read_tflite(path_or_bytes, subgraph: int = 0) -> TFLiteModel:
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        buf = bytes(path_or_bytes)
    else:
        with open(path_or_bytes, "rb") as f:
            buf = f.read()
    if len(buf) < 8:
        raise TFLiteParseError("file too small to be a tflite flatbuffer")
    if buf[4:8] != FILE_IDENTIFIER:
        raise TFLiteParseError(
            f"bad file identifier {buf[4:8]!r} (expected {FILE_IDENTIFIER!r})")

    root = Table(buf, encode.Get(N.UOffsetTFlags.packer_type, buf, 0))
    version = _scalar(root, 0, N.Uint32Flags, 0)

    # operator_codes: resolve each to a builtin name.  Newer files put the
    # code in the int32 `builtin_code` field (slot 3) and clamp the legacy
    # int8 field (slot 0) at 127; `max` of the two is the documented rule.
    opcodes: List[str] = []
    for oc in _vec_tables(root, 1):
        legacy = _scalar(oc, 0, N.Int8Flags, 0)
        modern = _scalar(oc, 3, N.Int32Flags, 0)
        code = max(int(legacy), int(modern))
        name = BUILTIN_OPS.get(code)
        if name is None:
            name = f"BUILTIN_{code}"
        if name == "CUSTOM":
            name = f"CUSTOM:{_string(oc, 1) or '?'}"
        opcodes.append(name)

    buffers = _vec_tables(root, 4)
    subgraphs = _vec_tables(root, 2)
    if not subgraphs:
        raise TFLiteParseError("model has no subgraphs")
    if subgraph >= len(subgraphs):
        raise TFLiteParseError(
            f"subgraph {subgraph} out of range ({len(subgraphs)} present)")
    sg = subgraphs[subgraph]

    tensors: List[TFLTensor] = []
    for i, tt in enumerate(_vec_tables(sg, 0)):
        shape = tuple(int(x) for x in _vec_np(tt, 0, N.Int32Flags))
        dtype_code = _scalar(tt, 1, N.Int8Flags, 0)
        dtype = TENSOR_DTYPES.get(dtype_code)
        if dtype is None:
            raise TFLiteParseError(
                f"tensor {i}: unknown TensorType code {dtype_code}")
        buf_idx = _scalar(tt, 2, N.Uint32Flags, 0)
        data = None
        if 0 < buf_idx < len(buffers):
            raw = _vec_bytes_zero_copy(buffers[buf_idx], 0)
            if raw is not None and len(raw) > 0:
                if dtype in ("string", "resource", "variant"):
                    raise TFLiteParseError(
                        f"tensor {i}: unsupported constant dtype {dtype}")
                arr = np.frombuffer(raw, dtype=np.dtype(dtype))
                data = arr.reshape(shape) if shape else arr.reshape(())
        tensors.append(TFLTensor(
            index=i,
            name=_string(tt, 3) or f"t{i}",
            shape=shape,
            dtype=dtype,
            buffer=buf_idx,
            quant=_parse_quant(_table(tt, 4)),
            data=data,
        ))

    ops: List[TFLOp] = []
    for ot in _vec_tables(sg, 3):
        idx = _scalar(ot, 0, N.Uint32Flags, 0)
        if idx >= len(opcodes):
            raise TFLiteParseError(f"opcode index {idx} out of range")
        name = opcodes[idx]
        decoder = _OPT_DECODERS.get(name)
        options: Dict[str, Any] = {}
        if decoder is not None:
            opt_table = _union_table(ot, 4)
            options = decoder(opt_table if opt_table is not None
                              else _EMPTY_TABLE)
        ops.append(TFLOp(
            opcode=name,
            inputs=[int(x) for x in _vec_np(ot, 1, N.Int32Flags)],
            outputs=[int(x) for x in _vec_np(ot, 2, N.Int32Flags)],
            options=options,
        ))

    return TFLiteModel(
        version=version,
        description=_string(root, 3) or "",
        tensors=tensors,
        inputs=[int(x) for x in _vec_np(sg, 1, N.Int32Flags)],
        outputs=[int(x) for x in _vec_np(sg, 2, N.Int32Flags)],
        ops=ops,
    )
