"""ONNX model reader — no ``onnx`` package dependency.

Parses ``.onnx`` files (protobuf ``ModelProto``) with a minimal
protobuf *wire-format* reader: varint/64-bit/length-delimited/32-bit
records walked directly, field numbers fixed by the public
``onnx/onnx.proto3`` schema.  Only the subset the lowerer consumes is
extracted (graph topology, initializers, value-info shapes, node
attributes).

Reference capability being replaced: the reference runs .onnx through
vendor subplugins (``tensor_filter_openvino.cc``,
``tensor_filter_snpe.cc``, TensorRT's onnx parser …) — each wraps a
closed runtime.  Here the graph lowers to jnp and XLA is the runtime
(see ``onnx_lower.py``).
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np


class OnnxParseError(ValueError):
    pass


# -- protobuf wire-format primitives ----------------------------------------

def _read_varint(buf: memoryview, off: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if off >= len(buf):
            raise OnnxParseError("truncated varint")
        b = buf[off]
        off += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, off
        shift += 7
        if shift > 70:
            raise OnnxParseError("varint too long")


def _signed(v: int) -> int:
    """Interpret a varint as two's-complement int64 (protobuf int64)."""
    if v >= 1 << 63:
        v -= 1 << 64
    return v


def iter_fields(buf: memoryview) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, payload).  Payload is an int for
    varint/fixed types, a memoryview for length-delimited."""
    off = 0
    n = len(buf)
    while off < n:
        key, off = _read_varint(buf, off)
        fno, wt = key >> 3, key & 7
        if wt == 0:          # varint
            val, off = _read_varint(buf, off)
            yield fno, wt, val
        elif wt == 1:        # 64-bit
            if off + 8 > n:
                raise OnnxParseError("truncated 64-bit field")
            val = buf[off:off + 8]
            off += 8
            yield fno, wt, val
        elif wt == 2:        # length-delimited
            ln, off = _read_varint(buf, off)
            if off + ln > n:
                raise OnnxParseError("truncated length-delimited field")
            yield fno, wt, buf[off:off + ln]
            off += ln
        elif wt == 5:        # 32-bit
            if off + 4 > n:
                raise OnnxParseError("truncated 32-bit field")
            val = buf[off:off + 4]
            off += 4
            yield fno, wt, val
        else:
            raise OnnxParseError(f"unsupported wire type {wt}")


def _packed_varints(view: memoryview, signed: bool = True) -> List[int]:
    out = []
    off = 0
    while off < len(view):
        v, off = _read_varint(view, off)
        out.append(_signed(v) if signed else v)
    return out


# -- ONNX data types ---------------------------------------------------------

ONNX_DTYPES = {
    1: "float32", 2: "uint8", 3: "int8", 4: "uint16", 5: "int16",
    6: "int32", 7: "int64", 9: "bool", 10: "float16", 11: "float64",
    12: "uint32", 13: "uint64", 16: "bfloat16",
}


@dataclass
class OnnxAttr:
    name: str
    value: Any  # float | int | bytes | np.ndarray | list[...]


@dataclass
class OnnxNode:
    op_type: str
    inputs: List[str]
    outputs: List[str]
    name: str = ""
    attrs: Dict[str, Any] = field(default_factory=dict)


@dataclass
class OnnxValueInfo:
    name: str
    dtype: Optional[str]
    shape: Optional[Tuple[Optional[int], ...]]  # None dim = dynamic


@dataclass
class OnnxModel:
    ir_version: int
    opset: int
    nodes: List[OnnxNode]
    initializers: Dict[str, np.ndarray]
    inputs: List[OnnxValueInfo]      # graph inputs MINUS initializers
    outputs: List[OnnxValueInfo]

    def op_histogram(self) -> Dict[str, int]:
        h: Dict[str, int] = {}
        for n in self.nodes:
            h[n.op_type] = h.get(n.op_type, 0) + 1
        return h


# -- message decoders --------------------------------------------------------

def _decode_tensor(view: memoryview) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    data_type = 1
    raw: Optional[memoryview] = None
    name = ""
    float_data: List[float] = []
    int_data: List[int] = []
    for fno, wt, val in iter_fields(view):
        if fno == 1:                      # dims
            if wt == 2:
                dims.extend(_packed_varints(val))
            else:
                dims.append(_signed(val))
        elif fno == 2 and wt == 0:        # data_type
            data_type = val
        elif fno == 4:                    # float_data (packed or not)
            if wt == 2:
                float_data.extend(
                    struct.unpack(f"<{len(val) // 4}f", bytes(val)))
            else:
                float_data.append(struct.unpack("<f", bytes(val))[0])
        elif fno == 5:                    # int32_data
            if wt == 2:
                int_data.extend(_packed_varints(val))
            else:
                int_data.append(_signed(val))
        elif fno == 7:                    # int64_data
            if wt == 2:
                int_data.extend(_packed_varints(val))
            else:
                int_data.append(_signed(val))
        elif fno == 8 and wt == 2:        # name
            name = bytes(val).decode("utf-8", "replace")
        elif fno == 9 and wt == 2:        # raw_data
            raw = val
        elif fno == 10:                   # double_data
            if wt == 2:
                float_data.extend(
                    struct.unpack(f"<{len(val) // 8}d", bytes(val)))
            else:
                float_data.append(struct.unpack("<d", bytes(val))[0])
    dtype_name = ONNX_DTYPES.get(data_type)
    if dtype_name is None:
        raise OnnxParseError(f"tensor {name!r}: unsupported data_type "
                             f"{data_type}")
    np_dtype = (np.dtype(np.uint16) if dtype_name == "bfloat16"
                else np.dtype(dtype_name))
    shape = tuple(int(d) for d in dims)
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype)
    elif float_data:
        arr = np.asarray(float_data, dtype=np_dtype)
    elif int_data:
        if dtype_name in ("float16", "bfloat16"):
            # spec: fp16/bf16 ride int32_data as raw BIT PATTERNS
            arr = np.asarray(int_data, np.uint16).view(np_dtype)
        else:
            arr = np.asarray(int_data, dtype=np_dtype)
    else:
        if int(np.prod(shape, dtype=np.int64)) > (1 << 28):
            raise OnnxParseError(
                f"tensor {name!r}: declared dims {shape} with no data")
        arr = np.zeros(shape, np_dtype)
    if dtype_name == "bfloat16":
        # widen via bit manipulation: bf16 is the top half of f32
        arr = (arr.astype(np.uint32) << 16).view(np.float32)
    return name, arr.reshape(shape) if shape else arr.reshape(())


def _decode_attr(view: memoryview) -> OnnxAttr:
    name = ""
    atype = 0
    f_val = i_val = s_val = t_val = None
    floats: List[float] = []
    ints: List[int] = []
    strings: List[bytes] = []
    for fno, wt, val in iter_fields(view):
        if fno == 1 and wt == 2:
            name = bytes(val).decode()
        elif fno == 2 and wt == 5:
            f_val = struct.unpack("<f", bytes(val))[0]
        elif fno == 3 and wt == 0:
            i_val = _signed(val)
        elif fno == 4 and wt == 2:
            s_val = bytes(val)
        elif fno == 5 and wt == 2:
            t_val = _decode_tensor(val)[1]
        elif fno == 7:
            if wt == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", bytes(val)))
            else:
                floats.append(struct.unpack("<f", bytes(val))[0])
        elif fno == 8:
            if wt == 2:
                ints.extend(_packed_varints(val))
            else:
                ints.append(_signed(val))
        elif fno == 9 and wt == 2:
            strings.append(bytes(val))
        elif fno == 20 and wt == 0:
            atype = val
    # AttributeType: FLOAT=1 INT=2 STRING=3 TENSOR=4 FLOATS=6 INTS=7 STRINGS=8
    if atype == 1 or (atype == 0 and f_val is not None):
        return OnnxAttr(name, f_val)
    if atype == 2 or (atype == 0 and i_val is not None):
        return OnnxAttr(name, i_val)
    if atype == 3 or (atype == 0 and s_val is not None):
        return OnnxAttr(name, s_val)
    if atype == 4 or (atype == 0 and t_val is not None):
        return OnnxAttr(name, t_val)
    if atype == 6 or floats:
        return OnnxAttr(name, list(floats))
    if atype == 7 or ints:
        return OnnxAttr(name, list(ints))
    if atype == 8 or strings:
        return OnnxAttr(name, strings)
    return OnnxAttr(name, None)


def _decode_node(view: memoryview) -> OnnxNode:
    node = OnnxNode("", [], [])
    for fno, wt, val in iter_fields(view):
        if fno == 1 and wt == 2:
            node.inputs.append(bytes(val).decode())
        elif fno == 2 and wt == 2:
            node.outputs.append(bytes(val).decode())
        elif fno == 3 and wt == 2:
            node.name = bytes(val).decode()
        elif fno == 4 and wt == 2:
            node.op_type = bytes(val).decode()
        elif fno == 5 and wt == 2:
            a = _decode_attr(val)
            node.attrs[a.name] = a.value
    return node


def _decode_value_info(view: memoryview) -> OnnxValueInfo:
    name = ""
    dtype = None
    shape: Optional[Tuple[Optional[int], ...]] = None
    for fno, wt, val in iter_fields(view):
        if fno == 1 and wt == 2:
            name = bytes(val).decode()
        elif fno == 2 and wt == 2:           # TypeProto
            for f2, w2, v2 in iter_fields(val):
                if f2 == 1 and w2 == 2:      # tensor_type
                    dims: List[Optional[int]] = []
                    for f3, w3, v3 in iter_fields(v2):
                        if f3 == 1 and w3 == 0:   # elem_type
                            dtype = ONNX_DTYPES.get(v3)
                        elif f3 == 2 and w3 == 2:  # shape
                            for f4, w4, v4 in iter_fields(v3):
                                if f4 == 1 and w4 == 2:  # dim
                                    dv: Optional[int] = None
                                    for f5, w5, v5 in iter_fields(v4):
                                        if f5 == 1 and w5 == 0:
                                            dv = _signed(v5)
                                    dims.append(dv)
                    shape = tuple(dims)
    return OnnxValueInfo(name, dtype, shape)


def read_onnx(path_or_bytes) -> OnnxModel:
    """Parse a .onnx file (or bytes) into an OnnxModel.

    Model files cross trust boundaries; every malformed input fails with
    :class:`OnnxParseError` — low-level decode errors (struct/unicode/
    numpy) never escape raw."""
    try:
        return _read_onnx(path_or_bytes)
    except OnnxParseError:
        raise
    except (struct.error, UnicodeDecodeError, ValueError, IndexError,
            KeyError, OverflowError, TypeError, MemoryError) as e:
        raise OnnxParseError(f"malformed onnx protobuf: {e}") from e


def _read_onnx(path_or_bytes) -> OnnxModel:
    if isinstance(path_or_bytes, (bytes, bytearray, memoryview)):
        buf = memoryview(bytes(path_or_bytes))
    else:
        with open(path_or_bytes, "rb") as f:
            buf = memoryview(f.read())

    ir_version = 0
    opset = 0
    graph_view: Optional[memoryview] = None
    try:
        for fno, wt, val in iter_fields(buf):
            if fno == 1 and wt == 0:
                ir_version = val
            elif fno == 8 and wt == 2:       # opset_import
                for f2, w2, v2 in iter_fields(val):
                    if f2 == 2 and w2 == 0:
                        opset = max(opset, _signed(v2))
            elif fno == 7 and wt == 2:
                graph_view = val
    except OnnxParseError as e:
        raise OnnxParseError(f"not an ONNX protobuf: {e}") from None
    if graph_view is None:
        raise OnnxParseError("no GraphProto in model (field 7 missing) — "
                             "is this really an .onnx file?")

    nodes: List[OnnxNode] = []
    initializers: Dict[str, np.ndarray] = {}
    inputs: List[OnnxValueInfo] = []
    outputs: List[OnnxValueInfo] = []
    for fno, wt, val in iter_fields(graph_view):
        if fno == 1 and wt == 2:
            nodes.append(_decode_node(val))
        elif fno == 5 and wt == 2:
            name, arr = _decode_tensor(val)
            initializers[name] = arr
        elif fno == 11 and wt == 2:
            inputs.append(_decode_value_info(val))
        elif fno == 12 and wt == 2:
            outputs.append(_decode_value_info(val))

    # graph.input lists initializers too (pre-IR4 style); real runtime
    # inputs are the ones without initializer data
    inputs = [vi for vi in inputs if vi.name not in initializers]
    return OnnxModel(
        ir_version=ir_version,
        opset=opset,
        nodes=nodes,
        initializers=initializers,
        inputs=inputs,
        outputs=outputs,
    )
