/**
 * scaler_custom.cc — example native custom-filter subplugin.
 *
 * Reference analog: tests/nnstreamer_example scaffolding subplugins
 * (passthrough/scaler fake backends used as deterministic test models).
 * Multiplies every float32 element by `mult:<f>` from the custom-props
 * string; identity otherwise.  Shape-polymorphic (set_input_info echoes
 * the input schema).
 *
 * Build: g++ -shared -fPIC -O2 -I../include scaler_custom.cc -o libscaler.so
 */

#include <cstdlib>
#include <cstring>
#include <string>

#include "nns_tpu_custom_filter.h"

namespace {

struct Instance {
  float mult = 1.0f;
  nns_tensor_spec in_specs[NNS_TPU_TENSOR_LIMIT];
  uint32_t num_in = 0;
};

}  // namespace

extern "C" {

void *nns_custom_open (const char *custom_props)
{
  Instance *inst = new Instance ();
  if (custom_props != nullptr) {
    std::string s (custom_props);
    auto pos = s.find ("mult:");
    if (pos != std::string::npos)
      inst->mult = std::strtof (s.c_str () + pos + 5, nullptr);
  }
  return inst;
}

int nns_custom_get_model_info (void *, nns_tensor_spec *, uint32_t *,
    nns_tensor_spec *, uint32_t *)
{
  return 1; /* shape-polymorphic: use set_input_info */
}

int nns_custom_set_input_info (void *handle, const nns_tensor_spec *in_specs,
    uint32_t num_in, nns_tensor_spec *out_specs, uint32_t *num_out)
{
  Instance *inst = static_cast<Instance *> (handle);
  if (num_in > NNS_TPU_TENSOR_LIMIT)
    return -1;
  std::memcpy (inst->in_specs, in_specs, num_in * sizeof (nns_tensor_spec));
  inst->num_in = num_in;
  std::memcpy (out_specs, in_specs, num_in * sizeof (nns_tensor_spec));
  *num_out = num_in;
  return 0;
}

int nns_custom_invoke (void *handle, const nns_tensor_mem *inputs,
    uint32_t num_in, nns_tensor_mem *outputs, uint32_t num_out)
{
  Instance *inst = static_cast<Instance *> (handle);
  if (num_in != num_out)
    return -1;
  for (uint32_t i = 0; i < num_in; ++i) {
    if (outputs[i].nbytes < inputs[i].nbytes)
      return -2;
    if (i < inst->num_in && inst->in_specs[i].dtype == NNS_FLOAT32) {
      const float *src = static_cast<const float *> (inputs[i].data);
      float *dst = static_cast<float *> (outputs[i].data);
      uint64_t n = inputs[i].nbytes / sizeof (float);
      for (uint64_t j = 0; j < n; ++j)
        dst[j] = src[j] * inst->mult;
    } else {
      std::memcpy (outputs[i].data, inputs[i].data, inputs[i].nbytes);
    }
  }
  return 0;
}

void nns_custom_close (void *handle)
{
  delete static_cast<Instance *> (handle);
}

}  /* extern "C" */
