/**
 * nns_tpu_custom_filter.h — C ABI for native custom filter subplugins.
 *
 * Reference analog: gst/nnstreamer/include/tensor_filter_custom.h (the
 * user-.so ABI loaded by tensor_filter_custom.c:338) and the v0/v1
 * framework ABI in nnstreamer_plugin_api_filter.h.  A shared object
 * implementing these four symbols can be run by the framework via
 * `tensor_filter framework=custom model=<path.so>`.
 *
 * Memory contract: the framework owns every buffer.  For invoke(), input
 * buffers are read-only; output buffers are pre-allocated by the framework
 * to the sizes advertised by get_model_info (or set_input_info) and must be
 * filled in place — the zero-copy analog of the reference's mapped
 * GstMemory.  No allocation crosses the ABI.
 */

#ifndef NNS_TPU_CUSTOM_FILTER_H
#define NNS_TPU_CUSTOM_FILTER_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define NNS_TPU_ABI_VERSION 1
#define NNS_TPU_RANK_LIMIT 16
#define NNS_TPU_TENSOR_LIMIT 16

/* element types; values match the reference tensor_typedef.h enum order */
typedef enum {
  NNS_INT32 = 0,
  NNS_UINT32,
  NNS_INT16,
  NNS_UINT16,
  NNS_INT8,
  NNS_UINT8,
  NNS_FLOAT64,
  NNS_FLOAT32,
  NNS_INT64,
  NNS_UINT64,
  NNS_FLOAT16,
} nns_tensor_type;

typedef struct {
  uint32_t dtype;                      /* nns_tensor_type */
  uint32_t rank;                       /* <= NNS_TPU_RANK_LIMIT */
  uint64_t dims[NNS_TPU_RANK_LIMIT];   /* row-major, dims[0] outermost */
} nns_tensor_spec;

typedef struct {
  void *data;
  uint64_t nbytes;
} nns_tensor_mem;

/**
 * Create an instance.  custom_props is the raw string of the element's
 * `custom=` property ("" when unset).  Returns an opaque handle, or NULL
 * on failure.
 */
void *nns_custom_open (const char *custom_props);

/**
 * Static model schema.  Fill in/out spec arrays (capacity
 * NNS_TPU_TENSOR_LIMIT each) and counts.  Return 0 on success, nonzero if
 * the filter is shape-polymorphic (then set_input_info is used instead).
 */
int nns_custom_get_model_info (void *handle,
    nns_tensor_spec *in_specs, uint32_t *num_in,
    nns_tensor_spec *out_specs, uint32_t *num_out);

/**
 * Shape-polymorphic schema: given the negotiated input specs, fill the
 * output specs.  Optional symbol; needed only when get_model_info returns
 * nonzero.  Return 0 on success.
 */
int nns_custom_set_input_info (void *handle,
    const nns_tensor_spec *in_specs, uint32_t num_in,
    nns_tensor_spec *out_specs, uint32_t *num_out);

/**
 * Run one frame.  Inputs are read-only; outputs are pre-allocated and
 * filled in place.  Return 0 on success, nonzero on error.
 */
int nns_custom_invoke (void *handle,
    const nns_tensor_mem *inputs, uint32_t num_in,
    nns_tensor_mem *outputs, uint32_t num_out);

/** Destroy the instance. */
void nns_custom_close (void *handle);

#ifdef __cplusplus
}
#endif

#endif /* NNS_TPU_CUSTOM_FILTER_H */
