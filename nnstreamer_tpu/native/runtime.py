"""ctypes bindings for the native core (libnns_tpu_core.so).

Builds the library on demand with g++ (no pybind11 in this image; the C
ABI + ctypes keeps the boundary simple).  Everything degrades gracefully:
if the toolchain or build is unavailable, ``available()`` returns False and
the pipeline runtime falls back to ``queue.Queue``.

:class:`NativeMailbox` is API-compatible with the ``queue.Queue`` subset
the scheduler uses (put/put_nowait/get/get_nowait raising queue.Full/Empty)
but blocks inside the C++ condvar with the GIL released — immediate
wakeups instead of Python poll loops.  Python object lifetime: a strong
reference is taken (Py_IncRef) before the pointer enters the native queue
and handed back to Python on pop; close() drains and releases leftovers.
"""

from __future__ import annotations

import ctypes
import os
import queue as _pyqueue
import subprocess
import threading
from typing import Any, Optional

from ..core.log import get_logger

log = get_logger("native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "core", "nns_tpu_core.cc")
_BUILD_DIR = os.path.join(_HERE, "build")
_SO = os.path.join(_BUILD_DIR, "libnns_tpu_core.so")

_lib: Optional[ctypes.CDLL] = None
_build_lock = threading.Lock()
_build_failed = False


def _build() -> Optional[str]:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    if _so_fresh():
        return _SO
    # compile to a temp name and rename atomically: a concurrent loader (or
    # a second process) must never dlopen a half-written .so
    tmp = f"{_SO}.{os.getpid()}.tmp"
    cmd = [
        "g++", "-O2", "-std=c++17", "-fPIC", "-shared", "-pthread",
        "-o", tmp, _SRC,
    ]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        log.warning("native core build failed to run: %s", e)
        return None
    if r.returncode != 0:
        log.warning("native core build failed:\n%s", r.stderr)
        return None
    os.replace(tmp, _SO)
    return _SO


_bg_build: Optional[threading.Thread] = None


def _so_fresh() -> bool:
    return os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)


def _load(block: bool = False) -> Optional[ctypes.CDLL]:
    """dlopen the core library.  When the .so is not built yet, `block=False`
    (the pipeline-start path) kicks off a background compile and returns
    None — the FIRST pipeline falls back to queue.Queue instead of stalling
    behind a 2-minute g++ run; later pipelines pick the library up."""
    global _lib, _build_failed, _bg_build
    if _lib is not None:
        return _lib
    if _build_failed:
        return None
    if os.environ.get("NNS_TPU_NO_NATIVE"):
        return None
    if not _so_fresh() and not block:
        with _build_lock:
            if _bg_build is None or not _bg_build.is_alive():
                def _bg():
                    global _build_failed
                    if _build() is None:
                        _build_failed = True  # fail once, fall back forever

                _bg_build = threading.Thread(
                    target=_bg, name="nns-native-build", daemon=True
                )
                _bg_build.start()
        return None
    with _build_lock:
        if _lib is not None or _build_failed:
            return _lib
        so = _SO if _so_fresh() else _build()
        if so is None:
            _build_failed = True
            return None
        lib = ctypes.CDLL(so)
        lib.nns_oq_create.restype = ctypes.c_void_p
        lib.nns_oq_create.argtypes = [ctypes.c_size_t]
        lib.nns_oq_push.restype = ctypes.c_int
        lib.nns_oq_push.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_double,
        ]
        lib.nns_oq_pop.restype = ctypes.c_int
        lib.nns_oq_pop.argtypes = [
            ctypes.c_void_p, ctypes.c_double,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.nns_oq_pop_n.restype = ctypes.c_int
        lib.nns_oq_pop_n.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_double,
            ctypes.POINTER(ctypes.c_void_p),
        ]
        lib.nns_oq_push_n.restype = ctypes.c_int
        lib.nns_oq_push_n.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_size_t, ctypes.c_double,
        ]
        lib.nns_oq_size.restype = ctypes.c_size_t
        lib.nns_oq_size.argtypes = [ctypes.c_void_p]
        lib.nns_oq_close.argtypes = [ctypes.c_void_p]
        lib.nns_oq_destroy.argtypes = [ctypes.c_void_p]
        lib.nns_pool_create.restype = ctypes.c_void_p
        lib.nns_pool_create.argtypes = [
            ctypes.c_size_t, ctypes.c_size_t, ctypes.c_size_t,
        ]
        lib.nns_pool_acquire.restype = ctypes.c_void_p
        lib.nns_pool_acquire.argtypes = [ctypes.c_void_p]
        lib.nns_pool_release.restype = ctypes.c_int
        lib.nns_pool_release.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
        lib.nns_pool_block_size.restype = ctypes.c_size_t
        lib.nns_pool_block_size.argtypes = [ctypes.c_void_p]
        lib.nns_pool_outstanding.restype = ctypes.c_size_t
        lib.nns_pool_outstanding.argtypes = [ctypes.c_void_p]
        lib.nns_pool_destroy.argtypes = [ctypes.c_void_p]
        lib.nns_reader_open.restype = ctypes.c_void_p
        lib.nns_reader_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.nns_reader_total.restype = ctypes.c_uint64
        lib.nns_reader_total.argtypes = [ctypes.c_void_p]
        lib.nns_reader_read.restype = ctypes.c_int
        lib.nns_reader_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p,
        ]
        lib.nns_reader_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.nns_reader_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        log.info("native core loaded: %s", so)
        return _lib


def available(block: bool = False) -> bool:
    """True when the native core is loadable now.  ``block=True`` waits for
    (or performs) the compile — tests use it; the runtime path does not."""
    return _load(block=block) is not None


class NativeMailbox:
    """queue.Queue-compatible bounded mailbox backed by the C++ condvar
    queue.  Raises queue.Full / queue.Empty like the stdlib class."""

    def __init__(self, maxsize: int = 0):
        lib = _load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._h = lib.nns_oq_create(max(0, maxsize))
        self._maxsize = max(0, maxsize)
        self._closed = False

    # -- stdlib-compatible subset -------------------------------------------
    def put(self, item: Any, timeout: Optional[float] = None) -> None:
        if self._closed:
            raise _pyqueue.Full
        ref = ctypes.py_object(item)
        ctypes.pythonapi.Py_IncRef(ref)
        # CPython: id(obj) IS the PyObject* address
        rc = self._lib.nns_oq_push(
            self._h, id(item), -1.0 if timeout is None else float(timeout)
        )
        if rc != 0:
            ctypes.pythonapi.Py_DecRef(ref)
            raise _pyqueue.Full

    def put_nowait(self, item: Any) -> None:
        self.put(item, timeout=0.0)

    def put_many(self, items: list, timeout: Optional[float] = None) -> int:
        """Push a run of items in ONE native call (block handoff): waits
        (bounded) for space for the first, appends the rest as capacity
        allows — one lock/wakeup cycle per run instead of one per frame.
        Returns the number of leading items consumed (0 on timeout)."""
        if self._closed:
            raise _pyqueue.Full
        n_items = len(items)
        if n_items == 0:
            return 0
        arr = (ctypes.c_void_p * n_items)()
        for i, item in enumerate(items):
            # strong ref per item BEFORE the pointer enters the queue
            ctypes.pythonapi.Py_IncRef(ctypes.py_object(item))
            arr[i] = id(item)
        rc = self._lib.nns_oq_push_n(
            self._h, arr, n_items,
            -1.0 if timeout is None else float(timeout),
        )
        consumed = max(0, rc)
        for i in range(consumed, n_items):
            # unconsumed tail: release the refs taken above
            ctypes.pythonapi.Py_DecRef(ctypes.py_object(items[i]))
        if rc == -2:
            raise _pyqueue.Full  # closed
        return consumed

    def _pop(self, timeout: Optional[float]) -> Any:
        out = ctypes.c_void_p()
        rc = self._lib.nns_oq_pop(
            self._h, -1.0 if timeout is None else float(timeout),
            ctypes.byref(out),
        )
        if rc != 0:
            raise _pyqueue.Empty
        obj = ctypes.cast(out, ctypes.py_object).value
        ctypes.pythonapi.Py_DecRef(ctypes.py_object(obj))
        return obj

    def get(self, timeout: Optional[float] = None) -> Any:
        if self._closed:
            raise _pyqueue.Empty
        return self._pop(timeout)

    def get_many(self, max_n: int, timeout: Optional[float] = None) -> list:
        """Pop up to ``max_n`` items in ONE native call: wait (bounded)
        for the first, drain the rest without waiting — the micro-batch
        collector's amortized path (one lock/wakeup cycle per batch
        instead of one per frame).  Raises queue.Empty on timeout."""
        if self._closed or max_n <= 0:
            raise _pyqueue.Empty
        arr = (ctypes.c_void_p * max_n)()
        rc = self._lib.nns_oq_pop_n(
            self._h, max_n,
            -1.0 if timeout is None else float(timeout), arr,
        )
        if rc <= 0:
            raise _pyqueue.Empty
        out = []
        for i in range(rc):
            obj = ctypes.cast(arr[i], ctypes.py_object).value
            ctypes.pythonapi.Py_DecRef(ctypes.py_object(obj))
            out.append(obj)
        return out

    def get_nowait(self) -> Any:
        return self.get(timeout=0.0)

    def qsize(self) -> int:
        if self._closed:
            return 0
        return int(self._lib.nns_oq_size(self._h))

    def empty(self) -> bool:
        return self.qsize() == 0

    @property
    def maxsize(self) -> int:  # parity with queue.Queue introspection
        return self._maxsize

    # -- lifecycle ----------------------------------------------------------
    def close(self) -> None:
        """Wake all waiters, drain, release refs.  The native queue itself
        is freed at GC (__del__): destroying here could free it under a
        straggler thread entering put/get — after close they just see the
        closed flag and raise, against still-valid memory."""
        if self._closed:
            return
        self._closed = True
        self._lib.nns_oq_close(self._h)
        while True:
            try:
                self._pop(timeout=0.0)
            except _pyqueue.Empty:
                break

    def __del__(self):  # pragma: no cover — GC order dependent
        try:
            if self._h:
                self.close()
                # no references left -> no concurrent callers; destroy
                # still waits for any waiter mid-exit in C++
                self._lib.nns_oq_destroy(self._h)
                self._h = None
        except Exception:  # allow-silent: __del__ during interpreter exit
            pass


class BufferPool:
    """Aligned recycled buffers (≙ gst_tensor_allocator): acquire() returns
    a writable memoryview over an aligned block; release() recycles it."""

    def __init__(self, block_size: int, prealloc: int = 4, alignment: int = 64):
        lib = _load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._h = lib.nns_pool_create(block_size, prealloc, alignment)
        if not self._h:
            raise ValueError("bad pool parameters (alignment power of two?)")
        self.block_size = block_size

    def acquire(self):
        ptr = self._lib.nns_pool_acquire(self._h)
        if not ptr:
            raise MemoryError("pool allocation failed")
        buf = (ctypes.c_char * self.block_size).from_address(ptr)
        mv = memoryview(buf).cast("B")
        return ptr, mv

    def release(self, ptr: int) -> None:
        if self._lib.nns_pool_release(self._h, ptr) != 0:
            raise ValueError("double release of pool block")

    @property
    def outstanding(self) -> int:
        return int(self._lib.nns_pool_outstanding(self._h))

    def destroy(self) -> None:
        if self._h:
            self._lib.nns_pool_destroy(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover
        try:
            self.destroy()
        except Exception:  # allow-silent: GC-order-dependent teardown
            pass


class SampleReader:
    """mmap-backed fixed-size sample reader — the native datarepo loader.

    ≙ the reference's C data reader (gstdatareposrc.c): the repo file is
    mapped once; ``read(i)`` is a single memcpy out of the page cache with
    the GIL released, and ``prefetch(i)`` madvises the next sample so
    shuffled epochs stream without per-sample seek/read syscalls.
    """

    def __init__(self, path: str, sample_size: int):
        import numpy as np

        lib = _load()
        if lib is None:
            raise RuntimeError("native core unavailable")
        self._lib = lib
        self._np = np
        self._h = lib.nns_reader_open(path.encode(), sample_size)
        if not self._h:
            raise OSError(f"cannot map {path!r} (empty or unreadable)")
        self.sample_size = sample_size
        self.total = int(lib.nns_reader_total(self._h))

    def read(self, index: int):
        """-> uint8 numpy array holding sample `index`."""
        # validate here too (a negative int becomes 2^64-1 through ctypes;
        # the C side also rejects, but never hand it a bad index)
        if not 0 <= int(index) < self.total:
            raise IndexError(f"sample {index} out of range (total {self.total})")
        out = self._np.empty(self.sample_size, self._np.uint8)
        rc = self._lib.nns_reader_read(
            self._h, int(index), out.ctypes.data_as(ctypes.c_void_p)
        )
        if rc != 0:
            raise IndexError(f"sample {index} out of range (total {self.total})")
        return out

    def prefetch(self, index: int) -> None:
        if self._h and 0 <= index < self.total:
            self._lib.nns_reader_prefetch(self._h, int(index))

    def close(self) -> None:
        if self._h:
            self._lib.nns_reader_close(self._h)
            self._h = None

    def __del__(self):  # pragma: no cover — GC order dependent
        try:
            self.close()
        except Exception:  # allow-silent: GC-order-dependent teardown
            pass
