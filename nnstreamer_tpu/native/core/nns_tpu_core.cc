/**
 * nns_tpu_core — native data-plane primitives for the pipeline runtime.
 *
 * Reference analog: the reference's core runtime is C (GStreamer queues,
 * streaming threads, GstAllocator — SURVEY §2.1/§L0); this library is the
 * TPU build's native equivalent under the Python orchestration layer:
 *
 *  - opaque-pointer mailbox (bounded MPMC queue, condvar blocking): element
 *    mailboxes block in native code with the GIL released (ctypes foreign
 *    calls drop it), so handoff wakeups are immediate instead of poll-loop
 *    latency, and producers get real backpressure.
 *  - aligned buffer pool (≙ gst_tensor_allocator, tensor_allocator.c:128):
 *    recycled aligned blocks for receive/scratch buffers.
 *
 * Pure C ABI over C++17 internals; loaded via ctypes (no pybind11 in this
 * image).  The library never touches Python objects — the Python wrapper
 * owns all refcounting.
 */

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

extern "C" {

/* ------------------------------------------------------------------ *
 * Opaque-pointer mailbox                                             *
 * ------------------------------------------------------------------ */

struct NnsQueue {
  std::mutex m;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::condition_variable idle; /* destroy waits for parked waiters */
  std::deque<void *> items;
  size_t capacity;
  int waiters = 0;
  bool closed = false;
};

namespace {
struct WaiterGuard {
  NnsQueue *q; /* lock must be held at construction and destruction */
  explicit WaiterGuard (NnsQueue *q_) : q (q_) { q->waiters++; }
  ~WaiterGuard ()
  {
    if (--q->waiters == 0)
      q->idle.notify_all ();
  }
};
} // namespace

void *nns_oq_create (size_t capacity)
{
  auto *q = new NnsQueue ();
  q->capacity = capacity ? capacity : SIZE_MAX;
  return q;
}

/* 0 = ok, -1 = timeout, -2 = closed.  timeout_s < 0 blocks forever. */
int nns_oq_push (void *h, void *obj, double timeout_s)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || q->items.size () < q->capacity; };
  if (timeout_s < 0) {
    q->not_full.wait (lk, ready);
  } else if (!q->not_full.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->closed)
    return -2;
  q->items.push_back (obj);
  q->not_empty.notify_one ();
  return 0;
}

/* 0 = ok (obj in *out), -1 = timeout, -2 = closed-and-drained. */
int nns_oq_pop (void *h, double timeout_s, void **out)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || !q->items.empty (); };
  if (timeout_s < 0) {
    q->not_empty.wait (lk, ready);
  } else if (!q->not_empty.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->items.empty ())
    return -2; /* closed */
  *out = q->items.front ();
  q->items.pop_front ();
  q->not_full.notify_one ();
  return 0;
}

size_t nns_oq_size (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::lock_guard<std::mutex> lk (q->m);
  return q->items.size ();
}

/* wake all waiters; pending items remain poppable until drained */
void nns_oq_close (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  {
    std::lock_guard<std::mutex> lk (q->m);
    q->closed = true;
  }
  q->not_full.notify_all ();
  q->not_empty.notify_all ();
}

/* caller must have drained (or accept leaking the queued pointers' refs —
 * the Python wrapper drains first).  Blocks until every parked waiter has
 * left push/pop so the mutex/condvars are never freed under a waiter. */
void nns_oq_destroy (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  {
    std::unique_lock<std::mutex> lk (q->m);
    q->closed = true;
    q->not_full.notify_all ();
    q->not_empty.notify_all ();
    q->idle.wait (lk, [q] { return q->waiters == 0; });
  }
  delete q;
}

/* ------------------------------------------------------------------ *
 * Aligned buffer pool (≙ gst_tensor_allocator)                       *
 * ------------------------------------------------------------------ */

struct NnsPool {
  std::mutex m;
  std::vector<void *> free_blocks;
  size_t block_size;
  size_t alignment;
  size_t outstanding = 0;
};

void *nns_pool_create (size_t block_size, size_t prealloc, size_t alignment)
{
  if (alignment == 0 || (alignment & (alignment - 1)))
    return nullptr; /* must be a power of two */
  auto *p = new NnsPool ();
  p->block_size = block_size;
  p->alignment = alignment < sizeof (void *) ? sizeof (void *) : alignment;
  for (size_t i = 0; i < prealloc; i++) {
    void *b = nullptr;
    if (posix_memalign (&b, p->alignment, block_size) == 0)
      p->free_blocks.push_back (b);
  }
  return p;
}

void *nns_pool_acquire (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  p->outstanding++;
  if (!p->free_blocks.empty ()) {
    void *b = p->free_blocks.back ();
    p->free_blocks.pop_back ();
    return b;
  }
  void *b = nullptr;
  if (posix_memalign (&b, p->alignment, p->block_size) != 0) {
    p->outstanding--;
    return nullptr;
  }
  return b;
}

/* 0 = ok, -1 = double release (ignored: the block stays usable once) */
int nns_pool_release (void *h, void *block)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  for (void *b : p->free_blocks)
    if (b == block)
      return -1;
  if (p->outstanding > 0)
    p->outstanding--;
  p->free_blocks.push_back (block);
  return 0;
}

size_t nns_pool_block_size (void *h)
{
  return static_cast<NnsPool *> (h)->block_size;
}

size_t nns_pool_outstanding (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  return p->outstanding;
}

void nns_pool_destroy (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  for (void *b : p->free_blocks)
    free (b);
  delete p;
}

} /* extern "C" */
