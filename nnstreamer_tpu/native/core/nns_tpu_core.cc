/**
 * nns_tpu_core — native data-plane primitives for the pipeline runtime.
 *
 * Reference analog: the reference's core runtime is C (GStreamer queues,
 * streaming threads, GstAllocator — SURVEY §2.1/§L0); this library is the
 * TPU build's native equivalent under the Python orchestration layer:
 *
 *  - opaque-pointer mailbox (bounded MPMC queue, condvar blocking): element
 *    mailboxes block in native code with the GIL released (ctypes foreign
 *    calls drop it), so handoff wakeups are immediate instead of poll-loop
 *    latency, and producers get real backpressure.
 *  - aligned buffer pool (≙ gst_tensor_allocator, tensor_allocator.c:128):
 *    recycled aligned blocks for receive/scratch buffers.
 *
 * Pure C ABI over C++17 internals; loaded via ctypes (no pybind11 in this
 * image).  The library never touches Python objects — the Python wrapper
 * owns all refcounting.
 */

#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

/* ------------------------------------------------------------------ *
 * Opaque-pointer mailbox                                             *
 * ------------------------------------------------------------------ */

struct NnsQueue {
  std::mutex m;
  std::condition_variable not_full;
  std::condition_variable not_empty;
  std::condition_variable idle; /* destroy waits for parked waiters */
  std::deque<void *> items;
  size_t capacity;
  int waiters = 0;
  bool closed = false;
};

namespace {
struct WaiterGuard {
  NnsQueue *q; /* lock must be held at construction and destruction */
  explicit WaiterGuard (NnsQueue *q_) : q (q_) { q->waiters++; }
  ~WaiterGuard ()
  {
    if (--q->waiters == 0)
      q->idle.notify_all ();
  }
};
} // namespace

void *nns_oq_create (size_t capacity)
{
  auto *q = new NnsQueue ();
  q->capacity = capacity ? capacity : SIZE_MAX;
  return q;
}

/* 0 = ok, -1 = timeout, -2 = closed.  timeout_s < 0 blocks forever. */
int nns_oq_push (void *h, void *obj, double timeout_s)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || q->items.size () < q->capacity; };
  if (timeout_s < 0) {
    q->not_full.wait (lk, ready);
  } else if (!q->not_full.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->closed)
    return -2;
  q->items.push_back (obj);
  q->not_empty.notify_one ();
  return 0;
}

/* 0 = ok (obj in *out), -1 = timeout, -2 = closed-and-drained. */
int nns_oq_pop (void *h, double timeout_s, void **out)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || !q->items.empty (); };
  if (timeout_s < 0) {
    q->not_empty.wait (lk, ready);
  } else if (!q->not_empty.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->items.empty ())
    return -2; /* closed */
  *out = q->items.front ();
  q->items.pop_front ();
  q->not_full.notify_one ();
  return 0;
}

/* Bulk pop: wait (like nns_oq_pop) for the FIRST item, then drain up to
 * max_n without further waiting — one lock/wakeup cycle per micro-batch
 * instead of one per frame.  Returns the item count (>0), -1 = timeout,
 * -2 = closed-and-drained. */
int nns_oq_pop_n (void *h, size_t max_n, double timeout_s, void **out)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || !q->items.empty (); };
  if (timeout_s < 0) {
    q->not_empty.wait (lk, ready);
  } else if (!q->not_empty.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->items.empty ())
    return -2; /* closed */
  size_t n = 0;
  while (n < max_n && !q->items.empty ()) {
    out[n++] = q->items.front ();
    q->items.pop_front ();
  }
  if (n > 1)
    q->not_full.notify_all (); /* several slots freed at once */
  else
    q->not_full.notify_one ();
  return (int) n;
}

/* Bulk push (block handoff): wait (like nns_oq_push) for space for the
 * FIRST item, then append as many of the rest as fit without further
 * waiting — one lock/wakeup cycle per run of outputs instead of one per
 * frame.  Returns the count consumed (>0), -1 = timeout, -2 = closed. */
int nns_oq_push_n (void *h, void **objs, size_t n_objs, double timeout_s)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::unique_lock<std::mutex> lk (q->m);
  WaiterGuard wg (q);
  auto ready = [q] { return q->closed || q->items.size () < q->capacity; };
  if (timeout_s < 0) {
    q->not_full.wait (lk, ready);
  } else if (!q->not_full.wait_for (
                 lk, std::chrono::duration<double> (timeout_s), ready)) {
    return -1;
  }
  if (q->closed)
    return -2;
  size_t n = 0;
  while (n < n_objs && q->items.size () < q->capacity)
    q->items.push_back (objs[n++]);
  if (n > 1)
    q->not_empty.notify_all (); /* several items landed at once */
  else
    q->not_empty.notify_one ();
  return (int) n;
}

size_t nns_oq_size (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  std::lock_guard<std::mutex> lk (q->m);
  return q->items.size ();
}

/* wake all waiters; pending items remain poppable until drained */
void nns_oq_close (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  {
    std::lock_guard<std::mutex> lk (q->m);
    q->closed = true;
  }
  q->not_full.notify_all ();
  q->not_empty.notify_all ();
}

/* caller must have drained (or accept leaking the queued pointers' refs —
 * the Python wrapper drains first).  Blocks until every parked waiter has
 * left push/pop so the mutex/condvars are never freed under a waiter. */
void nns_oq_destroy (void *h)
{
  auto *q = static_cast<NnsQueue *> (h);
  {
    std::unique_lock<std::mutex> lk (q->m);
    q->closed = true;
    q->not_full.notify_all ();
    q->not_empty.notify_all ();
    q->idle.wait (lk, [q] { return q->waiters == 0; });
  }
  delete q;
}

/* ------------------------------------------------------------------ *
 * Aligned buffer pool (≙ gst_tensor_allocator)                       *
 * ------------------------------------------------------------------ */

struct NnsPool {
  std::mutex m;
  std::vector<void *> free_blocks;
  size_t block_size;
  size_t alignment;
  size_t outstanding = 0;
};

void *nns_pool_create (size_t block_size, size_t prealloc, size_t alignment)
{
  if (alignment == 0 || (alignment & (alignment - 1)))
    return nullptr; /* must be a power of two */
  auto *p = new NnsPool ();
  p->block_size = block_size;
  p->alignment = alignment < sizeof (void *) ? sizeof (void *) : alignment;
  for (size_t i = 0; i < prealloc; i++) {
    void *b = nullptr;
    if (posix_memalign (&b, p->alignment, block_size) == 0)
      p->free_blocks.push_back (b);
  }
  return p;
}

void *nns_pool_acquire (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  p->outstanding++;
  if (!p->free_blocks.empty ()) {
    void *b = p->free_blocks.back ();
    p->free_blocks.pop_back ();
    return b;
  }
  void *b = nullptr;
  if (posix_memalign (&b, p->alignment, p->block_size) != 0) {
    p->outstanding--;
    return nullptr;
  }
  return b;
}

/* 0 = ok, -1 = double release (ignored: the block stays usable once) */
int nns_pool_release (void *h, void *block)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  for (void *b : p->free_blocks)
    if (b == block)
      return -1;
  if (p->outstanding > 0)
    p->outstanding--;
  p->free_blocks.push_back (block);
  return 0;
}

size_t nns_pool_block_size (void *h)
{
  return static_cast<NnsPool *> (h)->block_size;
}

size_t nns_pool_outstanding (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  std::lock_guard<std::mutex> lk (p->m);
  return p->outstanding;
}

void nns_pool_destroy (void *h)
{
  auto *p = static_cast<NnsPool *> (h);
  for (void *b : p->free_blocks)
    free (b);
  delete p;
}

/* ------------------------------------------------------------------ *
 * mmap sample reader — the datarepo data loader                       *
 *                                                                     *
 * Reference analog: gstdatareposrc.c reads training samples in C      *
 * (read()/seek per sample).  Here: the whole repo file is mapped      *
 * once; a sample read is one memcpy out of the page cache with the    *
 * GIL released (ctypes call), and nns_reader_prefetch() madvises the  *
 * next sample so shuffled epochs stream at page-cache speed.          *
 * ------------------------------------------------------------------ */

struct NnsReader {
  uint8_t *base = nullptr;
  uint64_t file_size = 0;
  uint64_t sample_size = 0;
  int fd = -1;
};

void *nns_reader_open (const char *path, uint64_t sample_size)
{
  if (sample_size == 0)
    return nullptr;
  int fd = ::open (path, O_RDONLY);
  if (fd < 0)
    return nullptr;
  struct stat st;
  if (fstat (fd, &st) != 0 || st.st_size <= 0) {
    ::close (fd);
    return nullptr;
  }
  void *base = mmap (nullptr, (size_t) st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (base == MAP_FAILED) {
    ::close (fd);
    return nullptr;
  }
  madvise (base, (size_t) st.st_size, MADV_WILLNEED);
  auto *r = new NnsReader ();
  r->base = static_cast<uint8_t *> (base);
  r->file_size = (uint64_t) st.st_size;
  r->sample_size = sample_size;
  r->fd = fd;
  return r;
}

uint64_t nns_reader_total (void *h)
{
  auto *r = static_cast<NnsReader *> (h);
  return r->file_size / r->sample_size;
}

/* copy sample `index` into out (caller allocates sample_size bytes);
 * 0 = ok, -1 = out of range.  Bounds-check BEFORE the multiply: a huge
 * index (e.g. (uint64_t)-1 from a negative Python int) would overflow
 * `index * sample_size` and wrap past the `off + size > file_size` test. */
int nns_reader_read (void *h, uint64_t index, uint8_t *out)
{
  auto *r = static_cast<NnsReader *> (h);
  if (index >= r->file_size / r->sample_size)
    return -1;
  memcpy (out, r->base + index * r->sample_size, r->sample_size);
  return 0;
}

void nns_reader_prefetch (void *h, uint64_t index)
{
  auto *r = static_cast<NnsReader *> (h);
  if (index >= r->file_size / r->sample_size)
    return;
  madvise (r->base + index * r->sample_size, r->sample_size, MADV_WILLNEED);
}

void nns_reader_close (void *h)
{
  auto *r = static_cast<NnsReader *> (h);
  if (r->base)
    munmap (r->base, r->file_size);
  if (r->fd >= 0)
    ::close (r->fd);
  delete r;
}

} /* extern "C" */
