"""Model zoo: flax implementations of the model families the reference's
example pipelines run (MobileNet-v2, SSD-MobileNet, YOLOv5, PoseNet, MNIST
CNN, plus a long-context transformer for the parallel/ subsystem).

``build(name, custom_props)`` returns ``(fn, params, in_spec, out_spec)``
with ``fn(params, inputs: list) -> list`` jit-traceable — the contract the
jax-xla backend consumes (``custom=arch:<name>``).
"""

from __future__ import annotations

from importlib import import_module
from typing import Dict, Optional, Tuple

_ZOO = {
    "mobilenet_v2": "nnstreamer_tpu.models.mobilenet_v2",
    "ssd_mobilenet_v2": "nnstreamer_tpu.models.ssd_mobilenet",
    "yolov5s": "nnstreamer_tpu.models.yolov5",
    "posenet": "nnstreamer_tpu.models.posenet",
    "mnist_cnn": "nnstreamer_tpu.models.mnist_cnn",
    "transformer": "nnstreamer_tpu.models.transformer",
    "deeplab": "nnstreamer_tpu.models.deeplab",
    "kws_cnn": "nnstreamer_tpu.models.kws_cnn",
    "vit": "nnstreamer_tpu.models.vit",
}


def available() -> Tuple[str, ...]:
    """Families whose modules are actually present."""
    import importlib.util

    return tuple(
        name for name, mod in _ZOO.items()
        if importlib.util.find_spec(mod) is not None
    )


def build(name: str, custom_props: Optional[Dict[str, str]] = None):
    if name not in _ZOO:
        raise KeyError(f"unknown model family {name!r}; available: {sorted(_ZOO)}")
    try:
        mod = import_module(_ZOO[name])
    except ModuleNotFoundError as e:
        raise KeyError(f"model family {name!r} is not built yet: {e}") from None
    props = dict(custom_props or {})
    if "dtype" not in props:
        # hw-probed default: bfloat16 on accelerators (MXU-native),
        # float32 on host CPU (core/hw.py, ≙ reference hw_accel.c probe)
        from ..core import hw

        props["dtype"] = hw.preferred_dtype()
    return mod.build(props)
