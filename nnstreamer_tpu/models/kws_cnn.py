"""Keyword-spotting CNN on log-mel features — the audio model family.

Pairs with the audio ingest path (``audiofilesrc -> tensor_converter``):
raw PCM frames in, keyword class logits out.  The reference exercises
audio through generic tensor pipelines (audio/x-raw converter framing,
``gsttensor_converter.c`` audio chain); this family gives the framework a
native speech workload, TPU-first:

* the WHOLE front-end (pre-emphasis, framing, Hann window, |STFT| via
  matmul against DFT bases, mel filterbank, log) runs INSIDE the jitted
  program — matmuls on the MXU, zero host preprocams;
* conv stack over the (frames, mels) "spectrogram image".

fn(params, [pcm_i16 (samples, channels)]) -> [logits (classes,)]
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init


def _mel_matrix(n_mels: int, n_fft: int, rate: int) -> np.ndarray:
    """Triangular mel filterbank (HTK mel scale), (n_fft//2+1, n_mels)."""
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    n_bins = n_fft // 2 + 1
    fft_freqs = np.linspace(0, rate / 2, n_bins)
    mel_pts = mel_to_hz(np.linspace(
        hz_to_mel(20.0), hz_to_mel(rate / 2), n_mels + 2
    ))
    weights = np.zeros((n_bins, n_mels), np.float32)
    for i in range(n_mels):
        lo, ctr, hi = mel_pts[i], mel_pts[i + 1], mel_pts[i + 2]
        up = (fft_freqs - lo) / max(ctr - lo, 1e-6)
        down = (hi - fft_freqs) / max(hi - ctr, 1e-6)
        weights[:, i] = np.maximum(0.0, np.minimum(up, down))
    return weights


class KwsCNN(nn.Module):
    num_classes: int = 12  # Speech-Commands style: 10 words + silence/unknown
    rate: int = 16000
    n_fft: int = 400       # 25 ms @ 16 kHz
    hop: int = 160         # 10 ms
    n_mels: int = 40
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, pcm):
        # pcm (N, samples) float in [-1, 1]
        n = pcm.shape[-1]
        frames = 1 + (n - self.n_fft) // self.hop
        idx = (
            np.arange(self.n_fft)[None, :]
            + self.hop * np.arange(frames)[:, None]
        )
        x = pcm[..., idx]  # (N, frames, n_fft) — one gather, static shapes
        window = jnp.asarray(np.hanning(self.n_fft).astype(np.float32))
        x = x.astype(jnp.float32) * window
        # |DFT| as two matmuls against fixed cos/sin bases: MXU-native STFT
        k = np.arange(self.n_fft // 2 + 1)[:, None] * np.arange(self.n_fft)[None, :]
        ang = 2.0 * np.pi * k / self.n_fft
        cos_b = jnp.asarray(np.cos(ang).T.astype(np.float32))
        sin_b = jnp.asarray(np.sin(ang).T.astype(np.float32))
        re, im = x @ cos_b, x @ sin_b
        power = re * re + im * im  # (N, frames, bins)
        mel = power @ jnp.asarray(_mel_matrix(self.n_mels, self.n_fft, self.rate))
        feats = jnp.log1p(mel).astype(self.dtype)[..., None]  # (N, F, M, 1)
        h = nn.Conv(32, (3, 3), strides=2, dtype=self.dtype)(feats)
        h = nn.relu(h)
        h = nn.Conv(64, (3, 3), strides=2, dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(64, (3, 3), strides=2, dtype=self.dtype)(h)
        h = nn.relu(h)
        h = jnp.mean(h, axis=(-3, -2))
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            h.astype(jnp.float32)
        )


def build(custom_props=None):
    """Zoo entry: fn(params, [pcm (samples, ch) i16|f32]) -> [logits]."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[props.get("dtype", "bfloat16")]
    rate = int(props.get("rate", "16000"))
    samples = int(props.get("samples", "16000"))  # 1 s clip
    channels = int(props.get("channels", "1"))
    classes = int(props.get("classes", "12"))
    model = KwsCNN(num_classes=classes, rate=rate, dtype=dtype)
    if samples < model.n_fft:
        raise ValueError(
            f"kws_cnn needs samples >= n_fft ({model.n_fft}); got {samples}"
        )
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, samples), np.float32),
    )

    def fn(p, inputs):
        x = inputs[0]
        single = x.ndim == 2  # (samples, channels) per-frame
        if single:
            x = x[None]
        # mono mixdown; int PCM normalizes to [-1, 1], float passes as-is
        is_int = np.issubdtype(np.dtype(x.dtype), np.integer)
        x = jnp.mean(x.astype(jnp.float32), axis=-1)
        if is_int:
            x = x / 32768.0
        out = model.apply(p, x)
        return [out[0] if single else out]

    in_spec = StreamSpec(
        (TensorSpec((samples, channels), np.int16, "pcm"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((classes,), np.float32, "logits"),), FORMAT_STATIC
    )
    return fn, params, in_spec, out_spec
