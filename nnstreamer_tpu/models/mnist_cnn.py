"""MNIST CNN — the reference training example's model.

Reference context: tensor_trainer's canonical pipeline trains an MNIST CNN
through NNTrainer (``Documentation`` examples; trainer ABI
``nnstreamer_plugin_api_trainer.h``).  Small LeNet-style flax CNN; bf16
compute with f32 logits.
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init


class MnistCNN(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # (N, 28, 28, 1) float or uint8
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) / 255.0
        else:
            x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
        return x


def build(custom_props=None):
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    classes = int(props.get("classes", "10"))
    model = MnistCNN(num_classes=classes, dtype=dtype)
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, 28, 28, 1), np.float32),
    )

    def fn(p, inputs: List[Any]) -> List[Any]:
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        out = model.apply(p, x)
        return [out[0] if single else out]

    in_spec = StreamSpec(
        (TensorSpec((28, 28, 1), np.float32, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((classes,), np.float32, "logits"),), FORMAT_STATIC
    )
    return fn, params, in_spec, out_spec
