"""MobileNet-v2 (flax) — the headline classification model.

The reference runs MobileNet-v2 through TFLite
(``tests/test_models/models/mobilenet_v2_1.0_224_quant.tflite``; BASELINE
north star: MobileNet-v2 labeling ≥1000 fps/chip).  This is a from-scratch
flax implementation of the architecture (Sandler et al. 2018), TPU-tuned:

* uint8 frames in; normalization to [-1, 1] happens INSIDE the jitted
  function so XLA fuses it with the first conv (no host-side preprocess).
* compute dtype configurable (bfloat16 default on TPU — MXU native).
* inference uses folded-constant batch stats (BatchNorm in
  use_running_average mode), so the whole network is one fused XLA program.

Output: 1001 logits (class 0 = background, TFLite-compatible labeling).
"""

from __future__ import annotations

from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init
from ._quant_flax import QuantConv

# (expansion t, channels c, repeats n, stride s) — standard v2 table
_CFG: Sequence[Tuple[int, int, int, int]] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class ConvBN(nn.Module):
    features: int
    kernel: Tuple[int, int] = (3, 3)
    strides: int = 1
    groups: int = 1
    act: bool = True
    dtype: Any = jnp.bfloat16
    quant: bool = False  # int8 MXU path (ops/quantize.py)

    @nn.compact
    def __call__(self, x):
        if self.quant:
            # name="Conv_0" keeps the param path (and RNG fold) identical
            # to nn.Conv: quantized and float builds share weights
            x = QuantConv(
                self.features,
                self.kernel,
                strides=self.strides,
                feature_group_count=self.groups,
                dtype=self.dtype,
                name="Conv_0",
            )(x)
        else:
            x = nn.Conv(
                self.features,
                self.kernel,
                strides=self.strides,
                padding="SAME",
                feature_group_count=self.groups,
                use_bias=False,
                dtype=self.dtype,
            )(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        if self.act:
            x = jnp.minimum(jnp.maximum(x, 0.0), 6.0)  # relu6
        return x


class InvertedResidual(nn.Module):
    features: int
    stride: int
    expand: int
    dtype: Any = jnp.bfloat16
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        c_in = x.shape[-1]
        h = x
        if self.expand != 1:
            h = ConvBN(
                c_in * self.expand, (1, 1), dtype=self.dtype, quant=self.quant
            )(h)
        h = ConvBN(
            c_in * self.expand if self.expand != 1 else c_in,
            (3, 3),
            strides=self.stride,
            groups=c_in * self.expand if self.expand != 1 else c_in,
            dtype=self.dtype,
            quant=self.quant,
        )(h)
        h = ConvBN(
            self.features, (1, 1), act=False, dtype=self.dtype,
            quant=self.quant,
        )(h)
        if self.stride == 1 and c_in == self.features:
            h = h + x
        return h


class MobileNetV2(nn.Module):
    num_classes: int = 1001
    width_mult: float = 1.0
    dtype: Any = jnp.bfloat16
    pallas_preprocess: bool = False
    quant: bool = False  # int8 conv stack (≙ reference's quant tflite)

    @nn.compact
    def __call__(self, x):
        # fused-in preprocess: uint8 [0,255] -> [-1, 1]; custom prop
        # pallas:1 swaps in the ops/ Pallas kernel (VMEM-tiled) on TPU
        if x.dtype == jnp.uint8:
            if self.pallas_preprocess:
                from ..ops import normalize_u8

                x = normalize_u8(x, dtype=self.dtype)
            else:
                x = x.astype(self.dtype) * (2.0 / 255.0) - 1.0
        else:
            x = x.astype(self.dtype)
        c = _make_divisible(32 * self.width_mult)
        x = ConvBN(c, (3, 3), strides=2, dtype=self.dtype, quant=self.quant)(x)
        for t, ch, n, s in _CFG:
            out_c = _make_divisible(ch * self.width_mult)
            for i in range(n):
                x = InvertedResidual(
                    out_c, s if i == 0 else 1, t, dtype=self.dtype,
                    quant=self.quant,
                )(x)
        last = _make_divisible(1280 * max(self.width_mult, 1.0))
        x = ConvBN(last, (1, 1), dtype=self.dtype, quant=self.quant)(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x.astype(jnp.float32))
        return x


def build(custom_props=None):
    """Zoo entry: returns (fn, params, in_spec, out_spec).

    fn(params, [images_u8 (N,224,224,3)]) -> [logits (N,1001)]
    """
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}[
        props.get("dtype", "bfloat16")
    ]
    size = int(props.get("size", "224"))
    num_classes = int(props.get("classes", "1001"))
    width = float(props.get("width", "1.0"))
    model = MobileNetV2(
        num_classes=num_classes,
        width_mult=width,
        dtype=dtype,
        pallas_preprocess=props.get("pallas", "0") in ("1", "true"),
        quant=props.get("quantize", "") == "int8",
    )
    variables = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )

    def fn(params, inputs: List[Any]) -> List[Any]:
        x = inputs[0]
        single = x.ndim == 3  # per-frame invoke: add/strip the batch dim
        if single:
            x = x[None]
        out = model.apply(params, x)
        return [out[0] if single else out]

    # stream-frame schemas (no batch dim; the filter element batches)
    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((num_classes,), np.float32, "logits"),), FORMAT_STATIC
    )
    return fn, variables, in_spec, out_spec
