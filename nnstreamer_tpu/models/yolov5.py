"""YOLOv5s (flax) — single-stage detector for the yolov5 decoder mode.

The reference decodes yolov5 exports with ``tensor_decoder
mode=bounding_boxes option1=yolov5`` expecting one tensor ``[N, 5+C]`` of
(cx, cy, w, h, objectness, class...) — normalized coordinates with
``option3`` scaled=0 (``tensordec-boundingbox.c`` yolov5 path).  This is a
from-scratch flax YOLOv5s-style network (CSP backbone, SPPF, PANet-lite
neck, 3-scale anchored detect head) whose grid/anchor decode runs INSIDE
the jitted program — one fused XLA executable emitting the final [N, 5+C]
tensor, TPU-style (no host post-processing before the decoder).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init

# (stride, anchors (w,h) in px @ 640) — standard yolov5 anchor table
_ANCHORS: Sequence[Tuple[int, Tuple[Tuple[float, float], ...]]] = (
    (8, ((10, 13), (16, 30), (33, 23))),
    (16, ((30, 61), (62, 45), (59, 119))),
    (32, ((116, 90), (156, 198), (373, 326))),
)


class ConvBnSiLU(nn.Module):
    features: int
    kernel: int = 1
    stride: int = 1
    dtype: Any = jnp.bfloat16
    quant: bool = False  # int8 MXU path (ops/quantize.py)

    @nn.compact
    def __call__(self, x):
        if self.quant:
            from ._quant_flax import QuantConv

            # name="Conv_0" keeps the param path (and RNG fold) identical
            # to nn.Conv: quantized and float builds share weights
            x = QuantConv(
                self.features, (self.kernel, self.kernel),
                strides=self.stride, dtype=self.dtype, name="Conv_0",
            )(x)
        else:
            x = nn.Conv(self.features, (self.kernel, self.kernel),
                        strides=self.stride, padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        return x * jax.nn.sigmoid(x)  # SiLU


class Bottleneck(nn.Module):
    features: int
    shortcut: bool = True
    dtype: Any = jnp.bfloat16
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        h = ConvBnSiLU(self.features, 1, dtype=self.dtype,
                       quant=self.quant)(x)
        h = ConvBnSiLU(self.features, 3, dtype=self.dtype,
                       quant=self.quant)(h)
        return x + h if self.shortcut and x.shape[-1] == self.features else h


class C3(nn.Module):
    features: int
    n: int = 1
    shortcut: bool = True
    dtype: Any = jnp.bfloat16
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        c = self.features // 2
        a = ConvBnSiLU(c, 1, dtype=self.dtype, quant=self.quant)(x)
        for _ in range(self.n):
            a = Bottleneck(c, self.shortcut, dtype=self.dtype,
                           quant=self.quant)(a)
        b = ConvBnSiLU(c, 1, dtype=self.dtype, quant=self.quant)(x)
        return ConvBnSiLU(self.features, 1, dtype=self.dtype,
                          quant=self.quant)(
            jnp.concatenate([a, b], -1)
        )


class SPPF(nn.Module):
    features: int
    dtype: Any = jnp.bfloat16
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        c = self.features // 2
        x = ConvBnSiLU(c, 1, dtype=self.dtype, quant=self.quant)(x)
        p1 = nn.max_pool(x, (5, 5), padding="SAME")
        p2 = nn.max_pool(p1, (5, 5), padding="SAME")
        p3 = nn.max_pool(p2, (5, 5), padding="SAME")
        return ConvBnSiLU(self.features, 1, dtype=self.dtype,
                          quant=self.quant)(
            jnp.concatenate([x, p1, p2, p3], -1)
        )


def _upsample2(x):
    B, H, W, C = x.shape
    return jax.image.resize(x, (B, H * 2, W * 2, C), "nearest")


class YOLOv5s(nn.Module):
    num_classes: int = 80
    size: int = 640
    dtype: Any = jnp.bfloat16
    # int8 MXU backbone/neck; the per-scale detect heads stay float32
    # (precision-sensitive box regression, negligible FLOPs)
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) / 255.0
        else:
            x = x.astype(self.dtype)
        d, q = self.dtype, self.quant
        # backbone (depth/width of the "s" variant)
        x = ConvBnSiLU(32, 6, 2, dtype=d, quant=q)(x)       # P1/2
        x = ConvBnSiLU(64, 3, 2, dtype=d, quant=q)(x)       # P2/4
        x = C3(64, 1, dtype=d, quant=q)(x)
        x = ConvBnSiLU(128, 3, 2, dtype=d, quant=q)(x)      # P3/8
        p3 = C3(128, 2, dtype=d, quant=q)(x)
        x = ConvBnSiLU(256, 3, 2, dtype=d, quant=q)(p3)     # P4/16
        p4 = C3(256, 3, dtype=d, quant=q)(x)
        x = ConvBnSiLU(512, 3, 2, dtype=d, quant=q)(p4)     # P5/32
        x = C3(512, 1, dtype=d, quant=q)(x)
        p5 = SPPF(512, dtype=d, quant=q)(x)
        # neck (FPN + PAN)
        h5 = ConvBnSiLU(256, 1, dtype=d, quant=q)(p5)
        h4 = C3(256, 1, shortcut=False, dtype=d, quant=q)(
            jnp.concatenate([_upsample2(h5), p4], -1))
        h4r = ConvBnSiLU(128, 1, dtype=d, quant=q)(h4)
        h3 = C3(128, 1, shortcut=False, dtype=d, quant=q)(
            jnp.concatenate([_upsample2(h4r), p3], -1))      # out P3
        h4o = C3(256, 1, shortcut=False, dtype=d, quant=q)(
            jnp.concatenate(
                [ConvBnSiLU(128, 3, 2, dtype=d, quant=q)(h3), h4r], -1))
        h5o = C3(512, 1, shortcut=False, dtype=d, quant=q)(
            jnp.concatenate(
                [ConvBnSiLU(256, 3, 2, dtype=d, quant=q)(h4o), h5], -1))

        # detect head: per scale, raw conv -> sigmoid -> grid/anchor decode
        outs = []
        no = 5 + self.num_classes
        for i, (feat, (stride, anchor_list)) in enumerate(
            zip((h3, h4o, h5o), _ANCHORS)
        ):
            na = len(anchor_list)
            raw = nn.Conv(na * no, (1, 1), dtype=jnp.float32,
                          name=f"detect{i}")(feat.astype(jnp.float32))
            B, H, W, _ = raw.shape
            raw = raw.reshape(B, H, W, na, no)
            y = jax.nn.sigmoid(raw)
            gy, gx = jnp.meshgrid(jnp.arange(H), jnp.arange(W), indexing="ij")
            grid = jnp.stack([gx, gy], -1).astype(jnp.float32)  # (H,W,2) x,y
            anc = jnp.asarray(anchor_list, jnp.float32)          # (na,2) w,h
            xy = (y[..., :2] * 2.0 - 0.5 + grid[:, :, None]) * stride
            wh = (y[..., 2:4] * 2.0) ** 2 * anc[None, None]
            box = jnp.concatenate([xy, wh], -1) / self.size  # normalized
            outs.append(
                jnp.concatenate([box, y[..., 4:]], -1).reshape(B, -1, no)
            )
        return jnp.concatenate(outs, 1)  # (B, N, 5+C)


def num_candidates(size: int) -> int:
    return sum(
        (size // s) * (size // s) * len(a) for s, a in _ANCHORS
    )


def build(custom_props=None):
    """Zoo entry: fn(params, [images_u8 (N,size,size,3)]) ->
    [pred (N, boxes, 5+C)] — feed ``tensor_decoder mode=bounding_boxes
    option1=yolov5``."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    size = int(props.get("size", "640"))
    if size % 32:
        raise ValueError("yolov5 input size must be a multiple of 32")
    classes = int(props.get("classes", "80"))
    with_nms = props.get("nms", "0") in ("1", "true")
    iou_thr = float(props.get("iou", "0.45"))
    nms_topk = int(props.get("nms_topk", "300"))
    model = YOLOv5s(
        num_classes=classes, size=size, dtype=dtype,
        quant=props.get("quantize", "") == "int8",
    )
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )

    def fn(params, inputs):
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        out = model.apply(params, x)
        if with_nms:
            # in-graph batched NMS (custom prop nms:1): suppressed
            # candidates get objectness 0, so the decoder's threshold
            # drops them — whole micro-batch in one device call.
            # Top-k pre-filter keeps the IoU matrix K x K (not N x N), and
            # class-offset boxes make suppression per-class (standard
            # yolov5 postprocess: different classes never overlap).
            from ..ops import batched_nms

            B, N = out.shape[0], out.shape[1]
            K = min(nms_topk, N)
            cxcy, wh = out[..., :2], out[..., 2:4]
            boxes = jnp.concatenate([cxcy - wh / 2, cxcy + wh / 2], -1)
            cls = jnp.argmax(out[..., 5:], -1)
            boxes = boxes + (cls.astype(boxes.dtype) * 2.0)[..., None]
            score = out[..., 4] * jnp.max(out[..., 5:], -1)
            topv, topi = jax.lax.top_k(score, K)
            boxes_k = jnp.take_along_axis(boxes, topi[..., None], 1)
            keep_k = batched_nms(boxes_k, topv, iou_thr=iou_thr)
            mask = jnp.zeros((B, N), bool).at[
                jnp.arange(B)[:, None], topi
            ].set(keep_k)
            out = out.at[..., 4].multiply(mask.astype(out.dtype))
        return [out[0] if single else out]

    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((num_candidates(size), 5 + classes), np.float32, "pred"),),
        FORMAT_STATIC,
    )
    return fn, params, in_spec, out_spec
