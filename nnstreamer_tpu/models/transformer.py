"""Decoder-only transformer LM with first-class mesh parallelism.

The long-context / distributed flagship for the parallel subsystem
(SURVEY §5.7-5.8 mark these "absent / net-new" in the reference): a GPT
style LM whose attention runs as ring attention when the sequence axis is
sharded (``sp``), with tensor-parallel params (``tp``) and data-parallel
batch (``dp``) — all via NamedSharding + GSPMD, collectives inserted by XLA
except the explicit ring ppermute.

Provides the zoo ``build`` (inference) and :func:`make_train_step` (the
sharded training step used by ``__graft_entry__.dryrun_multichip`` and the
trainer element).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init
from ..parallel.ring_attention import reference_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 512
    max_seq: int = 256
    dtype: Any = jnp.bfloat16
    # sequence-parallel attention strategy when the mesh has sp > 1:
    # auto (ulysses when heads divide sp, else ring) | ring | ulysses
    sp_strategy: str = "auto"
    # single-device attention kernel: xla (fused reference) | flash
    # (Pallas online-softmax kernel, ops/flash_attention.py)
    attn_impl: str = "xla"
    # int8 MXU dense layers (_quant_flax.QuantDense; quantize:int8 prop)
    quant: bool = False


class Block(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    seq_axis: str = "sp"
    decode: bool = False  # KV-cache single-token step (generation serving)
    # continuous batching (core/slots.py): the cache becomes SLOT-INDEXED
    # pages — per-slot write positions instead of one shared scalar, so
    # independent generation streams at different depths share one batch.
    # Each slot's pages are written through its own dynamic_update_slice
    # (a joining stream touches only its slot; a leaving stream's pages
    # are reusable without touching neighbors) and the causal mask is
    # per-slot, so the jitted step stays shape-stable as streams churn.
    slotted: bool = False

    def _dense(self, features, name):
        from ._quant_flax import dense_or_quant

        # same explicit name -> same param path/RNG fold either way
        return dense_or_quant(self.cfg.quant, features, self.cfg.dtype, name)

    @nn.compact
    def __call__(self, x, active=None):
        cfg = self.cfg
        B, T, D = x.shape
        H = cfg.n_heads
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln1")(x)
        qkv = self._dense(3 * D, "attn_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, T, H, D // H)
        v = v.reshape(B, T, H, D // H)
        if self.decode and self.slotted:
            # per-slot paged KV cache: index is a VECTOR (one write
            # position per slot).  Idle slots (active=0) keep writing
            # harmlessly into their frozen position but never advance —
            # the mask math stays identical for every occupied slot, so
            # a single occupant's row is bit-identical to the unslotted
            # path (row independence; pinned in tests).
            ck = self.variable(
                "cache", "key",
                lambda: jnp.zeros((B, cfg.max_seq, H, D // H), cfg.dtype),
            )
            cv = self.variable(
                "cache", "value",
                lambda: jnp.zeros((B, cfg.max_seq, H, D // H), cfg.dtype),
            )
            idx = self.variable(
                "cache", "index", lambda: jnp.zeros((B,), jnp.int32)
            )
            pos = idx.value  # (B,)

            # per-slot page write WITHOUT a scatter: vmapped
            # dynamic_update_slice lowers to lax.scatter, which XLA's CPU
            # backend executes orders of magnitude slower than the
            # equivalent dense select; one broadcast `where` per chunk
            # position (T is static) keeps the write a single vectorized
            # pass over the slot's pages
            def write(c, kk):
                for t in range(T):
                    hit = (
                        jnp.arange(cfg.max_seq)[None, :]
                        == (pos + t)[:, None]
                    )[..., None, None]  # (B, S, 1, 1)
                    c = jnp.where(hit, kk[:, t:t + 1], c)
                return c

            ck.value = write(ck.value, k)
            cv.value = write(cv.value, v)
            adv = T if active is None else T * active.astype(jnp.int32)
            idx.value = pos + adv
            # slot b, query i (global position pos[b]+i) sees cache
            # slots <= pos[b]+i
            mask = (
                jnp.arange(cfg.max_seq)[None, None, :]
                <= (pos[:, None] + jnp.arange(T)[None, :])[..., None]
            )  # (B, T, S)
            scores = jnp.einsum(
                "bthd,bshd->bhts", q.astype(jnp.float32),
                ck.value.astype(jnp.float32),
            ) / np.sqrt(D // H)
            scores = jnp.where(mask[:, None], scores, -1e30)
            attn = jnp.einsum(
                "bhts,bshd->bthd",
                jax.nn.softmax(scores, axis=-1),
                cv.value.astype(jnp.float32),
            ).astype(cfg.dtype)
        elif self.decode:
            # KV-cache attention over a static-shape ring of max_seq slots
            # (dynamic_update_slice keeps the generate loop one compiled
            # program — no growing shapes).  T == 1 is the per-token decode
            # step; T > 1 is chunked PREFILL: the whole prompt attends
            # causally in one pass while filling the cache, so prefill
            # costs one forward instead of T sequential steps.
            ck = self.variable(
                "cache", "key",
                lambda: jnp.zeros((B, cfg.max_seq, H, D // H), cfg.dtype),
            )
            cv = self.variable(
                "cache", "value",
                lambda: jnp.zeros((B, cfg.max_seq, H, D // H), cfg.dtype),
            )
            idx = self.variable(
                "cache", "index", lambda: jnp.zeros((), jnp.int32)
            )
            pos = idx.value
            ck.value = jax.lax.dynamic_update_slice(
                ck.value, k, (0, pos, 0, 0)
            )
            cv.value = jax.lax.dynamic_update_slice(
                cv.value, v, (0, pos, 0, 0)
            )
            idx.value = pos + T
            # query i (global position pos+i) sees cache slots <= pos+i
            mask = (
                jnp.arange(cfg.max_seq)[None, :]
                <= (pos + jnp.arange(T))[:, None]
            )  # (T, S)
            scores = jnp.einsum(
                "bthd,bshd->bhts", q.astype(jnp.float32),
                ck.value.astype(jnp.float32),
            ) / np.sqrt(D // H)
            scores = jnp.where(mask[None, None], scores, -1e30)
            attn = jnp.einsum(
                "bhts,bshd->bthd",
                jax.nn.softmax(scores, axis=-1),
                cv.value.astype(jnp.float32),
            ).astype(cfg.dtype)
        elif self.mesh is not None and self.mesh.shape.get(self.seq_axis, 1) > 1:
            from ..parallel.ulysses import sequence_attention

            attn = sequence_attention(
                q, k, v, self.mesh, seq_axis=self.seq_axis, causal=True,
                strategy=cfg.sp_strategy,
            )
        elif cfg.attn_impl == "flash":
            from ..ops.flash_attention import flash_attention_grad

            # differentiable wrapper: kernel forward, recompute backward
            attn = flash_attention_grad(q, k, v, True)
        else:
            attn = reference_attention(q, k, v, causal=True)
        attn = attn.reshape(B, T, D)
        x = x + self._dense(D, "attn_out")(attn)
        h = nn.LayerNorm(dtype=cfg.dtype, name="ln2")(x)
        h = self._dense(cfg.d_ff, "mlp_up")(h)
        h = jax.nn.gelu(h)
        x = x + self._dense(D, "mlp_down")(h)
        return x


class TransformerLM(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[Mesh] = None
    seq_axis: str = "sp"
    decode: bool = False
    slotted: bool = False  # per-slot cache positions (continuous batching)

    @nn.compact
    def __call__(self, tokens, active=None):  # (B, T) int32
        cfg = self.cfg
        x = nn.Embed(cfg.vocab, cfg.d_model, dtype=cfg.dtype, name="embed")(tokens)
        B, T = tokens.shape
        if self.decode and self.slotted:
            # per-slot position counter: each stream advances its own
            # step; idle slots (active=0) stay frozen
            step = self.variable(
                "cache", "step", lambda: jnp.zeros((B,), jnp.int32)
            )
            positions = step.value[:, None] + jnp.arange(T)[None, :]
            adv = T if active is None else T * active.astype(jnp.int32)
            step.value = step.value + adv
        elif self.decode:
            step = self.variable(
                "cache", "step", lambda: jnp.zeros((), jnp.int32)
            )
            positions = step.value + jnp.arange(T)[None, :]
            step.value = step.value + T
        else:
            positions = jnp.arange(T)[None, :]
        pos = nn.Embed(cfg.max_seq, cfg.d_model, dtype=cfg.dtype, name="pos_embed")(
            positions
        )
        x = x + pos
        for i in range(cfg.n_layers):
            x = Block(
                cfg, self.mesh, self.seq_axis, decode=self.decode,
                slotted=self.slotted, name=f"block{i}",
            )(x, active)
        x = nn.LayerNorm(dtype=cfg.dtype, name="ln_f")(x)
        logits = nn.Dense(cfg.vocab, use_bias=False, dtype=jnp.float32, name="lm_head")(
            x.astype(jnp.float32)
        )
        return logits


def _cfg_from_props(props: Dict[str, str]) -> TransformerConfig:
    dt = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    return TransformerConfig(
        vocab=int(props.get("vocab", "256")),
        d_model=int(props.get("d_model", "128")),
        n_heads=int(props.get("heads", "4")),
        n_layers=int(props.get("layers", "2")),
        d_ff=int(props.get("d_ff", "512")),
        max_seq=int(props.get("seq", "256")),
        dtype=dt,
        sp_strategy=props.get("sp_strategy", "auto"),
        attn_impl=props.get("attn", "xla"),
        quant=props.get("quantize", "") == "int8",
    )


def make_generate(
    cfg: TransformerConfig,
    max_new: int,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
):
    """KV-cache generation: ``gen(params, prompt (B,Tp)) ->
    (B, Tp+max_new)``.

    ``temperature=0`` (default) is greedy argmax decoding;
    ``temperature>0`` samples from softmax(logits/temperature),
    optionally truncated to the ``top_k`` highest-probability tokens —
    deterministic for a given ``seed`` (the key is folded per step and
    per batch row).

    Expressed ON TOP of :func:`make_stream_generate`'s halves — chunked
    PREFILL (one causal pass fills the K/V cache) + ONE decode_chunk
    scan over the remaining tokens — so the one-shot and streaming paths
    share a single implementation and stay bit-equal by construction.
    The backend jit-compiles one XLA program per (B, Tp) bucket; no
    per-token Python dispatch, no growing shapes.  The serving analog of
    the reference's recurrence emulation (``tests/nnstreamer_repo_lstm``
    loops frames through tensor_repo); here the loop lives inside the
    compiled program.
    """
    prefill, decode_chunk = make_stream_generate(
        cfg, temperature=temperature, top_k=top_k, seed=seed
    )

    def gen(params, prompt):  # (B, Tp) int32
        B, Tp = prompt.shape
        if Tp + max_new > cfg.max_seq:
            raise ValueError(
                f"prompt {Tp} + generate {max_new} exceeds max_seq "
                f"{cfg.max_seq}"
            )
        cache, first = prefill(params, prompt)
        if max_new <= 1:
            generated = first[:, None]
        else:
            _, _, rest = decode_chunk(params, cache, first, 1, max_new - 1)
            generated = jnp.concatenate([first[:, None], rest], axis=1)
        return jnp.concatenate([prompt, generated], axis=1)

    return gen


def _make_pick(temperature: float, top_k: int):
    """The ONE sampling rule every generation path shares (one-shot,
    streaming, slotted): greedy argmax at ``temperature<=0``, else
    softmax(logits/temperature) truncated to ``top_k``.  Factored out so
    the slotted per-slot picker provably applies the same math per row."""

    def pick(logits, key):  # (B, V) -> (B,)
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        scaled = logits.astype(jnp.float32) / temperature
        if top_k > 0:
            kth = jax.lax.top_k(scaled, min(top_k, scaled.shape[-1]))[0][
                :, -1:
            ]
            scaled = jnp.where(scaled >= kth, scaled, -1e30)
        return jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    return pick


def make_stream_generate(
    cfg: TransformerConfig,
    temperature: float = 0.0,
    top_k: int = 0,
    seed: int = 0,
):
    """Chunked KV-cache decoding for STREAMING serving: unlike
    :func:`make_generate` (whole completion in one traced program), this
    returns two jittable halves whose cache pytree is carried BETWEEN
    calls by the caller, so tokens can leave the pipeline while later
    chunks are still decoding:

    * ``prefill(params, prompt (B,Tp)) -> (cache, first_tok (B,))`` —
      one causal pass fills the cache and picks token 1;
    * ``decode_chunk(params, cache, tok, t0, n) -> (cache, last_tok,
      toks (B, n))`` — n more tokens via one ``lax.scan`` (compile
      buckets: one per distinct n; callers use a fixed chunk + one tail).

    ``elements/generator.py`` streams these through a pipeline.  Sampling
    semantics (greedy / temperature / top-k, per-step key folding) are
    IDENTICAL to make_generate — the streamed token sequence is
    bit-equal to the one-shot path for the same seed.
    """
    model_dec = TransformerLM(cfg, decode=True)
    pick = _make_pick(temperature, top_k)
    key0 = jax.random.PRNGKey(seed)

    def prefill(params, prompt):
        B, Tp = prompt.shape
        cache_shapes = jax.eval_shape(
            lambda: model_dec.init(
                jax.random.PRNGKey(0), jnp.zeros((B, 1), jnp.int32)
            )["cache"]
        )
        cache0 = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
        )
        logits_p, upd = model_dec.apply(
            {"params": params["params"], "cache": cache0},
            prompt, mutable=["cache"],
        )
        return upd["cache"], pick(logits_p[:, -1, :], key0)

    def decode_chunk(params, cache, tok, t0, n):
        """n is static per compile bucket; t0 is traced (key folding)."""

        def step(carry, i):
            cache, tok = carry
            logits, upd = model_dec.apply(
                {"params": params["params"], "cache": cache},
                tok[:, None], mutable=["cache"],
            )
            nxt = pick(logits[:, -1, :], jax.random.fold_in(key0, t0 + i))
            return (upd["cache"], nxt), nxt

        (cache, tok), toks = jax.lax.scan(
            step, (cache, tok), jnp.arange(n)
        )
        return cache, tok, jnp.moveaxis(toks, 0, 1)  # (B, n)

    return prefill, decode_chunk


def build_stream(props: Dict[str, str]):
    """Factory for the streaming-generation element: same ``custom``
    dialect (and seed semantics: ``seed`` = params, ``gen_seed`` =
    sampling) as the zoo transformer, so the streamed tokens are
    bit-equal to ``generate:<N>`` one-shot serving.  Returns
    (prefill, decode_chunk, params, max_seq)."""
    cfg = _cfg_from_props(props)
    params = host_init(
        TransformerLM(cfg).init,
        int(props.get("seed", "0")),
        np.zeros((1, min(8, cfg.max_seq)), np.int32),
    )
    prefill, decode_chunk = make_stream_generate(
        cfg,
        temperature=float(props.get("temperature", "0")),
        top_k=int(props.get("top_k", "0")),
        seed=int(props.get("gen_seed", "0")),
    )
    return prefill, decode_chunk, params, cfg.max_seq


class SlotModel:
    """The jittable halves of the SLOTTED decode path (continuous
    batching, ``core/slots.py``): a fixed-width slot batch whose cache
    pytree is slot-indexed pages with PER-SLOT positions, so independent
    generation streams join/leave at token boundaries without retracing.

    Sampling semantics are IDENTICAL to :func:`make_stream_generate`:
    token 1 is picked with the raw gen_seed key, token j>=1 with
    ``fold_in(key0, j)`` — per slot, via a vmapped per-row pick (vmap of
    a key-batched draw is bit-equal to the per-row loop), so a single
    occupant's token stream is bit-identical to the seed ``generate:<N>``
    one-shot path and to the unslotted streaming path.

    * ``init_cache()`` — zeroed (slots, max_seq, ...) page pytree;
    * ``reset_slot(cache, slot)`` — zero ONE slot's pages + positions (a
      join touches only its own slot; jitted once, slot is traced);
    * ``prefill_chunk(params, cache, toks (1,n), slot)`` — slice the
      slot's pages to a B=1 view, run one causal chunk (the chunked
      prefill that interleaves with decode), scatter back; returns
      ``(cache, last_logits (1,V))``.  One compile bucket per distinct
      n — callers bound them (core/slots.py LRU);
    * ``pick_first(logits (1,V))`` — token 1 (same op as the unslotted
      prefill pick);
    * ``decode_fn(k)(params, cache, tok (S,), gen (S,), active (S,))`` —
      ``k`` tokens for every active slot in ONE ``lax.scan`` dispatch
      (the same per-chunk amortization the unslotted path gets; callers
      pick ``k = min(chunk, min remaining)`` so streams complete exactly
      at scan boundaries).  Compiled once per (slot width, k) — the
      idle-slot mask keeps each bucket shape-stable as streams churn.
      The cache argument is DONATED off-CPU (the engine's cache is
      caller-private — PR-6 donation discipline; XLA ignores donation on
      CPU and warns, so it is gated exactly like
      ``backends/jax_xla._donation_ok``).
    """

    def __init__(self, cfg: TransformerConfig, slots: int,
                 temperature: float = 0.0, top_k: int = 0, seed: int = 0,
                 donate: Optional[bool] = None,
                 mesh: Optional[Mesh] = None):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.slots = int(slots)
        self._model = TransformerLM(cfg, decode=True, slotted=True)
        self._pick = _make_pick(temperature, top_k)
        self._temperature = temperature
        self._key0 = jax.random.PRNGKey(seed)
        # mesh-sharded decode (continuous batching past one chip): the
        # per-slot KV pages shard on HEADS along tp — pages are
        # (slots, max_seq, H, D/H), so dim 2 scatters and every device
        # holds all slots' pages for its head shard; the slot batch
        # itself stays replicated (the engine's tok/gen/active vectors
        # are tiny).  GSPMD propagates the placements through the jitted
        # step, so the shape-stable bucket contract is unchanged.
        self.mesh = mesh
        self._page_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            tp = mesh.shape.get("tp", 1)

            def page_spec(shape):
                # shard the heads dim when it exists and divides; the
                # per-slot index/step vectors replicate
                if len(shape) >= 3 and shape[2] % tp == 0 and tp > 1:
                    return NamedSharding(mesh, P(None, None, "tp"))
                return NamedSharding(mesh, P())

            self._page_sharding = page_spec
        if donate is None:
            donate = jax.default_backend() != "cpu"
        self._donate = (1,) if donate else ()
        #: compile counters — the shape-stability contract is observable
        #: (tests pin decode_compiles staying at the bucket count across
        #: join/leave churn)
        self.decode_compiles = 0
        self.prefill_compiles = 0
        self.reset_slot = jax.jit(self._reset_slot)
        self.pick_first = jax.jit(self._pick_first)

    def shard_params(self, params):
        """Place a host param pytree for this model's mesh (tp rules;
        fully staged before return) — identity when unsharded."""
        if self.mesh is None:
            return params
        from ..parallel.sharding import shard_params, transformer_rules

        params = shard_params(params, self.mesh, transformer_rules())
        jax.block_until_ready(params)
        return params

    # -- cache lifecycle ----------------------------------------------------
    def init_cache(self):
        shapes = jax.eval_shape(
            lambda: self._model.init(
                jax.random.PRNGKey(0),
                jnp.zeros((self.slots, 1), jnp.int32),
            )["cache"]
        )
        if self._page_sharding is not None:
            page = self._page_sharding
            return jax.tree.map(
                lambda s: jax.device_put(
                    jnp.zeros(s.shape, s.dtype), page(s.shape)),
                shapes,
            )
        return jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), shapes
        )

    @staticmethod
    def _row_start(c, slot):
        return (slot,) + (0,) * (c.ndim - 1)

    def _reset_slot(self, cache, slot):
        def zero_row(c):
            row = jnp.zeros((1,) + c.shape[1:], c.dtype)
            return jax.lax.dynamic_update_slice(
                c, row, self._row_start(c, slot))

        return jax.tree.map(zero_row, cache)

    # -- shared-prefix page export / attach ---------------------------------
    # (core/slots.py PrefixCache): a slot's low KV pages for positions
    # [start, stop) are immutable once prefill has passed them — prefill
    # and decode only ever write FORWARD of the per-slot index — so they
    # can be published for reuse by later streams sharing the prefix.
    def export_prefix(self, cache, slot: int, start: int, stop: int):
        """COPY one slot's KV pages for positions ``[start, stop)``.

        The result is a fresh pytree (slice outputs are new buffers, and
        per-slot position counters are replaced by a placeholder), so a
        later donated prefill/decode step consuming the source cache can
        never invalidate a published entry.  Opaque to the engine —
        only :meth:`attach_prefix` interprets it."""
        n = int(stop) - int(start)

        def cut(c):
            if c.ndim < 2:
                # per-slot write positions: recomputed (= n) on attach
                return jnp.zeros((1,), c.dtype)
            return jax.lax.dynamic_slice(
                c, (slot, int(start)) + (0,) * (c.ndim - 2),
                (1, n) + tuple(c.shape[2:]))

        return jax.tree.map(cut, cache)

    def attach_prefix(self, cache, slot: int, pages_list, n: int):
        """Write published prefix pages (ordered per-grain chunks
        covering ``[0, n)``) into one freshly-reset slot and set its
        write position to ``n``.

        Bit-exactness by construction: the pages are the verbatim
        buffers a cold prefill produced at the same chunk boundaries, so
        the slot's state (pages ``[0, n)`` + zeros above + position
        ``n``) is indistinguishable from a cold run paused at
        ``prefill_pos == n`` — every subsequent prefill/decode program
        is the same XLA program on the same inputs."""

        def cat(*ps):
            if ps[0].ndim < 2:
                return ps[0]
            return ps[0] if len(ps) == 1 else jnp.concatenate(ps, axis=1)

        pages = jax.tree.map(cat, *pages_list)

        def put(c, p):
            if c.ndim < 2:
                return jax.lax.dynamic_update_slice(
                    c, jnp.full((1,), n, c.dtype), (slot,))
            return jax.lax.dynamic_update_slice(
                c, p.astype(c.dtype), (slot, 0) + (0,) * (c.ndim - 2))

        return jax.tree.map(put, cache, pages)

    # -- prefill (chunked, one slot at a time) ------------------------------
    def _prefill_chunk(self, params, cache, toks, slot):
        sl = jax.tree.map(
            lambda c: jax.lax.dynamic_slice(
                c, self._row_start(c, slot), (1,) + c.shape[1:]),
            cache,
        )
        logits, upd = self._model.apply(
            {"params": params["params"], "cache": sl},
            toks, mutable=["cache"],
        )
        cache = jax.tree.map(
            lambda c, u: jax.lax.dynamic_update_slice(
                c, u, self._row_start(c, slot)),
            cache, upd["cache"],
        )
        return cache, logits[:, -1, :]

    def prefill_fn(self, n: int):
        """One jitted prefill bucket for chunk length ``n`` (caller
        caches/bounds these — core/slots.py shares the LRU discipline of
        the generator element's decode buckets)."""

        def traced(params, cache, toks, slot):
            self.prefill_compiles += 1  # trace-time only
            return self._prefill_chunk(params, cache, toks, slot)

        del n  # bucketing key only; the shape specializes the jit
        return jax.jit(traced, donate_argnums=self._donate)

    def _pick_first(self, logits):  # (1, V) -> (1,)
        return self._pick(logits, self._key0)

    # -- decode (whole slot batch, k tokens per dispatch) -------------------
    def _pick_slots(self, lg, gen):  # (S, V), (S,) -> (S,)
        if self._temperature <= 0.0:
            return jnp.argmax(lg, axis=-1).astype(jnp.int32)
        # per-slot key folded at the slot's OWN generated count — the
        # same fold the unslotted scan applies at global step t (vmap of
        # a key-batched draw is bit-equal to the per-row loop)
        key0 = self._key0
        keys = jax.vmap(lambda g: jax.random.fold_in(key0, g))(gen)
        keys = jnp.where((gen == 0)[:, None], key0[None], keys)
        pick = self._pick

        def one(l, k):  # (V,), key -> ()
            return pick(l[None], k)[0]

        return jax.vmap(one)(lg, keys).astype(jnp.int32)

    def _decode_scan(self, k, params, cache, tok, gen, active):
        def step(carry, _i):
            cache, tok, gen = carry
            logits, upd = self._model.apply(
                {"params": params["params"], "cache": cache},
                tok[:, None], mutable=["cache"], active=active,
            )
            nxt = self._pick_slots(logits[:, -1, :], gen)
            # idle slots keep their token/fold-count frozen, so the
            # scan is bit-transparent for every occupied row
            tok = jnp.where(active > 0, nxt, tok)
            gen = gen + active
            return (upd["cache"], tok, gen), nxt

        (cache, tok, gen), toks = jax.lax.scan(
            step, (cache, tok, gen), jnp.arange(k)
        )
        return cache, tok, gen, jnp.moveaxis(toks, 0, 1)  # (S, k)

    def decode_fn(self, k: int):
        """One jitted decode bucket: ``k`` tokens for every active slot
        per dispatch (caller caches/bounds these alongside the prefill
        buckets).  Returns ``(cache, tok, gen, toks (S, k))``."""

        def traced(params, cache, tok, gen, active):
            self.decode_compiles += 1  # trace-time only
            return self._decode_scan(k, params, cache, tok, gen, active)

        return jax.jit(traced, donate_argnums=self._donate)


def build_slot_stream(props: Dict[str, str], slots: int,
                      donate: Optional[bool] = None,
                      mesh: Optional[Mesh] = None):
    """Factory for the CONTINUOUS-BATCHING generator path: same
    ``custom`` dialect and seed semantics as :func:`build_stream`
    (``seed`` = params, ``gen_seed`` = sampling), so a single occupant's
    stream is bit-equal to ``generate:<N>`` one-shot serving.  With a
    ``mesh`` the params tensor-shard on tp and the per-slot KV pages
    shard on heads along tp (params fully staged across the mesh before
    return) — the token SEQUENCE is unchanged, only its placement, so
    the stream-continuity resume signature deliberately excludes the
    mesh.  Returns ``(SlotModel, params, max_seq)``."""
    cfg = _cfg_from_props(props)
    params = host_init(
        TransformerLM(cfg).init,
        int(props.get("seed", "0")),
        np.zeros((1, min(8, cfg.max_seq)), np.int32),
    )
    model = SlotModel(
        cfg, slots,
        temperature=float(props.get("temperature", "0")),
        top_k=int(props.get("top_k", "0")),
        seed=int(props.get("gen_seed", "0")),
        donate=donate,
        mesh=mesh,
    )
    params = model.shard_params(params)
    return model, params, cfg.max_seq


def build(custom_props=None):
    """Zoo entry: fn(params, [tokens (B,T) or (T,)]) -> [logits].

    With custom prop ``generate:<N>`` the entry serves greedy KV-cache
    generation instead: tokens in -> prompt+N completion tokens out.
    """
    props = custom_props or {}
    cfg = _cfg_from_props(props)
    model = TransformerLM(cfg)
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, min(8, cfg.max_seq)), np.int32),
    )
    max_new = int(props.get("generate", "0"))
    in_spec = StreamSpec((TensorSpec((None,), np.int32, "tokens"),), FORMAT_STATIC)

    if max_new > 0:
        gen = make_generate(
            cfg,
            max_new,
            temperature=float(props.get("temperature", "0")),
            top_k=int(props.get("top_k", "0")),
            seed=int(props.get("gen_seed", "0")),
        )

        def fn(p, inputs):
            toks = inputs[0]
            single = toks.ndim == 1
            if single:
                toks = toks[None]
            out = gen(p, toks)
            return [out[0] if single else out]

        out_spec = StreamSpec(
            (TensorSpec((None,), np.int32, "tokens"),), FORMAT_STATIC
        )
        return fn, params, in_spec, out_spec

    def fn(p, inputs):
        toks = inputs[0]
        single = toks.ndim == 1
        if single:
            toks = toks[None]
        out = model.apply(p, toks)
        return [out[0] if single else out]

    out_spec = StreamSpec(
        (TensorSpec((None, cfg.vocab), np.float32, "logits"),), FORMAT_STATIC
    )
    return fn, params, in_spec, out_spec


# ---------------------------------------------------------------------------
# Sharded training step (dp × tp × sp)
# ---------------------------------------------------------------------------
def make_train_step(
    mesh: Mesh,
    cfg: Optional[TransformerConfig] = None,
    learning_rate: float = 1e-3,
    seq_axis: str = "sp",
):
    """Build a fully-sharded LM training step over `mesh`.

    Returns (train_step, params, opt_state, data_sharding) where
    ``train_step(params, opt_state, tokens) -> (params, opt_state, loss)``
    is jitted with NamedShardings: params tensor-parallel per
    transformer_rules, tokens sharded (dp, sp), loss replicated.
    """
    import optax

    from ..parallel.sharding import batch_sharding, shard_params, transformer_rules

    cfg = cfg or TransformerConfig()
    # init with an unsharded twin (same param structure; ring attention needs
    # shard-divisible shapes the tiny init batch doesn't have)
    params = host_init(
        TransformerLM(cfg).init, 0, np.zeros((1, 8), np.int32)
    )
    model = TransformerLM(cfg, mesh=mesh, seq_axis=seq_axis)
    tx = optax.adamw(learning_rate)

    rules = transformer_rules(tp_axis="tp")
    params = shard_params(params, mesh, rules)
    opt_state = tx.init(params)
    # optimizer moments mirror the param shardings automatically (they are
    # tree_map'ed from params), so no separate annotation pass is needed.
    data_sh = batch_sharding(mesh, "dp", seq_axis)

    def loss_fn(p, tokens):
        # next-token LM loss on the full (sp-divisible) sequence; targets are
        # tokens rolled left, with the wrapped final position masked out.
        logits = model.apply(p, tokens)
        targets = jnp.roll(tokens, -1, axis=1)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        mask = jnp.ones_like(ll).at[:, -1].set(0.0)
        return -(ll * mask).sum() / mask.sum()

    def _step(p, opt, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens)
        updates, opt = tx.update(grads, opt, p)
        p = optax.apply_updates(p, updates)
        return p, opt, loss

    # donate params+opt_state: XLA reuses their HBM for the updated copies
    # (without this, peak memory is ~2x params+optimizer every step)
    train_step = jax.jit(_step, donate_argnums=(0, 1))
    return train_step, params, opt_state, data_sh
