"""PoseNet (flax) — keypoint heatmap model for the pose decoder.

The reference's pose demo decodes PoseNet heatmaps with ``tensor_decoder
mode=pose_estimation`` (``tensordec-pose.c``): tensor 0 = heatmaps
(grid_h, grid_w, K), optional tensor 1 = offsets (grid_h, grid_w, 2K) for
``option4=heatmap-offset``.  This module: MobileNet-v2 backbone truncated
at stride 16 + 1x1 heads, emitting exactly those tensors (K = 17 COCO
keypoints by default).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init
from .mobilenet_v2 import _CFG, ConvBN, InvertedResidual, _make_divisible


class PoseNet(nn.Module):
    num_keypoints: int = 17
    with_offsets: bool = True
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) * (2.0 / 255.0) - 1.0
        else:
            x = x.astype(self.dtype)
        c = _make_divisible(32)
        x = ConvBN(c, (3, 3), strides=2, dtype=self.dtype)(x)
        for t, ch, n, s in _CFG:
            if ch > 96:
                break  # truncate at stride 16 (pose wants resolution)
            out_c = _make_divisible(ch)
            for i in range(n):
                x = InvertedResidual(out_c, s if i == 0 else 1, t,
                                     dtype=self.dtype)(x)
        x32 = x.astype(jnp.float32)
        heat = nn.Conv(self.num_keypoints, (1, 1), dtype=jnp.float32,
                       name="heatmap")(x32)
        if not self.with_offsets:
            return (heat,)
        off = nn.Conv(2 * self.num_keypoints, (1, 1), dtype=jnp.float32,
                      name="offsets")(x32)
        return heat, off


def build(custom_props=None):
    """Zoo entry: fn(params, [images_u8 (N,257,257,3)]) ->
    [heatmap (N,gh,gw,K)[, offsets (N,gh,gw,2K)]] — feed ``tensor_decoder
    mode=pose_estimation``."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    size = int(props.get("size", "257"))
    kpts = int(props.get("keypoints", "17"))
    with_off = props.get("offsets", "1") not in ("0", "false")
    model = PoseNet(num_keypoints=kpts, with_offsets=with_off, dtype=dtype)
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )
    gh = gw = (size + 15) // 16

    def fn(params, inputs):
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        outs = model.apply(params, x)
        return [o[0] for o in outs] if single else list(outs)

    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_tensors = [TensorSpec((gh, gw, kpts), np.float32, "heatmap")]
    if with_off:
        out_tensors.append(TensorSpec((gh, gw, 2 * kpts), np.float32, "offsets"))
    out_spec = StreamSpec(tuple(out_tensors), FORMAT_STATIC)
    return fn, params, in_spec, out_spec
