"""DeepLab-style semantic segmentation (flax) — pairs with the
``image_segment`` decoder.

The reference runs segmentation through TFLite DeepLab models decoded by
``tensordec-imagesegment.c`` (mode ``tflite-deeplab``: a (H, W, classes)
class-score grid).  This is a from-scratch TPU-friendly implementation:
MobileNet-v2 backbone at output-stride 16, an ASPP-lite head (1x1 + two
atrous 3x3 branches + image pooling), bilinear upsample back to the input
grid — all static shapes, one fused XLA program.

fn(params, [img_u8 (H,W,3) or (N,H,W,3)]) -> [(H,W,classes) scores]
(per-frame; the filter element batches).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init
from .mobilenet_v2 import _CFG, ConvBN, InvertedResidual, _make_divisible


class _Backbone(nn.Module):
    """MobileNet-v2 trunk, stride capped at 16 (dilate the last stage)."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = ConvBN(32, (3, 3), strides=2, dtype=self.dtype)(x)
        stride_seen = 2
        for t, ch, n, s in _CFG:
            out_c = _make_divisible(ch)
            for i in range(n):
                s_i = s if i == 0 else 1
                if stride_seen >= 16 and s_i == 2:
                    s_i = 1  # keep output-stride 16 (dilation-free approx)
                stride_seen *= s_i
                x = InvertedResidual(out_c, s_i, t, dtype=self.dtype)(x)
        return x


class _ASPPLite(nn.Module):
    features: int = 128
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        h, w = x.shape[-3], x.shape[-2]
        b1 = ConvBN(self.features, (1, 1), dtype=self.dtype)(x)
        b2 = nn.Conv(self.features, (3, 3), kernel_dilation=(2, 2),
                     padding="SAME", use_bias=False, dtype=self.dtype)(x)
        b3 = nn.Conv(self.features, (3, 3), kernel_dilation=(4, 4),
                     padding="SAME", use_bias=False, dtype=self.dtype)(x)
        # image-level pooling branch, broadcast back to the grid
        gp = jnp.mean(x, axis=(-3, -2), keepdims=True)
        gp = ConvBN(self.features, (1, 1), dtype=self.dtype)(gp)
        gp = jnp.broadcast_to(gp, gp.shape[:-3] + (h, w, self.features))
        y = jnp.concatenate([b1, b2, b3, gp], axis=-1)
        return ConvBN(self.features, (1, 1), dtype=self.dtype)(y)


class DeepLabLite(nn.Module):
    num_classes: int = 21  # Pascal VOC + background (tflite-deeplab layout)
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        size = (x.shape[-3], x.shape[-2])
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) * (2.0 / 255.0) - 1.0
        else:
            x = x.astype(self.dtype)
        x = _Backbone(dtype=self.dtype)(x)
        x = _ASPPLite(dtype=self.dtype)(x)
        x = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
        # bilinear upsample to the input grid (XLA lowers resize to gathers
        # + matmuls; static scale so it compiles once)
        return jax.image.resize(
            x, x.shape[:-3] + size + (self.num_classes,), method="bilinear"
        )


def build(custom_props=None):
    """Zoo entry: fn(params, [img (H,W,3) u8]) -> [(H,W,classes) f32]."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[props.get("dtype", "bfloat16")]
    size = int(props.get("size", "257"))
    classes = int(props.get("classes", "21"))
    model = DeepLabLite(num_classes=classes, dtype=dtype)
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )

    def fn(p, inputs):
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        out = model.apply(p, x)
        return [out[0] if single else out]

    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((size, size, classes), np.float32, "class_scores"),),
        FORMAT_STATIC,
    )
    return fn, params, in_spec, out_spec
