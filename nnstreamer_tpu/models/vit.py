"""ViT image classifier (flax) — the transformer-era vision family.

The reference's model zoo is convnet-centric (per-vendor tflite/onnx
classifiers); a Vision Transformer is the TPU-native complement: patch
embedding + attention blocks are large dense matmuls that map straight
onto the MXU, and the encoder reuses this framework's transformer Block
machinery (``models/transformer.py``) including the flash-attention
Pallas kernel via ``attn:flash``.

Zoo entry ``vit``: fn(params, [images_u8 (N,S,S,3)]) -> [logits (N,classes)].
Props: size (default 224), patch (16), d_model (192), heads (3),
layers (6), d_ff (768), classes (1001), dtype, attn (xla|flash).
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init


class EncoderBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    quant: bool = False  # int8 MXU dense layers (_quant_flax.QuantDense)

    def _dense(self, features, name):
        from ._quant_flax import dense_or_quant

        # same explicit name -> same param path/RNG fold either way
        return dense_or_quant(self.quant, features, self.dtype, name)

    @nn.compact
    def __call__(self, x):  # (B, T, D), pre-norm ViT block
        B, T, D = x.shape
        H = self.n_heads
        h = nn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        qkv = self._dense(3 * D, "attn_qkv")(h)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, D // H)
        k = k.reshape(B, T, H, D // H)
        v = v.reshape(B, T, H, D // H)
        if self.attn_impl == "flash":
            from ..ops.flash_attention import flash_attention_grad

            # differentiable wrapper: kernel forward, recompute backward
            a = flash_attention_grad(q, k, v, False)
        else:
            from ..parallel.ring_attention import reference_attention

            a = reference_attention(q, k, v, causal=False)
        x = x + self._dense(D, "attn_out")(a.reshape(B, T, D))
        h = nn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = self._dense(self.d_ff, "mlp_up")(h)
        h = jax.nn.gelu(h)
        return x + self._dense(D, "mlp_down")(h)


class ViT(nn.Module):
    size: int = 224
    patch: int = 16
    d_model: int = 192
    n_heads: int = 3
    n_layers: int = 6
    d_ff: int = 768
    num_classes: int = 1001
    dtype: Any = jnp.bfloat16
    attn_impl: str = "xla"
    quant: bool = False

    @nn.compact
    def __call__(self, x):  # (B, S, S, 3) uint8 or float
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) * (2.0 / 255.0) - 1.0
        else:
            x = x.astype(self.dtype)
        # patchify as one conv: the embedding matmul the MXU loves
        x = nn.Conv(
            self.d_model, (self.patch, self.patch),
            strides=(self.patch, self.patch), padding="VALID",
            dtype=self.dtype, name="patch_embed",
        )(x)
        B = x.shape[0]
        x = x.reshape(B, -1, self.d_model)  # (B, T, D)
        T = x.shape[1]
        cls = self.param(
            "cls", nn.initializers.zeros, (1, 1, self.d_model)
        ).astype(self.dtype)
        x = jnp.concatenate([jnp.broadcast_to(cls, (B, 1, self.d_model)), x], 1)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (1, T + 1, self.d_model),
        ).astype(self.dtype)
        x = x + pos
        for i in range(self.n_layers):
            x = EncoderBlock(
                self.d_model, self.n_heads, self.d_ff,
                dtype=self.dtype, attn_impl=self.attn_impl,
                quant=self.quant, name=f"block{i}",
            )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="ln_f")(x)
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, name="head"
        )(x[:, 0].astype(jnp.float32))


def build(custom_props=None):
    """Zoo entry: fn(params, [images_u8 (N,S,S,3)]) -> [logits]."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    size = int(props.get("size", "224"))
    patch = int(props.get("patch", "16"))
    if size % patch:
        raise ValueError(f"size {size} not divisible by patch {patch}")
    model = ViT(
        size=size,
        patch=patch,
        d_model=int(props.get("d_model", "192")),
        n_heads=int(props.get("heads", "3")),
        n_layers=int(props.get("layers", "6")),
        d_ff=int(props.get("d_ff", "768")),
        num_classes=int(props.get("classes", "1001")),
        dtype=dtype,
        attn_impl=props.get("attn", "xla"),
        quant=props.get("quantize", "") == "int8",
    )
    variables = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )

    def fn(params, inputs: List[Any]) -> List[Any]:
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        out = model.apply(params, x)
        return [out[0] if single else out]

    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (TensorSpec((model.num_classes,), np.float32, "logits"),),
        FORMAT_STATIC,
    )
    return fn, variables, in_spec, out_spec
