"""SSD-MobileNet-v2 (flax) — detection head for the bounding-box decoder.

The reference's detection demos run ssd_mobilenet_v2 through TFLite with
``tensor_decoder mode=bounding_boxes option1=mobilenet-ssd
option3=box-priors.txt`` (``tensordec-boundingbox.c`` update_mobilenet_ssd).
This module is the TPU-native model for that pipeline: MobileNet-v2
backbone (shared blocks from :mod:`.mobilenet_v2`) + SSD box/class heads
over 6 feature scales.

Outputs match the decoder contract exactly:
  * loc    (P, 4)  raw (yc, xc, h, w) offsets (decoder divides by the
           10/10/5/5 scale factors and applies the priors)
  * scores (P, C)  logits (decoder applies sigmoid)

:func:`anchors` generates the matching priors (yc, xc, h, w, normalized)
and :func:`write_box_priors` emits the 4-row ``box-priors.txt`` file the
decoder's option3 loads (``mobilenet_ssd_load_box_priors``).
"""

from __future__ import annotations

import itertools
from typing import Any, List, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from ..core.types import FORMAT_STATIC, StreamSpec, TensorSpec
from ._init_util import host_init
from .mobilenet_v2 import _CFG, ConvBN, InvertedResidual, _make_divisible

# one (grid, scale, aspect-ratios) row per SSD feature map, 300x300 layout
_FEATURE_MAPS: Sequence[Tuple[int, float]] = (
    (19, 0.2), (10, 0.35), (5, 0.5), (3, 0.65), (2, 0.8), (1, 0.95),
)
_ASPECTS = (1.0, 2.0, 0.5)


def anchors() -> np.ndarray:
    """SSD priors [P, 4] = (yc, xc, h, w), normalized to [0, 1]."""
    out: List[Tuple[float, float, float, float]] = []
    for i, (grid, scale) in enumerate(_FEATURE_MAPS):
        nxt = _FEATURE_MAPS[i + 1][1] if i + 1 < len(_FEATURE_MAPS) else 1.0
        for y, x in itertools.product(range(grid), repeat=2):
            yc = (y + 0.5) / grid
            xc = (x + 0.5) / grid
            for ar in _ASPECTS:
                out.append((yc, xc, scale / np.sqrt(ar), scale * np.sqrt(ar)))
            out.append((yc, xc, np.sqrt(scale * nxt), np.sqrt(scale * nxt)))
    return np.asarray(out, np.float64)


def num_priors() -> int:
    return sum(g * g * (len(_ASPECTS) + 1) for g, _ in _FEATURE_MAPS)


def write_box_priors(path: str) -> str:
    """Write the decoder's option3 file: 4 whitespace rows (yc, xc, h, w)."""
    pri = anchors().T  # [4, P]
    with open(path, "w", encoding="utf-8") as f:
        for row in pri:
            f.write(" ".join(f"{v:.8f}" for v in row) + "\n")
    return path


class SSDMobileNetV2(nn.Module):
    num_classes: int = 91
    dtype: Any = jnp.bfloat16
    # int8 MXU path for the backbone + extra feature convs (where the
    # FLOPs are); the tiny loc/conf heads stay float32 — box regression
    # is precision-sensitive and the heads are a rounding error of the
    # compute (≙ the reference's quantized-tflite ssd flagship)
    quant: bool = False

    @nn.compact
    def __call__(self, x):
        if x.dtype == jnp.uint8:
            x = x.astype(self.dtype) * (2.0 / 255.0) - 1.0
        else:
            x = x.astype(self.dtype)
        feats: List[jnp.ndarray] = []
        c = _make_divisible(32)
        x = ConvBN(c, (3, 3), strides=2, dtype=self.dtype,
                   quant=self.quant)(x)
        for t, ch, n, s in _CFG:
            out_c = _make_divisible(ch)
            for i in range(n):
                x = InvertedResidual(out_c, s if i == 0 else 1, t,
                                     dtype=self.dtype, quant=self.quant)(x)
            if ch == 96:
                feats.append(x)   # stride 16 -> 19x19 @ 300
        x = ConvBN(_make_divisible(1280), (1, 1), dtype=self.dtype,
                   quant=self.quant)(x)
        feats.append(x)           # stride 32 -> 10x10
        # extra SSD feature layers down to 1x1
        for ch in (512, 256, 256, 128):
            x = ConvBN(ch // 2, (1, 1), dtype=self.dtype,
                       quant=self.quant)(x)
            x = ConvBN(ch, (3, 3), strides=2, dtype=self.dtype,
                       quant=self.quant)(x)
            feats.append(x)

        locs, confs = [], []
        per_cell = len(_ASPECTS) + 1
        for i, f in enumerate(feats):
            B = f.shape[0]
            loc = nn.Conv(per_cell * 4, (3, 3), padding="SAME",
                          dtype=jnp.float32, name=f"loc{i}")(
                f.astype(jnp.float32))
            conf = nn.Conv(per_cell * self.num_classes, (3, 3),
                           padding="SAME", dtype=jnp.float32,
                           name=f"conf{i}")(f.astype(jnp.float32))
            locs.append(loc.reshape(B, -1, 4))
            confs.append(conf.reshape(B, -1, self.num_classes))
        return jnp.concatenate(locs, 1), jnp.concatenate(confs, 1)


def build(custom_props=None):
    """Zoo entry: fn(params, [images_u8 (N,300,300,3)]) ->
    [loc (N,P,4), scores (N,P,C)]."""
    props = custom_props or {}
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}[
        props.get("dtype", "bfloat16")
    ]
    size = int(props.get("size", "300"))
    if size != 300:
        # anchors()/num_priors() encode the 300x300 feature-map layout;
        # other sizes would desync priors from the head outputs
        raise ValueError("ssd_mobilenet_v2 supports size=300 only")
    classes = int(props.get("classes", "91"))
    model = SSDMobileNetV2(
        num_classes=classes, dtype=dtype,
        quant=props.get("quantize", "") == "int8",
    )
    params = host_init(
        model.init,
        int(props.get("seed", "0")),
        np.zeros((1, size, size, 3), np.uint8),
    )

    def fn(params, inputs):
        x = inputs[0]
        single = x.ndim == 3
        if single:
            x = x[None]
        loc, conf = model.apply(params, x)
        if single:
            return [loc[0], conf[0]]
        return [loc, conf]

    P = num_priors()
    in_spec = StreamSpec(
        (TensorSpec((size, size, 3), np.uint8, "image"),), FORMAT_STATIC
    )
    out_spec = StreamSpec(
        (
            TensorSpec((P, 4), np.float32, "loc"),
            TensorSpec((P, classes), np.float32, "scores"),
        ),
        FORMAT_STATIC,
    )
    return fn, params, in_spec, out_spec
