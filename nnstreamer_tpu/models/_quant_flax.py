"""Flax drop-in modules for int8 MXU inference (ops/quantize.py).

``QuantConv`` / ``QuantDense`` mirror ``nn.Conv`` / ``nn.Dense`` (bias-free
forms) but run int8×int8→int32 with per-channel weight scales and
per-sample dynamic activation scales.  Given the SAME submodule ``name``
as the float module they replace, their param path — and therefore
flax's per-param RNG fold — is identical, so quantized and float builds
share identical weights for the same seed (pinned by tests).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp


class QuantConv(nn.Module):
    """Drop-in for bias-free ``nn.Conv`` on the int8 MXU path."""

    features: int
    kernel_size: Tuple[int, int]
    strides: int = 1
    feature_group_count: int = 1
    padding: str = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from ..ops.quantize import int8_conv

        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (
                *self.kernel_size,
                x.shape[-1] // self.feature_group_count,
                self.features,
            ),
        )
        return int8_conv(
            x,
            w,
            strides=(self.strides, self.strides),
            padding=self.padding,
            feature_group_count=self.feature_group_count,
            out_dtype=self.dtype,
        )


def dense_or_quant(quant: bool, features: int, dtype, name: str):
    """The bias-free Dense layer factory shared by the transformer and
    ViT blocks: ``nn.Dense`` normally, ``QuantDense`` under int8 — one
    switch, so the quant path cannot drift between model families."""
    if quant:
        return QuantDense(features, dtype=dtype, name=name)
    return nn.Dense(features, use_bias=False, dtype=dtype, name=name)


class QuantDense(nn.Module):
    """Drop-in for bias-free ``nn.Dense`` on the int8 MXU path."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from ..ops.quantize import int8_dense

        w = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (x.shape[-1], self.features),
        )
        return int8_dense(x, w, out_dtype=self.dtype)
