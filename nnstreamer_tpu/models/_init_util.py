"""Host-side parameter initialization for zoo models.

Flax ``model.init`` run eagerly dispatches every RNG/reshape/conv op to the
default device one by one.  On a locally attached chip that is merely slow;
through a tunneled/remote accelerator (this dev harness) each dispatch is a
network round trip and a full MobileNet init can hang for minutes — the
round-1 bench died exactly there (VERDICT.md item 1).

``host_init`` compiles the whole init as ONE program pinned to the host CPU
backend, so model construction never touches the accelerator.  Parameters
land as committed-CPU jax.Arrays; the jax-xla filter backend moves them to
the accelerator in a single bulk ``jax.device_put`` at ``open()``
(backends/jax_xla.py), which is the only device round trip model bring-up
pays.  (The reference loads model weights from disk straight into host
memory for the same reason — e.g. TFLiteInterpreter model load in
``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc``;
the accelerator only ever sees the finished buffers.)
"""

from __future__ import annotations

from typing import Any


def host_init(init_fn, seed: int, *dummies: Any) -> Any:
    """Run a flax ``init`` on host CPU as one compiled program.

    ``init_fn(rng, *dummies)`` is jitted with the PRNG key constructed
    *inside* the program (``jax.random.PRNGKey`` run eagerly is itself a
    device dispatch).  ``dummies`` must be host values (numpy arrays /
    ShapeDtypeStructs), never eagerly-created ``jnp`` arrays — those would
    already live on the default device before this function runs.
    """
    import jax

    cpu = jax.local_devices(backend="cpu")[0]
    with jax.default_device(cpu):
        return jax.jit(
            lambda *xs: init_fn(jax.random.PRNGKey(seed), *xs)
        )(*dummies)
